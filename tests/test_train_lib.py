"""JaxTrainer / WorkerGroup / checkpoint tests.

Reference model: train/tests (BackendExecutor + WorkerGroup tests) and
the v2 controller restart tests. Multi-worker runs use jax processes on
the CPU backend with virtual devices — the same rendezvous path a TPU
pod slice uses, minus the hardware."""

import os
import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu
import conftest


# train-loop functions below are module-level in a non-importable test
# module; ship them by value (reference equivalent: runtime_env
# working_dir makes the module importable on workers)
cloudpickle.register_pickle_by_value(sys.modules[__name__])
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    CheckpointManager,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
)


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


# ---------------------------------------------------------------- manager


def test_checkpoint_manager_topk(tmp_path):
    mgr = CheckpointManager(
        str(tmp_path / "exp"),
        CheckpointConfig(num_to_keep=2, checkpoint_score_attribute="acc"))
    paths = []
    for i, acc in enumerate([0.1, 0.9, 0.5, 0.3]):
        src = tmp_path / f"ck{i}"
        src.mkdir()
        (src / "model.txt").write_text(str(i))
        ck = mgr.register(Checkpoint(str(src)), {"acc": acc})
        paths.append(ck.path)
    kept = sorted(os.listdir(tmp_path / "exp"))
    # top-2 by acc = (0.9, 0.5) plus the most recent (0.3) is never deleted
    assert len(kept) == 3
    assert mgr.best() is not None
    with open(os.path.join(mgr.best().path, "model.txt")) as f:
        assert f.read() == "1"  # acc=0.9 was checkpoint index 1


def test_checkpoint_roundtrip(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "w.npy").write_bytes(b"abc")
    ck = Checkpoint.from_directory(str(src))
    dest = ck.to_directory(str(tmp_path / "dst"))
    assert (tmp_path / "dst" / "w.npy").read_bytes() == b"abc"
    with ck.as_directory() as d:
        assert os.path.exists(os.path.join(d, "w.npy"))
    assert dest


# ---------------------------------------------------------------- trainer


def _simple_loop(config):
    import ray_tpu.train as train

    ctx = train.get_context()
    for step in range(config["steps"]):
        train.report({"step": step, "rank": ctx.get_world_rank(),
                      "world": ctx.get_world_size()})
    return "done"


def test_single_worker_reports(cluster, tmp_path):
    trainer = JaxTrainer(
        _simple_loop,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3
    assert result.metrics["world"] == 1


def test_two_workers_rank_env(cluster, tmp_path):
    def loop(config):
        import os

        import ray_tpu.train as train

        ctx = train.get_context()
        train.report({
            "rank": ctx.get_world_rank(),
            "env_rank": int(os.environ["RAY_TPU_TRAIN_RANK"]),
            "world": int(os.environ["RAY_TPU_TRAIN_WORLD_SIZE"]),
        })

    trainer = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t2", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.metrics["rank"] == 0
    assert result.metrics["env_rank"] == 0
    assert result.metrics["world"] == 2


def _gpt2_loop(config):
    """GPT-2-tiny over however many jax processes the gang has."""
    import jax
    import numpy as np
    import optax

    import ray_tpu.train as train
    from ray_tpu.models.gpt2 import (
        GPT2Config,
        gpt2_loss,
        gpt2_partition_rules,
        init_gpt2,
    )
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train import checkpointing
    from ray_tpu.train.spmd import (
        batch_shardings,
        init_sharded_state,
        make_train_step,
    )

    ctx = train.get_context()
    cfg = GPT2Config.tiny()
    mesh = build_mesh(MeshSpec(data=-1), devices=jax.devices())
    tx = optax.adamw(1e-3)
    state = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh,
        gpt2_partition_rules())

    start_step = 0
    ck = train.get_checkpoint()
    if ck is not None:
        with ck.as_directory() as d:
            state = checkpointing.load_train_state(d, state)
        start_step = int(np.asarray(state.step))

    # deterministic GLOBAL batch, identical regardless of world layout
    B, T = 8, cfg.block_size
    rng = np.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size, (B, T + 1)).astype(np.int32)
    global_batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    sh = batch_shardings(mesh, global_batch)
    per = B // jax.process_count()
    lo = jax.process_index() * per
    batch = jax.tree.map(
        lambda arr, s: jax.make_array_from_process_local_data(
            s, arr[lo:lo + per], arr.shape),
        global_batch, sh)

    step_fn = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), tx)
    with mesh:
        for step in range(start_step, config["steps"]):
            if config.get("crash_at") == step and ctx.get_world_rank() == 0 \
                    and train.get_checkpoint() is None:
                import os
                import time as _t

                # let the driver DRAIN queued reports first (the prior
                # checkpoint must be registered before we die, or the
                # restart has nothing to resume from and crashes again —
                # a load-dependent flake otherwise)
                from ray_tpu.train import session as S

                deadline = _t.monotonic() + 30
                while not S.get_session().results.empty() and \
                        _t.monotonic() < deadline:
                    _t.sleep(0.05)
                _t.sleep(0.5)  # pop->register window
                os._exit(1)  # simulate a host loss mid-run (first try only)
            state, metrics = step_fn(state, batch)
            loss = float(np.asarray(metrics["loss"]))
            ckpt = None
            do_ckpt = (step + 1) % config.get("ckpt_every", 10 ** 9) == 0 \
                or step == config["steps"] - 1
            if do_ckpt:
                # collective save: EVERY process calls in; rank 0 reports
                tmp = f"{ctx.get_trial_dir()}/pending_ckpt_{step}"
                checkpointing.save_train_state(state, tmp)
                if ctx.get_world_rank() == 0:
                    ckpt = train.Checkpoint(tmp)
            train.report({"loss": loss, "step": step}, checkpoint=ckpt)


@pytest.mark.skipif(not conftest.jax_supports_multiprocess_cpu(),
                    reason="multiprocess SPMD unimplemented on "
                           "this jaxlib's CPU backend")
def test_gpt2_loss_parity_1_vs_2_workers(cluster, tmp_path):
    """Same global batch + init => identical loss whether the mesh spans
    one process or two (the SPMD-equivalence guarantee DDP tests assert
    via allreduce parity)."""
    losses = {}
    for n_workers, devs in ((1, 8), (2, 4)):
        trainer = JaxTrainer(
            _gpt2_loop,
            train_loop_config={"steps": 3},
            scaling_config=ScalingConfig(
                num_workers=n_workers,
                num_cpu_devices_per_worker=devs),
            run_config=RunConfig(name=f"parity{n_workers}",
                                 storage_path=str(tmp_path)),
        )
        result = trainer.fit()
        losses[n_workers] = [m["loss"] for m in result.metrics_history]
    assert len(losses[1]) == len(losses[2]) == 3
    np.testing.assert_allclose(losses[1], losses[2], rtol=1e-4, atol=1e-5)


def test_gang_restart_resumes_from_checkpoint(cluster, tmp_path):
    """Kill rank 0 mid-run; the gang restarts from the latest checkpoint
    and the loss curve continues (VERDICT r1 done-criterion)."""
    trainer = JaxTrainer(
        _gpt2_loop,
        train_loop_config={"steps": 6, "ckpt_every": 2, "crash_at": 4},
        scaling_config=ScalingConfig(num_workers=1,
                                     num_cpu_devices_per_worker=2),
        run_config=RunConfig(
            name="restart", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
            checkpoint_config=CheckpointConfig(num_to_keep=2)),
    )
    result = trainer.fit()
    steps = [m["step"] for m in result.metrics_history]
    # crashed at step 4 (before reporting), resumed from ckpt@step 3
    assert steps[-1] == 5
    assert 4 in steps
    assert result.checkpoint is not None
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]


def test_failure_budget_exhausted(cluster, tmp_path):
    def always_fail(config):
        raise RuntimeError("boom")

    trainer = JaxTrainer(
        always_fail,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="fail", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)),
    )
    with pytest.raises(TrainingFailedError):
        trainer.fit()


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_elastic_scaling_sizes_to_available(cluster, tmp_path):
    """min_workers turns on elastic sizing: ask for 6, floor 1, on an
    8-CPU cluster with 1-CPU workers the gang sizes to what fits
    (reference: Train v2 ScalingPolicy)."""

    def loop(config):
        import ray_tpu.train as train

        ctx = train.get_context()
        train.report({"world": ctx.get_world_size()})

    # occupy some CPUs so fewer than 6 fit
    @ray_tpu.remote(num_cpus=1)
    class Hog:
        def ping(self):
            return "ok"

    hogs = [Hog.remote() for _ in range(4)]
    for h in hogs:
        assert ray_tpu.get(h.ping.remote(), timeout=60) == "ok"
    import time

    time.sleep(1.2)  # heartbeat settles
    trainer = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=6, min_workers=1),
        run_config=RunConfig(name="elastic", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    for h in hogs:
        ray_tpu.kill(h)
    assert 1 <= result.metrics["world"] <= 4


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_dataset_ingestion_sharded(cluster, tmp_path):
    """JaxTrainer(datasets=...) ships per-worker Dataset shards;
    get_dataset_shard() streams them (reference: ray.train dataset
    ingestion via get_dataset_shard)."""
    from ray_tpu import data as rd

    ds = rd.range(64, parallelism=8).map(lambda x: x * 2)

    def loop(config):
        import numpy as np

        import ray_tpu.train as train

        shard = train.get_dataset_shard("train")
        total, count = 0, 0
        for batch in shard.iter_batches(batch_size=8):
            total += int(np.sum(batch))
            count += len(batch)
        train.report({"total": total, "count": count})

    trainer = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ingest", storage_path=str(tmp_path)),
        datasets={"train": ds},
    )
    result = trainer.fit()
    # rank0's shard: blocks 0,2,4,6 of range(64)*2
    assert result.metrics["count"] == 32
    history_total = result.metrics["total"]
    expected_rank0 = sum(
        x * 2 for i in range(0, 8, 2) for x in range(i * 8, (i + 1) * 8))
    assert history_total == expected_rank0
