"""End-to-end SPMD training slice on the virtual 8-device mesh
(model analogue of the reference's multi-node-on-one-box tests,
SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.models.gpt2 import (
    GPT2Config,
    count_params,
    gpt2_forward,
    gpt2_loss,
    gpt2_partition_rules,
    init_gpt2,
)
from ray_tpu.train.spmd import (
    TrainState,
    batch_shardings,
    init_sharded_state,
    make_train_step,
)


@pytest.fixture(scope="module")
def tiny_cfg():
    return GPT2Config.tiny()


def _batch(cfg, B=8, T=64, seed=1):
    toks = jax.random.randint(
        jax.random.PRNGKey(seed), (B, T + 1), 0, cfg.vocab_size, jnp.int32
    )
    return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}


def test_forward_shape(tiny_cfg):
    params = init_gpt2(jax.random.PRNGKey(0), tiny_cfg)
    logits = gpt2_forward(params, jnp.zeros((2, 16), jnp.int32), tiny_cfg)
    assert logits.shape == (2, 16, tiny_cfg.padded_vocab)
    assert logits.dtype == jnp.float32


def test_param_shardings(tiny_cfg, cpu_mesh8):
    rules = gpt2_partition_rules()
    tx = optax.adamw(1e-3)
    state = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), tiny_cfg), tx, cpu_mesh8, rules
    )
    qkv = state.params["blocks"]["attn_qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, "fsdp", "tensor")
    # adam moments shard like their params
    mu_qkv = state.opt_state[0].mu["blocks"]["attn_qkv"]["kernel"]
    assert mu_qkv.sharding.spec == P(None, "fsdp", "tensor")


def test_loss_decreases_on_mesh(tiny_cfg, cpu_mesh8):
    rules = gpt2_partition_rules()
    tx = optax.adamw(3e-4)
    state = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), tiny_cfg), tx, cpu_mesh8, rules
    )
    batch = jax.device_put(
        _batch(tiny_cfg), batch_shardings(cpu_mesh8, _batch(tiny_cfg))
    )
    step = make_train_step(lambda p, b: gpt2_loss(p, b, tiny_cfg), tx)
    losses = []
    with cpu_mesh8:
        for _ in range(5):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert state.step == 5


def test_spmd_matches_single_device(tiny_cfg, cpu_mesh8):
    """The sharded program must compute the same math as one device."""
    rules = gpt2_partition_rules()
    tx = optax.sgd(0.1)
    batch = _batch(tiny_cfg, B=4, T=32)

    # single device
    params = init_gpt2(jax.random.PRNGKey(0), tiny_cfg)
    state1 = TrainState.create(params, tx)
    step1 = make_train_step(lambda p, b: gpt2_loss(p, b, tiny_cfg), tx, donate=False)
    _, m1 = step1(state1, batch)

    # 8-device mesh
    state8 = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), tiny_cfg), tx, cpu_mesh8, rules
    )
    sbatch = jax.device_put(batch, batch_shardings(cpu_mesh8, batch))
    step8 = make_train_step(lambda p, b: gpt2_loss(p, b, tiny_cfg), tx, donate=False)
    with cpu_mesh8:
        _, m8 = step8(state8, sbatch)

    # bf16 compute: sharded contractions reduce in a different order,
    # so allow a few ulps beyond the fp32-ish 2e-4 bar
    assert abs(float(m1["loss"]) - float(m8["loss"])) < 5e-4


def test_param_count_gpt2_small():
    # 124M-class model (wte padded): sanity-check the architecture
    cfg = GPT2Config.small()
    n = count_params(init_gpt2(jax.random.PRNGKey(0), cfg))
    assert 124e6 < n < 126e6


def test_gpt2_size_presets():
    """Config presets cover the published GPT-2 family (the reference's
    flagship Train benchmark names GPT-2; sizes beyond small matter for
    multi-chip sharding)."""
    from ray_tpu.models.gpt2 import GPT2Config

    for cfg, params_m in ((GPT2Config.small(), 124), (GPT2Config.medium(), 355),
                          (GPT2Config.large(), 774), (GPT2Config.xl(), 1558)):
        # parameter-count sanity within 5% of the published sizes
        E, L, V = cfg.n_embd, cfg.n_layer, cfg.padded_vocab
        approx = V * E + cfg.block_size * E + L * 12 * E * E
        assert abs(approx / 1e6 - params_m) / params_m < 0.06, (
            cfg, approx / 1e6)
        assert cfg.n_embd % cfg.n_head == 0
