"""GCP TPU queued-resources provider (VERDICT r3 item 8b).

Reference parity: autoscaler/_private/gcp/node.py:191 (queued-resource
lifecycle), gcp/config.py (accelerator-type slice shape). The API is
mocked; the provider's state machine and the slice-label contract are
what these tests pin down.
"""

import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.autoscaler import AutoscalerConfig, StandardAutoscaler
from ray_tpu.autoscaler_gcp import (
    ACTIVE,
    FakeTPUQueuedResourceAPI,
    GCPTPUNodeProvider,
    slice_shape,
)
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import tpu as tpu_mod
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------------------
# pure units
# ---------------------------------------------------------------------------

def test_slice_shape_parsing():
    assert slice_shape("v4-8") == (2, 4)
    assert slice_shape("v4-16") == (4, 4)
    assert slice_shape("v5p-8") == (2, 4)
    assert slice_shape("v3-8") == (1, 4)  # 8 cores = 1 host of 4 chips
    assert slice_shape("v2-32") == (4, 4)
    with pytest.raises(ValueError):
        slice_shape("tpu")


def test_fake_api_lifecycle():
    api = FakeTPUQueuedResourceAPI(provision_polls=2)
    api.create_queued_resource("s1", "v4-16")
    st1 = api.get_queued_resource("s1")["state"]
    assert st1 != ACTIVE, "became ACTIVE on first poll"
    qr = api.get_queued_resource("s1")
    assert qr["state"] == ACTIVE
    assert len(qr["hosts"]) == 4  # all hosts appear together
    api.delete_queued_resource("s1")
    with pytest.raises(KeyError):
        api.get_queued_resource("s1")


def test_fake_api_stockout_injection():
    api = FakeTPUQueuedResourceAPI(provision_polls=1)
    api.fail_next_creations(1)
    api.create_queued_resource("bad", "v4-8")
    assert api.get_queued_resource("bad")["state"] == "FAILED"
    api.create_queued_resource("good", "v4-8")
    assert api.get_queued_resource("good")["state"] == ACTIVE


# ---------------------------------------------------------------------------
# provider against a live head
# ---------------------------------------------------------------------------

@pytest.fixture
def cluster(tmp_path):
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _wait_hosts(provider, n, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        hosts = [h for h in provider.non_terminated_nodes()
                 if provider.node_id(h)]
        if len(hosts) >= n:
            return hosts
        time.sleep(0.2)
    raise AssertionError(f"never saw {n} active hosts")


def test_slice_provisioning_registers_labeled_hosts(cluster, tmp_path):
    """An ACTIVE queued resource boots every host of the slice with
    slice-identity labels and the TPU-head marker on worker 0."""
    provider = GCPTPUNodeProvider(
        cluster.address,
        {"tpu": {"accelerator_type": "v4-16", "cpus_per_host": 2,
                 "topology": "2x2x2"}},
        session_dir=str(tmp_path / "gcp"))
    provider.create_node("tpu")
    # pending slices count toward capacity accounting before ACTIVE
    assert len(provider.non_terminated_nodes()) >= 1
    hosts = _wait_hosts(provider, 4)
    assert {h.worker_id for h in hosts} == {0, 1, 2, 3}

    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        view = [n for n in ray_tpu.nodes()
                if n["Labels"].get(tpu_mod.SLICE_LABEL)]
        if len(view) == 4 and all(n["Alive"] for n in view):
            break
        time.sleep(0.2)
    assert len(view) == 4, "hosts never registered with the head"
    heads = [n for n in view if "TPU-v4-16-head" in n["Resources"]]
    assert len(heads) == 1
    assert heads[0]["Labels"][tpu_mod.WORKER_ID_LABEL] == "0"
    assert all(n["Resources"].get("TPU") == 4.0 for n in view)
    assert all(n["Labels"][tpu_mod.TOPOLOGY_LABEL] == "2x2x2"
               for n in view)
    provider.terminate_node(hosts[0])


def test_slice_delete_is_atomic(cluster, tmp_path):
    """Terminating any host of a slice removes the WHOLE slice (pod
    slices are indivisible), and the queued resource is deleted."""
    provider = GCPTPUNodeProvider(
        cluster.address,
        {"tpu": {"accelerator_type": "v4-8", "cpus_per_host": 1}},
        session_dir=str(tmp_path / "gcp2"))
    provider.create_node("tpu")
    hosts = _wait_hosts(provider, 2)
    provider.terminate_node(hosts[1])
    assert provider.non_terminated_nodes() == []
    assert provider.api.delete_calls == 1


def test_failed_provisioning_cleaned_up(cluster, tmp_path):
    api = FakeTPUQueuedResourceAPI(provision_polls=1)
    api.fail_next_creations(1)
    provider = GCPTPUNodeProvider(
        cluster.address,
        {"tpu": {"accelerator_type": "v4-8"}},
        api=api, session_dir=str(tmp_path / "gcp3"))
    provider.create_node("tpu")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not provider.failed_slices:
        provider.poll()
        time.sleep(0.1)
    assert provider.failed_slices, "stockout never surfaced"
    assert provider.non_terminated_nodes() == []


def test_autoscaler_e2e_scales_tpu_slice_for_pending_pg(cluster, tmp_path):
    """The TPU-native end-to-end: a STRICT_PACK slice-gang placement
    group is PENDING → the autoscaler asks the provider for a slice →
    hosts register → the PG is placed across the slice in worker-id
    order (SURVEY slice-gang scheduling over autoscaled capacity)."""
    provider = GCPTPUNodeProvider(
        cluster.address,
        {"tpu": {"accelerator_type": "v4-16", "cpus_per_host": 2}},
        session_dir=str(tmp_path / "gcp4"))
    scaler = StandardAutoscaler(
        cluster.address, provider,
        AutoscalerConfig(min_workers=0, max_workers=4, node_type="tpu",
                         idle_timeout_s=60.0))

    pg = placement_group([{"TPU": 4.0}] * 4, strategy="STRICT_PACK")
    deadline = time.monotonic() + 60
    placed = False
    while time.monotonic() < deadline:
        scaler.reconcile()  # also advances queued-resource provisioning
        if pg.wait(1):
            placed = True
            break
        time.sleep(0.3)
    assert placed, "slice-gang PG never placed on autoscaled slice"
    assert scaler.num_launches >= 1
    table = placement_group_table(pg)
    assert table["state"] == "CREATED"
    remove_placement_group(pg)
