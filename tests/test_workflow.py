"""Durable workflows (reference model: python/ray/workflow tests —
run, crash, resume; completed steps never re-execute)."""

import os
import sys

import cloudpickle
import pytest

import ray_tpu
from ray_tpu import workflow

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _touch_count(path):
    n = int(open(path).read()) if os.path.exists(path) else 0
    with open(path, "w") as f:
        f.write(str(n + 1))
    return n + 1


def test_linear_dag_runs_and_persists(cluster, tmp_path):
    @workflow.step
    def load():
        return [1, 2, 3, 4]

    @workflow.step
    def double(xs):
        return [2 * x for x in xs]

    @workflow.step
    def total(xs):
        return sum(xs)

    dag = total.step(double.step(load.step()))
    out = workflow.run(dag, workflow_id="lin", storage=str(tmp_path))
    assert out == 20
    assert workflow.get_status("lin", storage=str(tmp_path)) == \
        workflow.SUCCESS
    assert workflow.get_output("lin", storage=str(tmp_path)) == 20
    assert ("lin", workflow.SUCCESS) in workflow.list_all(
        storage=str(tmp_path))


def test_diamond_shared_step_executes_once(cluster, tmp_path):
    marker = str(tmp_path / "source_runs")

    @workflow.step
    def source():
        _touch_count(marker)
        return 10

    @workflow.step
    def left(x):
        return x + 1

    @workflow.step
    def right(x):
        return x + 2

    @workflow.step
    def join(a, b):
        return a * b

    src = source.step()
    out = workflow.run(join.step(left.step(src), right.step(src)),
                       workflow_id="diamond", storage=str(tmp_path))
    assert out == 11 * 12
    assert int(open(marker).read()) == 1, "shared step ran twice"


def test_failure_then_resume_skips_finished_steps(cluster, tmp_path):
    """The durability contract: after a mid-DAG failure, resume()
    re-executes ONLY the unfinished suffix (reference:
    test_workflow resume semantics)."""
    a_runs = str(tmp_path / "a_runs")
    fixed = str(tmp_path / "fixed")

    @workflow.step
    def stage_a():
        _touch_count(a_runs)
        return 5

    @workflow.step(max_retries=0)
    def flaky(x):
        if not os.path.exists(fixed):
            raise RuntimeError("transient outage")
        return x * 100

    dag = flaky.step(stage_a.step())
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="crash", storage=str(tmp_path))
    assert workflow.get_status("crash", storage=str(tmp_path)) == \
        workflow.RESUMABLE
    assert int(open(a_runs).read()) == 1

    open(fixed, "w").close()  # outage over
    out = workflow.resume("crash", storage=str(tmp_path))
    assert out == 500
    assert int(open(a_runs).read()) == 1, "finished step re-executed"
    assert workflow.get_status("crash", storage=str(tmp_path)) == \
        workflow.SUCCESS


def test_resume_of_finished_workflow_returns_output(cluster, tmp_path):
    @workflow.step
    def one():
        return 1

    workflow.run(one.step(), workflow_id="done", storage=str(tmp_path))
    assert workflow.resume("done", storage=str(tmp_path)) == 1


def test_step_ids_deterministic_and_input_sensitive(cluster):
    @workflow.step
    def f(x):
        return x

    assert f.step(1).step_id() == f.step(1).step_id()
    assert f.step(1).step_id() != f.step(2).step_id()


def test_kwargs_and_options(cluster, tmp_path):
    @workflow.step
    def scale(x, *, factor=1):
        return x * factor

    out = workflow.run(scale.options(name="scaled").step(3, factor=7),
                       workflow_id="kw", storage=str(tmp_path))
    assert out == 21


def test_fan_in_steps_nested_in_containers(cluster, tmp_path):
    """StepNodes nested inside list/dict args resolve to their results
    and hash structurally (stable ids across resumes)."""

    @workflow.step
    def const(x):
        return x

    @workflow.step
    def total(parts, named):
        return sum(parts) + named["extra"]

    dag = total.step([const.step(1), const.step(2), const.step(3)],
                     {"extra": const.step(10)})
    assert workflow.run(dag, workflow_id="fanin",
                        storage=str(tmp_path)) == 16
    # resume of the finished workflow is a pure storage read
    assert workflow.resume("fanin", storage=str(tmp_path)) == 16

    dag2 = total.step([const.step(1), const.step(2), const.step(3)],
                      {"extra": const.step(10)})
    assert dag.step_id() == dag2.step_id()


def test_step_identity_includes_function_body(cluster, tmp_path):
    """Two same-named steps with different bodies must not share
    persisted results (fn code is part of the step id)."""

    def make(ret):
        @workflow.step(name="load")
        def load():
            return ret

        return load

    a, b = make("A"), make("B")
    assert a.step().step_id() != b.step().step_id()
    assert workflow.run(a.step(), workflow_id="ida",
                        storage=str(tmp_path)) == "A"
    assert workflow.run(b.step(), workflow_id="ida",
                        storage=str(tmp_path)) == "B"  # no stale reuse


def test_step_timeout_option(cluster, tmp_path):
    import time as _t

    @workflow.step(timeout_s=1.0, max_retries=0)
    def slow():
        _t.sleep(30)
        return 1

    with pytest.raises(Exception):
        workflow.run(slow.step(), workflow_id="slowwf",
                     storage=str(tmp_path))
    assert workflow.get_status("slowwf", storage=str(tmp_path)) == \
        workflow.RESUMABLE
