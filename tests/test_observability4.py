"""Latency attribution plane (ISSUE 7): per-request serve waterfalls,
per-step train waterfalls, span sampling + head spill, the one-call
flight recorder, and the metric-catalog drift gate."""

import json
import os
import re
import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.utils.events import TaskEventLog

cloudpickle.register_pickle_by_value(sys.modules[__name__])

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# per-request serve.llm waterfall
# ---------------------------------------------------------------------------

def _tiny_engine(**overrides):
    import jax.numpy as jnp

    from ray_tpu.models import gpt2
    from ray_tpu.serve.llm import EngineConfig, LLMEngine

    cfg = gpt2.GPT2Config(
        vocab_size=64, n_layer=1, n_head=2, n_embd=32, block_size=64,
        vocab_pad_multiple=64, dtype=jnp.float32, remat=False)
    kw = dict(model="gpt2", model_config=cfg, block_size=8,
              num_blocks=64, max_model_len=64, max_batch_size=4,
              prefill_chunk_size=8, seed=0)
    kw.update(overrides)
    return LLMEngine(EngineConfig(**kw))


@pytest.fixture(scope="module")
def engine():
    return _tiny_engine()


def test_request_breakdown_sums_to_e2e(engine):
    from ray_tpu.serve.llm.config import SamplingParams

    t0 = time.monotonic()
    final = engine.generate(list(range(1, 11)),
                            SamplingParams(max_tokens=8), drive=True)
    wall = time.monotonic() - t0
    bd = final["breakdown"]
    assert final["finish_reason"] == "length"
    # the acceptance contract: phases sum to within 5% of e2e latency
    phase_sum = sum(v for k, v in bd.items() if k != "e2e")
    assert bd["e2e"] > 0
    assert abs(phase_sum - bd["e2e"]) <= 0.05 * bd["e2e"], bd
    # and the reported e2e is the request's real wall time
    assert abs(bd["e2e"] - wall) <= 0.05 * wall + 0.01, (bd, wall)
    # the work phases exist and dominate for a compute-bound request
    assert bd.get("prefill", 0) > 0 and bd.get("decode", 0) > 0, bd
    # cumulative per-phase totals surface through engine stats (the
    # llm_status() face of the same numbers)
    st = engine.stats()
    assert st["finished_requests"] >= 1
    assert st["phase_seconds"].get("decode", 0) > 0


def test_request_waterfall_child_spans_recorded(engine):
    from ray_tpu.serve.llm.config import SamplingParams
    from ray_tpu.util import tracing

    with tracing.span("obs4-root") as root:
        final = engine.generate([1, 2, 3, 4], SamplingParams(max_tokens=3),
                                drive=True)
    assert final["breakdown"]["e2e"] > 0
    spans = tracing._fallback_log.chrome_trace()
    req = [e for e in spans if e["name"] == "llm.request"
           and e.get("args", {}).get("trace_id") == root["trace_id"]]
    assert req, "llm.request span missing (or not under the root trace)"
    phases = [e for e in spans if e["name"].startswith("llm.request.")
              and e.get("args", {}).get("trace_id") == root["trace_id"]]
    names = {e["name"] for e in phases}
    assert {"llm.request.prefill", "llm.request.decode"} <= names, names
    # children are laid inside the parent's window, in waterfall order
    parent = req[-1]
    last_end = parent["ts"] - 50.0
    for e in sorted(phases, key=lambda e: e["ts"]):
        assert e["ts"] >= last_end - 50.0  # 50us float slack
        last_end = e["ts"] + e["dur"]
    assert last_end <= parent["ts"] + parent["dur"] + 1e3


def test_slo_metrics_exposed(engine):
    from ray_tpu.serve.llm.config import SamplingParams
    from ray_tpu.util.metrics import prometheus_text

    engine.generate([5, 6, 7], SamplingParams(max_tokens=4), drive=True)
    text = prometheus_text()
    assert 'serve_slo_ttft_ms_count{model="gpt2",phase="queue"}' in text
    assert 'serve_slo_ttft_ms_count{model="gpt2",phase="prefill"}' in text
    assert 'serve_slo_ttft_ms_count{model="gpt2",phase="total"}' in text
    assert "serve_slo_tpot_ms_count" in text


def test_breakdown_greedy_output_unchanged(engine):
    """Attribution must not perturb generation: same prompt, same
    greedy tokens as an engine without a single breakdown consumer."""
    from ray_tpu.serve.llm.config import SamplingParams

    a = engine.generate([9, 8, 7, 6], SamplingParams(max_tokens=6),
                        drive=True)
    b = _tiny_engine().generate([9, 8, 7, 6],
                                SamplingParams(max_tokens=6), drive=True)
    assert a["token_ids"] == b["token_ids"]


# ---------------------------------------------------------------------------
# per-step train waterfall
# ---------------------------------------------------------------------------

def test_train_waterfall_sums_to_step_time():
    import numpy as np
    import optax

    from ray_tpu.models.gpt2 import (
        GPT2Config, gpt2_loss, gpt2_partition_rules, init_gpt2)
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train import spmd
    from ray_tpu.train.spmd import (
        batch_shardings, init_sharded_state, make_train_step)
    import jax
    import jax.numpy as jnp

    cfg = GPT2Config.tiny()
    mesh = build_mesh(MeshSpec(data=-1))
    tx = optax.sgd(0.01)
    state = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh,
        gpt2_partition_rules())
    B = 2 * jax.device_count()
    toks = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (B, 129)).astype(np.int32)
    batch = {"tokens": jnp.asarray(toks[:, :-1]),
             "targets": jnp.asarray(toks[:, 1:])}
    batch = jax.device_put(batch, batch_shardings(mesh, batch))
    step = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), tx,
                           donate=False)

    spmd.enable_step_waterfall()
    try:
        with mesh:
            # two warmup steps: the first compiles for the init-time
            # state layout, the second for the steady-state layout the
            # jit output carries — the timed window must be compile-free
            state, m = step(state, batch)
            state, m = step(state, batch)
            spmd.waterfall.reset()
            t0 = time.perf_counter()
            for _ in range(5):
                with spmd.data_wait():
                    time.sleep(0.002)
                state, m = step(state, batch)
            dt = time.perf_counter() - t0
    finally:
        spmd.enable_step_waterfall(False)

    s = spmd.waterfall.summary()
    assert s["steps"] == 5
    # acceptance: attributed phases sum to within 5% of measured time
    assert abs(s["total_seconds"] - dt) <= 0.05 * dt, (s, dt)
    assert s["phases"].get("compute", 0) > 0
    assert s["phases"].get("data_wait", 0) >= 0.005
    assert "compile" not in s["phases"]  # warmed up before the window
    # the attribution table bench.py --trace prints: percents sum ~100
    pct = sum(s["percent"].values())
    assert 99.0 <= pct <= 101.0
    table = spmd.waterfall.table()
    assert "compute" in table and "%" in table


def test_train_waterfall_off_by_default():
    from ray_tpu.train import spmd

    assert spmd.waterfall.enabled is False
    before = spmd.waterfall.steps
    import jax.numpy as jnp
    import optax

    from ray_tpu.train.spmd import TrainState, make_train_step

    tx = optax.sgd(0.1)
    s0 = TrainState.create({"w": jnp.zeros(4)}, tx)
    step = make_train_step(
        lambda p, b: jnp.sum((p["w"] - b["x"]) ** 2), tx, donate=False)
    step(s0, {"x": jnp.ones(4)})
    assert spmd.waterfall.steps == before  # nothing accumulated


# ---------------------------------------------------------------------------
# span sampling + counters
# ---------------------------------------------------------------------------

def test_sampling_keeps_first_per_name_and_counts_drops():
    log = TaskEventLog(capacity=10_000)
    log.configure_sampling({"max_per_s": 1.0})
    pairs = [("alpha", "cat1"), ("beta", "cat1"), ("gamma", "cat2")]
    n_each = 50
    t = time.monotonic_ns()
    for i in range(n_each):
        for name, cat in pairs:
            log.record(name, cat, t, t + 1000)
    events = log.drain()
    kept, dropped = log.span_counts()
    # >= 1 span survived per (category, name) — the hard guarantee
    seen = {(e["cat"], e["name"]) for e in events}
    assert {(c, n) for n, c in pairs} <= seen
    # everything else was dropped AND counted (nothing silent)
    total = n_each * len(pairs)
    assert sum(kept.values()) == len(events)
    assert sum(kept.values()) + sum(dropped.values()) == total
    assert dropped.get("cat1", 0) > 0 and dropped.get("cat2", 0) > 0
    # counters reach the metrics registry via the flush-loop sync
    log.sync_metrics()
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    assert 'spans_dropped_total{category="cat1"}' in text
    assert 'spans_sampled_total{category="cat2"}' in text


def test_sampling_off_means_no_drops():
    log = TaskEventLog(capacity=100)
    t = time.monotonic_ns()
    for i in range(50):
        log.record(f"s{i}", "c", t, t + 10)
    kept, dropped = log.span_counts()
    assert sum(kept.values()) == 50 and not dropped
    # buffer overflow IS counted even without a sampling policy
    for i in range(100):
        log.record(f"o{i}", "c", t, t + 10)
    kept, dropped = log.span_counts()
    assert sum(dropped.values()) == 50 - len(log.drain()) + 100


def test_span_policy_rpc_auto_rate_limit():
    from ray_tpu.core.head import Head
    from ray_tpu.core.rpc import RpcClient

    head = Head(span_rate_limit=100.0).start()
    try:
        c = RpcClient.shared()
        assert c.call(head.address, "span_policy", {},
                      timeout=10)["policy"] is None
        # flood past the cap: the head starts handing out shares
        t = time.time() * 1e6
        spans = [{"name": f"s{i}", "cat": "task", "ph": "X", "ts": t,
                  "dur": 1.0, "proc": "w1"} for i in range(3000)]
        c.call(head.address, "dump_timeline", {"spans": spans},
               timeout=10)
        policy = c.call(head.address, "span_policy", {},
                        timeout=10)["policy"]
        assert policy is not None and policy["max_per_s"] <= 100.0
        # operator policy wins over automatic mode
        head.set_span_policy({"categories": {"task": 5.0}})
        policy = c.call(head.address, "span_policy", {},
                        timeout=10)["policy"]
        assert policy == {"categories": {"task": 5.0}}
    finally:
        head.stop()


# ---------------------------------------------------------------------------
# head spill round-trip
# ---------------------------------------------------------------------------

def test_head_spill_roundtrips_through_timeline(tmp_path):
    from ray_tpu.core.head import Head
    from ray_tpu.util import state

    head = Head(span_capacity=100,
                span_spill_dir=str(tmp_path / "spill")).start()
    try:
        t = time.time() * 1e6
        batches = [
            [{"name": f"span-{b}-{i}", "cat": "task", "ph": "X",
              "ts": t + b * 1000 + i, "dur": 5.0, "node": "n1",
              "proc": "w1", "tid": 1} for i in range(50)]
            for b in range(10)  # 500 spans vs a 100-span window
        ]
        from ray_tpu.core.rpc import RpcClient

        for batch in batches:
            RpcClient.shared().call(head.address, "dump_timeline",
                                    {"spans": batch}, timeout=10)
        tl = state.cluster_timeline(address=head.address)
        names = {e["name"] for e in tl if e.get("ph") == "X"}
        # the EARLIEST spans fell out of the memory window but came
        # back from the spill; the latest are still in memory
        assert "span-0-0" in names, "spilled span lost"
        assert "span-9-49" in names
        assert sum(1 for e in tl if e.get("ph") == "X") == 500
        assert head._span_spill.spilled_total >= 400
        # and the spill directory is real bounded JSONL
        files = os.listdir(tmp_path / "spill")
        assert any(f.endswith(".jsonl") for f in files)
    finally:
        head.stop()


# ---------------------------------------------------------------------------
# flight recorder on a live (then degraded) cluster
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster2():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 4, "resources": {"o4a": 2.0}})
    c.add_node(num_cpus=4, resources={"o4b": 2.0})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_debug_dump_collects_every_artifact(cluster2, tmp_path):
    from ray_tpu.util import state

    @ray_tpu.remote(num_cpus=0.1)
    def obs4_task():
        return ray_tpu.get_runtime_context().node_id.hex()

    ray_tpu.get([obs4_task.remote() for _ in range(3)], timeout=60)
    out = state.debug_dump(out_dir=str(tmp_path / "dump"), deadline_s=60)
    files = set(os.listdir(out))
    for expected in ("summary.json", "nodes.json", "actors.json",
                     "tasks.json", "objects.json",
                     "placement_groups.json", "memory.txt",
                     "metrics.prom", "timeline.json", "serve_status.json",
                     "logs"):
        assert expected in files, (expected, files)
    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    core = {"nodes", "actors", "tasks", "objects", "placement_groups",
            "memory", "metrics", "timeline", "serve_status"}
    assert core <= set(summary["artifacts"]), summary
    with open(os.path.join(out, "nodes.json")) as f:
        nodes = json.load(f)
    assert len(nodes) == 2
    # both nodes' logs were tailed
    assert len(os.listdir(os.path.join(out, "logs"))) == 2
    with open(os.path.join(out, "metrics.prom")) as f:
        assert 'node="' in f.read()
    with open(os.path.join(out, "timeline.json")) as f:
        assert isinstance(json.load(f), list)


def test_debug_dump_degraded_cluster_respects_deadline(cluster2,
                                                       tmp_path):
    """LAST test in the module: it stops a node. The dump must finish
    inside its deadline (plus write slack) and still produce the
    artifacts the surviving node can answer for."""
    from ray_tpu.util import state

    victim = cluster2.nodelets[-1]
    cluster2.remove_node(victim)
    deadline = 45.0
    t0 = time.monotonic()
    out = state.debug_dump(out_dir=str(tmp_path / "degraded"),
                           deadline_s=deadline)
    elapsed = time.monotonic() - t0
    assert elapsed < deadline + 10.0, elapsed
    files = set(os.listdir(out))
    assert {"summary.json", "nodes.json", "timeline.json"} <= files
    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert "nodes" in summary["artifacts"]


# ---------------------------------------------------------------------------
# drift gate: source == catalog == docs == dashboard
# ---------------------------------------------------------------------------

def _docs_metric_names() -> set[str]:
    """Metric names declared in OBSERVABILITY.md's catalog table (the
    first column's backticked tokens, tag annotations stripped)."""
    names: set[str] = set()
    with open(os.path.join(REPO, "OBSERVABILITY.md")) as f:
        for line in f:
            if not line.startswith("| `"):
                continue
            # split on table pipes only (tag values escape theirs: \|)
            first_col = re.split(r"(?<!\\)\|", line)[1]
            for tok in re.findall(r"`([^`]+)`", first_col):
                tok = tok.split("{", 1)[0].strip()
                if re.fullmatch(r"[a-z][a-z0-9_]+", tok):
                    names.add(tok)
    return names


def test_metric_catalog_matches_source():
    from ray_tpu.util.metrics_catalog import CATALOG, source_metrics

    src = source_metrics()
    cat = {m["name"]: m["type"] for m in CATALOG}
    assert set(src) == set(cat), (
        f"registered-but-uncataloged: {set(src) - set(cat)}; "
        f"cataloged-but-unregistered: {set(cat) - set(src)}")
    for name, mtype in src.items():
        assert cat[name] == mtype, (name, mtype, cat[name])


def test_metric_catalog_matches_docs():
    from ray_tpu.util.metrics_catalog import catalog_names

    docs = _docs_metric_names()
    cat = catalog_names()
    assert cat - docs == set(), f"undocumented metrics: {cat - docs}"
    assert docs - cat == set(), f"stale docs rows: {docs - cat}"


def test_dashboard_matches_catalog():
    from ray_tpu.devtools.grafana import dashboard_json
    from ray_tpu.util.metrics_catalog import catalog_names

    path = os.path.join(REPO, "dashboards", "ray_tpu.json")
    with open(path) as f:
        committed = f.read()
    assert committed == dashboard_json(), (
        "dashboards/ray_tpu.json is stale — regenerate with "
        "`python -m ray_tpu.devtools.grafana`")
    panels = {p["title"] for p in json.loads(committed)["panels"]
              if p["type"] == "timeseries"}
    assert panels == catalog_names()
