"""Native shm channel + Communicator tests (reference model:
python/ray/tests/test_channel.py — mutable-object channels)."""

import sys
import threading

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.experimental.channel import Channel, ChannelClosed, ShmCommunicator

cloudpickle.register_pickle_by_value(sys.modules[__name__])


def test_channel_roundtrip_bytes():
    ch = Channel(capacity=1 << 16)
    try:
        ch.put_bytes(b"hello")
        ch.put_bytes(b"world" * 100)
        assert ch.get_bytes(timeout=5) == b"hello"
        assert ch.get_bytes(timeout=5) == b"world" * 100
    finally:
        ch.destroy()


def test_channel_objects_and_wraparound():
    ch = Channel(capacity=1 << 12)  # small: forces ring wrap
    try:
        for i in range(200):
            ch.put({"i": i, "pad": b"x" * 100}, timeout=5)
            got = ch.get(timeout=5)
            assert got["i"] == i
    finally:
        ch.destroy()


def test_channel_backpressure_and_close():
    ch = Channel(capacity=1 << 12)
    try:
        with pytest.raises(TimeoutError):
            while True:
                ch.put_bytes(b"y" * 512, timeout=0.2)
        ch.close()
        with pytest.raises(ChannelClosed):
            ch.put_bytes(b"z")
        # drain what's there, then closed signal
        while True:
            try:
                ch.get_bytes(timeout=0.2)
            except ChannelClosed:
                break
    finally:
        ch.destroy()


def test_channel_threaded_producer_consumer():
    ch = Channel(capacity=1 << 14)
    N = 500
    out = []

    def producer():
        for i in range(N):
            ch.put_bytes(i.to_bytes(4, "little"), timeout=10)

    t = threading.Thread(target=producer)
    t.start()
    try:
        for _ in range(N):
            out.append(int.from_bytes(ch.get_bytes(timeout=10), "little"))
        t.join()
        assert out == list(range(N))
    finally:
        ch.destroy()


def test_channel_cross_process():
    """Driver <-> actor worker over the shm ring (bypasses RPC)."""
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        ch = Channel(capacity=1 << 16)

        @ray_tpu.remote
        class Producer:
            def produce(self, name, n):
                from ray_tpu.experimental.channel import Channel as Ch

                out = Ch(name=name, create=False)
                for i in range(n):
                    out.put({"seq": i, "data": np.arange(4) * i}, timeout=30)
                return "done"

        p = Producer.remote()
        ref = p.produce.remote(ch.name, 50)
        for i in range(50):
            msg = ch.get(timeout=30)
            assert msg["seq"] == i
            assert int(msg["data"][1]) == i
        assert ray_tpu.get(ref, timeout=60) == "done"
        ch.destroy()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_shm_communicator_allreduce_threads():
    comms = [ShmCommunicator("g1", 3, r) for r in range(3)]
    results = [None] * 3

    def run(r):
        results[r] = comms[r].allreduce(np.full(4, float(r + 1)))

    ts = [threading.Thread(target=run, args=(r,)) for r in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for r in results:
        np.testing.assert_array_equal(r, np.full(4, 6.0))
    comms[0].destroy()
