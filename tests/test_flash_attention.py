"""Flash-attention kernel vs the einsum reference (interpret mode on
CPU — SURVEY.md §4: pure-logic kernel tests without hardware)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import causal_attention_reference
from ray_tpu.ops.flash_attention import flash_attention


def _rand_qkv(key, B, T, H, D, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("T,block", [(256, 128), (128, 128), (256, 64)])
def test_forward_matches_reference(T, block):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 2, T, 2, 64)
    out = flash_attention(q, k, v, causal=True, block_q=block, block_k=block,
                          interpret=True)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_forward_noncausal():
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), 1, 128, 2, 32)
    out = flash_attention(q, k, v, causal=False, block_q=64, block_k=64,
                          interpret=True)
    # non-causal reference
    scale = 1.0 / (32 ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    probs = jax.nn.softmax(logits, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gradients_match_reference():
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), 1, 128, 2, 32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                            interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = causal_attention_reference(q, k, v)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4, err_msg=f"d{name}")


def test_bfloat16_inputs():
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), 1, 128, 2, 64, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = causal_attention_reference(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               atol=3e-2, rtol=3e-2)


def test_indivisible_seq_raises():
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), 1, 96, 1, 32)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
