"""Log plane (ISSUE 13): structured JSONL records with trace/task
attribution, worker stdout capture + driver mirroring, the
nodelet/head `log_query`/`cluster_logs` query path, the `ray_tpu logs`
CLI, the watchtower error-rate rule with attached log context, and the
debug-dump incident-logs artifact."""

import io
import json
import logging
import os
import sys
import threading
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.utils import logging as slog

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------------------
# units: sink, handler, capture, query (no cluster)
# ---------------------------------------------------------------------------

def test_sink_rotation_stays_under_budget(tmp_path):
    path = str(tmp_path / "unit.jsonl")
    budget = 64 * 1024
    sink = slog.LogSink(path, max_bytes=budget)
    for i in range(4000):
        sink.write({"ts": float(i), "level": "info",
                    "msg": "x" * 64, "i": i})
    assert sink.written == 4000 and sink.dropped == 0
    total = sum(os.path.getsize(os.path.join(tmp_path, f))
                for f in os.listdir(tmp_path))
    assert total <= budget + 4096, total  # two-file rotation bound
    assert os.path.exists(path + ".1")  # the rotated half exists
    # the current file still parses, newest records last
    with open(path) as f:
        last = json.loads(f.readlines()[-1])
    assert last["i"] == 3999


def test_handler_emits_schema_records(tmp_path):
    path = str(tmp_path / "h.jsonl")
    handler = slog.StructuredLogHandler(
        slog.LogSink(path), node="n1", proc="p1", role="worker")
    logger = logging.getLogger("logplane.unit")
    logger.addHandler(handler)
    logger.setLevel(logging.DEBUG)
    logger.propagate = False
    try:
        logger.error("boom %d", 7)
        logger.info("fine")
    finally:
        logger.removeHandler(handler)
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert len(recs) == 2
    err = recs[0]
    assert err["level"] == "error" and err["msg"] == "boom 7"
    assert err["logger"] == "logplane.unit" and err["source"] == "log"
    assert err["node"] == "n1" and err["proc"] == "p1"
    assert err["role"] == "worker" and err["pid"] == os.getpid()
    # epoch-anchored ts: comparable with wall clock (PR 3 contract)
    assert abs(err["ts"] - time.time()) < 60.0
    assert recs[1]["level"] == "info"


def test_stream_capture_lines_levels_and_mirror(tmp_path):
    sink = slog.LogSink(str(tmp_path / "cap.jsonl"))
    inner = io.StringIO()
    mirrored = []
    cap = slog.StdStreamCapture(
        inner, "stderr", sink, {"node": "n", "proc": "p",
                                "role": "worker", "pid": 1},
        mirror_fn=lambda line, src: mirrored.append((line, src)))
    print("first line", file=cap)
    cap.write("partial ")
    cap.write("then complete\nand more\n")
    # passthrough preserved byte-for-byte
    assert inner.getvalue() == ("first line\npartial then complete\n"
                                "and more\n")
    with open(str(tmp_path / "cap.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    assert [r["msg"] for r in recs] == ["first line",
                                       "partial then complete",
                                       "and more"]
    assert all(r["source"] == "stderr" and r["level"] == "warning"
               for r in recs)
    assert [m[0] for m in mirrored] == [r["msg"] for r in recs]


def test_stream_capture_reentry_guard(tmp_path):
    sink = slog.LogSink(str(tmp_path / "re.jsonl"))
    inner = io.StringIO()
    cap = slog.StdStreamCapture(inner, "stdout", sink,
                                {"node": "n", "proc": "p",
                                 "role": "worker", "pid": 1})
    # a mirror that itself prints (a failing send logging its failure)
    # must pass through without recursing into a second emit
    cap.mirror_fn = lambda line, src: cap.write("side effect\n")
    print("real line", file=cap)
    with open(str(tmp_path / "re.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    assert [r["msg"] for r in recs] == ["real line"]
    assert "side effect" in inner.getvalue()  # passthrough still ran


def test_stream_capture_armed_overhead_under_1pct(tmp_path):
    """The PR 12 overhead pattern: the capture meters its own CPU; a
    busy loop that prints at a realistic cadence must spend <1% of its
    thread time inside the structured-emit path."""
    sink = slog.LogSink(str(tmp_path / "ov.jsonl"))
    inner = io.StringIO()
    cap = slog.StdStreamCapture(inner, "stdout", sink,
                                {"node": "n", "proc": "p",
                                 "role": "worker", "pid": 1})
    window = 0.5
    x = 0
    n_prints = 0
    cpu0 = time.thread_time()
    t0 = time.monotonic()
    next_print = t0
    while time.monotonic() - t0 < window:
        x += sum(range(256))
        now = time.monotonic()
        if now >= next_print:
            print(f"progress {x}", file=cap)
            n_prints += 1
            next_print = now + 0.02
    busy_cpu = time.thread_time() - cpu0
    assert n_prints >= 5
    assert cap.cpu_seconds < 0.01 * busy_cpu, (
        f"capture burned {cap.cpu_seconds:.5f}s of a {busy_cpu:.3f}s "
        f"busy window across {n_prints} prints")


def _write_records(sink, base_ts):
    rows = [
        {"ts": base_ts + 1, "level": "info", "msg": "alpha starting",
         "logger": "app", "node": "nodeaa", "task": "t1",
         "trace_id": "traceX", "proc": "w1", "source": "log"},
        {"ts": base_ts + 2, "level": "error", "msg": "alpha failed",
         "logger": "app", "node": "nodeaa", "task": "t1",
         "trace_id": "traceX", "proc": "w1", "source": "log"},
        {"ts": base_ts + 3, "level": "warning", "msg": "beta slow",
         "logger": "other", "node": "nodeaa", "task": "t2",
         "trace_id": "traceY", "proc": "w2", "source": "stdout"},
    ]
    for r in rows:
        sink.write(r)
    return rows


def test_query_log_dir_filters_and_follow(tmp_path):
    d = str(tmp_path)
    sink = slog.LogSink(os.path.join(d, "worker-w1.jsonl"))
    base = time.time()
    _write_records(sink, base)
    # level is a minimum severity
    r = slog.query_log_dir(d, level="warning")
    assert [x["msg"] for x in r["records"]] == ["alpha failed",
                                               "beta slow"]
    # grep over msg, trace/task/proc exact, time window
    assert [x["msg"] for x in
            slog.query_log_dir(d, grep="alph")["records"]] == \
        ["alpha starting", "alpha failed"]
    assert all(x["task"] == "t1" for x in
               slog.query_log_dir(d, task="t1")["records"])
    assert [x["msg"] for x in
            slog.query_log_dir(d, trace_id="traceY")["records"]] == \
        ["beta slow"]
    assert [x["proc"] for x in
            slog.query_log_dir(d, proc="w2")["records"]] == ["w2"]
    assert [x["msg"] for x in
            slog.query_log_dir(d, since=base + 2.5)["records"]] == \
        ["beta slow"]
    # bounded reply: limit keeps the LAST records by ts + truncated flag
    r = slog.query_log_dir(d, limit=1)
    assert r["truncated"] and [x["msg"] for x in r["records"]] == \
        ["beta slow"]
    # node filter drops foreign-origin records (shared-dir clusters)
    assert slog.query_log_dir(d, node="nodebb")["records"] == []
    # follow: offsets make the next query incremental
    r = slog.query_log_dir(d)
    assert len(r["records"]) == 3
    sink.write({"ts": base + 9, "level": "info", "msg": "new one",
                "node": "nodeaa", "source": "log"})
    r2 = slog.query_log_dir(d, offsets=r["offsets"])
    assert [x["msg"] for x in r2["records"]] == ["new one"]
    # nothing new -> empty, offsets stable
    r3 = slog.query_log_dir(d, offsets=r2["offsets"])
    assert r3["records"] == [] and r3["offsets"] == r2["offsets"]


def test_stream_capture_concurrent_threads_lose_nothing(tmp_path):
    """Line assembly is per-thread: N exec threads printing through
    the ONE worker capture interleave at line granularity — every line
    lands exactly once (a shared buffer would drop or merge
    concurrently-appended partials)."""
    sink = slog.LogSink(str(tmp_path / "mt.jsonl"))
    cap = slog.StdStreamCapture(io.StringIO(), "stdout", sink,
                                {"node": "n", "proc": "p",
                                 "role": "worker", "pid": 1})

    def chatter(tid):
        for i in range(200):
            # two writes per line forces a cross-call partial buffer
            cap.write(f"thread{tid} ")
            cap.write(f"line{i}\n")

    threads = [threading.Thread(target=chatter, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with open(str(tmp_path / "mt.jsonl")) as f:
        msgs = [json.loads(line)["msg"] for line in f]
    assert sorted(msgs) == sorted(
        f"thread{t} line{i}" for t in range(4) for i in range(200))


def test_query_follow_survives_rotation_without_duplicates(tmp_path):
    """A rotation between two follow polls carries the cursor over to
    the `.1` half: the follower sees every record exactly once."""
    d = str(tmp_path)
    sink = slog.LogSink(os.path.join(d, "worker-w1.jsonl"),
                        max_bytes=8 * 1024)
    seen: list[int] = []
    offsets = None
    i = 0
    for _ in range(6):
        for _ in range(20):  # ~100B/record: rotation every ~2 rounds
            sink.write({"ts": float(i), "level": "info", "i": i,
                        "msg": f"record {i:04d} " + "x" * 64,
                        "node": "nodeaa", "source": "log"})
            i += 1
        r = slog.query_log_dir(d, offsets=offsets, limit=5000)
        seen.extend(rec["i"] for rec in r["records"])
        offsets = r["offsets"]
    assert os.path.exists(os.path.join(d, "worker-w1.jsonl.1"))
    assert seen == list(range(i)), (len(seen), i)


def test_query_follow_rotation_gap_no_current_file(tmp_path):
    """A poll landing in the rotation gap (current file replaced, next
    write not yet landed) still carries the cursor to the `.1` half —
    no re-delivery of the rotated-out records."""
    d = str(tmp_path)
    path = os.path.join(d, "worker-w1.jsonl")
    sink = slog.LogSink(path, max_bytes=1 << 20)
    for i in range(10):
        sink.write({"ts": float(i), "level": "info", "i": i,
                    "msg": f"r{i}", "node": "nodeaa", "source": "log"})
    r = slog.query_log_dir(d)
    assert len(r["records"]) == 10
    # rotation between polls; nothing has recreated the current file
    sink._close_fh_locked()
    os.replace(path, path + ".1")
    r2 = slog.query_log_dir(d, offsets=r["offsets"])
    assert r2["records"] == [], [x["i"] for x in r2["records"]]
    # the next write recreates the current file; only IT is new
    sink.write({"ts": 99.0, "level": "info", "i": 99, "msg": "new",
                "node": "nodeaa", "source": "log"})
    r3 = slog.query_log_dir(d, offsets=r2["offsets"])
    assert [x["i"] for x in r3["records"]] == [99]


def test_query_follow_rotation_outgrown_current_file(tmp_path):
    """Rotation is detected by inode IDENTITY, not size: if the
    recreated current file grows past the stale cursor before the next
    poll (an error burst — exactly when someone is tailing), the
    cursor still carries to the `.1` half and nothing is skipped or
    re-shown."""
    d = str(tmp_path)
    path = os.path.join(d, "worker-w1.jsonl")
    sink = slog.LogSink(path, max_bytes=1 << 20)

    def w(i, pad=16):
        sink.write({"ts": float(i), "level": "info", "i": i,
                    "msg": "m" * pad, "node": "nodeaa",
                    "source": "log"})

    for i in range(5):
        w(i)
    r = slog.query_log_dir(d)
    assert len(r["records"]) == 5
    for i in range(5, 8):
        w(i)  # unread tail about to rotate away
    sink._close_fh_locked()
    os.replace(path, path + ".1")
    sink._cur_bytes = 0
    for i in range(8, 28):
        w(i, pad=64)  # burst: the new file outgrows the stale cursor
    assert os.path.getsize(path) > r["offsets"]["worker-w1.jsonl"][1]
    r2 = slog.query_log_dir(d, offsets=r["offsets"])
    assert [x["i"] for x in r2["records"]] == list(range(5, 28))


# ---------------------------------------------------------------------------
# watchtower: the error-rate-spike rule + context attachment (synthetic)
# ---------------------------------------------------------------------------

def test_log_error_spike_rule_fires_with_context_and_resolves():
    from ray_tpu.util.watchtower import Watchtower, default_rules

    rules = {r.name: r for r in default_rules()}
    rule = rules["log-error-spike"]
    assert rule.metric == "log_records_total"
    assert rule.labels == {"level": "error"}
    cur = {"v": 0.0}
    ctx_calls = []

    def scrape():
        return (f'log_records_total{{level="error",proc="w1"}} '
                f'{cur["v"]}\n')

    def log_ctx(n):
        ctx_calls.append(n)
        return [{"level": "error", "msg": f"ctx line {i}"}
                for i in range(n + 7)]

    wt = Watchtower(scrape, period_s=0, rules=[rule],
                    log_context_fn=log_ctx)
    t = 1000.0
    for _ in range(4):
        wt.sample_once(now=t)
        t += 5.0
    assert wt.alerts_dict()["alerts"] == []
    fired = None
    for _ in range(20):  # burst: ~12 errors/s sustained
        cur["v"] += 60.0
        wt.sample_once(now=t)
        t += 5.0
        firing = [a for a in wt.alerts_dict()["alerts"]
                  if a["state"] == "firing"]
        if firing:
            fired = firing[0]
            break
    assert fired is not None, wt.alerts_dict()
    assert fired["rule"] == "log-error-spike"
    # the firing transition fetched and attached BOUNDED log context
    assert ctx_calls == [20]
    assert len(fired["context"]) == 20
    assert fired["context"][0]["level"] == "error"
    # burst over: the windowed rate decays and the alert resolves
    for _ in range(20):
        wt.sample_once(now=t)
        t += 5.0
        if not wt.alerts_dict()["alerts"]:
            break
    assert wt.alerts_dict()["alerts"] == []


# ---------------------------------------------------------------------------
# CLI follow: terminates cleanly when the head goes away
# ---------------------------------------------------------------------------

def test_follow_terminates_cleanly_on_head_shutdown():
    from ray_tpu.core.head import Head
    from ray_tpu.scripts.cli import main as cli_main

    head = Head(watchtower_period_s=0).start()
    rc = {}

    def run():
        rc["v"] = cli_main(["logs", "--address", head.address,
                            "--follow", "--poll", "0.2",
                            "--rpc-timeout", "2", "--tail", "5"])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(1.0)  # at least one poll round against the live head
    assert t.is_alive()
    head.stop()
    # the follow rides out up to 3 consecutive missed polls (a busy
    # head mid-incident must not kill the tail) at ~(rpc_timeout+5)s
    # each before concluding the head is gone
    t.join(timeout=45)
    assert not t.is_alive(), "--follow hung after head shutdown"
    assert rc.get("v") == 0, rc


# ---------------------------------------------------------------------------
# live 2-node cluster: THE correlation gate + CLI + dump + degraded
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster2():
    from ray_tpu.cluster_utils import Cluster

    os.environ["RAY_TPU_LOG_TO_DRIVER"] = "1"
    # the error-burst test drives the head watchtower's sample_once
    # manually with deterministic timestamps; its wall-clock loop must
    # not interleave real-now samples into the same history
    os.environ["RAY_TPU_WATCHTOWER"] = "0"
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 4, "resources": {"lpa": 2.0}})
    c.add_node(num_cpus=4, resources={"lpb": 2.0})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    os.environ.pop("RAY_TPU_LOG_TO_DRIVER", None)
    os.environ.pop("RAY_TPU_WATCHTOWER", None)


@ray_tpu.remote(num_cpus=0.1)
def lp_task():
    print("hello from lp_task stdout")
    logging.getLogger("lp.app").error("lp synthetic failure")
    return ray_tpu.get_runtime_context().get_task_id()


@ray_tpu.remote(num_cpus=0.1)
def lp_error_burst(n):
    log = logging.getLogger("lp.burst")
    for i in range(n):
        log.error("burst error %d", i)
    return n


def _query(retries=20, **kw):
    """cluster_logs with a short settle loop (worker sink writes are
    synchronous, but the records must exist before the query)."""
    from ray_tpu.util import state

    for _ in range(retries):
        r = state.cluster_logs(**kw)
        if r["records"]:
            return r
        time.sleep(0.25)
    return r


def test_log_correlation_e2e(cluster2):
    """THE acceptance gate: a task that both print()s and logs an
    error has BOTH lines retrievable by task id and by trace id,
    tagged with the same trace_id as the task's span on the merged
    timeline; the driver mirror carries the (task, node) prefix."""
    from ray_tpu.core import api as _api
    from ray_tpu.util import state, tracing

    with tracing.span("lp-e2e") as tr:
        task_id = ray_tpu.get(
            lp_task.options(resources={"lpa": 0.5}).remote(),
            timeout=60)
    trace_id = tr["trace_id"]

    r = _query(task=task_id)
    by_source = {rec["source"]: rec for rec in r["records"]}
    assert set(by_source) == {"stdout", "log"}, r["records"]
    assert by_source["stdout"]["msg"] == "hello from lp_task stdout"
    assert by_source["log"]["msg"] == "lp synthetic failure"
    assert by_source["log"]["level"] == "error"
    assert by_source["log"]["logger"] == "lp.app"
    # both lines carry the submitting span's trace context
    assert all(rec["trace_id"] == trace_id for rec in r["records"])
    assert all(rec["task"] == task_id for rec in r["records"])
    assert all(rec.get("task_name") == "lp_task"
               for rec in r["records"])

    # the same two lines come back by trace id
    r2 = _query(trace_id=trace_id)
    assert {rec["source"] for rec in r2["records"]} == {"stdout", "log"}

    # ...and the trace_id matches the task's span on the merged
    # timeline (worker span flush is ~1s periodic)
    span = None
    for _ in range(30):
        tl = state.cluster_timeline()
        spans = [e for e in tl if e.get("ph") == "X"
                 and e.get("name") == "lp_task"
                 and e.get("args", {}).get("trace_id") == trace_id]
        if spans:
            span = spans[0]
            break
        time.sleep(0.5)
    assert span is not None, "task span with the log lines' trace_id"

    # driver mirroring: the print arrived with (task, node) identity
    rt = _api._runtime
    mirrored = [m for m in rt._mirrored_logs
                if m.get("task_id") == task_id]
    assert mirrored, list(rt._mirrored_logs)
    assert mirrored[0]["task"] == "lp_task"
    assert mirrored[0]["line"] == "hello from lp_task stdout"
    assert mirrored[0]["node"]  # node identity rides the mirror
    assert mirrored[0]["pid"]

    # the log counters reached the cluster metrics page
    text = state.cluster_metrics()
    assert 'log_records_total{level="error"' in text
    assert "log_bytes_total" in text


def test_logs_cli_task_and_trace_filters(cluster2, capsys):
    from ray_tpu.scripts.cli import main as cli_main
    from ray_tpu.util import tracing

    with tracing.span("lp-cli") as tr:
        task_id = ray_tpu.get(
            lp_task.options(resources={"lpb": 0.5}).remote(),
            timeout=60)
    _query(task=task_id)  # settle
    rc = cli_main(["logs", "--address", cluster2.address,
                   "--task", task_id])
    assert rc == 0
    out = capsys.readouterr().out
    assert "hello from lp_task stdout" in out
    assert "lp synthetic failure" in out
    assert "[lp_task]" in out  # the formatted line names the task
    rc = cli_main(["logs", "--address", cluster2.address,
                   "--trace-id", tr["trace_id"], "--json"])
    assert rc == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.splitlines() if ln.strip()]
    assert {rec["source"] for rec in lines} >= {"stdout", "log"}
    # legacy raw-file mode still lists a node's files
    nid = cluster2.nodelets[0].node_id.hex()[:12]
    rc = cli_main(["logs", nid, "--address", cluster2.address])
    assert rc == 0
    assert json.loads(capsys.readouterr().out), "raw file listing"


def test_error_burst_fires_live_watchtower_with_context(cluster2):
    """Synthetic error burst on the LIVE cluster: real scrape, real
    log-context fan-out; sample ticks driven with deterministic
    timestamps (the watchtower loop is disabled in this fixture)."""
    wt = cluster2.head.watchtower
    t = 50_000.0
    for _ in range(3):
        wt.sample_once(now=t)
        t += 5.0
    fired = None
    for _ in range(12):
        ray_tpu.get(lp_error_burst.options(
            resources={"lpa": 0.2}).remote(40), timeout=60)
        wt.sample_once(now=t)
        t += 5.0
        firing = [a for a in wt.alerts_dict()["alerts"]
                  if a["rule"] == "log-error-spike"
                  and a["state"] == "firing"]
        if firing:
            fired = firing[0]
            break
    assert fired is not None, wt.alerts_dict()
    # the attached context is real error lines from the cluster
    assert fired.get("context"), fired
    assert any("burst error" in rec.get("msg", "")
               for rec in fired["context"])
    # burst over: the rate window drains and the alert resolves
    resolved = False
    for _ in range(20):
        wt.sample_once(now=t)
        t += 5.0
        if not [a for a in wt.alerts_dict()["alerts"]
                if a["rule"] == "log-error-spike"]:
            resolved = True
            break
    assert resolved, wt.alerts_dict()


def test_debug_dump_includes_incident_logs(cluster2, tmp_path):
    from ray_tpu.util import state

    ray_tpu.get(lp_task.remote(), timeout=60)
    out = state.debug_dump(out_dir=str(tmp_path / "dump"),
                           deadline_s=45)
    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert "cluster_logs" in summary["artifacts"], summary
    with open(os.path.join(out, "logs.jsonl")) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    assert recs, "incident-window structured logs captured"
    assert any(rec["level"] == "error" for rec in recs)
    # the raw per-node tails are still there alongside
    assert os.path.isdir(os.path.join(out, "logs"))


def test_cluster_logs_rpc_defaults_omitted_limit(cluster2):
    """The head RPC is public: a caller omitting "limit" (or sending
    None) gets the documented 1000-record default, not a per-node
    TypeError dressed up as every node timing out."""
    from ray_tpu.core import api as _api

    rt = _api._runtime
    r = rt.client.call(rt.head_address, "cluster_logs", {}, timeout=15)
    assert r["records"], r
    assert not r["errors"], r["errors"]
    r2 = rt.client.call(rt.head_address, "cluster_logs",
                        {"limit": None}, timeout=15)
    assert r2["records"] and not r2["errors"], r2["errors"]


def test_degraded_cluster_log_query_lands_in_errors(cluster2):
    """LAST test in the module: it stops a node. The stopped node
    costs only the shared per-query budget and lands in `errors`;
    the gather still returns the surviving node's records."""
    from ray_tpu.util import state

    victim = cluster2.nodelets[-1]
    vid = victim.node_id.hex()[:12]
    cluster2.remove_node(victim)
    t0 = time.monotonic()
    r = state.cluster_logs(timeout=4, limit=100)
    elapsed = time.monotonic() - t0
    assert elapsed < 15.0, elapsed
    assert r["records"], "surviving node still answers"
    assert all(rec.get("node") != vid for rec in r["records"])
    # immediately after the stop the head still lists the node alive,
    # so it must appear as an errors entry; once aged out of the view
    # it is excluded entirely — both are correct degraded shapes
    assert vid in r["errors"] or vid not in r["offsets"], r["errors"]
