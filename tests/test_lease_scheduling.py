"""Worker-lease reuse + arg-locality scheduling (VERDICT r2 items 6/8).

Reference parity: lease reuse / pipelined pushes
(src/ray/core_worker/transport/normal_task_submitter.cc:137 OnWorkerIdle)
and locality-aware lessor choice (core_worker/lease_policy.h:58).
"""

import os
import sys
import time

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture
def ray_boot():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_lease_reuse_same_worker(ray_boot):
    """Repeated same-shape tasks run on ONE reused leased worker — no
    per-task scheduling hop, no process churn."""

    @ray_tpu.remote(num_cpus=1)
    def whoami():
        return os.getpid()

    pids = {ray_tpu.get(whoami.remote()) for _ in range(20)}
    assert len(pids) == 1, f"expected one leased worker, saw {pids}"


def test_lease_scales_out_under_backlog(ray_boot):
    """A burst larger than one worker's pipeline leases more workers."""

    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(0.3)
        return os.getpid()

    pids = set(ray_tpu.get([slow.remote() for _ in range(8)], timeout=60))
    assert len(pids) >= 2, f"burst should fan out, saw {pids}"


def test_lease_returned_after_idle(ray_boot):
    """Idle leases are handed back to the nodelet (resources released)."""

    @ray_tpu.remote(num_cpus=1)
    def nop():
        return 1

    assert ray_tpu.get(nop.remote()) == 1
    from ray_tpu.core.api import _global_runtime

    rt = _global_runtime()
    nodelet = rt._booted[1]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with nodelet._lock:
            if not nodelet._leases:
                break
        time.sleep(0.2)
    with nodelet._lock:
        assert not nodelet._leases, "lease not returned after idle"
    deadline = time.monotonic() + 5  # heartbeat-cached view refresh
    while time.monotonic() < deadline:
        if ray_tpu.available_resources().get("CPU") == 4.0:
            break
        time.sleep(0.2)
    assert ray_tpu.available_resources().get("CPU") == 4.0


def test_leased_worker_death_is_retried(ray_boot, tmp_path):
    """A leased worker dying mid-task surfaces as a retryable failure:
    the nodelet's lease_broken notification makes the owner resubmit."""
    flag = str(tmp_path / "died_once")

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def die_once():
        if not os.path.exists(flag):
            open(flag, "w").close()
            os._exit(1)
        return "recovered"

    assert ray_tpu.get(die_once.remote(), timeout=60) == "recovered"


def test_leased_worker_death_no_retries_errors(ray_boot):
    @ray_tpu.remote(num_cpus=1, max_retries=0)
    def die():
        os._exit(1)

    from ray_tpu.core.exceptions import RayTpuError

    with pytest.raises(RayTpuError):
        ray_tpu.get(die.remote(), timeout=60)


# ---------------------------------------------------------------------------
# arg locality
# ---------------------------------------------------------------------------

def test_arg_locality_prefers_data_node():
    """A task consuming a large remote-stored arg runs on the node that
    holds the bytes (lease_policy.h:58 semantics)."""
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2, resources={"data_node": 1.0})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        @ray_tpu.remote(resources={"data_node": 0.1}, num_cpus=0.1)
        def produce():
            return np.zeros(1 << 20, np.uint8)  # 1MB -> store-resident

        @ray_tpu.remote(num_cpus=0.1)
        def consume(a):
            import ray_tpu as rt

            return (int(a.nbytes),
                    rt.get_runtime_context().node_id.hex())

        ref = produce.remote()
        ray_tpu.get(ref)  # materialized on the data node
        data_node = [n for n in ray_tpu.nodes()
                     if "data_node" in n["Resources"]][0]["NodeID"]
        nbytes, ran_on = ray_tpu.get(consume.remote(ref), timeout=60)
        assert nbytes == 1 << 20
        assert ran_on == data_node, "task did not follow its large arg"
    finally:
        ray_tpu.shutdown()
        c.shutdown()

# ---------------------------------------------------------------------------
# lease TTL expiry (r3 ADVICE: expiry must notify the owner)
# ---------------------------------------------------------------------------

def test_lease_expiry_then_worker_death_recovers(ray_boot):
    """The r3 ADVICE hang: TTL expiry silently cleared w.lease_id, so a
    subsequent worker death never sent lease_broken to the owner and its
    enqueue-acked in-flight push hung forever. Now expiry itself sends
    lease_broken (and the worker rejects stale pushes), so the owner
    resubmits and the task completes."""

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def slow():
        time.sleep(8)
        return "done"

    ref = slow.remote()
    from ray_tpu.core.api import _global_runtime

    rt = _global_runtime()
    nodelet = rt._booted[1]
    # wait for the lease grant, then force-expire it mid-flight
    pid = None
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        with nodelet._lock:
            if nodelet._leases:
                for le in nodelet._leases.values():
                    le.expiry = 0.0
                    pid = le.worker.proc.pid
                break
        time.sleep(0.05)
    assert pid is not None, "no lease ever granted"
    # wait for the reap loop to expire it (sends lease_broken now)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with nodelet._lock:
            if not nodelet._leases:
                break
        time.sleep(0.05)
    # kill the worker: pre-fix, no lease_broken was ever sent and this hung
    import signal

    try:
        os.kill(pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    assert ray_tpu.get(ref, timeout=60) == "done"
