"""Multi-process × dcn-mesh end to end (VERDICT r3 item 6).

The 8→256-chip shape in miniature: 2 jax PROCESSES (jax.distributed
rendezvous through the WorkerGroup) × 4 virtual devices each, a hybrid
dcn×(data,fsdp,tensor) mesh whose dcn axis crosses the process
boundary, slice-gang placement from TPU labels — with loss parity
against the same global computation in ONE process (SURVEY §7 stage 7).
"""

import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu
import conftest
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import tpu as tpu_mod
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig


cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _slice_labels(slice_name, worker_id, pod_type="v4-8"):
    return {
        tpu_mod.SLICE_LABEL: slice_name,
        tpu_mod.WORKER_ID_LABEL: str(worker_id),
        tpu_mod.POD_TYPE_LABEL: pod_type,
    }


@pytest.fixture(scope="module")
def slice_cluster():
    """One fake slice x two hosts (TPU:4 each) + a CPU head."""
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    for wid in (0, 1):
        c.add_node(num_cpus=4, num_tpus=4,
                   labels=_slice_labels("slice-dcn", wid))
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _dcn_loop(config):
    """One hybrid-dcn train step; reports the loss and the world facts
    the assertions need."""
    import os

    import jax

    import ray_tpu.train as train
    import __graft_entry__ as graft

    ctx = train.get_context()
    expect_procs = config["expect_procs"]
    assert jax.process_count() == expect_procs, jax.process_count()
    assert len(jax.devices()) == 8  # global across both processes
    loss = graft._hybrid_dcn_step_loss()
    train.report({
        "loss": loss,
        "rank": ctx.get_world_rank(),
        "n_procs": jax.process_count(),
        "hostnames": len(os.environ.get("TPU_WORKER_HOSTNAMES",
                                        "").split(",")),
    })


@pytest.mark.skipif(not conftest.jax_supports_multiprocess_cpu(),
                    reason="multiprocess SPMD unimplemented on "
                           "this jaxlib's CPU backend")
def test_two_process_dcn_matches_single_process(slice_cluster, tmp_path):
    losses = {}
    for n_workers, devs in ((2, 4), (1, 8)):
        trainer = JaxTrainer(
            _dcn_loop,
            train_loop_config={"expect_procs": n_workers},
            scaling_config=ScalingConfig(
                num_workers=n_workers,
                use_tpu=(n_workers == 2),
                num_cpu_devices_per_worker=devs,
                resources_per_worker={"CPU": 1.0, "TPU": 4.0}
                if n_workers == 2 else {"CPU": 1.0},
                placement_strategy="STRICT_PACK"),
            run_config=RunConfig(name=f"dcn{n_workers}",
                                 storage_path=str(tmp_path)),
        )
        result = trainer.fit()
        m = result.metrics_history[-1]
        losses[n_workers] = m["loss"]
        if n_workers == 2:
            # slice-gang placement engaged: the slice topology env was
            # derived from the labels (one hostname per gang member)
            assert m["hostnames"] == 2
    assert np.isfinite(losses[1]) and losses[1] > 0
    np.testing.assert_allclose(losses[1], losses[2], rtol=1e-4, atol=1e-5)
