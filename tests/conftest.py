"""Test fixtures.

Tests run on a virtual 8-device CPU mesh (SURVEY.md §4: the reference
tests multi-node logic on one box with faked resources; we test
multi-chip SPMD logic with faked devices). The axon TPU plugin in this
image force-registers itself, so we must both set XLA_FLAGS before
backend init and override jax_platforms via config (the env var alone is
not enough).
"""

import os

# Must happen before the first jax backend initialization.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def jax_supports_multiprocess_cpu() -> bool:
    """jaxlib <0.5 CPU backend: "Multiprocess computations aren't
    implemented on the CPU backend" — the gang forms, the first
    collective aborts. Tests that need a multi-process SPMD world
    gate on this instead of failing on those builds."""
    return tuple(int(x) for x in jax.__version__.split(".")[:2]) >= (0, 5)


import pytest  # noqa: E402


@pytest.fixture
def ray_local():
    """In-process local-mode runtime (reference fixture: ray_start_regular,
    python/ray/tests/conftest.py:532)."""
    import ray_tpu

    ray_tpu.init(local_mode=True, num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def cpu_mesh8():
    """8-device mesh: data=2, fsdp=2, tensor=2."""
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
