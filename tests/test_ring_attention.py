"""Ring / Ulysses attention vs the dense reference on a seq-sharded
virtual mesh (SURVEY.md §7.8: CP/long-context is a first-class build
target; the reference has no equivalent — parity is against math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import causal_attention_reference
from ray_tpu.parallel import ops
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.ring_attention import ring_attention, ulysses_attention


@pytest.fixture(scope="module")
def seq_mesh():
    return build_mesh(MeshSpec(data=1, seq=8, tensor=1))


def _qkv(key, B=2, T=64, H=4, D=16):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, T, H, D), jnp.float32) for k in ks)


def test_ring_attention_matches_dense(seq_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ring = ops.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "seq"),
        seq_mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"))
    out = ring(q, k, v)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_gradients(seq_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(1), B=1, T=32, H=2, D=8)

    ring = ops.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "seq"),
        seq_mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"))

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(ring(q, k, v)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(causal_attention_reference(q, k, v)))

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3, err_msg=f"d{name}")


def test_ring_attention_jit_end_to_end(seq_mesh):
    """Inside jit with shardings — the real usage shape."""
    q, k, v = _qkv(jax.random.PRNGKey(2), T=128)
    fn = jax.jit(ops.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "seq"),
        seq_mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq")))
    out = fn(q, k, v)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_matches_dense(seq_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(3), H=8)  # H divisible by n=8
    uly = ops.shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, "seq"),
        seq_mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"))
    out = uly(q, k, v)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_noncausal(seq_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(4), T=32)
    ring = ops.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "seq", causal=False),
        seq_mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"))
    out = ring(q, k, v)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    probs = jax.nn.softmax(logits, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
