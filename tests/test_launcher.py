"""Cluster launcher tests (reference model: `ray up/down` driven through
the local provider — test_autoscaler.py + fake_multi_node e2e).
"""

import os
import time

import pytest
import yaml

import ray_tpu
from ray_tpu import launcher
from ray_tpu.autoscaler import ResourceDemandScheduler


def _config(tmp_path, workers=2):
    return {
        "cluster_name": "lt",
        "max_workers": 4,
        "provider": {"type": "local"},
        "head_node_type": "head",
        "available_node_types": {
            "head": {"resources": {"CPU": 2.0}, "min_workers": 0},
            "worker": {"resources": {"CPU": 1.0}, "min_workers": workers},
        },
        "initialization_commands": [],
        "setup_commands": ["true"],  # exercises the setup phase
    }


@pytest.fixture
def launched(tmp_path):
    state_dir = str(tmp_path / "clusters")
    cfg = _config(tmp_path)
    state = launcher.up(cfg, state_dir=state_dir)
    yield state, state_dir
    try:
        launcher.down("lt", state_dir=state_dir)
    except FileNotFoundError:
        pass


def test_up_boots_head_and_workers(launched):
    """VERDICT done-criterion: up boots head+2 workers from a YAML on
    one box; all three register with the head."""
    state, _ = launched
    assert state["head"]["status"] == launcher.RUNNING
    assert len(state["workers"]) == 2
    assert all(w["status"] == launcher.RUNNING for w in state["workers"])

    ray_tpu.init(address=state["head"]["address"])
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            nodes = [n for n in ray_tpu.nodes() if n["Alive"]]
            if len(nodes) >= 3:
                break
            time.sleep(0.5)
        assert len(nodes) == 3
        total = ray_tpu.cluster_resources()
        assert total.get("CPU", 0) == 4.0  # 2 head + 2x1 worker

        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get(f.remote(21), timeout=60) == 42
    finally:
        ray_tpu.shutdown()


def test_down_terminates_processes(tmp_path):
    state_dir = str(tmp_path / "clusters")
    state = launcher.up(_config(tmp_path, workers=1), state_dir=state_dir)
    pids = [state["head"]["pid"]] + [w["pid"] for w in state["workers"]]
    assert all(launcher.pid_alive(pid) for pid in pids)
    launcher.down("lt", state_dir=state_dir)
    deadline = time.time() + 15
    while time.time() < deadline:
        gone = sum(0 if launcher.pid_alive(pid) else 1 for pid in pids)
        if gone == len(pids):
            break
        time.sleep(0.2)
    assert gone == len(pids)
    assert not os.path.exists(
        os.path.join(state_dir, "lt.json"))


def test_autoscaler_v2_adopts_launched_workers(launched):
    """VERDICT done-criterion: the v2 reconciler adopts nodes it did not
    launch itself (reference: reconciler adoption of unknown cloud
    instances)."""
    from ray_tpu.autoscaler_v2 import RAY_RUNNING, Reconciler

    state, state_dir = launched
    provider = launcher.LaunchedNodeProvider("lt", state_dir=state_dir)
    rec = Reconciler(state["head"]["address"], provider,
                     min_workers=0, max_workers=4)
    deadline = time.time() + 30
    adopted = []
    while time.time() < deadline:
        rec.reconcile()
        adopted = rec.storage.list(RAY_RUNNING)
        if len(adopted) >= 2:
            break
        time.sleep(0.5)
    assert len(adopted) == 2
    ids = {i.node_id for i in adopted}
    assert ids == {bytes.fromhex(w["node_id_hex"])
                   for w in state["workers"]}


def test_cli_up_down_roundtrip(tmp_path):
    from ray_tpu.scripts.cli import main

    state_dir = str(tmp_path / "clusters")
    yml = tmp_path / "cluster.yaml"
    yml.write_text(yaml.safe_dump(_config(tmp_path, workers=1)))
    assert main(["up", str(yml), "--state-dir", state_dir]) == 0
    st = launcher.load_state("lt", state_dir=state_dir)
    assert len(st["workers"]) == 1
    assert main(["down", "lt", "--state-dir", state_dir]) == 0
    assert not os.path.exists(os.path.join(state_dir, "lt.json"))


def test_failed_setup_command_raises(tmp_path):
    cfg = _config(tmp_path, workers=0)
    cfg["setup_commands"] = ["false"]
    with pytest.raises(RuntimeError, match="setup command failed"):
        launcher.up(cfg, state_dir=str(tmp_path / "clusters"))


# ----------------------------------------------- demand scheduler (v1)


def test_demand_scheduler_packs_onto_cheapest_type():
    """Bin-packing chooses the type that satisfies each shape cheapest
    (reference: resource_demand_scheduler.py:102)."""
    sched = ResourceDemandScheduler({
        "small": {"resources": {"CPU": 2.0}, "cost": 1.0},
        "big": {"resources": {"CPU": 8.0, "TPU": 4.0}, "cost": 5.0},
    }, max_workers=10)
    # CPU-only demand → cheap small nodes, packed 2-per-node
    plan = sched.get_nodes_to_launch(
        [{"CPU": 1.0}] * 4, existing_headroom=[], existing_count=0)
    assert plan == {"small": 2}
    # TPU demand opens ONE big node; CPU demand then rides its spare
    # capacity instead of launching more smalls
    plan = sched.get_nodes_to_launch(
        [{"CPU": 1.0}] * 4 + [{"TPU": 2.0}],
        existing_headroom=[], existing_count=0)
    assert plan == {"big": 1}
    # existing headroom absorbs demand first
    plan2 = sched.get_nodes_to_launch(
        [{"CPU": 1.0}], existing_headroom=[{"CPU": 4.0}],
        existing_count=1)
    assert plan2 == {}
    # budget respected
    plan3 = sched.get_nodes_to_launch(
        [{"CPU": 2.0}] * 9, existing_headroom=[], existing_count=8)
    assert sum(plan3.values()) <= 2


def test_demand_scheduler_infeasible_shape_skipped():
    sched = ResourceDemandScheduler(
        {"small": {"resources": {"CPU": 2.0}}}, max_workers=4)
    plan = sched.get_nodes_to_launch(
        [{"GPU": 1.0}, {"CPU": 1.0}], existing_headroom=[],
        existing_count=0)
    assert plan == {"small": 1}  # GPU shape infeasible, CPU shape packed
