"""RLlib slice tests (reference model: rllib/algorithms/ppo/tests/
test_ppo.py — short real training runs on CartPole asserting learning).
"""

import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.rllib import PPOConfig
from ray_tpu.rllib.learner import compute_gae

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_gae_matches_bruteforce():
    T, N = 5, 2
    rng = np.random.RandomState(0)
    rewards = rng.rand(T, N).astype(np.float32)
    values = rng.rand(T, N).astype(np.float32)
    dones = np.zeros((T, N), bool)
    dones[2, 0] = True
    last = rng.rand(N).astype(np.float32)
    gamma, lam = 0.9, 0.8
    adv, tgt = compute_gae(rewards, values, dones, last, gamma, lam)

    # brute force per env
    for n in range(N):
        vals = np.append(values[:, n], last[n])
        expected = np.zeros(T)
        gae = 0.0
        for t in range(T - 1, -1, -1):
            nonterm = 0.0 if dones[t, n] else 1.0
            delta = rewards[t, n] + gamma * vals[t + 1] * nonterm - vals[t]
            gae = delta + gamma * lam * nonterm * gae
            expected[t] = gae
        np.testing.assert_allclose(adv[:, n], expected, rtol=1e-5)
    np.testing.assert_allclose(tgt, adv + values, rtol=1e-6)


def test_ppo_learns_cartpole_inline():
    """Learner + sampling logic sanity without the cluster (fast)."""
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=128)
            .training(num_sgd_iter=6, minibatch_size=256)).build()
    best = 0.0
    for _ in range(40):
        r = algo.train()
        if r["episode_return_mean"] == r["episode_return_mean"]:  # not nan
            best = max(best, r["episode_return_mean"])
        if best >= 195:
            break
    assert best >= 195, f"PPO failed to learn CartPole (best {best})"


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_ppo_pipelined_learns_cartpole():
    """pipeline_sampling=True (async-learner overlap, one-update-stale
    batches): still learns CartPole — the clipped ratio absorbs the
    staleness (reference: multi_gpu_learner_thread.py overlap)."""
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=128)
            .training(num_sgd_iter=6, minibatch_size=256,
                      pipeline_sampling=True)).build()
    best = 0.0
    for _ in range(40):
        r = algo.train()
        assert r["env_steps_per_sec"] > 0
        if r["episode_return_mean"] == r["episode_return_mean"]:
            best = max(best, r["episode_return_mean"])
        if best >= 195:
            break
    algo.stop()
    assert best >= 195, f"pipelined PPO failed to learn (best {best})"


def test_ppo_distributed_env_runners(cluster):
    """The VERDICT done-criterion: PPO on CartPole THROUGH the runtime —
    env-runner actors sampling remotely, weight sync via the object
    store, reward >= 195 in < 5 min."""
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                         rollout_fragment_length=128)
            .training(num_sgd_iter=6, minibatch_size=256)).build()
    import time

    t0 = time.time()
    best = 0.0
    steps_per_sec = []
    while time.time() - t0 < 300:
        r = algo.train()
        steps_per_sec.append(r["env_steps_per_sec"])
        if r["episode_return_mean"] == r["episode_return_mean"]:
            best = max(best, r["episode_return_mean"])
        if best >= 195:
            break
    algo.stop()
    assert best >= 195, f"PPO (distributed) failed to learn (best {best})"
    assert max(steps_per_sec) > 100  # sanity: sampling actually parallel


def test_dqn_learns_cartpole_inline():
    """Second algorithm family: off-policy DQN with replay buffer +
    target network (reference: rllib/algorithms/dqn)."""
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                         rollout_fragment_length=32)
            .training(updates_per_iteration=64,
                      num_steps_sampled_before_learning=500)).build()
    import time

    t0 = time.time()
    best = 0.0
    while time.time() - t0 < 240:
        r = algo.train()
        m = r["episode_return_mean"]
        if m == m:
            best = max(best, m)
        if best >= 195:
            break
    assert best >= 195, f"DQN failed to learn CartPole (best {best})"


def test_replay_buffer_wraparound():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(10, 2)
    obs = np.arange(16 * 2, dtype=np.float32).reshape(16, 2)
    buf.add_batch(obs, np.arange(16), np.ones(16, np.float32), obs,
                  np.zeros(16, bool))
    assert buf.size == 10 and buf.pos == 6
    s = buf.sample(32, np.random.RandomState(0))
    assert s["obs"].shape == (32, 2)


def test_vtrace_reduces_to_returns_on_policy():
    """With identical behavior/target policies, rho=c=1 and V-trace
    targets equal the TD(lambda=1)-corrected values."""
    from ray_tpu.rllib import vtrace

    T, N = 6, 2
    rng = np.random.RandomState(0)
    logp = np.log(rng.uniform(0.2, 0.9, (T, N))).astype(np.float32)
    rewards = rng.rand(T, N).astype(np.float32)
    values = rng.rand(T, N).astype(np.float32)
    dones = np.zeros((T, N), bool)
    last = rng.rand(N).astype(np.float32)
    gamma = 0.9
    vs, adv = vtrace(logp, logp, rewards, values, dones, last, gamma)
    # on-policy: vs_t = sum_k gamma^{k-t} r_k + gamma^{T-t} V(last)
    for n in range(N):
        expected = last[n]
        for t in range(T - 1, -1, -1):
            expected = rewards[t, n] + gamma * expected
            if t == 0:
                np.testing.assert_allclose(vs[0, n], expected, rtol=1e-4)


def test_impala_learns_cartpole_async_thread():
    """Async sampling + background learner thread (the BASELINE's
    MultiGPULearnerThread role)."""
    from ray_tpu.rllib import IMPALAConfig

    algo = (IMPALAConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=64)).build()
    import time

    t0 = time.time()
    best = 0.0
    while time.time() - t0 < 240:
        r = algo.train()
        m = r["episode_return_mean"]
        if m == m:
            best = max(best, m)
        if best >= 195:
            break
    algo.stop()
    assert best >= 195, f"IMPALA failed to learn (best {best})"
    assert r["learner_updates"] > 50  # the background thread really ran


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_impala_distributed_async(cluster):
    """Remote env runners sampled asynchronously (no per-iteration
    barrier) — learning still happens end-to-end through the runtime."""
    from ray_tpu.rllib import IMPALAConfig

    algo = (IMPALAConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                         rollout_fragment_length=64)).build()
    import time

    t0 = time.time()
    best = 0.0
    while time.time() - t0 < 280:
        r = algo.train()
        m = r["episode_return_mean"]
        if m == m:
            best = max(best, m)
        if best >= 150:
            break
    algo.stop()
    assert best >= 150, f"IMPALA (distributed) no learning (best {best})"


def test_ppo_multi_learner_mesh_parity():
    """num_learners=4 -> the SPMD update runs over a 4-device learner
    mesh; a fixed minibatch must produce the same updated params as the
    single-device learner (allreduce-parity, the DDP guarantee)."""
    import jax

    from ray_tpu.rllib.learner import PPOLearner, PPOLearnerConfig

    cfg = PPOLearnerConfig(num_sgd_iter=1, minibatch_size=64)
    rng = np.random.RandomState(0)
    batch = {
        "obs": rng.randn(64, 4).astype(np.float32),
        "actions": rng.randint(0, 2, 64),
        "logp_old": rng.randn(64).astype(np.float32) * 0.1,
        "advantages": rng.randn(64).astype(np.float32),
        "value_targets": rng.randn(64).astype(np.float32),
    }
    single = PPOLearner(4, 2, cfg, mesh=None, seed=0)
    single.update(dict(batch))

    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
    multi = PPOLearner(4, 2, cfg, mesh=mesh, seed=0)
    multi.update(dict(batch))
    for a, b in zip(jax.tree_util.tree_leaves(single.get_weights()),
                    jax.tree_util.tree_leaves(multi.get_weights())):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4)


def test_ppo_num_learners_config():
    from ray_tpu.rllib import PPOConfig

    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                           rollout_fragment_length=64)
              .learners(num_learners=4))
    import pickle

    pickle.dumps(config)  # configs stay pure data (shippable to trials)
    assert config._resolve_learner_mesh() is not None
    algo = config.build()
    r = algo.train()
    assert r["training_iteration"] == 1
    algo.stop()
