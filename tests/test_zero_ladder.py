"""ZeRO-2/3 ladder (train/spmd.py zero_stage + accum_steps).

Extends the ZeRO-1 gates of test_zero1.py up the ladder:
- stage 2 keeps the grad-accum buffer resident reduce-scattered 1/N
  between accumulation boundaries; stage 3 shards the resident params
  1/N with a just-in-time all-gather inside the jitted step.
- Parity is exact arithmetic, not "close": the double-constraint pin
  (grads to the rule layout before the scatter; stage-3 params to the
  rule layout before the loss) keeps every GEMM partitioning identical
  to the unsharded program, so sgd(+momentum) losses AND params match
  at 1e-5 on gpt2 and llama.
- The memory rungs are test-gated at <= 1.25/N per component, and the
  stage-3 program structurally carries the param gathers.
"""

import numpy as np
import pytest

import jax
import optax

from ray_tpu.models.gpt2 import (
    GPT2Config,
    gpt2_loss,
    gpt2_partition_rules,
    init_gpt2,
)
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.train.spmd import (
    init_sharded_state,
    make_train_step,
    optimizer_state_bytes,
)

from tests.test_zero1 import _batch

DATA = 4  # data-axis size the byte-shrink assertions divide by


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(MeshSpec(data=DATA, tensor=2))


def _run(mesh, rules, init_fn, loss_fn, tx, batch, stage, steps,
         accum=1):
    state = init_sharded_state(init_fn, tx, mesh, rules,
                               zero_stage=stage, accum_steps=accum)
    step = make_train_step(loss_fn, tx, zero_stage=stage,
                           mesh=mesh if stage else None,
                           rules=rules if stage else None,
                           accum_steps=accum)
    losses = []
    with mesh:
        for _ in range(steps):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
    return state, losses


def _gpt2_parts(mesh, seed=0):
    cfg = GPT2Config.tiny()
    rules = gpt2_partition_rules()
    batch = _batch(mesh, cfg.vocab_size, seed=seed)

    def init_fn():
        return init_gpt2(jax.random.PRNGKey(0), cfg)

    def loss_fn(p, b):
        return gpt2_loss(p, b, cfg)

    return rules, init_fn, loss_fn, batch


@pytest.fixture(scope="module")
def gpt2_reference(mesh):
    """The stage-0 oracle run, shared by both parity rungs (one
    compile instead of one per parametrization)."""
    rules, init_fn, loss_fn, batch = _gpt2_parts(mesh)
    tx = optax.sgd(0.05, momentum=0.9)
    state, losses = _run(mesh, rules, init_fn, loss_fn, tx, batch,
                         0, 4)
    return state, losses, batch


@pytest.mark.parametrize("stage", [2, 3])
def test_gpt2_parity_up_the_ladder(mesh, gpt2_reference, stage):
    """Loss AND param parity at 1e-5 vs the unsharded step, stages 2
    and 3, sgd+momentum (elementwise-stable update, exact gate)."""
    s_r, l_r, batch = gpt2_reference
    rules, init_fn, loss_fn, _ = _gpt2_parts(mesh)
    tx = optax.sgd(0.05, momentum=0.9)
    s_z, l_z = _run(mesh, rules, init_fn, loss_fn, tx, batch, stage, 4)
    assert l_r[0] > l_r[-1]  # it actually trains
    np.testing.assert_allclose(l_r, l_z, atol=1e-5)
    for a, b in zip(jax.tree.leaves(s_r.params),
                    jax.tree.leaves(s_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)


def _llama_parts(mesh):
    from ray_tpu.models.llama import (
        LlamaConfig,
        init_llama,
        llama_loss,
        llama_partition_rules,
    )

    cfg = LlamaConfig.tiny()
    rules = llama_partition_rules()
    batch = _batch(mesh, cfg.vocab_size, T=32, seed=1)

    def init_fn():
        return init_llama(jax.random.PRNGKey(0), cfg)

    def loss_fn(p, b):
        return llama_loss(p, b, cfg)

    return rules, init_fn, loss_fn, batch


@pytest.fixture(scope="module")
def llama_reference(mesh):
    rules, init_fn, loss_fn, batch = _llama_parts(mesh)
    tx = optax.sgd(0.05, momentum=0.9)
    _, losses = _run(mesh, rules, init_fn, loss_fn, tx, batch, 0, 4)
    return losses, batch


@pytest.mark.parametrize("stage", [2, 3])
def test_llama_parity_up_the_ladder(mesh, llama_reference, stage):
    l_r, batch = llama_reference
    rules, init_fn, loss_fn, _ = _llama_parts(mesh)
    tx = optax.sgd(0.05, momentum=0.9)
    _, l_z = _run(mesh, rules, init_fn, loss_fn, tx, batch, stage, 4)
    np.testing.assert_allclose(l_r, l_z, atol=1e-5)


def test_grad_accum_parity_across_stages(mesh):
    """accum_steps=2: the accumulate-then-select update must match the
    accum_steps=2 unsharded step exactly at stages 2 and 3 (losses at
    every microstep — the select keeps params frozen off-boundary)."""
    rules, init_fn, loss_fn, batch = _gpt2_parts(mesh, seed=3)
    tx = optax.sgd(0.05, momentum=0.9)
    s0, l0 = _run(mesh, rules, init_fn, loss_fn, tx, batch, 0, 6,
                  accum=2)
    # off-boundary steps keep params frozen -> pairwise-equal losses
    assert l0[0] == pytest.approx(l0[1], abs=1e-6)
    assert l0[0] > l0[-1]
    for stage in (2, 3):
        s_z, l_z = _run(mesh, rules, init_fn, loss_fn, tx, batch,
                        stage, 6, accum=2)
        np.testing.assert_allclose(l0, l_z, atol=1e-5)
        for a, b in zip(jax.tree.leaves(s0.params),
                        jax.tree.leaves(s_z.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)


def test_state_bytes_shrink_per_rung(mesh):
    """The per-component memory claims: grad-accum bytes 1/N at stage
    >= 2, resident param bytes 1/N at stage 3 (<= 1.25/N slack for
    indivisible leaves), optimizer bytes 1/N from stage 1 on — and the
    per-component gauges expose both layouts."""
    rules, init_fn, _, _ = _gpt2_parts(mesh)
    tx = optax.sgd(0.05, momentum=0.9)

    def bytes_at(stage):
        s = init_sharded_state(init_fn, tx, mesh, rules,
                               zero_stage=stage, accum_steps=2)
        return (optimizer_state_bytes(s.opt_state),
                optimizer_state_bytes(s.grad_accum),
                optimizer_state_bytes(s.params))

    o0, g0, p0 = bytes_at(0)
    o2, g2, p2 = bytes_at(2)
    o3, g3, p3 = bytes_at(3)
    assert g0 > 0 and p0 > 0
    bound = 1.25 / DATA
    assert o2 / o0 <= bound, (o0, o2)          # stage >= 1 rung
    assert g2 / g0 <= bound, (g0, g2)          # stage >= 2 rung
    assert p2 == p0                            # params untouched < 3
    assert g3 / g0 <= bound and o3 / o0 <= bound
    assert p3 / p0 <= bound, (p0, p3)          # stage 3 rung

    from ray_tpu.train.spmd import (
        _grad_state_bytes_gauge,
        _param_state_bytes_gauge,
    )

    exposed_g = "\n".join(_grad_state_bytes_gauge().expose())
    assert 'layout="replicated"' in exposed_g
    assert 'layout="zero2"' in exposed_g
    exposed_p = "\n".join(_param_state_bytes_gauge().expose())
    assert 'layout="replicated"' in exposed_p
    assert 'layout="zero3"' in exposed_p


def test_zero3_program_carries_param_gathers(mesh):
    """Structural census: the stage-3 program all-gathers the resident
    1/N params just-in-time inside the step — collectives the
    replicated program does not have."""
    from ray_tpu.parallel.ops import collective_op_counts

    rules, init_fn, loss_fn, batch = _gpt2_parts(mesh)
    tx = optax.sgd(0.05, momentum=0.9)

    def census(stage):
        state = init_sharded_state(init_fn, tx, mesh, rules,
                                   zero_stage=stage)
        step = make_train_step(loss_fn, tx, zero_stage=stage,
                               mesh=mesh if stage else None,
                               rules=rules if stage else None,
                               donate=False)
        with mesh:
            txt = step.jitted.lower(state, batch).compile().as_text()
        return collective_op_counts(txt)

    plain, zero3 = census(0), census(3)
    assert plain.get("allreduce", 0) > 0  # DP grad reduction exists
    assert zero3.get("all_gather", 0) > plain.get("all_gather", 0), \
        (plain, zero3)


def test_resolve_zero_stage_back_compat():
    """The shard_optimizer bool keeps meaning stage 1; explicit
    zero_stage wins; out-of-range stages are rejected."""
    from ray_tpu.train.spmd import _resolve_zero_stage

    assert _resolve_zero_stage(None, False) == 0
    assert _resolve_zero_stage(None, True) == 1
    assert _resolve_zero_stage(2, False) == 2
    assert _resolve_zero_stage(3, True) == 3
    assert _resolve_zero_stage(0, True) == 0  # explicit wins
    with pytest.raises(ValueError):
        _resolve_zero_stage(4, False)


def test_zero_shardings_component_rungs(mesh):
    """zero_shardings applies the +data-axis layout iff the stage
    reaches the component's rung (optimizer: 1, grads: 2, params: 3),
    else falls back to the rule layout."""
    from ray_tpu.parallel.sharding import PartitionRules
    from ray_tpu.train.spmd import zero1_shardings, zero_shardings

    rules = PartitionRules([])
    tree = {"w": np.zeros((8, 8), np.float32)}
    zero = zero1_shardings(rules, tree, mesh)["w"]
    for component, rung in (("optimizer", 1), ("grads", 2),
                            ("params", 3)):
        for stage in range(4):
            got = zero_shardings(rules, tree, mesh, stage,
                                 component=component)["w"]
            want = zero if stage >= rung else \
                rules.shardings(tree, mesh)["w"]
            assert got.spec == want.spec, (component, stage, got)
    with pytest.raises(ValueError):
        zero_shardings(rules, tree, mesh, 1, component="nonsense")


def test_gather_share_gauge_populates_at_stage3(mesh):
    """Attribution runs at zero_stage>=3 set train_zero_gather_share —
    the watchtower train-zero-gather-stall rule's input."""
    from ray_tpu.train import spmd
    from ray_tpu.util.metrics import Gauge

    rules, init_fn, loss_fn, batch = _gpt2_parts(mesh, seed=5)
    tx = optax.sgd(0.05, momentum=0.9)
    state = init_sharded_state(init_fn, tx, mesh, rules, zero_stage=3)
    step = make_train_step(loss_fn, tx, zero_stage=3, mesh=mesh,
                           rules=rules)
    spmd.waterfall.reset()
    spmd.enable_step_waterfall(True)
    try:
        with mesh:
            state, _ = step(state, batch)
            state, _ = step(state, batch)
    finally:
        spmd.enable_step_waterfall(False)
    g = Gauge("train_zero_gather_share", "")  # registry-backed handle
    share = g._values.get((), None)
    assert share is not None, "gauge never set"
    assert 0.0 <= share <= 1.0, share
