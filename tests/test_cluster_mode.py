"""Distributed (multi-process) runtime tests.

Reference model: python/ray/tests/ with the ray_start_cluster fixture
(conftest.py:613) — multi-node on one box with asserted fake resources;
worker processes are real.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    # warm worker pools: these tests assert scheduling behavior
    # (parallel dispatch, spread), not cold python-process spawn
    # latency, which dominates wall time on slow CI boxes
    os.environ["RAY_TPU_PRESTART_WORKERS"] = "4"
    try:
        c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
        c.add_node(num_cpus=4, resources={"magic": 2.0})
        c.wait_for_nodes()
        ray_tpu.init(address=c.address)
        yield c
        ray_tpu.shutdown()
        c.shutdown()
    finally:
        os.environ.pop("RAY_TPU_PRESTART_WORKERS", None)


def test_remote_task_roundtrip(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(2, 3)) == 5


def test_task_with_large_result(cluster):
    @ray_tpu.remote
    def big():
        return np.arange(1 << 18, dtype=np.float32)

    out = ray_tpu.get(big.remote())
    assert out.shape == (1 << 18,)
    assert out[-1] == (1 << 18) - 1


def test_put_get_large(cluster):
    arr = np.random.rand(1 << 16)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    assert np.array_equal(out, arr)


def test_object_ref_as_arg(cluster):
    @ray_tpu.remote
    def double(x):
        return x * 2

    ref1 = ray_tpu.put(21)
    assert ray_tpu.get(double.remote(ref1)) == 42
    # chained task outputs (worker resolves from another worker's owner)
    ref2 = double.remote(double.remote(10))
    assert ray_tpu.get(ref2) == 40


def test_large_arg_through_store(cluster):
    arr = np.ones(1 << 17, dtype=np.float64)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(ray_tpu.put(arr))) == float(1 << 17)


def test_task_error_propagates(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    from ray_tpu.core.exceptions import TaskError

    with pytest.raises(TaskError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_nested_tasks(cluster):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(1)) == 12


def test_custom_resource_scheduling(cluster):
    @ray_tpu.remote(resources={"magic": 1.0}, num_cpus=0.1)
    def where():
        return ray_tpu.get_runtime_context().node_id.hex()

    node = ray_tpu.get(where.remote())
    magic_nodes = [n["NodeID"] for n in ray_tpu.nodes()
                   if n["Resources"].get("magic")]
    assert node in magic_nodes


def test_parallel_tasks_spread(cluster):
    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(0.3)
        return ray_tpu.get_runtime_context().node_id.hex()

    t0 = time.monotonic()
    nodes = ray_tpu.get([slow.remote() for _ in range(8)])
    elapsed = time.monotonic() - t0
    # 8 CPUs across 2 nodes: parallel, and both nodes used
    assert elapsed < 2.5
    assert len(set(nodes)) == 2


def test_actor_basic(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote()) == 11
    assert ray_tpu.get(c.incr.remote(5)) == 16


def test_actor_ordering(cluster):
    @ray_tpu.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)
            return list(self.items)

    a = Appender.remote()
    refs = [a.add.remote(i) for i in range(20)]
    final = ray_tpu.get(refs[-1])
    assert final == list(range(20))


def test_named_actor(cluster):
    @ray_tpu.remote
    class Svc:
        def ping(self):
            return "pong"

    Svc.options(name="svc1").remote()
    h = ray_tpu.get_actor("svc1")
    assert ray_tpu.get(h.ping.remote()) == "pong"


def test_actor_error_propagates(cluster):
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor-oops")

    from ray_tpu.core.exceptions import TaskError

    b = Bad.remote()
    with pytest.raises(TaskError, match="actor-oops"):
        ray_tpu.get(b.fail.remote())


def test_kill_actor(cluster):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "alive"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote()) == "alive"
    ray_tpu.kill(v)
    from ray_tpu.core import exceptions as exc

    time.sleep(0.5)
    with pytest.raises((exc.ActorDiedError, exc.ActorUnavailableError,
                        exc.TaskError)):
        ray_tpu.get(v.ping.remote(), timeout=10)


def test_wait(cluster):
    @ray_tpu.remote
    def sleepy(t):
        time.sleep(t)
        return t

    refs = [sleepy.remote(0.05), sleepy.remote(5)]
    ready, pending = ray_tpu.wait(refs, num_returns=1, timeout=3)
    assert len(ready) == 1 and len(pending) == 1
    assert ray_tpu.get(ready[0]) == 0.05


def test_cluster_resources(cluster):
    total = ray_tpu.cluster_resources() if hasattr(ray_tpu, "cluster_resources") \
        else None
    nodes = ray_tpu.nodes()
    assert len(nodes) == 2
    assert sum(n["Resources"].get("CPU", 0) for n in nodes) == 8.0


def test_task_retry_on_worker_crash(cluster):
    @ray_tpu.remote(max_retries=2, num_cpus=0.1)
    def flaky(key):
        # crash the whole worker process the first time, by key
        import os
        import tempfile

        marker = f"{tempfile.gettempdir()}/crash_{key}"
        if not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)
        os.unlink(marker)
        return "recovered"

    import secrets

    assert ray_tpu.get(flaky.remote(secrets.token_hex(4)), timeout=60) == "recovered"


def test_delta_heartbeats_keep_view_fresh(cluster):
    """Payload-less liveness beats (delta sync, ray_syncer.h:83 role):
    the head's availability view still reflects changes promptly, and
    nodes stay alive through unchanged periods."""
    import time as _t

    before = {n["NodeID"]: n["Available"].get("CPU")
              for n in ray_tpu.nodes()}

    @ray_tpu.remote(num_cpus=2)
    class Holder:
        def ping(self):
            return 1

    h = Holder.remote()
    ray_tpu.get(h.ping.remote())
    deadline = _t.monotonic() + 10
    changed = False
    while _t.monotonic() < deadline and not changed:
        now = {n["NodeID"]: n["Available"].get("CPU")
               for n in ray_tpu.nodes()}
        changed = any(now[k] != before.get(k) for k in now)
        _t.sleep(0.3)
    assert changed, "availability change never propagated"
    # quiet period LONGER than NODE_DEATH_AFTER_S (5.0): if liveness-only
    # beats were not actually sent, the monitor would mark nodes dead
    _t.sleep(6.0)
    assert all(n["Alive"] for n in ray_tpu.nodes())
    ray_tpu.kill(h)
