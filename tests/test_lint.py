"""graftlint tier-1 tests.

Covers: every rule firing on its fixture and staying quiet on the
clean twin, suppression comments, the baseline round-trip, and — the
gate that matters — a clean full-package run: ``ray_tpu/`` must have
zero non-baselined findings (and this repo's committed baseline is
empty, so zero findings, full stop).
"""

import json
import os
import time

import pytest

from ray_tpu.devtools import baseline as baseline_mod
from ray_tpu.devtools.driver import lint_paths, lint_source
from ray_tpu.devtools.lint import default_baseline_path, main, repo_root
from ray_tpu.devtools.registry import all_rules, rule_catalog

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def lint_fixture(name):
    return lint_paths([os.path.join(FIXTURES, name)], all_rules(),
                      root=FIXTURES)


# -------------------------------------------------------------- rule cases

RULE_CASES = [
    ("GL001", "async-blocking", "gl001_fire.py", "gl001_ok.py", 3),
    ("GL002", "discarded-future", "gl002_fire.py", "gl002_ok.py", 2),
    ("GL003", "spmd-nondeterminism", "gl003_fire.py", "gl003_ok.py", 3),
    ("GL004", "host-transfer", "gl004_fire.py", "gl004_ok.py", 3),
    ("GL005", "guarded-by", "gl005_fire.py", "gl005_ok.py", 3),
    ("GL006", "except-hygiene", "gl006_fire.py", "gl006_ok.py", 3),
    ("GL007", "unreleased-store-ref", "gl007_fire.py", "gl007_ok.py", 3),
    ("GL008", "oneway-return", "gl008_fire.py", "gl008_ok.py", 4),
    ("GL009", "lock-order", "gl009_fire.py", "gl009_ok.py", 3),
    ("GL010", "global-guarded-by", "gl010_fire.py", "gl010_ok.py", 3),
    ("GL011", "oneway-exception", "gl011_fire.py", "gl011_ok.py", 4),
    ("GL012", "blocking-under-lock", "gl012_fire.py", "gl012_ok.py", 3),
    ("GL013", "handler-reentry", "gl013_fire.py", "gl013_ok.py", 3),
    ("GL014", "sequential-rpc-in-loop", "gl014_fire.py", "gl014_ok.py", 3),
    ("GL015", "wallclock-duration", "gl015_fire.py", "gl015_ok.py", 3),
    ("GL016", "bare-print", "gl016_fire.py", "gl016_ok.py", 3),
]


@pytest.mark.parametrize("code,name,fire,ok,n_expected", RULE_CASES,
                         ids=[c[0] for c in RULE_CASES])
def test_rule_fires_and_stays_quiet(code, name, fire, ok, n_expected):
    firing = lint_fixture(fire)
    assert [f.code for f in firing] == [code] * n_expected, (
        f"{fire}: expected {n_expected} {code} findings, got "
        f"{[(f.code, f.line, f.message) for f in firing]}")
    assert all(f.rule == name for f in firing)
    clean = lint_fixture(ok)
    assert clean == [], (
        f"{ok} should be clean, got "
        f"{[(f.code, f.line, f.message) for f in clean]}")


def test_rule_catalog_complete():
    catalog = rule_catalog()
    assert [c.code for c in catalog] == [
        "GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007",
        "GL008", "GL009", "GL010", "GL011", "GL012", "GL013", "GL014",
        "GL015", "GL016"]
    for cls in catalog:
        assert cls.name and cls.description and cls.invariant


def test_select_filters_rules():
    findings = lint_paths([os.path.join(FIXTURES, "gl006_fire.py")],
                          all_rules({"GL002"}), root=FIXTURES)
    assert findings == []  # only the discarded-future rule ran


# ------------------------------------------------------------ suppressions

def test_suppression_comments():
    assert lint_fixture("suppressed.py") == []


def test_suppression_file_level():
    src = ("# graftlint: disable-file=discarded-future\n"
           "def kick(f):\n"
           "    f.remote(1)\n")
    assert lint_source(src, "x.py", all_rules()) == []


def test_unsuppressed_twin_still_fires():
    src = "def kick(f):\n    f.remote(1)\n"
    findings = lint_source(src, "x.py", all_rules())
    assert [f.code for f in findings] == ["GL002"]


# ---------------------------------------------------------------- baseline

def test_baseline_round_trip(tmp_path):
    path = str(tmp_path / "baseline.json")
    findings = lint_fixture("gl002_fire.py")
    assert findings
    baseline_mod.save(path, findings)

    known = baseline_mod.load(path)
    assert len(known) == len(findings)
    new, baselined = baseline_mod.split(lint_fixture("gl002_fire.py"), known)
    assert new == [] and len(baselined) == len(findings)

    # a NEW violation is not absorbed by the baseline
    extra = lint_source("def go(f):\n    f.remote()\n", "new_file.py",
                        all_rules())
    new2, _ = baseline_mod.split(extra, known)
    assert [f.code for f in new2] == ["GL002"]


def test_baseline_fingerprint_survives_line_moves():
    src1 = "def kick(f):\n    f.remote(1)\n"
    src2 = "import os\n\n\ndef kick(f):\n    f.remote(1)\n"
    fp1 = lint_source(src1, "x.py", all_rules())[0].fingerprint()
    fp2 = lint_source(src2, "x.py", all_rules())[0].fingerprint()
    assert fp1 == fp2


def test_baseline_prune(tmp_path):
    path = str(tmp_path / "baseline.json")
    baseline_mod.save(path, lint_fixture("gl002_fire.py"))
    removed = baseline_mod.prune(path, [])  # everything got fixed
    assert removed == 2
    assert baseline_mod.load(path) == {}


# ------------------------------------------------------------------- CLI

def test_cli_list_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in ("GL001", "GL006"):
        assert code in out


def test_cli_json_output(capsys):
    rc = main([os.path.join(FIXTURES, "gl002_fire.py"), "--no-baseline",
               "--json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert len(data["new"]) == 2
    assert data["baselined"] == []
    assert all(f["code"] == "GL002" for f in data["new"])


def test_cli_bad_path():
    assert main(["/nonexistent/nowhere.py"]) == 2


# ------------------------------------------------- the gate: clean package

def test_package_is_lint_clean_tier1():
    """ray_tpu/ has zero non-baselined findings, in pre-commit time.

    This is the PR gate the devtools exist for: new concurrency/SPMD
    violations fail here before they reach the runtime hot paths.
    """
    pkg = os.path.join(repo_root(), "ray_tpu")
    t0 = time.monotonic()
    findings = lint_paths([pkg], all_rules(), root=repo_root())
    elapsed = time.monotonic() - t0
    known = baseline_mod.load(default_baseline_path())
    new, _ = baseline_mod.split(findings, known)
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.render() for f in new)
    # pre-commit viability bar from the devtools charter
    assert elapsed < 10.0, f"full-package lint took {elapsed:.1f}s"


def test_committed_baseline_is_empty():
    """Burn-down complete: keep it that way (fix, don't baseline)."""
    assert baseline_mod.load(default_baseline_path()) == {}
