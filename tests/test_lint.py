"""graftlint tier-1 tests.

Covers: every rule firing on its fixture and staying quiet on the
clean twin, the interprocedural (semantic-index) layer firing on
cross-function shapes the single-pass engine provably misses,
suppression comments, the baseline round-trip, the index cache, and —
the gate that matters — a clean full-package run: ``ray_tpu/`` must
have zero non-baselined findings (and this repo's committed baseline
is empty, so zero findings, full stop) in under 10 seconds.
"""

import json
import os
import time

import pytest

from ray_tpu.devtools import baseline as baseline_mod
from ray_tpu.devtools.driver import lint_paths, lint_source
from ray_tpu.devtools.lint import default_baseline_path, main, repo_root
from ray_tpu.devtools.registry import (all_index_rules, all_rules,
                                       index_rule_catalog, rule_catalog)
from ray_tpu.devtools.semindex import build_index

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def lint_fixture(name, index_rules=None):
    # index_cache="" keeps fixture runs hermetic (no shared temp cache)
    return lint_paths([os.path.join(FIXTURES, name)], all_rules(),
                      root=FIXTURES, index_rules=index_rules,
                      index_cache="")


# -------------------------------------------------------------- rule cases

RULE_CASES = [
    ("GL001", "async-blocking", "gl001_fire.py", "gl001_ok.py", 3),
    ("GL002", "discarded-future", "gl002_fire.py", "gl002_ok.py", 2),
    ("GL003", "spmd-nondeterminism", "gl003_fire.py", "gl003_ok.py", 3),
    ("GL004", "host-transfer", "gl004_fire.py", "gl004_ok.py", 3),
    ("GL005", "guarded-by", "gl005_fire.py", "gl005_ok.py", 3),
    ("GL006", "except-hygiene", "gl006_fire.py", "gl006_ok.py", 3),
    ("GL007", "unreleased-store-ref", "gl007_fire.py", "gl007_ok.py", 3),
    ("GL008", "oneway-return", "gl008_fire.py", "gl008_ok.py", 4),
    ("GL009", "lock-order", "gl009_fire.py", "gl009_ok.py", 3),
    ("GL010", "global-guarded-by", "gl010_fire.py", "gl010_ok.py", 3),
    ("GL011", "oneway-exception", "gl011_fire.py", "gl011_ok.py", 4),
    ("GL012", "blocking-under-lock", "gl012_fire.py", "gl012_ok.py", 3),
    ("GL013", "handler-reentry", "gl013_fire.py", "gl013_ok.py", 3),
    ("GL014", "sequential-rpc-in-loop", "gl014_fire.py", "gl014_ok.py", 3),
    ("GL015", "wallclock-duration", "gl015_fire.py", "gl015_ok.py", 3),
    ("GL016", "bare-print", "gl016_fire.py", "gl016_ok.py", 3),
    ("GL018", "unbounded-accumulator", "gl018_fire.py", "gl018_ok.py", 3),
    ("GL019", "host-sync-in-step-loop", "gl019_fire.py", "gl019_ok.py", 4),
]


@pytest.mark.parametrize("code,name,fire,ok,n_expected", RULE_CASES,
                         ids=[c[0] for c in RULE_CASES])
def test_rule_fires_and_stays_quiet(code, name, fire, ok, n_expected):
    firing = lint_fixture(fire)
    assert [f.code for f in firing] == [code] * n_expected, (
        f"{fire}: expected {n_expected} {code} findings, got "
        f"{[(f.code, f.line, f.message) for f in firing]}")
    assert all(f.rule == name for f in firing)
    clean = lint_fixture(ok)
    assert clean == [], (
        f"{ok} should be clean, got "
        f"{[(f.code, f.line, f.message) for f in clean]}")


def test_rule_catalog_complete():
    catalog = rule_catalog()
    assert [c.code for c in catalog] == [
        "GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007",
        "GL008", "GL009", "GL010", "GL011", "GL012", "GL013", "GL014",
        "GL015", "GL016", "GL018", "GL019"]
    for cls in catalog:
        assert cls.name and cls.description and cls.invariant
    index_catalog = index_rule_catalog()
    assert [c.selector() for c in index_catalog] == [
        "GL009.inter", "GL012.inter", "GL013.inter", "GL017"]
    for cls in index_catalog:
        assert cls.name and cls.description and cls.invariant


def test_select_filters_rules():
    findings = lint_paths([os.path.join(FIXTURES, "gl006_fire.py")],
                          all_rules({"GL002"}), root=FIXTURES,
                          index_cache="")
    assert findings == []  # only the discarded-future rule ran


# ------------------------------------------- the indexed (v2) layer

# (code, fire fixture, ok fixture, expected finding count). Every fire
# fixture is a shape the pre-v2 single-pass engine PROVABLY misses —
# asserted below by running it with the indexed layer disabled.
INTER_CASES = [
    ("GL012", "gl012_inter_fire.py", "gl012_inter_ok.py", 2),
    ("GL013", "gl013_inter_fire.py", "gl013_inter_ok.py", 3),
    ("GL009", "gl009_inter_fire.py", "gl009_inter_ok.py", 2),
    ("GL012", "effects_override_fire.py", "effects_override_ok.py", 1),
    ("GL017", "gl017_fire.py", "gl017_ok.py", 2),
]


@pytest.mark.parametrize("code,fire,ok,n_expected", INTER_CASES,
                         ids=[c[1][:-3] for c in INTER_CASES])
def test_interprocedural_fires_and_stays_quiet(code, fire, ok,
                                               n_expected):
    firing = lint_fixture(fire)
    assert [f.code for f in firing] == [code] * n_expected, (
        f"{fire}: expected {n_expected} {code} findings, got "
        f"{[(f.code, f.line, f.message) for f in firing]}")
    if code != "GL017":  # GL017 needs no chain: the annotation IS the site
        assert all(f.chain for f in firing), "indexed finding lost its chain"
    clean = lint_fixture(ok)
    assert clean == [], (
        f"{ok} should be clean, got "
        f"{[(f.code, f.line, f.message) for f in clean]}")


@pytest.mark.parametrize("code,fire,ok,n_expected", INTER_CASES,
                         ids=[c[1][:-3] for c in INTER_CASES])
def test_single_pass_engine_misses_inter_fixture(code, fire, ok,
                                                 n_expected):
    """The point of the index: the per-file engine alone (index_rules
    disabled — exactly the pre-v2 behavior) sees nothing here."""
    assert lint_fixture(fire, index_rules=[]) == []


def test_effects_annotation_freezes_inference():
    """The ok twin only differs from firing by its '# effects: none'
    line — inference would flag the statically-blocking callee."""
    src = open(os.path.join(FIXTURES, "effects_override_ok.py")).read()
    assert "# effects: none" in src
    assert lint_fixture("effects_override_ok.py") == []


def test_chain_excluded_from_fingerprint():
    f1, f2 = lint_fixture("gl012_inter_fire.py")
    bare = type(f1)(path=f1.path, line=f1.line, col=f1.col,
                    rule=f1.rule, code=f1.code, message=f1.message,
                    line_text=f1.line_text, occurrence=f1.occurrence)
    assert f1.chain and bare.fingerprint() == f1.fingerprint()


def test_select_inter_sublayer_only():
    """GL012.inter selects only the indexed layer; plain GL012 both."""
    inter_only = lint_paths(
        [os.path.join(FIXTURES, "gl012_fire.py")],
        all_rules({"GL012.inter"}), root=FIXTURES,
        index_rules=all_index_rules({"GL012.inter"}), index_cache="")
    assert inter_only == []  # per-file shapes: inter layer is quiet
    both = lint_paths(
        [os.path.join(FIXTURES, "gl012_inter_fire.py")],
        all_rules({"GL012"}), root=FIXTURES,
        index_rules=all_index_rules({"GL012"}), index_cache="")
    assert [f.code for f in both] == ["GL012", "GL012"]
    with pytest.raises(ValueError):
        all_index_rules({"GL099.inter"})


def test_suppression_covers_indexed_layer(tmp_path):
    src = open(os.path.join(FIXTURES, "gl012_inter_fire.py")).read()
    src = src.replace(
        "self._table[key] = self._read_disk(path)  # GL012.inter",
        "self._table[key] = self._read_disk(path)  "
        "# graftlint: disable=blocking-under-lock")
    src = src.replace(
        "self._nap()  # GL012.inter",
        "self._nap()  # graftlint: disable=GL012")
    p = tmp_path / "suppressed_inter.py"
    p.write_text(src)
    findings = lint_paths([str(p)], all_rules(), root=str(tmp_path),
                          index_cache="")
    assert findings == []


# ------------------------------------------------------ index cache

def test_index_cache_invalidation(tmp_path):
    a, b = tmp_path / "a.py", tmp_path / "b.py"
    a.write_text("def f():\n    return 1\n")
    b.write_text("def g():\n    return 2\n")
    cache = str(tmp_path / "cache.json")
    paths, root = [str(a), str(b)], str(tmp_path)

    idx = build_index(paths, root, cache_path=cache)
    assert sorted(idx.stats.extracted) == ["a.py", "b.py"]
    # clean re-run: everything served from the content-hash cache
    idx = build_index(paths, root, cache_path=cache)
    assert idx.stats.extracted == []
    assert sorted(idx.stats.cached) == ["a.py", "b.py"]
    # edit one file: only it re-extracts
    a.write_text("def f():\n    return 3\n")
    idx = build_index(paths, root, cache_path=cache)
    assert idx.stats.extracted == ["a.py"]
    assert idx.stats.cached == ["b.py"]


def test_index_cache_warm_run_same_findings(tmp_path):
    cache = str(tmp_path / "cache.json")
    fixture = os.path.join(FIXTURES, "gl009_inter_fire.py")
    cold = lint_paths([fixture], all_rules(), root=FIXTURES,
                      index_cache=cache)
    warm = lint_paths([fixture], all_rules(), root=FIXTURES,
                      index_cache=cache)
    assert [f.to_dict() for f in warm] == [f.to_dict() for f in cold]
    assert len(cold) == 2


# ------------------------------------------------------------ suppressions

def test_suppression_comments():
    assert lint_fixture("suppressed.py") == []


def test_suppression_file_level():
    src = ("# graftlint: disable-file=discarded-future\n"
           "def kick(f):\n"
           "    f.remote(1)\n")
    assert lint_source(src, "x.py", all_rules()) == []


def test_unsuppressed_twin_still_fires():
    src = "def kick(f):\n    f.remote(1)\n"
    findings = lint_source(src, "x.py", all_rules())
    assert [f.code for f in findings] == ["GL002"]


# ---------------------------------------------------------------- baseline

def test_baseline_round_trip(tmp_path):
    path = str(tmp_path / "baseline.json")
    findings = lint_fixture("gl002_fire.py")
    assert findings
    baseline_mod.save(path, findings)

    known = baseline_mod.load(path)
    assert len(known) == len(findings)
    new, baselined = baseline_mod.split(lint_fixture("gl002_fire.py"), known)
    assert new == [] and len(baselined) == len(findings)

    # a NEW violation is not absorbed by the baseline
    extra = lint_source("def go(f):\n    f.remote()\n", "new_file.py",
                        all_rules())
    new2, _ = baseline_mod.split(extra, known)
    assert [f.code for f in new2] == ["GL002"]


def test_baseline_fingerprint_survives_line_moves():
    src1 = "def kick(f):\n    f.remote(1)\n"
    src2 = "import os\n\n\ndef kick(f):\n    f.remote(1)\n"
    fp1 = lint_source(src1, "x.py", all_rules())[0].fingerprint()
    fp2 = lint_source(src2, "x.py", all_rules())[0].fingerprint()
    assert fp1 == fp2


def test_baseline_prune(tmp_path):
    path = str(tmp_path / "baseline.json")
    baseline_mod.save(path, lint_fixture("gl002_fire.py"))
    removed = baseline_mod.prune(path, [])  # everything got fixed
    assert removed == 2
    assert baseline_mod.load(path) == {}


# ------------------------------------------------------------------- CLI

def test_cli_list_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in ("GL001", "GL006", "GL012.inter", "GL013.inter",
                 "GL009.inter", "GL017"):
        assert code in out


def test_cli_explain_prints_chain(capsys):
    rc = main([os.path.join(FIXTURES, "gl012_inter_fire.py"),
               "--no-baseline", "--explain"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "    | " in out
    assert "blocks: time.sleep" in out


def test_cli_json_chain_field(capsys):
    rc = main([os.path.join(FIXTURES, "gl013_inter_fire.py"),
               "--no-baseline", "--json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert len(data["new"]) == 3
    assert all(f["chain"] for f in data["new"])
    rc = main([os.path.join(FIXTURES, "gl002_fire.py"),
               "--no-baseline", "--json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert all(f["chain"] == [] for f in data["new"])  # per-file layer


def test_cli_json_output(capsys):
    rc = main([os.path.join(FIXTURES, "gl002_fire.py"), "--no-baseline",
               "--json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert len(data["new"]) == 2
    assert data["baselined"] == []
    assert all(f["code"] == "GL002" for f in data["new"])


def test_cli_bad_path():
    assert main(["/nonexistent/nowhere.py"]) == 2


# ------------------------------------------------- the gate: clean package

def test_package_is_lint_clean_tier1():
    """ray_tpu/ has zero non-baselined findings, in pre-commit time.

    This is the PR gate the devtools exist for: new concurrency/SPMD
    violations fail here before they reach the runtime hot paths.
    """
    pkg = os.path.join(repo_root(), "ray_tpu")
    t0 = time.monotonic()
    findings = lint_paths([pkg], all_rules(), root=repo_root())
    elapsed = time.monotonic() - t0
    known = baseline_mod.load(default_baseline_path())
    new, _ = baseline_mod.split(findings, known)
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.render() for f in new)
    # pre-commit viability bar from the devtools charter
    assert elapsed < 10.0, f"full-package lint took {elapsed:.1f}s"


def test_committed_baseline_is_empty():
    """Burn-down complete: keep it that way (fix, don't baseline)."""
    assert baseline_mod.load(default_baseline_path()) == {}
