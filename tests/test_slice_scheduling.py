"""Multi-host TPU slice-bundle gang scheduling (VERDICT r2 item 1).

Reference parity: bundle gang placement over pod slices
(src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h:82-106),
slice identity from pod metadata (python/ray/_private/accelerators/
tpu.py:19-44), and the shared topology env across a train gang
(python/ray/train/_internal/backend_executor.py:306-322).

Fake hosts: Cluster nodelets with asserted TPU:4 + slice labels — the
reference's multi-node-on-one-box test strategy (SURVEY.md §4).
"""

import sys

import cloudpickle
import pytest

import ray_tpu

cloudpickle.register_pickle_by_value(sys.modules[__name__])
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import tpu as tpu_mod
from ray_tpu.util.placement_group import placement_group, remove_placement_group


def _slice_labels(slice_name, worker_id, pod_type="v4-16"):
    return {
        tpu_mod.SLICE_LABEL: slice_name,
        tpu_mod.WORKER_ID_LABEL: str(worker_id),
        tpu_mod.POD_TYPE_LABEL: pod_type,
        tpu_mod.TOPOLOGY_LABEL: "2x2x2",
    }


@pytest.fixture(scope="module")
def slice_cluster():
    """Two fake slices x two fake hosts, TPU:4 each (a v4-16 pair)."""
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    nodes = {}
    for sl in ("slice-a", "slice-b"):
        for wid in (0, 1):
            nl = c.add_node(num_cpus=4, num_tpus=4,
                            labels=_slice_labels(sl, wid))
            nodes[(sl, wid)] = nl.node_id.hex()
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c, nodes
    ray_tpu.shutdown()
    c.shutdown()


def _labels_by_node_hex():
    return {n["NodeID"]: n.get("Labels") or {} for n in ray_tpu.nodes()}


def test_strict_pack_gang_one_slice_worker_order(slice_cluster):
    """A 2x{TPU:4} STRICT_PACK gang = a slice bundle: both bundles on the
    hosts of ONE slice, bundle i on TPU_WORKER_ID i."""
    _, nodes = slice_cluster
    pg = placement_group([{"TPU": 4.0}, {"TPU": 4.0}],
                         strategy="STRICT_PACK")
    assert pg.wait(30)
    placed = pg._state()["nodes"]
    labels = _labels_by_node_hex()
    slices = {labels[nid][tpu_mod.SLICE_LABEL] for nid in placed}
    assert len(slices) == 1, f"gang crossed slices: {slices}"
    wids = [int(labels[nid][tpu_mod.WORKER_ID_LABEL]) for nid in placed]
    assert wids == [0, 1], f"bundle->worker-id order wrong: {wids}"
    remove_placement_group(pg)


def test_spread_gang_prefers_distinct_slices(slice_cluster):
    """SPREAD with TPU bundles puts one gang member per DCN domain."""
    pg = placement_group([{"TPU": 2.0}, {"TPU": 2.0}], strategy="SPREAD")
    assert pg.wait(30)
    placed = pg._state()["nodes"]
    labels = _labels_by_node_hex()
    slices = {labels[nid][tpu_mod.SLICE_LABEL] for nid in placed}
    assert len(slices) == 2, f"SPREAD stayed within one slice: {slices}"
    remove_placement_group(pg)


def test_slice_head_marker_resource(slice_cluster):
    """Worker 0 of each slice asserts TPU-{pod_type}-head (reference:
    accelerators/tpu.py marker resource) so one task targets each slice."""
    total = ray_tpu.cluster_resources()
    assert total.get("TPU-v4-16-head") == 2.0  # one per slice


def test_strict_pack_single_host_still_packs(slice_cluster):
    """A gang that fits one host must not be force-spread."""
    pg = placement_group([{"TPU": 2.0}, {"TPU": 2.0}],
                         strategy="STRICT_PACK")
    assert pg.wait(30)
    placed = pg._state()["nodes"]
    assert len(set(placed)) == 1
    remove_placement_group(pg)


# ---------------------------------------------------------------------------
# End-to-end: a JaxTrainer gang lands one worker per host of one slice
# with the slice-derived libtpu topology env.
# ---------------------------------------------------------------------------

def _probe_loop(config):
    import os

    import ray_tpu as rt
    from ray_tpu import train

    ctx = train.get_context()
    my_node = os.environ["RAY_TPU_NODE_ID"]
    labels = {n["NodeID"]: n.get("Labels") or {} for n in rt.nodes()}[my_node]
    # slice-derived worker id, not join order
    assert os.environ["TPU_WORKER_ID"] == labels["ray.io/tpu-worker-id"], (
        os.environ["TPU_WORKER_ID"], labels)
    hostnames = os.environ["TPU_WORKER_HOSTNAMES"].split(",")
    assert len(hostnames) == ctx.get_world_size()
    assert os.environ["TPU_ACCELERATOR_TYPE"] == "v4-16"
    assert os.environ["TPU_NAME"] == labels["ray.io/tpu-slice"]
    assert ctx.get_local_world_size() == 1  # one worker per host
    train.report({
        "rank": ctx.get_world_rank(),
        "tpu_worker_id": int(os.environ["TPU_WORKER_ID"]),
        "node_rank": ctx.get_node_rank(),
    })


def test_trainer_gang_slice_topology(slice_cluster, tmp_path):
    from ray_tpu.train import (
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )

    trainer = JaxTrainer(
        _probe_loop,
        train_loop_config={},
        scaling_config=ScalingConfig(
            num_workers=2,
            use_tpu=True,
            resources_per_worker={"CPU": 1.0, "TPU": 4.0},
            placement_strategy="STRICT_PACK",
            num_cpu_devices_per_worker=1,
        ),
        run_config=RunConfig(name="slice_gang", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    # rank i landed on slice worker i (bundle->worker-id order)
    assert result.metrics_history[0]["tpu_worker_id"] == \
        result.metrics_history[0]["rank"]
