"""RL-for-LLMs flywheel tests: trajectory schema, GRPO math, the
drain-free weight hot-swap contract, and rollout-logprob determinism.

The hot-swap gates are THE correctness tests of this subsystem:

- 8 concurrent streams receive `update_weights` mid-generation — zero
  streams drop, the swap never lands inside a decode step (entry/exit
  weight-version of every runner call match), and every emitted
  trajectory's version tags split cleanly at the swap boundary;
- a non-stale trajectory's rollout logprobs are reproduced by a
  teacher-forced forward at the tagged version (atol 2e-4, f32) — the
  determinism contract the GRPO importance ratios rely on.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt2
from ray_tpu.rllib.llm import (
    DigitSumTask,
    FlywheelConfig,
    LLMLearner,
    LLMLearnerConfig,
    RLFlywheel,
    RolloutConfig,
    RolloutWorker,
    SortTask,
    Trajectory,
    group_relative_advantages,
    to_train_batch,
)
from ray_tpu.serve.llm import EngineConfig, LLMEngine, SamplingParams


def _tiny_cfg(vocab=64):
    return gpt2.GPT2Config(
        vocab_size=vocab, n_layer=1, n_head=2, n_embd=32,
        block_size=64, vocab_pad_multiple=64, dtype=jnp.float32,
        remat=False)


def _engine(cfg, params=None, *, num_blocks=128, max_batch_size=8,
            max_model_len=48, prefix_cache=True, seed=0):
    return LLMEngine(EngineConfig(
        model="gpt2", model_config=cfg, block_size=4,
        num_blocks=num_blocks, max_model_len=max_model_len,
        max_batch_size=max_batch_size, prefill_chunk_size=8,
        enable_prefix_cache=prefix_cache, seed=seed), params=params)


def _drive_all(engine, streams, timeout=120.0):
    deadline = time.monotonic() + timeout
    while any(s.final() is None for s in streams):
        if not engine.step():
            time.sleep(0.001)
        assert time.monotonic() < deadline, "engine stalled"
    return [s.final() for s in streams]


# ----------------------------------------------------- trajectory schema


def test_trajectory_from_final_and_batch_layout():
    final = {"done": True, "token_ids": [5, 6], "logprobs": [-1.0, -2.0],
             "weight_version": 3, "weight_versions": [3], "stale": False,
             "cached_tokens": 4, "finish_reason": "length"}
    t = Trajectory.from_final([1, 2, 3], final, reward=1.0, group_id=7,
                              temperature=1.0)
    assert (t.tokens, t.weight_version, t.stale) == ([5, 6], 3, False)
    batch = to_train_batch([t], np.asarray([0.5], np.float32),
                           max_len=64)
    # inputs[t] predicts targets[t]; mask covers exactly the generated
    # targets: positions 2,3 (targets 5,6 after prompt [1,2,3])
    assert batch["inputs"].shape == batch["targets"].shape
    assert batch["inputs"][0, :4].tolist() == [1, 2, 3, 5]
    assert batch["targets"][0, :4].tolist() == [2, 3, 5, 6]
    assert batch["mask"][0].sum() == 2 and batch["mask"][0, 2] == 1 \
        and batch["mask"][0, 3] == 1
    assert batch["old_logprobs"][0, 2] == -1.0
    assert batch["advantages"][0] == 0.5

    with pytest.raises(ValueError):
        Trajectory.from_final([1], {"token_ids": [2], "weight_version": 0,
                                    "weight_versions": [0],
                                    "stale": False},
                              reward=0, group_id=0, temperature=1.0)


def test_group_relative_advantages():
    def tr(gid, r):
        return Trajectory([1], [2], [-1.0], r, 0, [0], False, gid, 1.0)

    trajs = [tr(0, 1.0), tr(0, 0.0), tr(1, 0.5), tr(1, 0.5)]
    adv = group_relative_advantages(trajs)
    assert adv[0] > 0 > adv[1]  # within-group contrast
    assert adv[2] == adv[3] == 0.0  # zero-variance group: no gradient
    assert abs(adv[0] + adv[1]) < 1e-5


def test_reward_tasks_are_verifiable():
    task = DigitSumTask()
    p = task.make_prompt(3, 9)
    assert p[:task.prefix_len] == task.prefix
    assert task.reward(p, [task.target(p)]) == 1.0
    assert task.reward(p, [task.digit_base + 5]) == pytest.approx(0.1)
    assert task.reward(p, [task.prefix_base]) == 0.0
    assert task.target(p) == task.digit_base + 2  # (3+9)%10

    sort = SortTask(k=3)
    sp = sort.make_prompt([4, 1, 2])
    want = [sort.digit_base + d for d in (1, 2, 4)]
    assert sort.reward(sp, want) == 1.0
    assert sort.reward(sp, want[:1]) == pytest.approx(1 / 3)


# ------------------------------------------------------- weight hot-swap


def test_hot_swap_8_streams_mid_generation():
    """The satellite gate: 8 concurrent streams, update_weights lands
    mid-generation. No stream drops, the swap never lands inside a
    device step, version tags split cleanly at the boundary."""
    cfg = _tiny_cfg()
    eng = _engine(cfg)
    # spy: a swap must never change the version while a decode program
    # is in flight (the no-mid-decode-step-version-mix contract)
    orig_decode = eng.runner.decode
    batches = []

    def spy(items):
        v_in = eng.weight_version
        out = orig_decode(items)
        assert eng.weight_version == v_in, \
            "weight swap landed inside a decode step"
        batches.append((v_in, len(items)))
        return out

    eng.runner.decode = spy
    rng = np.random.RandomState(0)
    sp = SamplingParams(max_tokens=16, logprobs=True)
    streams = [eng.add_request(rng.randint(1, 60, size=6).tolist(), sp)
               for _ in range(8)]
    for _ in range(12):  # all prefilled, several decode steps in
        eng.step()
    new_params = gpt2.init_gpt2(jax.random.PRNGKey(7), cfg)
    stats = eng.update_weights(1, new_params)
    assert stats["in_flight_streams"] == 8
    finals = _drive_all(eng, streams)

    assert all(f is not None and f["done"] for f in finals), \
        "a stream dropped across the swap"
    assert all(f["num_generated"] == 16 for f in finals)
    for f in finals:
        vers = f["weight_versions"]
        assert set(vers) <= {0, 1}
        # tokens are tagged in sample order: all v0 tokens precede v1
        assert f["stale"], "mid-generation swap must tag the stream"
    # every decode batch ran entirely on one version, both versions ran
    assert {v for v, _ in batches} == {0, 1}
    # per-token tags are non-decreasing within each stream
    for f in finals:
        # reconstruct per-token versions from the final tags: engine
        # also exposes them per token event; here use weight_versions
        assert f["weight_versions"] == sorted(set(f["weight_versions"]))


def test_hot_swap_rejects_non_increasing_version():
    cfg = _tiny_cfg()
    eng = _engine(cfg)
    p = gpt2.init_gpt2(jax.random.PRNGKey(1), cfg)
    eng.update_weights(3, p)
    with pytest.raises(ValueError, match="must increase"):
        eng.update_weights(3, p)
    with pytest.raises(ValueError, match="must increase"):
        eng.update_weights(1, p)
    assert eng.weight_version == 3


def test_hot_swap_invalidates_prefix_cache():
    """Old-weight KV must never be matched after a swap: the same
    prompt that prefix-hit before the swap re-prefills after it."""
    cfg = _tiny_cfg()
    eng = _engine(cfg)
    prompt = list(range(1, 13))  # 3 full pages
    sp = SamplingParams(max_tokens=2)
    eng.generate(prompt, sp, drive=True)
    warm = eng.generate(prompt, sp, drive=True)
    assert warm["cached_tokens"] > 0  # pages parked + matched
    eng.update_weights(1, gpt2.init_gpt2(jax.random.PRNGKey(7), cfg))
    assert eng.pool.stats()["registered"] == 0
    cold = eng.generate(prompt, sp, drive=True)
    assert cold["cached_tokens"] == 0, \
        "post-swap admission matched stale KV"
    assert not cold["stale"]  # fully sampled at v1: consistent
    rewarm = eng.generate(prompt, sp, drive=True)
    assert rewarm["cached_tokens"] > 0  # v1 pages are shareable again


def test_swap_concurrent_with_step_loop_thread():
    """update_weights from a foreign thread while a loop thread steps:
    the step lock serializes them (the deployment shape)."""
    cfg = _tiny_cfg()
    eng = _engine(cfg)
    sp = SamplingParams(max_tokens=24, logprobs=True)
    rng = np.random.RandomState(1)
    streams = [eng.add_request(rng.randint(1, 60, size=5).tolist(), sp)
               for _ in range(4)]
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            if not eng.step():
                time.sleep(0.001)

    th = threading.Thread(target=loop, daemon=True)
    th.start()
    try:
        deadline = time.monotonic() + 60
        # wait until generation is genuinely under way
        while eng.stats()["running"] == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.002)
        stats = eng.update_weights(
            1, gpt2.init_gpt2(jax.random.PRNGKey(9), cfg))
        finals = []
        for s in streams:
            while s.final() is None:
                assert time.monotonic() < deadline, "stream stalled"
                time.sleep(0.002)
            finals.append(s.final())
    finally:
        stop.set()
        th.join(timeout=10)
    assert stats["version"] == 1
    assert all(f["done"] and f["num_generated"] == 24 for f in finals)


# ------------------------------------------------- logprob determinism


def test_rollout_logprobs_match_teacher_forced_at_tagged_version():
    """Determinism contract: a non-stale trajectory's logprobs are
    reproduced by a teacher-forced forward at the tagged version —
    before AND after a hot-swap (each at its own version's params)."""
    cfg = _tiny_cfg()
    task = DigitSumTask()
    learner = LLMLearner("gpt2", cfg,
                         config=LLMLearnerConfig(temperature=1.0), seed=0)
    w0 = learner.get_weights()
    eng = _engine(cfg, params=w0)
    worker = RolloutWorker(
        engine=eng, reward_fn=task.reward,
        config=RolloutConfig(group_size=4, max_tokens=4, temperature=1.0))
    prompts = [task.make_prompt(2, 5), task.make_prompt(9, 9)]
    trajs = worker.rollout(prompts)
    assert len(trajs) == 8
    for t in trajs:
        assert not t.stale and t.weight_version == 0
        got = learner.teacher_forced_logprobs(t, params=w0)
        np.testing.assert_allclose(got, t.logprobs, atol=2e-4)
        assert t.cached_tokens >= 0
    # the shared task prefix rode the prefix cache: after group 1's
    # first admission, later rollouts matched pages
    assert eng.stats()["prefix_hit_pages"] > 0

    # swap to fresh params, roll again: v1 trajectories reproduce at
    # the NEW params, and verifiably NOT at the old ones
    w1 = jax.tree.map(lambda a: np.asarray(a), gpt2.init_gpt2(
        jax.random.PRNGKey(11), cfg))
    eng.update_weights(1, w1)
    t1 = worker.rollout([task.make_prompt(1, 3)])[0]
    assert t1.weight_version == 1 and not t1.stale
    np.testing.assert_allclose(
        learner.teacher_forced_logprobs(t1, params=w1), t1.logprobs,
        atol=2e-4)
    diff = np.abs(learner.teacher_forced_logprobs(t1, params=w0)
                  - np.asarray(t1.logprobs))
    assert diff.max() > 1e-3, "distinct params should disagree"


def test_greedy_rollout_logprobs_teacher_forced():
    """Greedy (temp 0) rollouts report the unscaled policy logprob of
    the argmax token; teacher-forced at τ=1 reproduces it."""
    cfg = _tiny_cfg()
    learner = LLMLearner("gpt2", cfg, seed=0)
    eng = _engine(cfg, params=learner.get_weights())
    task = DigitSumTask()
    worker = RolloutWorker(
        engine=eng, reward_fn=task.reward,
        config=RolloutConfig(group_size=2, max_tokens=3, temperature=0.0))
    (t, _) = worker.rollout([task.make_prompt(4, 4)])
    np.testing.assert_allclose(
        learner.teacher_forced_logprobs(t), t.logprobs, atol=2e-4)


# ------------------------------------------------------ staleness guard


def test_staleness_guard_drops_stale_and_old():
    cfg = _tiny_cfg()
    learner = LLMLearner("gpt2", cfg,
                         config=LLMLearnerConfig(max_staleness=1))
    learner.version = 3

    def tr(version, stale, r=1.0, gid=0):
        return Trajectory([1, 2], [3], [-1.0], r, version,
                          [version], stale, gid, 1.0)

    trajs = [tr(3, False), tr(2, False), tr(1, False), tr(3, True)]
    kept, dropped = learner.filter_stale(trajs)
    assert len(kept) == 2  # versions 3 and 2 (lag 0, 1)
    assert dropped == {"stale": 1, "too_old": 1}


def test_learner_rejects_temperature_mismatch():
    """Rollouts sampled at a different τ than the learner scales its
    logp by would silently bias every importance ratio — fail loud."""
    cfg = _tiny_cfg()
    learner = LLMLearner("gpt2", cfg,
                         config=LLMLearnerConfig(temperature=1.0))
    bad = Trajectory([1, 2], [3], [-1.0], 1.0, 0, [0], False, 0,
                     temperature=0.7)
    with pytest.raises(ValueError, match="temperature"):
        learner.update([bad])
    # greedy (τ=0) records the unscaled policy log-prob == effective
    # τ=1, so it composes with the default learner config
    ok = Trajectory([1, 2], [3], [-1.0], 1.0, 0, [0], False, 0,
                    temperature=0.0)
    assert learner.update([ok])["kept"] == 1


def test_learner_update_moves_policy_toward_reward():
    """One GRPO step must increase the probability of the rewarded
    completion relative to the unrewarded one (same prompt group)."""
    cfg = _tiny_cfg()
    learner = LLMLearner("gpt2", cfg,
                         config=LLMLearnerConfig(lr=5e-3), seed=0)
    prompt = [20, 21, 22, 5, 7]
    good, bad = [9], [3]

    def lp(tokens):
        t = Trajectory(prompt, tokens, [0.0], 0.0, 0, [0], False, 0, 1.0)
        return learner.teacher_forced_logprobs(t)[0]

    def mk(tokens, r):
        t = Trajectory(prompt, tokens, [lp(tokens)], r,
                       learner.version, [learner.version], False, 0, 1.0)
        return t

    before = lp(good) - lp(bad)
    metrics = learner.update([mk(good, 1.0), mk(bad, 0.0)])
    assert metrics["kept"] == 2 and metrics["version"] == 1
    after = lp(good) - lp(bad)
    assert after > before, "update did not prefer the rewarded tokens"


# ------------------------------------------------------- closed loop


def test_flywheel_closed_loop_smoke():
    """Rollout → stream → GRPO update → hot-swap, four laps in-process:
    versions advance in lockstep, probe streams survive every swap,
    prefix cache serves the shared task prefix."""
    cfg = _tiny_cfg()
    task = DigitSumTask()
    learner = LLMLearner(
        "gpt2", cfg, config=LLMLearnerConfig(lr=1e-2, temperature=1.0),
        seed=0)
    eng = _engine(cfg, params=learner.get_weights(), num_blocks=256)
    worker = RolloutWorker(
        engine=eng, reward_fn=task.reward,
        config=RolloutConfig(group_size=4, max_tokens=2, temperature=1.0))
    rng = np.random.RandomState(0)

    def prompt_fn(it):
        return [task.make_prompt(rng.randint(0, 10), rng.randint(0, 10))
                for _ in range(6)]

    fly = RLFlywheel(worker, learner, prompt_fn,
                     FlywheelConfig(swap_during_rollout=True))
    for lap in range(4):
        m = fly.iteration()
        assert m["kept"] > 0
        assert m["swap"]["version"] == m["version"] == lap + 1
        assert m["swap"]["probe_dropped"] == 0
        assert m["swap"]["probe_streams"] == 2
        # the swap provably landed with the probes mid-generation
        assert m["swap"]["in_flight_streams"] >= 1
    assert eng.stats()["weight_version"] == 4
    assert eng.stats()["prefix_hit_pages"] > 0
    # the rl_* metrics surfaced on the process metrics page
    from ray_tpu.util.metrics import prometheus_text

    page = prometheus_text()
    assert "rl_rollout_tokens_total" in page
    assert "rl_reward_mean" in page
    assert "rl_weight_swap_seconds" in page
    assert "rl_traj_staleness" in page


# -------------------------------------------- serve deployment hot-swap


@pytest.fixture(scope="module")
def rl_cluster():
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    serve.shutdown()
    ray_tpu.shutdown()
    c.shutdown()


def test_deployment_handle_update_weights_mid_generation(rl_cluster):
    """The tentpole's serving surface: a replica serving 8 concurrent
    token streams receives `DeploymentHandle.update_weights(version,
    ref)` (params through the object store) mid-generation — zero
    stream drops, the new version is live for subsequent requests."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import EngineConfig
    from ray_tpu.serve.llm.deployment import LLMServer

    cfg = _tiny_cfg()
    dep = serve.deployment(
        LLMServer, name="llm-rl", num_replicas=1,
        max_ongoing_requests=16, payload_affinity=True)
    app = dep.bind(
        EngineConfig(model="gpt2", model_config=cfg, block_size=4,
                     num_blocks=128, max_model_len=64, max_batch_size=8,
                     prefill_chunk_size=8),
        warmup=False)
    handle = serve.run(app, name="llm-rl")
    try:
        sh = handle.options(stream=True, generator_backpressure=128)
        rng = np.random.RandomState(3)
        n_req, n_tok = 8, 48
        gens = [sh.remote({"prompt": rng.randint(1, 60, size=4).tolist(),
                           "max_tokens": n_tok, "temperature": 1.0,
                           "logprobs": True})
                for _ in range(n_req)]
        results, errors = [None] * n_req, []
        started = threading.Barrier(n_req + 1, timeout=180)

        def consume(i, gen):
            try:
                events, waited = [], False
                for r in gen:
                    events.append(ray_tpu.get(r, timeout=120))
                    if not waited:
                        waited = True
                        started.wait()  # stream is live: swap may land
                results[i] = events
            except Exception as e:  # noqa: BLE001
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=consume, args=(i, g))
                   for i, g in enumerate(gens)]
        for t in threads:
            t.start()
        started.wait()  # every stream produced >= 1 token
        w1 = jax.tree.map(np.asarray,
                          gpt2.init_gpt2(jax.random.PRNGKey(7), cfg))
        swap = handle.update_weights(1, ray_tpu.put(w1))
        assert len(swap) == 1 and swap[0]["version"] == 1
        for t in threads:
            t.join(timeout=180)
        assert not errors, f"streams dropped across the swap: {errors}"
        for events in results:
            *toks, final = events
            assert final["done"] and final["num_generated"] == n_tok
            assert set(final["weight_versions"]) <= {0, 1}

        from ray_tpu.util.state import llm_status

        stats = llm_status("llm-rl")
        assert stats[0]["weight_version"] == 1
        # a fresh request runs (and is tagged) entirely on v1
        post = [ray_tpu.get(r, timeout=120) for r in sh.remote(
            {"prompt": [5, 6, 7], "max_tokens": 4, "logprobs": True})]
        assert post[-1]["weight_version"] == 1
        assert not post[-1]["stale"]
    finally:
        serve.delete("llm-rl")
