"""Llama-family model: RoPE/RMSNorm/SwiGLU/GQA correctness + SPMD.

Second model family (SURVEY.md §2.4 breadth) built TPU-first like
models/gpt2.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models.llama import (
    LlamaConfig,
    _rope,
    init_llama,
    llama_forward,
    llama_loss,
    llama_partition_rules,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_llama(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_forward_shape_and_finite(tiny):
    cfg, params = tiny
    toks = jnp.zeros((2, 16), jnp.int32)
    logits = jax.jit(lambda p, t: llama_forward(p, t, cfg))(params, toks)
    assert logits.shape == (2, 16, cfg.padded_vocab)
    assert np.isfinite(np.asarray(logits)).all()


def test_rope_preserves_norm_and_relative_shift():
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 2, 16))
    r = _rope(x, 10000.0)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(r), axis=-1), rtol=1e-5)
    # rotation at position 0 is the identity
    np.testing.assert_allclose(np.asarray(r[:, 0]), np.asarray(x[:, 0]),
                               rtol=1e-5)
    # RoPE is relative: q·k after rotation depends only on the offset
    q = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 1, 16))
    k = jax.random.normal(jax.random.PRNGKey(3), (1, 8, 1, 16))
    # place the same q,k content at different absolute positions
    qa = jnp.roll(q, 2, axis=1)
    ka = jnp.roll(k, 2, axis=1)
    dot1 = jnp.sum(_rope(q, 1e4)[0, 3, 0] * _rope(k, 1e4)[0, 1, 0])
    dot2 = jnp.sum(_rope(qa, 1e4)[0, 5, 0] * _rope(ka, 1e4)[0, 3, 0])
    np.testing.assert_allclose(float(dot1), float(dot2), rtol=1e-4)


def test_gqa_reduces_kv_params(tiny):
    cfg, params = tiny
    E, hd = cfg.n_embd, cfg.head_dim
    assert params["blocks"]["wk"].shape == (cfg.n_layer, E,
                                            cfg.n_kv_head * hd)
    assert params["blocks"]["wq"].shape == (cfg.n_layer, E, E)
    assert cfg.n_kv_head < cfg.n_head


def test_loss_decreases_under_training(tiny):
    cfg, params = tiny
    tx = optax.adam(1e-3)
    opt = tx.init(params)
    toks = jax.random.randint(jax.random.PRNGKey(4), (4, 33), 0,
                              cfg.vocab_size, jnp.int32)
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}

    @jax.jit
    def step(params, opt):
        loss, g = jax.value_and_grad(
            lambda p: llama_loss(p, batch, cfg))(params)
        up, opt = tx.update(g, opt)
        return optax.apply_updates(params, up), opt, loss

    losses = []
    for _ in range(8):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.1
    assert losses[0] == pytest.approx(np.log(cfg.vocab_size), rel=0.2)


def test_spmd_sharded_step_matches_single_device():
    """The sharded train step over an fsdp x tensor mesh computes the
    same loss as single-device execution (SPMD-equivalence)."""
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.spmd import (
        batch_shardings,
        init_sharded_state,
        make_train_step,
    )

    cfg = LlamaConfig.tiny()
    tx = optax.adamw(1e-3)
    toks = jax.random.randint(jax.random.PRNGKey(5), (8, 33), 0,
                              cfg.vocab_size, jnp.int32)
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    losses = {}
    for name, spec in (("single", MeshSpec(data=1)),
                       ("sharded", MeshSpec(data=2, fsdp=2, tensor=2))):
        devices = jax.devices()[:1] if name == "single" else jax.devices()[:8]
        mesh = build_mesh(spec, devices=devices)
        state = init_sharded_state(
            lambda: init_llama(jax.random.PRNGKey(0), cfg), tx, mesh,
            llama_partition_rules())
        b = jax.device_put(batch, batch_shardings(mesh, batch))
        step = make_train_step(lambda p, bb: llama_loss(p, bb, cfg), tx)
        with mesh:
            state, metrics = step(state, b)
        losses[name] = float(metrics["loss"])
    np.testing.assert_allclose(losses["single"], losses["sharded"],
                               rtol=1e-4)
