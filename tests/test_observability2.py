"""Round-3 auxiliary fixes: log streaming, trace propagation, fixed-point
resources, mid-run elastic scaling (VERDICT r2 weak items 4/7 + missing
item 10).

Reference parity: python/ray/_private/log_monitor.py:103 (log
streaming), ray/util/tracing/tracing_helper.py:34 (span propagation),
src/ray/common/scheduling/fixed_point.h (resource arithmetic),
train/v2/_internal/execution/scaling_policy/scaling_policy.py:26
(continuous scaling decisions).
"""

import sys
import time

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_log_streaming(cluster):
    """Worker stdout is tailable through the state API / nodelet
    (the `ray logs` + dashboard log-monitor capability)."""
    from ray_tpu.util import state

    @ray_tpu.remote(num_cpus=1)
    def noisy():
        print("hello-from-worker-log", flush=True)
        return 1

    assert ray_tpu.get(noisy.remote(), timeout=60) == 1
    node_id = ray_tpu.nodes()[0]["NodeID"]
    logs = state.list_logs(node_id)
    worker_logs = [l for l in logs if l["file"].startswith("worker-")]
    assert worker_logs, logs
    found = False
    ends = {}
    for lg in worker_logs:
        text, end = state.tail_log(node_id, lg["file"])
        ends[lg["file"]] = end
        if "hello-from-worker-log" in text:
            found = True
    assert found, "worker stdout not streamed"
    # incremental follow: offset at THIS file's end returns empty
    first = worker_logs[0]["file"]
    text2, _ = state.tail_log(node_id, first, offset=ends[first])
    assert text2 == ""


def test_trace_propagates_through_nested_tasks(cluster):
    """A task submitting a nested task carries the same trace_id; span
    parent links chain (OTel-style propagation)."""

    @ray_tpu.remote(num_cpus=0.1)
    def inner():
        from ray_tpu.core.api import _global_runtime

        return _global_runtime()._ctx.trace

    @ray_tpu.remote(num_cpus=0.1)
    def outer():
        from ray_tpu.core.api import _global_runtime

        my = _global_runtime()._ctx.trace
        child = ray_tpu.get(inner.remote(), timeout=60)
        return my, child

    my, child = ray_tpu.get(outer.remote(), timeout=60)
    assert my["trace_id"] == child["trace_id"]
    assert child["parent_id"] == my["span_id"]
    assert my["span_id"] != child["span_id"]


def test_fixed_point_resources_no_drift(cluster):
    """1000 acquire/release cycles of 0.1 CPU leave the ledger exactly
    whole (fixed_point.h semantics)."""
    from ray_tpu.core.nodelet import _fpq

    x = 4.0
    for _ in range(1000):
        x = _fpq(x - 0.1)
        x = _fpq(x + 0.1)
    assert x == 4.0
    # plain float arithmetic drifts; the quantized ledger must not
    y = 4.0
    for _ in range(1000):
        y = y - 0.1 + 0.1
    assert _fpq(y) == 4.0


def test_gcs_client_typed_accessors(cluster):
    """Typed accessor suite over the head (reference:
    src/ray/gcs/gcs_client/accessor.h:43-583)."""
    from ray_tpu.core.gcs_client import GcsClient

    gcs = GcsClient()
    nodes = gcs.get_all_node_info()
    assert nodes and nodes[0]["alive"] and "CPU" in nodes[0]["resources"]
    assert gcs.get_node_info(nodes[0]["node_id"])["address"]
    assert gcs.get_cluster_resources()["CPU"] >= 4.0

    assert gcs.internal_kv_put("k1", b"v1") is True
    assert gcs.internal_kv_put("k1", b"v2", overwrite=False) is False
    assert gcs.internal_kv_get("k1") == b"v1"
    assert "k1" in gcs.internal_kv_keys("k")
    assert gcs.internal_kv_del("k1") is True
    assert gcs.internal_kv_get("k1") is None

    @ray_tpu.remote(num_cpus=0)
    class A:
        def ping(self):
            return 1

    a = A.options(name="gcs_probe").remote()
    ray_tpu.get(a.ping.remote())
    actors = gcs.get_all_actor_info()
    assert any(x.get("name") == "gcs_probe" for x in actors)
    named = gcs.get_named_actor_info("gcs_probe")
    assert named.get("found")
    assert gcs.get_task_events(limit=10) is not None
    ray_tpu.kill(a)


def test_pubsub_long_poll_subscriber(cluster):
    """Long-poll subscription buffers messages while the subscriber is
    away (reference: per-subscriber mailboxes, pubsub/publisher.h:297)."""
    import threading

    from ray_tpu.core.api import _global_runtime

    rt = _global_runtime()
    head = rt.head_address
    sub = {"subscriber_id": "test-sub-1", "topics": ["custom"],
           "mode": "poll"}
    assert rt.client.call(head, "subscribe", sub, timeout=10)["subscribed"]
    # publish while NOT polling: messages buffer instead of dropping
    for i in range(3):
        rt.client.send_oneway(head, "publish",
                              {"topic": "custom", "data": {"i": i}})
    deadline = time.monotonic() + 10
    msgs = []
    while time.monotonic() < deadline and len(msgs) < 3:
        r = rt.client.call(head, "poll_messages",
                           {"subscriber_id": "test-sub-1", "timeout": 1.0},
                           timeout=30)
        msgs.extend(r["messages"])
    assert [m["data"]["i"] for m in msgs] == [0, 1, 2]

    # long-poll blocks until a message arrives
    got = {}

    def poll():
        got.update(rt.client.call(
            head, "poll_messages",
            {"subscriber_id": "test-sub-1", "timeout": 8.0}, timeout=30))

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.3)
    rt.client.send_oneway(head, "publish",
                          {"topic": "custom", "data": {"i": 99}})
    t.join(timeout=10)
    assert [m["data"]["i"] for m in got["messages"]] == [99]
    rt.client.call(head, "unsubscribe",
                   {"subscriber_id": "test-sub-1"}, timeout=10)


def test_state_list_objects_and_memory_summary(cluster):
    """state.list_objects covers worker-owned objects too (a borrower
    chain: driver owns the produced ref; the worker's own table shows
    during execution) and memory_summary aggregates stores."""
    import numpy as np

    from ray_tpu.util import state

    @ray_tpu.remote(num_cpus=0.1)
    def produce():
        return np.ones(512 * 1024, np.uint8)

    refs = [produce.remote() for _ in range(3)]
    ray_tpu.get(refs, timeout=60)
    objs = state.list_objects()
    ids = {o["object_id"] for o in objs}
    assert all(r.id.hex() in ids for r in refs)
    s = state.memory_summary()
    assert s["objects_total"] >= 3
    assert s["objects_bytes"] >= 3 * 512 * 1024
    assert any(n["store_bytes_allocated"] > 0 for n in s["nodes"])
    report = state.memory_report()
    assert "object store per node" in report and "owned objects" in report
