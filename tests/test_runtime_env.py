"""Runtime environments (reference model: python/ray/tests/
test_runtime_env*.py — env_vars + working_dir materialization)."""

import os

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_env_vars_applied(cluster):
    @ray_tpu.remote(num_cpus=0.1,
                    runtime_env={"env_vars": {"MY_FLAG": "hello42"}})
    def read_env():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "hello42"

    @ray_tpu.remote(num_cpus=0.1)
    def read_plain():
        return os.environ.get("MY_FLAG")

    # a worker WITHOUT the env must not see the variable (no pool mixing)
    assert ray_tpu.get(read_plain.remote(), timeout=60) is None


def test_working_dir_shipped(cluster, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "mymodule.py").write_text("MAGIC = 'from-working-dir'\n")
    (pkg / "data.txt").write_text("payload")

    @ray_tpu.remote(num_cpus=0.1, runtime_env={"working_dir": str(pkg)})
    def use_module():
        import mymodule  # importable from the materialized working_dir

        with open("data.txt") as f:  # cwd is the working_dir
            return mymodule.MAGIC, f.read()

    magic, payload = ray_tpu.get(use_module.remote(), timeout=60)
    assert magic == "from-working-dir"
    assert payload == "payload"


def test_actor_runtime_env(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_ENV": "yes"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_ENV")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == "yes"


def test_gated_plugins_actionable_error(cluster):
    """pip/uv/conda keep their reference field names but fail fast with
    an actionable message (installs impossible here) — the plugin seam
    exists for them (reference: runtime_env/pip.py, uv.py)."""
    with pytest.raises(Exception) as ei:
        @ray_tpu.remote(num_cpus=0.1, runtime_env={"pip": ["requests"]})
        def f():
            return 1

        ray_tpu.get(f.remote(), timeout=30)
    assert "working_dir/py_modules" in str(ei.value)


def test_unknown_keys_rejected(cluster):
    with pytest.raises(Exception) as ei:
        @ray_tpu.remote(num_cpus=0.1, runtime_env={"bogus_plugin": 1})
        def f():
            return 1

        ray_tpu.get(f.remote(), timeout=30)
    assert "unsupported" in str(ei.value)


# ---------------------------------------------------------------------------
# plugin layer (VERDICT r3 item 7): py_modules + custom plugin ordering
# ---------------------------------------------------------------------------

def test_py_modules_cross_worker_import(cluster, tmp_path):
    """A local package listed in py_modules is importable on every
    worker WITHOUT being the cwd (reference: py_modules.py:1)."""
    pkg = tmp_path / "shiplib"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("VERSION = 'shipped-1.2'\n")
    (pkg / "helper.py").write_text("def double(x):\n    return 2 * x\n")

    @ray_tpu.remote(num_cpus=0.1,
                    runtime_env={"py_modules": [str(pkg)]})
    def use_pkg():
        import shiplib
        from shiplib.helper import double

        return shiplib.VERSION, double(21), os.getcwd()

    version, val, cwd = ray_tpu.get(use_pkg.remote(), timeout=60)
    assert version == "shipped-1.2"
    assert val == 42
    assert "shiplib" not in cwd  # import path, not working dir


def test_py_modules_with_working_dir(cluster, tmp_path):
    """py_modules and working_dir compose: cwd comes from working_dir,
    imports resolve from both."""
    lib = tmp_path / "extralib"
    lib.mkdir()
    (lib / "__init__.py").write_text("NAME = 'extra'\n")
    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "local.py").write_text("WHERE = 'cwd'\n")

    @ray_tpu.remote(num_cpus=0.1,
                    runtime_env={"working_dir": str(wd),
                                 "py_modules": [str(lib)]})
    def both():
        import extralib
        import local

        return extralib.NAME, local.WHERE

    assert ray_tpu.get(both.remote(), timeout=60) == ("extra", "cwd")


def test_plugin_ordering_and_custom_plugin():
    """Plugins materialize in priority order against one shared context
    (reference: plugin.py priority ordering)."""
    from ray_tpu.core import runtime_env as rtenv

    calls = []

    class FirstPlugin(rtenv.RuntimeEnvPlugin):
        name = "test_first"
        priority = 1

        def validate(self, value):
            return value

        def materialize(self, value, ctx, session_dir, client, head):
            calls.append("first")
            ctx.env["ORDER"] = "first"

    class LastPlugin(rtenv.RuntimeEnvPlugin):
        name = "test_last"
        priority = 99

        def materialize(self, value, ctx, session_dir, client, head):
            calls.append("last")
            # later plugins see earlier contributions in the context
            ctx.env["ORDER"] = ctx.env["ORDER"] + "+last"

    rtenv.register_plugin(FirstPlugin())
    rtenv.register_plugin(LastPlugin())
    try:
        norm = rtenv.normalize({"test_last": True, "test_first": True},
                               client=None, head_address="")
        extra, cwd = rtenv.materialize(norm, "/tmp", None, "")
        assert calls == ["first", "last"]
        assert extra["ORDER"] == "first+last"
        assert cwd is None
    finally:
        rtenv.registered_plugins()  # leave registry clean for other tests
        rtenv._REGISTRY.pop("test_first", None)
        rtenv._REGISTRY.pop("test_last", None)


def test_plugin_validate_rejects_bad_values():
    from ray_tpu.core import runtime_env as rtenv

    with pytest.raises(ValueError):
        rtenv.normalize({"py_modules": ["/definitely/missing/dir"]},
                        client=None, head_address="")
    with pytest.raises(ValueError):
        rtenv.normalize({"env_vars": "notadict"}, client=None,
                        head_address="")
