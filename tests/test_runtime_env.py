"""Runtime environments (reference model: python/ray/tests/
test_runtime_env*.py — env_vars + working_dir materialization)."""

import os

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_env_vars_applied(cluster):
    @ray_tpu.remote(num_cpus=0.1,
                    runtime_env={"env_vars": {"MY_FLAG": "hello42"}})
    def read_env():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "hello42"

    @ray_tpu.remote(num_cpus=0.1)
    def read_plain():
        return os.environ.get("MY_FLAG")

    # a worker WITHOUT the env must not see the variable (no pool mixing)
    assert ray_tpu.get(read_plain.remote(), timeout=60) is None


def test_working_dir_shipped(cluster, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "mymodule.py").write_text("MAGIC = 'from-working-dir'\n")
    (pkg / "data.txt").write_text("payload")

    @ray_tpu.remote(num_cpus=0.1, runtime_env={"working_dir": str(pkg)})
    def use_module():
        import mymodule  # importable from the materialized working_dir

        with open("data.txt") as f:  # cwd is the working_dir
            return mymodule.MAGIC, f.read()

    magic, payload = ray_tpu.get(use_module.remote(), timeout=60)
    assert magic == "from-working-dir"
    assert payload == "payload"


def test_actor_runtime_env(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_ENV": "yes"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_ENV")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == "yes"


def test_unsupported_keys_rejected(cluster):
    with pytest.raises(Exception) as ei:
        @ray_tpu.remote(num_cpus=0.1, runtime_env={"pip": ["requests"]})
        def f():
            return 1

        ray_tpu.get(f.remote(), timeout=30)
    assert "unsupported" in str(ei.value)
