"""Runtime environments (reference model: python/ray/tests/
test_runtime_env*.py — env_vars + working_dir materialization)."""

import os

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_env_vars_applied(cluster):
    @ray_tpu.remote(num_cpus=0.1,
                    runtime_env={"env_vars": {"MY_FLAG": "hello42"}})
    def read_env():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "hello42"

    @ray_tpu.remote(num_cpus=0.1)
    def read_plain():
        return os.environ.get("MY_FLAG")

    # a worker WITHOUT the env must not see the variable (no pool mixing)
    assert ray_tpu.get(read_plain.remote(), timeout=60) is None


def test_working_dir_shipped(cluster, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "mymodule.py").write_text("MAGIC = 'from-working-dir'\n")
    (pkg / "data.txt").write_text("payload")

    @ray_tpu.remote(num_cpus=0.1, runtime_env={"working_dir": str(pkg)})
    def use_module():
        import mymodule  # importable from the materialized working_dir

        with open("data.txt") as f:  # cwd is the working_dir
            return mymodule.MAGIC, f.read()

    magic, payload = ray_tpu.get(use_module.remote(), timeout=60)
    assert magic == "from-working-dir"
    assert payload == "payload"


def test_actor_runtime_env(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_ENV": "yes"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_ENV")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == "yes"


def _make_demo_wheel(directory, name: str, version: str, body: str) -> str:
    """Hand-craft a minimal pure-python wheel (a .whl is a zip with
    dist-info metadata) — no network, no build backend needed."""
    import zipfile

    dist = f"{name}-{version}.dist-info"
    whl = os.path.join(str(directory), f"{name}-{version}-py3-none-any.whl")
    files = {
        f"{name}/__init__.py": body,
        f"{dist}/METADATA": (f"Metadata-Version: 2.1\nName: {name}\n"
                             f"Version: {version}\n"),
        f"{dist}/WHEEL": ("Wheel-Version: 1.0\nGenerator: test\n"
                          "Root-Is-Purelib: true\nTag: py3-none-any\n"),
    }
    record = "".join(f"{p},,\n" for p in files) + f"{dist}/RECORD,,\n"
    files[f"{dist}/RECORD"] = record
    with zipfile.ZipFile(whl, "w") as z:
        for p, content in files.items():
            z.writestr(p, content)
    return whl


def test_pip_env_e2e(cluster, tmp_path):
    """Real pip materialization (reference: runtime_env/pip.py): a task
    runs in a venv holding a package the driver process does NOT have,
    resolved offline from a local wheel source; a second worker (an
    actor) shares the cached env."""
    wheel_dir = tmp_path / "wheels"
    wheel_dir.mkdir()
    _make_demo_wheel(wheel_dir, "rtenv_demo_pkg", "0.1",
                     "VALUE = 42\n")
    with pytest.raises(ImportError):
        import rtenv_demo_pkg  # noqa: F401  (driver must not have it)

    env = {"pip": {"packages": ["rtenv_demo_pkg"],
                   "find_links": str(wheel_dir)}}

    @ray_tpu.remote(num_cpus=0.1, runtime_env=env)
    def use_pkg():
        import sys

        import rtenv_demo_pkg

        return rtenv_demo_pkg.VALUE, sys.prefix, os.getpid()

    val, prefix, pid1 = ray_tpu.get(use_pkg.remote(), timeout=120)
    assert val == 42
    assert "env_cache" in prefix  # interpreter IS the venv python

    @ray_tpu.remote(num_cpus=0.1, runtime_env=env)
    class PkgActor:
        def read(self):
            import rtenv_demo_pkg

            return rtenv_demo_pkg.VALUE, os.getpid()

    a = PkgActor.remote()
    val2, pid2 = ray_tpu.get(a.read.remote(), timeout=120)
    assert val2 == 42
    assert pid2 != pid1  # second worker process, same cached env
    ray_tpu.kill(a)


def test_uv_env_e2e(cluster, tmp_path):
    """The uv flavor of the env plugin (reference: runtime_env/uv.py)
    builds through the uv binary when present."""
    wheel_dir = tmp_path / "wheels"
    wheel_dir.mkdir()
    _make_demo_wheel(wheel_dir, "rtenv_uv_pkg", "0.2", "WHO = 'uv'\n")

    @ray_tpu.remote(num_cpus=0.1, runtime_env={
        "uv": {"packages": ["rtenv_uv_pkg"],
               "find_links": str(wheel_dir)}})
    def use_pkg():
        import rtenv_uv_pkg

        return rtenv_uv_pkg.WHO

    assert ray_tpu.get(use_pkg.remote(), timeout=120) == "uv"


def test_pip_without_wheel_source_actionable_error(cluster):
    """Zero-egress deployments need a local wheel source; the error
    says exactly that instead of a network failure."""
    with pytest.raises(Exception) as ei:
        @ray_tpu.remote(num_cpus=0.1, runtime_env={"pip": ["requests"]})
        def f():
            return 1

        ray_tpu.get(f.remote(), timeout=60)
    assert "find_links" in str(ei.value)


def test_unknown_keys_rejected(cluster):
    with pytest.raises(Exception) as ei:
        @ray_tpu.remote(num_cpus=0.1, runtime_env={"bogus_plugin": 1})
        def f():
            return 1

        ray_tpu.get(f.remote(), timeout=30)
    assert "unsupported" in str(ei.value)


# ---------------------------------------------------------------------------
# plugin layer (VERDICT r3 item 7): py_modules + custom plugin ordering
# ---------------------------------------------------------------------------

def test_py_modules_cross_worker_import(cluster, tmp_path):
    """A local package listed in py_modules is importable on every
    worker WITHOUT being the cwd (reference: py_modules.py:1)."""
    pkg = tmp_path / "shiplib"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("VERSION = 'shipped-1.2'\n")
    (pkg / "helper.py").write_text("def double(x):\n    return 2 * x\n")

    @ray_tpu.remote(num_cpus=0.1,
                    runtime_env={"py_modules": [str(pkg)]})
    def use_pkg():
        import shiplib
        from shiplib.helper import double

        return shiplib.VERSION, double(21), os.getcwd()

    version, val, cwd = ray_tpu.get(use_pkg.remote(), timeout=60)
    assert version == "shipped-1.2"
    assert val == 42
    assert "shiplib" not in cwd  # import path, not working dir


def test_py_modules_with_working_dir(cluster, tmp_path):
    """py_modules and working_dir compose: cwd comes from working_dir,
    imports resolve from both."""
    lib = tmp_path / "extralib"
    lib.mkdir()
    (lib / "__init__.py").write_text("NAME = 'extra'\n")
    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "local.py").write_text("WHERE = 'cwd'\n")

    @ray_tpu.remote(num_cpus=0.1,
                    runtime_env={"working_dir": str(wd),
                                 "py_modules": [str(lib)]})
    def both():
        import extralib
        import local

        return extralib.NAME, local.WHERE

    assert ray_tpu.get(both.remote(), timeout=60) == ("extra", "cwd")


def test_plugin_ordering_and_custom_plugin():
    """Plugins materialize in priority order against one shared context
    (reference: plugin.py priority ordering)."""
    from ray_tpu.core import runtime_env as rtenv

    calls = []

    class FirstPlugin(rtenv.RuntimeEnvPlugin):
        name = "test_first"
        priority = 1

        def validate(self, value):
            return value

        def materialize(self, value, ctx, session_dir, client, head):
            calls.append("first")
            ctx.env["ORDER"] = "first"

    class LastPlugin(rtenv.RuntimeEnvPlugin):
        name = "test_last"
        priority = 99

        def materialize(self, value, ctx, session_dir, client, head):
            calls.append("last")
            # later plugins see earlier contributions in the context
            ctx.env["ORDER"] = ctx.env["ORDER"] + "+last"

    rtenv.register_plugin(FirstPlugin())
    rtenv.register_plugin(LastPlugin())
    try:
        norm = rtenv.normalize({"test_last": True, "test_first": True},
                               client=None, head_address="")
        extra, cwd, _py = rtenv.materialize(norm, "/tmp", None, "")
        assert calls == ["first", "last"]
        assert extra["ORDER"] == "first+last"
        assert cwd is None
    finally:
        rtenv.registered_plugins()  # leave registry clean for other tests
        rtenv._REGISTRY.pop("test_first", None)
        rtenv._REGISTRY.pop("test_last", None)


def test_plugin_validate_rejects_bad_values():
    from ray_tpu.core import runtime_env as rtenv

    with pytest.raises(ValueError):
        rtenv.normalize({"py_modules": ["/definitely/missing/dir"]},
                        client=None, head_address="")
    with pytest.raises(ValueError):
        rtenv.normalize({"env_vars": "notadict"}, client=None,
                        head_address="")
