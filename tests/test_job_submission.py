"""Job submission tests (reference model: dashboard/modules/job/tests)."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.job_submission import JobStatus, JobSubmissionClient


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_submit_and_succeed(cluster):
    client = JobSubmissionClient(cluster.address)
    job_id = client.submit_job(
        entrypoint="echo hello-from-job && python -c 'print(6*7)'")
    status = client.wait_until_finished(job_id, timeout=120)
    assert status == JobStatus.SUCCEEDED
    logs = client.get_job_logs(job_id)
    assert "hello-from-job" in logs
    assert "42" in logs


def test_failed_job_reports_exit_code(cluster):
    client = JobSubmissionClient(cluster.address)
    job_id = client.submit_job(entrypoint="python -c 'import sys; sys.exit(3)'")
    status = client.wait_until_finished(job_id, timeout=120)
    assert status == JobStatus.FAILED
    assert "exit code 3" in client.get_job_info(job_id).message


def test_list_and_stop(cluster):
    client = JobSubmissionClient(cluster.address)
    job_id = client.submit_job(entrypoint="sleep 60", submission_id="longjob")
    import time

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if client.get_job_status(job_id) == JobStatus.RUNNING:
            break
        time.sleep(0.2)
    assert client.stop_job(job_id)
    status = client.wait_until_finished(job_id, timeout=60)
    assert status == JobStatus.STOPPED
    jobs = client.list_jobs()
    assert any(j.submission_id == "longjob" for j in jobs)
