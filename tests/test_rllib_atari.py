"""Atari-class RLlib stack: catalog/conv, connectors, pixel PPO, PER,
APPO, SAC, metrics (VERDICT r2 items 3/9).

Reference parity: rllib/core/models/catalog.py:33 (conv encoder choice),
rllib/connectors/connector_v2.py:31 (pipelines),
rllib/execution/segment_tree.py (PER), rllib/algorithms/appo, sac,
rllib/utils/metrics/metrics_logger.py.
"""

import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------- connectors

def test_frame_stack_connector():
    from ray_tpu.rllib.connectors import FrameStack

    fs = FrameStack(3)
    fs.reset(2)
    f1 = np.ones((2, 4, 4, 1), np.float32)
    out = fs(f1)
    assert out.shape == (2, 4, 4, 3)
    assert (out == 1).all()  # fresh stack repeats the first frame
    f2 = np.full((2, 4, 4, 1), 2, np.float32)
    out = fs(f2, dones=np.array([False, False]))
    assert (out[..., -1] == 2).all() and (out[..., 0] == 1).all()
    # env 0 done: its stack restarts from the reset frame
    f3 = np.full((2, 4, 4, 1), 3, np.float32)
    out = fs(f3, dones=np.array([True, False]))
    assert (out[0, ..., 0] == 3).all()  # reset stack
    assert (out[1, ..., 0] == 1).all()  # ongoing stack keeps history


def test_frame_stack_multichannel_layout():
    """Frame-major stacking: whole frames tile (np.tile), channels never
    interleave (c=2 regression for the np.repeat bug)."""
    from ray_tpu.rllib.connectors import FrameStack

    fs = FrameStack(2)
    fs.reset(1)
    f1 = np.zeros((1, 2, 2, 2), np.float32)
    f1[..., 0], f1[..., 1] = 1, 2  # channels A=1, B=2
    out = fs(f1)
    assert out.shape == (1, 2, 2, 4)
    np.testing.assert_array_equal(out[0, 0, 0], [1, 2, 1, 2])  # [A,B|A,B]
    f2 = np.zeros((1, 2, 2, 2), np.float32)
    f2[..., 0], f2[..., 1] = 3, 4
    out = fs(f2, dones=np.array([False]))
    np.testing.assert_array_equal(out[0, 0, 0], [1, 2, 3, 4])


def test_normalize_and_pipeline_shapes():
    from ray_tpu.rllib.connectors import default_env_to_module

    pipe = default_env_to_module((10, 10, 1), framestack=4)
    assert pipe.output_shape((10, 10, 1)) == (10, 10, 4)
    pipe.reset(3)
    obs = np.full((3, 10, 10, 1), 255, np.uint8)
    out = pipe(obs)
    assert out.dtype == np.float32 and out.max() == 1.0
    assert out.shape == (3, 10, 10, 4)
    vec = default_env_to_module((4,))
    assert vec.output_shape((4,)) == (4,)


def test_gae_learner_connector_matches_direct():
    from ray_tpu.rllib.connectors import GeneralAdvantageEstimation
    from ray_tpu.rllib.learner import compute_gae

    rng = np.random.RandomState(0)
    sample = {
        "rewards": rng.rand(8, 3).astype(np.float32),
        "values": rng.rand(8, 3).astype(np.float32),
        "dones": rng.rand(8, 3) > 0.8,
        "last_values": rng.rand(3).astype(np.float32),
    }
    out = GeneralAdvantageEstimation(0.99, 0.95)(sample)
    adv, tgt = compute_gae(sample["rewards"], sample["values"],
                           sample["dones"], sample["last_values"], 0.99, 0.95)
    np.testing.assert_allclose(out["advantages"], adv)
    np.testing.assert_allclose(out["value_targets"], tgt)


# ---------------------------------------------------------------- catalog

def test_catalog_picks_conv_for_images():
    import jax

    from ray_tpu.rllib.catalog import Catalog
    from ray_tpu.rllib.models import forward, init_actor_critic

    params = init_actor_critic(jax.random.PRNGKey(0), (10, 10, 2), 3)
    assert "conv" in params["encoder"]
    obs = np.zeros((5, 10, 10, 2), np.float32)
    logits, value = jax.jit(forward)(params, obs)
    assert logits.shape == (5, 3) and value.shape == (5,)
    # vector spaces get the MLP encoder
    vec = init_actor_critic(jax.random.PRNGKey(0), (8,), 4)
    assert "mlp" in vec["encoder"]
    assert Catalog.is_image((84, 84, 4)) and not Catalog.is_image((6,))


# ---------------------------------------------------------------- PER

def test_sum_tree_proportional_sampling():
    from ray_tpu.rllib.replay import SumTree

    t = SumTree(8)
    t.set(np.arange(4), [1.0, 2.0, 3.0, 4.0])
    assert t.total() == 10.0
    rng = np.random.default_rng(0)
    counts = np.zeros(8)
    for _ in range(200):
        idx = t.sample(rng.random(50) * t.total())
        np.add.at(counts, idx, 1)
    freq = counts[:4] / counts.sum()
    np.testing.assert_allclose(freq, [0.1, 0.2, 0.3, 0.4], atol=0.02)
    assert counts[4:].sum() == 0  # zero-mass leaves never sampled


def test_prioritized_buffer_priorities_shift_sampling():
    from ray_tpu.rllib.replay import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(64, alpha=1.0, seed=0)
    buf.add_batch({"x": np.arange(32, dtype=np.float32)})
    # boost priority of item 7 massively
    buf.update_priorities(np.array([7]), np.array([100.0]))
    batch = buf.sample(256)
    frac7 = float((batch["x"] == 7).mean())
    assert frac7 > 0.5, frac7
    # importance weights compensate: the over-sampled item carries a
    # smaller weight ((N*P)^-beta normalized; beta=0.4 default)
    assert batch["weights"].max() == 1.0
    w7 = batch["weights"][batch["x"] == 7]
    w_rest = batch["weights"][batch["x"] != 7]
    assert (w7 < 0.3).all() and (w_rest == 1.0).all()


def test_dqn_with_prioritized_replay_smoke():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.dqn import DQNConfig

    algo = DQNConfig().environment("CartPole-v1").training(
        prioritized_replay=True, num_steps_sampled_before_learning=200,
        updates_per_iteration=8, epsilon_decay_steps=2000).build()
    losses = []
    for _ in range(12):
        r = algo.train()
        if not np.isnan(r["learner/td_loss"]):
            losses.append(r["learner/td_loss"])
    algo.stop()
    assert losses, "no learner updates ran"


# ---------------------------------------------------------------- metrics

def test_metrics_logger_windows_and_lifetime():
    from ray_tpu.rllib.metrics import MetricsLogger

    m = MetricsLogger()
    for i in range(10):
        m.log_value("loss", float(i), window=4)
        m.log_value(("env", "steps"), 100, reduce="sum", window=None)
        m.log_value(("env", "return_max"), float(i), reduce="max")
    out = m.reduce()
    assert out["loss"] == pytest.approx(np.mean([6, 7, 8, 9]))
    assert out["env"]["steps"] == 1000
    assert out["env"]["return_max"] == 9.0
    assert m.peek(("env", "steps")) == 1000


# ---------------------------------------------------------------- pixel PPO

@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_ppo_pixel_env_with_learner_mesh():
    """PPO with the conv catalog + frame-stack connector LEARNS a pixel
    env, with the update jitted over a 4-device learner mesh (the
    BASELINE 'CartPole -> Atari-class' capability, num_learners=4)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.ppo import PPOConfig

    cfg = (PPOConfig().environment("PixelCatch-v0")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=32,
                        rollout_fragment_length=40)
           .learners(num_learners=4)
           .training(lr=2.5e-3, framestack=2, entropy_coeff=0.02,
                     num_sgd_iter=6, minibatch_size=256, gamma=0.95))
    algo = cfg.build()
    first, last = None, None
    for i in range(45):
        r = algo.train()
        if not np.isnan(r["episode_return_mean"]):
            if first is None:
                first = r["episode_return_mean"]
            last = r["episode_return_mean"]
    algo.stop()
    assert first is not None and last is not None
    assert last > 2.0, f"conv PPO failed to learn: first={first} last={last}"
    assert last > first + 2.0
    # hierarchical metrics recorded the run
    tree = algo.metrics.reduce()
    assert "learner" in tree and "env_runners" in tree


# ---------------------------------------------------------------- APPO

@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_appo_solves_cartpole():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.appo import APPOConfig

    algo = (APPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=64)
            .training(lr=1e-3, entropy_coeff=0.01, use_kl_loss=True)
            .build())
    import time

    t0 = time.time()
    best = -np.inf
    while time.time() - t0 < 220:
        r = algo.train()
        if not np.isnan(r["episode_return_mean"]):
            best = max(best, r["episode_return_mean"])
        if best > 150:
            break
    algo.stop()
    assert best > 150, f"APPO best return {best}"
    assert algo._appo_updates > 0


# ---------------------------------------------------------------- SAC

@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_sac_improves_on_pendulum():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.sac import SACConfig

    algo = SACConfig().training(
        seed=1, num_envs=4, rollout_fragment_length=16,
        updates_per_iteration=48,
        num_steps_sampled_before_learning=1000).build()
    early, late = [], []
    for i in range(160):
        r = algo.train()
        ret = r["episode_return_mean"]
        if not np.isnan(ret):
            (early if i < 60 else late).append(ret)
    algo.stop()
    assert np.mean(late[-20:]) > np.mean(early[:20]) + 300, \
        (np.mean(early[:20]), np.mean(late[-20:]))
    assert 0 < r["alpha"] < 1.0  # temperature auto-tuned down


# ---------------------------------------------------------------- checkpointable

def test_checkpointable_save_restore(tmp_path):
    """Uniform component-tree save/restore (reference:
    rllib/utils/checkpoints.py Checkpointable)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.ppo import PPOConfig

    cfg = (PPOConfig().environment("CartPole-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                        rollout_fragment_length=16))
    a = cfg.build()
    a.train()
    a.save_to_path(str(tmp_path / "ck"))
    b = (PPOConfig().environment("CartPole-v1")
         .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                      rollout_fragment_length=16)).build()
    b.restore_from_path(str(tmp_path / "ck"))
    assert b._iteration == a._iteration == 1
    wa = a.learner.get_weights()
    wb = b.learner.get_weights()
    la, lb = jax.tree_util.tree_leaves(wa), jax.tree_util.tree_leaves(wb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)
    a.stop()
    b.stop()
