"""Ray-Client-style remote drivers (VERDICT r3 missing item 5;
reference model: python/ray/util/client tests — tasks, actors, put/get,
refs as args, named actors, isolation between clients)."""

import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu import client as rc
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _settle(max_wait_s: float = 15.0):
    """Settle barrier (ROADMAP known flake): when this module runs
    right after test_chaos in half A, the chaos clusters' dying worker
    processes bleed CPU into our timing-sensitive wait tests on this
    throttled box. Give the load average a bounded chance to drop
    before booting the proxy cluster; an idle box passes straight
    through."""
    import os
    import time

    t0 = time.monotonic()
    target = max(1.5, 0.75 * (os.cpu_count() or 1))
    while time.monotonic() - t0 < max_wait_s:
        try:
            load1 = os.getloadavg()[0]
        except OSError:
            return
        if load1 < target:
            return
        time.sleep(1.0)


@pytest.fixture(scope="module")
def proxy():
    _settle()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    p = rc.start_client_server(c.address)
    # warm the worker pool through the proxy so the first timed test
    # never pays cold-start scheduling latency on a contended box
    warm = rc.connect(f"ray://{p.address}")
    try:
        warm.get(warm.put(1))
    finally:
        warm.disconnect()
    yield p
    p.stop()
    c.shutdown()


@pytest.fixture
def ctx(proxy):
    ctx = rc.connect(f"ray://{proxy.address}")
    yield ctx
    ctx.disconnect()


def test_remote_task_roundtrip(ctx):
    @ctx.remote(num_cpus=0.1)
    def add(a, b):
        return a + b

    assert ctx.get(add.remote(2, 3)) == 5


def test_put_get_and_refs_as_args(ctx):
    """The thin client has NO local store: values flow through the host
    (reference: client-mode object transport)."""
    ref = ctx.put(np.arange(10_000))
    assert int(ctx.get(ref).sum()) == 49995000

    @ctx.remote(num_cpus=0.1)
    def total(a):
        return int(a.sum())

    assert ctx.get(total.remote(ref)) == 49995000


def test_chained_task_refs(ctx):
    @ctx.remote(num_cpus=0.1)
    def double(x):
        return x * 2

    r = double.remote(double.remote(double.remote(1)))
    assert ctx.get(r) == 8


def test_actor_lifecycle(ctx):
    @ctx.remote(num_cpus=0.1)
    class Counter:
        def __init__(self, start):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote(10)
    assert ctx.get(c.inc.remote()) == 11
    assert ctx.get(c.inc.remote(5)) == 16
    ctx.kill(c)


def test_named_actor_from_client(ctx):
    @ctx.remote(num_cpus=0.1)
    class Store:
        def __init__(self):
            self.v = "hello"

        def read(self):
            return self.v

    Store.options(name="client-named").remote()
    h = ctx.get_actor("client-named")
    assert ctx.get(h.read.remote()) == "hello"
    ctx.kill(h)


def test_wait(ctx):
    import time as _t

    @ctx.remote(num_cpus=0.1)
    def slow(t):
        _t.sleep(t)
        return t

    # budgets widened from (5s task, 10s window) per the ROADMAP flake
    # note: the slow task must outlast the whole wait window so it is
    # still pending when wait returns, but stay bounded — its worker
    # keeps sleeping after this test, and an over-long pin would bleed
    # into the next test's pool exactly like the stale-lease wedge did
    fast, slow_ref = slow.remote(0.05), slow.remote(15)
    ready, pending = ctx.wait([fast, slow_ref], num_returns=1, timeout=12)
    assert ready == [fast] and pending == [slow_ref]


def test_two_clients_isolated_hosts(proxy):
    """Each client gets its OWN server-side driver (reference:
    proxier.py one SpecificServer per client)."""
    a = rc.connect(proxy.address)
    b = rc.connect(proxy.address)
    try:
        assert a._host != b._host
        ra = a.put("from-a")
        assert a.get(ra) == "from-a"

        @b.remote(num_cpus=0.1)
        def who():
            return "b"

        assert b.get(who.remote()) == "b"
    finally:
        a.disconnect()
        b.disconnect()
