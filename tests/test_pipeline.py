"""Pipeline parallelism vs sequential stage application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import ops
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.pipeline import pipeline_apply


@pytest.fixture(scope="module")
def pipe_mesh():
    return build_mesh(MeshSpec(data=2, pipe=4, tensor=1))


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _stacked_params(key, S, d):
    ks = jax.random.split(key, S)
    return {
        "w": jnp.stack([jax.random.normal(k, (d, d)) * 0.5 for k in ks]),
        "b": jnp.zeros((S, d)),
    }


def _sequential(params, x, S):
    h = x
    for i in range(S):
        h = _stage_fn(jax.tree.map(lambda a: a[i], params), h)
    return h


def test_pipeline_matches_sequential(pipe_mesh):
    S, d, B = 4, 8, 16
    params = _stacked_params(jax.random.PRNGKey(0), S, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, d))

    piped = ops.shard_map(
        lambda p, xx: pipeline_apply(
            lambda q, h: _stage_fn(jax.tree.map(lambda a: a[0], q), h),
            p, xx, "pipe"),
        pipe_mesh,
        in_specs=(P("pipe"), P()),
        out_specs=P())
    out = piped(params, x)
    ref = _sequential(params, x, S)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_more_microbatches(pipe_mesh):
    S, d, B = 4, 8, 32
    params = _stacked_params(jax.random.PRNGKey(2), S, d)
    x = jax.random.normal(jax.random.PRNGKey(3), (B, d))
    piped = ops.shard_map(
        lambda p, xx: pipeline_apply(
            lambda q, h: _stage_fn(jax.tree.map(lambda a: a[0], q), h),
            p, xx, "pipe", num_microbatches=8),
        pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P())
    np.testing.assert_allclose(np.asarray(piped(params, x)),
                               np.asarray(_sequential(params, x, S)),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_differentiable(pipe_mesh):
    S, d, B = 4, 4, 8
    params = _stacked_params(jax.random.PRNGKey(4), S, d)
    x = jax.random.normal(jax.random.PRNGKey(5), (B, d))

    piped = ops.shard_map(
        lambda p, xx: pipeline_apply(
            lambda q, h: _stage_fn(jax.tree.map(lambda a: a[0], q), h),
            p, xx, "pipe"),
        pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P())

    g1 = jax.grad(lambda p: jnp.sum(piped(p, x) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(_sequential(p, x, S) ** 2))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)
