"""Pipeline parallelism vs sequential stage application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import ops
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.pipeline import pipeline_apply


@pytest.fixture(scope="module")
def pipe_mesh():
    return build_mesh(MeshSpec(data=2, pipe=4, tensor=1))


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _stacked_params(key, S, d):
    ks = jax.random.split(key, S)
    return {
        "w": jnp.stack([jax.random.normal(k, (d, d)) * 0.5 for k in ks]),
        "b": jnp.zeros((S, d)),
    }


def _sequential(params, x, S):
    h = x
    for i in range(S):
        h = _stage_fn(jax.tree.map(lambda a: a[i], params), h)
    return h


def test_pipeline_matches_sequential(pipe_mesh):
    S, d, B = 4, 8, 16
    params = _stacked_params(jax.random.PRNGKey(0), S, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, d))

    piped = ops.shard_map(
        lambda p, xx: pipeline_apply(
            lambda q, h: _stage_fn(jax.tree.map(lambda a: a[0], q), h),
            p, xx, "pipe"),
        pipe_mesh,
        in_specs=(P("pipe"), P()),
        out_specs=P())
    out = piped(params, x)
    ref = _sequential(params, x, S)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_more_microbatches(pipe_mesh):
    S, d, B = 4, 8, 32
    params = _stacked_params(jax.random.PRNGKey(2), S, d)
    x = jax.random.normal(jax.random.PRNGKey(3), (B, d))
    piped = ops.shard_map(
        lambda p, xx: pipeline_apply(
            lambda q, h: _stage_fn(jax.tree.map(lambda a: a[0], q), h),
            p, xx, "pipe", num_microbatches=8),
        pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P())
    np.testing.assert_allclose(np.asarray(piped(params, x)),
                               np.asarray(_sequential(params, x, S)),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_differentiable(pipe_mesh):
    S, d, B = 4, 4, 8
    params = _stacked_params(jax.random.PRNGKey(4), S, d)
    x = jax.random.normal(jax.random.PRNGKey(5), (B, d))

    piped = ops.shard_map(
        lambda p, xx: pipeline_apply(
            lambda q, h: _stage_fn(jax.tree.map(lambda a: a[0], q), h),
            p, xx, "pipe"),
        pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P())

    g1 = jax.grad(lambda p: jnp.sum(piped(p, x) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(_sequential(p, x, S) ** 2))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)


# ------------------------------------------- interleaved (1F1B-class)


def test_interleaved_matches_sequential(pipe_mesh):
    """Circular schedule with R virtual stages per device == applying
    all S*R stages in order (round-robin placement reorder)."""
    from ray_tpu.parallel.pipeline import pipeline_apply_interleaved

    S, R, d, B = 4, 2, 8, 16
    V = S * R
    params = _stacked_params(jax.random.PRNGKey(3), V, d)
    x = jax.random.normal(jax.random.PRNGKey(4), (B, d))
    ref = _sequential(params, x, V)
    order = np.argsort(np.arange(V) % S, kind="stable")
    rr = jax.tree.map(lambda a: a[order], params)
    out = jax.jit(ops.shard_map(
        lambda p, xx: pipeline_apply_interleaved(
            _stage_fn, p, xx, "pipe", num_microbatches=8, num_repeats=R),
        pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P()))(rr, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=1e-6)


def test_interleaved_differentiable(pipe_mesh):
    from ray_tpu.parallel.pipeline import pipeline_apply_interleaved

    S, R, d, B = 4, 2, 8, 8
    V = S * R
    params = _stacked_params(jax.random.PRNGKey(5), V, d)
    order = np.argsort(np.arange(V) % S, kind="stable")
    rr = jax.tree.map(lambda a: a[order], params)
    x = jax.random.normal(jax.random.PRNGKey(6), (B, d))

    def loss(p):
        out = ops.shard_map(
            lambda pp, xx: pipeline_apply_interleaved(
                _stage_fn, pp, xx, "pipe", num_microbatches=4,
                num_repeats=R),
            pipe_mesh, in_specs=(P("pipe"), P()), out_specs=P())(p, x)
        return jnp.mean(out ** 2)

    g = jax.jit(jax.grad(loss))(rr)
    flat = jax.tree.leaves(jax.tree.map(np.asarray, g))
    assert all(np.isfinite(a).all() for a in flat)
    assert any(np.abs(a).sum() > 0 for a in flat)


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="partial-auto shard_map on jax<0.5 lowers to a PartitionId "
           "op XLA:CPU cannot SPMD-partition")
def test_pipelined_transformer_hybrid_mesh():
    """Multi-stage transformer (ring attention over fsdp inside the
    blocks, interleaved pipeline over pipe, tensor/dcn left to GSPMD):
    two SGD steps reduce the loss on an 8-device hybrid mesh."""
    from jax.sharding import NamedSharding

    from ray_tpu.models.pipelined import (
        PipelinedConfig,
        init_pipelined,
        pipelined_shardings,
        pipelined_train_step,
    )

    mesh = build_mesh(MeshSpec(dcn=2, pipe=2, fsdp=2, tensor=1))
    cfg = PipelinedConfig()
    params = init_pipelined(jax.random.PRNGKey(0), cfg)
    params = jax.device_put(params, pipelined_shardings(params, cfg, mesh))
    rng = np.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size,
                       (8, cfg.block_size + 1)).astype(np.int32)
    batch = jax.device_put(
        {"tokens": jnp.asarray(toks[:, :-1]),
         "targets": jnp.asarray(toks[:, 1:])},
        NamedSharding(mesh, P(("dcn", "data"),)))
    step = pipelined_train_step(cfg, mesh)
    with mesh:
        p1, l1 = step(params, batch)
        _, l2 = step(p1, batch)
    assert float(l2) < float(l1)
