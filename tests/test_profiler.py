"""Profiler plane (ISSUE 12): in-process stack sampler, cluster-wide
`profile` capture fan-out, per-task CPU attribution, memory
attribution + the stranded-ref auditor, and the watchtower rule that
pages on stranded bytes."""

import gc
import os
import sys
import threading
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.util import profiler

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------------------
# sampler units (no cluster)
# ---------------------------------------------------------------------------

def _p5_leaf(stop):
    while not stop.is_set():
        sum(range(64))


def _p5_mid(stop):
    _p5_leaf(stop)


def test_sampler_captures_stacks_root_first():
    stop = threading.Event()
    t = threading.Thread(target=_p5_mid, args=(stop,), daemon=True)
    t.start()
    s = profiler.StackSampler(hz=200).start()
    time.sleep(0.4)
    s.stop()
    stop.set()
    t.join(timeout=5)
    assert s.samples >= 10
    stacks = s.collapsed()
    hits = [k for k in stacks if ":_p5_mid" in k and ":_p5_leaf" in k]
    assert hits, f"busy thread's stack missing from {list(stacks)[:5]}"
    # root-first: the caller appears before the callee in every hit
    for k in hits:
        assert k.index(":_p5_mid") < k.index(":_p5_leaf")
    # the sampler excludes its own thread
    assert not any("stack-sampler" in k or "_run" in k.split(";")[-1]
                   for k in stacks if "profiler.py" in k.split(";")[-1])


def test_sampler_unique_stack_cap_counts_drops():
    stops = [threading.Event() for _ in range(3)]
    fns = [_p5_leaf, _p5_mid,
           lambda st: [time.sleep(0.01) for _ in iter(lambda: st.is_set(), True)]]
    threads = [threading.Thread(target=f, args=(st,), daemon=True)
               for f, st in zip(fns, stops)]
    for t in threads:
        t.start()
    s = profiler.StackSampler(hz=100, max_unique_stacks=1).start()
    time.sleep(0.3)
    s.stop()
    for st in stops:
        st.set()
    assert len(s.collapsed()) == 1  # the cap held
    assert s.stacks_dropped > 0  # and the overflow was COUNTED


def test_sampler_dormant_and_armed_overhead_gate():
    # dormant: no sampler thread exists at all
    assert not any(t.name == "stack-sampler"
                   for t in threading.enumerate())
    stop = threading.Event()
    t = threading.Thread(target=_p5_leaf, args=(stop,), daemon=True)
    t.start()
    window = 1.0
    s = profiler.StackSampler().start()  # default 25Hz
    time.sleep(window)
    s.stop()
    stop.set()
    t.join(timeout=5)
    # the overhead contract: the sampler's own measured CPU cost stays
    # under 2% of the armed window (thread_time is deterministic under
    # cgroup throttling, unlike a wall-clock A/B on this box)
    assert s.cpu_seconds < 0.02 * window, (
        f"sampler burned {s.cpu_seconds:.4f}s CPU in a {window}s window")
    # and dormant again after the window
    assert not any(th.name == "stack-sampler"
                   for th in threading.enumerate())


def test_collapsed_merge_prefix_text_and_chrome():
    a = {"f1;f2": 3, "f1;f3": 1}
    b = {"f1;f2": 2}
    merged = profiler.merge_collapsed([
        profiler.prefix_stacks(a, "node:n1;proc:w1"),
        profiler.prefix_stacks(b, "node:n1;proc:w1"),
        profiler.prefix_stacks(b, "node:n2;proc:w2"),
    ])
    assert merged["node:n1;proc:w1;f1;f2"] == 5  # identical stacks sum
    assert merged["node:n2;proc:w2;f1;f2"] == 2
    text = profiler.collapsed_text(merged)
    lines = text.strip().splitlines()
    assert lines[0] == "node:n1;proc:w1;f1;f2 5"  # heaviest first
    assert all(" " in ln and ln.rsplit(" ", 1)[1].isdigit()
               for ln in lines)
    events = profiler.collapsed_to_chrome(merged, hz=25.0)
    xs = [e for e in events if e.get("ph") == "X"]
    assert len(xs) == 3
    heavy = [e for e in xs if e["args"]["samples"] == 5]
    assert len(heavy) == 1
    assert heavy[0]["args"]["stack"] == "f1;f2"
    assert heavy[0]["dur"] == pytest.approx(5 * 1e6 / 25.0)
    # node split into pids, procs into tids, named by metadata
    metas = [e for e in events if e.get("ph") == "M"]
    assert {m["args"]["name"] for m in metas
            if m["name"] == "process_name"} == {"node:n1", "node:n2"}


def test_capture_to_file_noop_when_unarmed(tmp_path):
    before = set(threading.enumerate())
    with profiler.capture_to_file(None) as s:
        assert s is None
        assert set(threading.enumerate()) == before  # nothing spawned
    path = str(tmp_path / "x.collapsed")
    with profiler.capture_to_file(path, hz=100):
        time.sleep(0.1)
    with open(path) as f:
        assert f.read()  # something was written


# ---------------------------------------------------------------------------
# watchtower: the stranded-refs rule
# ---------------------------------------------------------------------------

def test_stranded_watchtower_rule_fires_on_synthetic_leak():
    from ray_tpu.util.watchtower import Watchtower, default_rules

    rules = {r.name: r for r in default_rules()}
    rule = rules["object-stranded-refs"]
    assert rule.metric == "object_store_stranded_bytes"
    cur = {"v": 0.0}
    wt = Watchtower(
        lambda: f'object_store_stranded_bytes{{proc="w1"}} {cur["v"]}\n',
        period_s=0, rules=[rule])
    # healthy: below threshold, no alert
    for i in range(5):
        wt.sample_once(now=float(i * 10))
    assert wt.alerts_dict()["alerts"] == []
    # synthetic leak: stranded bytes jump past the threshold and hold
    cur["v"] = rule.threshold * 2
    t = 50.0
    fired = False
    while t < 50.0 + rule.window_s + rule.for_s + 30:
        wt.sample_once(now=t)
        states = [a["state"] for a in wt.alerts_dict()["alerts"]]
        if "firing" in states:
            fired = True
            break
        t += 10.0
    assert fired, wt.alerts_dict()
    # leak fixed: the alert resolves
    cur["v"] = 0.0
    wt.sample_once(now=t + 10)
    assert wt.alerts_dict()["alerts"] == []


# ---------------------------------------------------------------------------
# live 2-node cluster: profile e2e, cpu attribution, auditor, dump
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster2():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 4, "resources": {"p5a": 2.0}})
    c.add_node(num_cpus=4, resources={"p5b": 2.0})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote(num_cpus=0.1)
def _p5_busy(seconds):
    t0 = time.monotonic()
    x = 0
    while time.monotonic() - t0 < seconds:
        x += sum(range(128))
    return x


def test_profile_e2e_two_nodes(cluster2):
    """THE live gate: `profile` returns merged node/proc-tagged stacks
    from both nodes of a 2-node cluster, with worker code visible."""
    from ray_tpu.util import state

    refs = ([_p5_busy.options(resources={"p5a": 0.5}).remote(3.0)
             for _ in range(2)] +
            [_p5_busy.options(resources={"p5b": 0.5}).remote(3.0)
             for _ in range(2)])
    time.sleep(0.5)  # workers spinning before the window opens
    r = state.profile(duration_s=1.0)
    ray_tpu.get(refs, timeout=120)
    assert r["errors"] == {}
    assert r["samples"] > 0
    node_tags = {k.split(";", 1)[0] for k in r["stacks"]
                 if k.startswith("node:")}
    expected = {f"node:{nl.node_id.hex()[:12]}"
                for nl in cluster2.nodelets}
    assert expected <= node_tags, (expected, node_tags)
    # the head and this driver sampled themselves too
    assert "node:head" in node_tags and "node:driver" in node_tags
    # worker procs are tagged, and the busy task's frames are visible
    busy = [k for k in r["stacks"] if ":_p5_busy" in k]
    assert busy and all(";proc:" in k for k in busy)
    busy_nodes = {k.split(";", 1)[0] for k in busy}
    assert len(busy_nodes) == 2, f"busy stacks from one node only: {busy_nodes}"
    # collapsed text + chrome conversion round-trip on real output
    text = profiler.collapsed_text(r["stacks"])
    assert text.splitlines()[0].rsplit(" ", 1)[1].isdigit()
    events = profiler.collapsed_to_chrome(r["stacks"], r["hz"])
    assert any(e.get("ph") == "X" for e in events)


def test_profile_cli_writes_collapsed(cluster2, tmp_path):
    from ray_tpu.scripts.cli import main as cli_main

    out = str(tmp_path / "p.collapsed")
    chrome = str(tmp_path / "p.json")
    rc = cli_main(["profile", "--address", cluster2.address,
                   "-d", "0.5", "-o", out, "--chrome", chrome])
    assert rc == 0
    with open(out) as f:
        content = f.read()
    assert "node:" in content and ";proc:" in content
    import json

    with open(chrome) as f:
        assert isinstance(json.load(f), list)


def test_cpu_attribution_cluster_wide(cluster2):
    from ray_tpu.util import state

    @ray_tpu.remote(num_cpus=0.1)
    class P5Actor:
        def burn(self, seconds):
            t0 = time.thread_time()
            x = 0
            while time.thread_time() - t0 < seconds:
                x += sum(range(128))
            return x

    ray_tpu.get([_p5_busy.remote(0.4) for _ in range(3)], timeout=120)
    a = P5Actor.remote()
    ray_tpu.get([a.burn.remote(0.3) for _ in range(2)], timeout=120)
    cpu = state.cpu_attribution()
    rows = {(r["label"], r["kind"]): r for r in cpu["rows"]}
    task_row = rows.get(("_p5_busy", "task"))
    assert task_row is not None, cpu["rows"]
    assert task_row["calls"] >= 3
    assert task_row["cpu_seconds"] > 0.5  # 3 x ~0.4s of pure spin
    actor_row = rows.get(("P5Actor.burn", "actor"))
    assert actor_row is not None, cpu["rows"]
    assert actor_row["calls"] >= 2 and actor_row["cpu_seconds"] > 0.3
    assert cpu["total_cpu_seconds"] >= task_row["cpu_seconds"]
    # the counter face reaches the cluster metrics page via the scrape
    # (the aggregation injects node=/proc= tags after the kind tag)
    text = state.cluster_metrics()
    assert 'core_task_cpu_seconds_total{kind="actor"' in text
    assert 'core_task_cpu_seconds_total{kind="task"' in text
    assert "object_store_stranded_bytes" in text


def test_stranded_auditor_flags_synthetic_leak(cluster2):
    from ray_tpu.core import api as _api

    rt = _api._runtime
    ref = ray_tpu.put(b"p5-leak" * 512)
    oid = ref.id.binary().hex()
    time.sleep(0.15)
    stranded = {o["object_id"]: o for o in rt.audit_stranded(0.1)}
    assert oid in stranded
    assert stranded[oid]["label"] == "put"
    assert stranded[oid]["size"] >= 7 * 512
    # consumer progress clears the flag
    ray_tpu.get(ref)
    assert oid not in {o["object_id"] for o in rt.audit_stranded(0.0)}
    # task returns: stranded until consumed, clean after
    r2 = _p5_busy.remote(0.01)
    ray_tpu.wait([r2], timeout=60)
    time.sleep(0.1)
    oid2 = r2.id.binary().hex()
    audit = {o["object_id"]: o for o in rt.audit_stranded(0.05)}
    assert oid2 in audit and audit[oid2]["label"] == "_p5_busy"
    ray_tpu.get(r2, timeout=60)
    assert oid2 not in {o["object_id"] for o in rt.audit_stranded(0.0)}


def test_errored_ref_regression_stays_clean(cluster2):
    """The PR 11 traceback-pin shape: a fetched error must not strand
    its oid — the ref frees from _owned on release, and the auditor
    never carries it forward."""
    from ray_tpu.core import api as _api

    rt = _api._runtime

    @ray_tpu.remote(num_cpus=0.1)
    def p5_boom():
        raise ValueError("p5 kaboom")

    ref = p5_boom.remote()
    with pytest.raises(Exception, match="p5 kaboom"):
        ray_tpu.get(ref, timeout=60)
    b = ref.id.binary()
    oid = b.hex()
    # consumed at the raising get: not stranded even at threshold 0
    assert oid not in {o["object_id"] for o in rt.audit_stranded(0.0)}
    del ref
    gc.collect()
    assert b not in rt._owned  # freed, not pinned by its own traceback


def test_memory_summary_attribution_and_report(cluster2):
    from ray_tpu.util import state

    keep = ray_tpu.put(b"p5-mem" * 1024)  # held, unconsumed
    time.sleep(0.15)
    s = state.memory_summary(stranded_age_s=0.1)
    assert "put" in s["by_label"]
    put_agg = s["by_label"]["put"]
    assert put_agg["count"] >= 1 and put_agg["bytes"] >= 6 * 1024
    assert put_agg["stranded_count"] >= 1
    assert sum(put_agg["ages"].values()) == put_agg["count"]
    assert s["stranded"]["count"] >= 1
    assert any(o["label"] == "put" for o in s["stranded"]["top"])
    rep = state.memory_report(stranded_age_s=0.1)
    for section in ("=== by owner ===", "=== by creator ===",
                    "stranded refs"):
        assert section in rep, rep
    ray_tpu.get(keep)


def test_debug_dump_includes_profile_and_attribution(cluster2, tmp_path):
    from ray_tpu.util import state

    ray_tpu.get(_p5_busy.remote(0.2), timeout=120)
    out = state.debug_dump(out_dir=str(tmp_path / "dump"), deadline_s=45)
    files = set(os.listdir(out))
    assert "profile.collapsed" in files, files
    import json

    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert "profile" in summary["artifacts"], summary
    with open(os.path.join(out, "profile.collapsed")) as f:
        collapsed = f.read()
    assert "node:" in collapsed
    with open(os.path.join(out, "memory.txt")) as f:
        mem = f.read()
    assert "=== by creator ===" in mem and "stranded refs" in mem
