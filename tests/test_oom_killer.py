"""Memory monitor + OOM worker-killing (VERDICT r3 item 4).

Reference parity: src/ray/common/memory_monitor.h:52 (threshold
sampling), src/ray/raylet/worker_killing_policy.h:34 and the two
shipped policies (worker_killing_policy_group_by_owner.cc,
worker_killing_policy_retriable_fifo.cc) — policy-choice behavior is
asserted at the unit level, then the kill→retry path end to end.
"""

import os
import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.core import oom
from ray_tpu.core.oom import (GROUP_BY_OWNER, RETRIABLE_FIFO, RETRIABLE_LIFO,
                              KillCandidate, MemorySnapshot,
                              is_above_threshold, select_worker_to_kill)

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------------------
# threshold semantics
# ---------------------------------------------------------------------------

def test_threshold_fraction_only():
    snap = MemorySnapshot(96, 100)
    assert is_above_threshold(snap, 0.95, -1)
    assert not is_above_threshold(MemorySnapshot(94, 100), 0.95, -1)


def test_threshold_min_free_is_anded():
    """min_memory_free_bytes relaxes the fraction on big hosts: BOTH
    conditions must hold (reference: memory_monitor.cc)."""
    snap = MemorySnapshot(96, 100)
    assert not is_above_threshold(snap, 0.95, 2)  # free=4 >= 2 floor
    assert is_above_threshold(snap, 0.95, 10)  # free=4 < 10


def test_threshold_empty_snapshot_safe():
    assert not is_above_threshold(MemorySnapshot(0, 0), 0.95, -1)


# ---------------------------------------------------------------------------
# policy choice (reference: worker_killing_policy_*_test.cc shapes)
# ---------------------------------------------------------------------------

def _c(name, owner, retriable, t):
    return KillCandidate(name, owner, retriable, t)


def test_fifo_kills_earliest_retriable():
    v, retry = select_worker_to_kill(
        [_c("late", "a", True, 10.0), _c("early", "a", True, 1.0)],
        RETRIABLE_FIFO)
    assert v.worker == "early" and retry


def test_fifo_prefers_retriable_over_older_nonretriable():
    v, _ = select_worker_to_kill(
        [_c("old-actor", "a", False, 1.0), _c("young-task", "b", True, 9.0)],
        RETRIABLE_FIFO)
    assert v.worker == "young-task"


def test_lifo_kills_newest_retriable():
    v, retry = select_worker_to_kill(
        [_c("late", "a", True, 10.0), _c("early", "a", True, 1.0)],
        RETRIABLE_LIFO)
    assert v.worker == "late" and retry


def test_group_by_owner_picks_largest_retriable_group_lifo_victim():
    cands = [
        _c("a1", "ownerA", True, 1.0), _c("a2", "ownerA", True, 5.0),
        _c("a3", "ownerA", True, 3.0),
        _c("b1", "ownerB", True, 0.5),
        _c("actor", "x", False, 0.1),
    ]
    v, retry = select_worker_to_kill(cands, GROUP_BY_OWNER)
    # largest retriable group is ownerA (3 members); LIFO inside → a2
    assert v.worker == "a2"
    assert retry, "group still has members: task should be retried"


def test_group_by_owner_last_member_not_retried():
    """Killing a retriable group's LAST member returns should_retry=False
    (reference: should_retry = size>1 && retriable)."""
    v, retry = select_worker_to_kill(
        [_c("only", "ownerA", True, 2.0)], GROUP_BY_OWNER)
    assert v.worker == "only" and not retry


def test_group_by_owner_nonretriable_share_one_group():
    """Non-retriable work all lands in ONE group regardless of owner; a
    retriable group is preferred over it even when smaller."""
    cands = [
        _c("n1", "o1", False, 1.0), _c("n2", "o2", False, 2.0),
        _c("n3", "o3", False, 3.0),
        _c("r1", "o4", True, 9.0),
    ]
    v, _ = select_worker_to_kill(cands, GROUP_BY_OWNER)
    assert v.worker == "r1"


def test_group_by_owner_ties_break_to_newest_group():
    cands = [
        _c("oldg", "A", True, 1.0),
        _c("newg", "B", True, 8.0),
    ]
    v, retry = select_worker_to_kill(cands, GROUP_BY_OWNER)
    assert v.worker == "newg" and not retry


def test_empty_candidates():
    assert select_worker_to_kill([], GROUP_BY_OWNER) == (None, False)


# ---------------------------------------------------------------------------
# end to end: pressure → kill → retry
# ---------------------------------------------------------------------------

@pytest.fixture
def fake_memory():
    os.environ["RAY_TPU_TEST_MEMORY_TOTAL_BYTES"] = str(100)
    os.environ["RAY_TPU_TEST_MEMORY_USED_BYTES"] = str(0)
    yield
    os.environ.pop("RAY_TPU_TEST_MEMORY_TOTAL_BYTES", None)
    os.environ.pop("RAY_TPU_TEST_MEMORY_USED_BYTES", None)


def test_oom_kill_retries_task_end_to_end(fake_memory, tmp_path):
    """A worker running under memory pressure is killed by the nodelet's
    monitor and its (retriable) task is resubmitted and completes."""
    ray_tpu.init(num_cpus=2)
    try:
        started = str(tmp_path / "started")

        @ray_tpu.remote(num_cpus=1, max_retries=2)
        def hog():
            if not os.path.exists(started):
                open(started, "w").close()
                time.sleep(60)  # parked until the OOM killer takes us
                return "survived"
            return "retried"

        ref = hog.remote()
        deadline = time.monotonic() + 30
        while not os.path.exists(started) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert os.path.exists(started), "task never started"
        from ray_tpu.core.api import _global_runtime

        nodelet = _global_runtime()._booted[1]
        # drive the node over the 95% threshold; the in-process nodelet's
        # reap loop samples the (faked) snapshot every 250ms
        os.environ["RAY_TPU_TEST_MEMORY_USED_BYTES"] = str(99)
        deadline = time.monotonic() + 15
        while nodelet._oom_kills == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        os.environ["RAY_TPU_TEST_MEMORY_USED_BYTES"] = str(0)
        assert nodelet._oom_kills >= 1, "monitor never killed under pressure"
        assert ray_tpu.get(ref, timeout=60) == "retried"
    finally:
        ray_tpu.shutdown()


def test_no_kill_below_threshold(fake_memory):
    """Sanity: with usage below threshold nothing is ever killed."""
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(num_cpus=1)
        def f(x):
            return x * 2

        assert ray_tpu.get([f.remote(i) for i in range(10)],
                           timeout=60) == [i * 2 for i in range(10)]
        from ray_tpu.core.api import _global_runtime

        assert _global_runtime()._booted[1]._oom_kills == 0
    finally:
        ray_tpu.shutdown()


def test_oom_snapshot_reads_proc():
    """The real (non-faked) sampler returns sane /proc numbers."""
    snap = oom.take_snapshot([os.getpid()])
    assert snap.total_bytes > 0
    assert 0 < snap.used_bytes <= snap.total_bytes
    assert snap.process_rss[os.getpid()] > 0
