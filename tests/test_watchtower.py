"""Watchtower plane (ISSUE 10): metric history bounds, rule predicates,
the alert lifecycle, the four agreeing alert surfaces on a live
cluster, and the autodump rate limit."""

import json
import os
import time

import pytest

from ray_tpu.util.watchtower import (
    MetricHistory,
    WatchRule,
    Watchtower,
    default_rules,
    evaluate_rule,
    parse_prometheus,
)


def _key(name, **tags):
    return (name, tuple(sorted(tags.items())))


# ---------------------------------------------------------------------------
# parsing + ring-buffer bounds
# ---------------------------------------------------------------------------

def test_parse_prometheus_samples_and_buckets():
    text = (
        "# HELP q waiting\n# TYPE q gauge\n"
        'serve_llm_queue_depth{node="a"} 3\n'
        "serve_llm_queue_depth 5.5\n"
        'train_step_seconds_bucket{le="0.1"} 10\n'
        'train_step_seconds_bucket{le="+Inf"} 12\n'
        "train_step_seconds_count 12\n"
        "broken_line{ 1\n"
        "not_a_number nan_is_finefloat\n")
    s = parse_prometheus(text)
    assert s[_key("serve_llm_queue_depth", node="a")] == 3.0
    assert s[_key("serve_llm_queue_depth")] == 5.5
    # histogram internals retain as ordinary series (le included) —
    # the raw material for windowed quantiles
    assert s[_key("train_step_seconds_bucket", le="+Inf")] == 12.0
    assert _key("broken_line") not in s
    assert _key("not_a_number") not in s


def test_history_series_cap_counts_overflow():
    h = MetricHistory(max_series=3, samples_per_series=4)
    page = {_key("m", i=str(i)): float(i) for i in range(10)}
    h.append(0.0, page)
    assert h.series_count == 3
    assert h.dropped_series_total == 7
    # known series keep updating; new ones stay capped + counted
    h.append(1.0, page)
    assert h.series_count == 3
    assert h.dropped_series_total == 14


def test_history_per_series_ring_is_bounded():
    h = MetricHistory(max_series=8, samples_per_series=5)
    for t in range(50):
        h.append(float(t), {_key("m"): float(t)})
    [(tags, ring)] = h.series("m")
    assert len(ring) == 5
    assert [v for _, v in ring] == [45.0, 46.0, 47.0, 48.0, 49.0]
    # query respects the trailing window
    [row] = h.query(["m"], window_s=2.5, now=49.0)
    assert [v for _, v in row["samples"]] == [47.0, 48.0, 49.0]


# ---------------------------------------------------------------------------
# rule predicates
# ---------------------------------------------------------------------------

def _fill(h, name, values, dt=5.0, **tags):
    for i, v in enumerate(values):
        h.append(i * dt, {_key(name, **tags): float(v)})


def test_threshold_rule_last_value_aggregates_series():
    h = MetricHistory()
    _fill(h, "serve_llm_queue_depth", [1, 2, 3], node="a")
    _fill(h, "serve_llm_queue_depth", [4, 5, 9], node="b")
    r = WatchRule("q", metric="serve_llm_queue_depth", op=">",
                  threshold=10.0, window_s=30, agg="sum")
    value, cond = evaluate_rule(r, h, 10.0)
    assert value == 12.0 and cond
    r_max = WatchRule("q", metric="serve_llm_queue_depth", op=">",
                      threshold=10.0, window_s=30, agg="max")
    value, cond = evaluate_rule(r_max, h, 10.0)
    assert value == 9.0 and not cond


def test_rate_rule_counter_reset_clamp():
    h = MetricHistory()
    # a restart mid-window (value drops) must not produce a huge
    # negative (or positive) rate — the window yields no data instead
    _fill(h, "serve_replica_restarts_total", [100, 110, 3])
    r = WatchRule("flap", metric="serve_replica_restarts_total",
                  kind="rate", op=">", threshold=0.5, window_s=30)
    value, cond = evaluate_rule(r, h, 10.0)
    assert value is None and not cond
    # monotone growth evaluates normally: +20 over 10s = 2/s
    h2 = MetricHistory()
    _fill(h2, "serve_replica_restarts_total", [0, 10, 20])
    value, cond = evaluate_rule(r, h2, 10.0)
    assert value == pytest.approx(2.0) and cond


def test_rate_rule_gauge_slope_detects_ramp():
    h = MetricHistory()
    _fill(h, "serve_llm_queue_depth", [0, 4, 8, 12, 16], dt=2.0)
    r = WatchRule("ramp", metric="serve_llm_queue_depth", kind="rate",
                  op=">", threshold=0.5, window_s=60)
    value, cond = evaluate_rule(r, h, 8.0)
    assert value == pytest.approx(2.0) and cond
    # a draining queue (negative slope) does not fire a ">" rule
    h2 = MetricHistory()
    _fill(h2, "serve_llm_queue_depth", [16, 8, 0], dt=2.0)
    value, cond = evaluate_rule(r, h2, 4.0)
    assert value == pytest.approx(-4.0) and not cond


def test_quantile_rule_p99_and_skew_from_buckets():
    h = MetricHistory()
    # 90 obs <=0.1s, 9 more <=1s, 1 more <=10s over the window
    for t, scale in ((0.0, 0.0), (30.0, 1.0)):
        h.append(t, {
            _key("train_step_seconds_bucket", le="0.1"): 90 * scale,
            _key("train_step_seconds_bucket", le="1.0"): 99 * scale,
            _key("train_step_seconds_bucket", le="10.0"): 100 * scale,
            _key("train_step_seconds_bucket", le="+Inf"): 100 * scale,
        })
    p99 = WatchRule("s", metric="train_step_seconds", stat="p99",
                    op=">", threshold=0.5, window_s=60)
    value, cond = evaluate_rule(p99, h, 30.0)
    assert value == pytest.approx(1.0) and cond
    skew = WatchRule("s", metric="train_step_seconds", stat="skew",
                     op=">", threshold=2.0, window_s=60)
    value, cond = evaluate_rule(skew, h, 30.0)
    # p50 interpolates inside [0, 0.1); p99 lands at 1.0 -> skew >> 2
    assert value > 2.0 and cond
    # empty window (no new observations): no value, no firing
    value, cond = evaluate_rule(p99, h, 300.0)
    assert value is None and not cond


def test_hit_ratio_rule_gated_on_activity_floor():
    hits, misses = ("serve_llm_prefix_cache_hits_total",
                    "serve_llm_prefix_cache_misses_total")
    r = WatchRule("thrash", metric=hits, stat="hit_ratio",
                  ratio_metric=misses, op="<", threshold=0.2,
                  min_rate=50.0, window_s=60)
    h = MetricHistory()
    _fill(h, hits, [0, 50, 100])       # 10 pages/s hit
    _fill(h, misses, [0, 450, 900])    # 90 pages/s miss -> ratio 0.1
    value, cond = evaluate_rule(r, h, 10.0)
    assert value == pytest.approx(0.1) and cond
    # same collapse below the activity floor: an idle cache never pages
    h2 = MetricHistory()
    _fill(h2, hits, [0, 1, 2])
    _fill(h2, misses, [0, 9, 18])
    value, cond = evaluate_rule(r, h2, 10.0)
    assert value is None and not cond


def test_absence_rule_staleness_needs_prior_activity():
    r = WatchRule("stall", metric="train_step_seconds_count",
                  kind="absence", window_s=60)
    h = MetricHistory()
    # grows for 50s, then flat: stale once quiet for a window (but
    # still inside the 3x-window "ended" horizon)
    for t in range(0, 250, 5):
        h.append(float(t),
                 {_key("train_step_seconds_count"): float(min(t, 50))})
    value, cond = evaluate_rule(r, h, 150.0)
    assert cond and value >= 60.0
    # still actively increasing: not stale
    h2 = MetricHistory()
    for t in range(0, 250, 5):
        h2.append(float(t), {_key("train_step_seconds_count"): float(t)})
    value, cond = evaluate_rule(r, h2, 245.0)
    assert not cond
    # a cluster that never trained never alerts
    h3 = MetricHistory()
    for t in range(0, 250, 5):
        h3.append(float(t), {_key("train_step_seconds_count"): 0.0})
    value, cond = evaluate_rule(r, h3, 245.0)
    assert value is None and not cond


def test_absence_rule_resolves_past_the_ended_horizon():
    """A normally-completed run must not page critical forever: past
    resolve_after_s (default 3x window) staleness means ENDED, and the
    alert clears."""
    r = WatchRule("stall", metric="train_step_seconds_count",
                  kind="absence", window_s=60)
    h = MetricHistory(samples_per_series=1000)
    for t in range(0, 1000, 5):
        h.append(float(t),
                 {_key("train_step_seconds_count"): float(min(t, 50))})
    # inside [window, 3*window): stalled -> fires
    _, cond = evaluate_rule(r, h, 50.0 + 90.0)
    assert cond
    # past the horizon: ended -> resolves
    _, cond = evaluate_rule(r, h, 50.0 + 200.0)
    assert not cond


def test_history_prunes_vanished_series():
    """Dead nodes/replicas free their series-cap slots: a series whose
    newest sample predates the prune floor is evicted, so churn can
    never permanently blind the watchtower to NEW series."""
    h = MetricHistory(max_series=2)
    h.append(0.0, {_key("m", node="dead"): 1.0})
    h.append(0.0, {_key("m", node="live"): 1.0})
    h.append(100.0, {_key("m", node="live"): 2.0,
                     _key("m", node="new"): 1.0})
    assert h.dropped_series_total == 1  # "new" hit the cap
    assert h.prune(50.0) == 1  # "dead" evicted
    h.append(101.0, {_key("m", node="new"): 1.0})  # slot freed
    assert {t["node"] for t, _ in h.series("m")} == {"live", "new"}


def test_default_rule_pack_covers_catalog_signals():
    rules = {r.name: r for r in default_rules()}
    assert {"serve-ttft-slo-burn", "serve-queue-ramp",
            "replica-flapping", "span-plane-overload",
            "prefix-cache-thrash", "spec-accept-collapse",
            "train-straggler",
            "train-stall", "train-pipeline-bubble",
            "train-zero-gather-stall", "log-error-spike",
            "task-queue-stall", "object-stranded-refs"} == set(rules)
    for r in rules.values():
        assert r.severity in ("info", "warning", "critical")
        assert r.description


# ---------------------------------------------------------------------------
# alert lifecycle + dedup (driven tick-by-tick with injected time)
# ---------------------------------------------------------------------------

def _ticker(rule, **kw):
    """A Watchtower around one gauge we control; no sampling thread."""
    cur = {"v": 0.0}
    wt = Watchtower(lambda: f"test_gauge {cur['v']}\n", period_s=0,
                    rules=[rule], **kw)
    return wt, cur


def test_alert_lifecycle_pending_firing_resolved_dedup():
    rule = WatchRule("hot", metric="test_gauge", op=">", threshold=5.0,
                     window_s=10, for_s=4.0, severity="warning")
    wt, cur = _ticker(rule)
    states = []
    for t, v in enumerate([0, 9, 9, 9, 9, 9, 9, 0, 0]):
        cur["v"] = float(v)
        wt.sample_once(now=float(t * 2))
        active = wt.alerts_dict(include_history=False)["alerts"]
        assert len(active) <= 1  # dedup: one alert per rule fingerprint
        states.append(active[0]["state"] if active else "-")
    # condition true at t=2 -> pending; for_s=4 holds it until t=6
    assert states == ["-", "pending", "pending", "firing", "firing",
                      "firing", "firing", "-", "-"]
    d = wt.alerts_dict()
    assert [(e["from"], e["to"]) for e in d["history"]] == [
        (None, "pending"), ("pending", "firing"),
        ("firing", "resolved")]
    # firing counted once per transition, not per tick
    from ray_tpu.util.metrics import prometheus_text

    assert 'watchtower_alerts_total{rule="hot"}' in prometheus_text()


def test_pending_that_clears_never_fires():
    rule = WatchRule("blip", metric="test_gauge", op=">", threshold=5.0,
                     window_s=10, for_s=6.0)
    wt, cur = _ticker(rule)
    for t, v in enumerate([9, 9, 0, 0]):
        cur["v"] = float(v)
        wt.sample_once(now=float(t * 2))
    d = wt.alerts_dict()
    assert d["alerts"] == []
    assert [e["to"] for e in d["history"]] == ["pending", "resolved"]
    assert all(e["to"] != "firing" for e in d["history"])


def test_task_queue_stall_rule_fires_and_resolves():
    """The flight-recorder rule: queue-wait p99 over the threshold for
    60s fires a warning; a burst of fast dispatches pulls the windowed
    p99 back under and resolves it. Driven synthetically from the
    cumulative bucket counts of `task_queue_wait_seconds`."""
    rule = {r.name: r for r in default_rules()}["task-queue-stall"]
    assert rule.severity == "warning" and rule.stat == "p99"
    counts = {"fast": 0, "slow": 0}  # <=1s vs (1s, 10s] observations

    def scrape():
        le1 = counts["fast"]
        le10 = counts["fast"] + counts["slow"]
        return (
            f'task_queue_wait_seconds_bucket{{le="1.0"}} {le1}\n'
            f'task_queue_wait_seconds_bucket{{le="10.0"}} {le10}\n'
            f'task_queue_wait_seconds_bucket{{le="+Inf"}} {le10}\n')

    wt = Watchtower(scrape, period_s=0, rules=[rule])
    states = []
    # (dt-advance handled via explicit now=) each tick is 30s apart
    for t, (fast, slow) in enumerate(
            [(0, 0), (0, 10), (0, 20), (0, 30), (1000, 30), (2000, 30)]):
        counts["fast"], counts["slow"] = fast, slow
        wt.sample_once(now=float(t * 30))
        active = wt.alerts_dict(include_history=False)["alerts"]
        states.append(active[0]["state"] if active else "-")
    # stalled dispatches land in the (1,10] bucket -> p99=10s > 5s:
    # pending at 30s, firing once held for_s=60, resolved when the
    # fast burst drags the windowed p99 under the threshold
    assert states == ["-", "pending", "pending", "firing", "-", "-"]
    d = wt.alerts_dict()
    assert [(e["from"], e["to"]) for e in d["history"]] == [
        (None, "pending"), ("pending", "firing"),
        ("firing", "resolved")]


def test_zero_gather_stall_rule_fires_and_resolves():
    """The ZeRO-3 rule: all-gather share of the train step held over
    the threshold for 30s fires a warning (the JIT param gathers are
    eating the step — drop to stage 2 or widen the data axis); the
    share falling back under resolves it. Driven synthetically from
    the train_zero_gather_share gauge."""
    rule = {r.name: r for r in default_rules()}["train-zero-gather-stall"]
    assert rule.severity == "warning"
    assert rule.metric == "train_zero_gather_share"
    cur = {"v": 0.0}
    wt = Watchtower(lambda: f"train_zero_gather_share {cur['v']}\n",
                    period_s=0, rules=[rule])
    states = []
    # healthy -> gather-bound (0.6 > 0.35) -> recovered; 15s ticks so
    # for_s=30 holds the pending state for two ticks before firing
    for t, v in enumerate([0.1, 0.6, 0.6, 0.6, 0.6, 0.1, 0.1]):
        cur["v"] = float(v)
        wt.sample_once(now=float(t * 15))
        active = wt.alerts_dict(include_history=False)["alerts"]
        states.append(active[0]["state"] if active else "-")
    assert states == ["-", "pending", "pending", "firing", "firing",
                      "-", "-"]
    d = wt.alerts_dict()
    assert [(e["from"], e["to"]) for e in d["history"]] == [
        (None, "pending"), ("pending", "firing"),
        ("firing", "resolved")]


def test_autodump_rate_limited_to_one_per_cooldown():
    rule = WatchRule("crit", metric="test_gauge", op=">", threshold=5.0,
                     window_s=10, for_s=0.0, severity="critical")
    dumps = []
    wt, cur = _ticker(rule, autodump="unused-dir",
                      autodump_cooldown_s=100.0,
                      dump_fn=lambda d: dumps.append(d))
    # three separate firing episodes inside one cooldown window
    pattern = [9, 9, 0, 9, 9, 0, 9, 9, 0]
    for t, v in enumerate(pattern):
        cur["v"] = float(v)
        wt.sample_once(now=float(t * 2))
    time.sleep(0.3)  # dump thread is fire-and-forget
    fired = [e for e in wt.alerts_dict()["history"]
             if e["to"] == "firing"]
    assert len(fired) == 3
    assert len(dumps) == 1 and wt.autodumps == 1
    # past the cooldown, the next firing dumps again
    cur["v"] = 9.0
    wt.sample_once(now=150.0)
    time.sleep(0.3)
    assert len(dumps) == 2 and wt.autodumps == 2


def test_autodump_off_by_default():
    rule = WatchRule("crit", metric="test_gauge", op=">", threshold=5.0,
                     window_s=10, for_s=0.0, severity="critical")
    dumps = []
    wt, cur = _ticker(rule, dump_fn=lambda d: dumps.append(d))
    cur["v"] = 9.0
    wt.sample_once(now=0.0)
    time.sleep(0.1)
    assert wt.autodumps == 0 and dumps == []


def test_warning_severity_never_autodumps():
    rule = WatchRule("warm", metric="test_gauge", op=">", threshold=5.0,
                     window_s=10, for_s=0.0, severity="warning")
    dumps = []
    wt, cur = _ticker(rule, autodump="somewhere",
                      dump_fn=lambda d: dumps.append(d))
    cur["v"] = 9.0
    wt.sample_once(now=0.0)
    time.sleep(0.1)
    assert dumps == []


def test_profiler_capture_noop_on_cpu(tmp_path):
    """The --trace TPU profiler satellite: on CPU the capture is a
    guarded no-op — nothing armed, nothing written, block still runs."""
    from ray_tpu.util import tracing

    out = str(tmp_path / "prof")
    ran = []
    with tracing.profiler_capture(out) as captured:
        ran.append(1)
    assert ran == [1]
    assert captured is None
    assert not os.path.exists(out)
    with tracing.profiler_capture(None) as captured:
        assert captured is None


# ---------------------------------------------------------------------------
# the end-to-end gate: a live cluster, a real rule, four agreeing faces
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def watch_cluster(tmp_path_factory):
    """Head (fast watchtower period, a responsive ramp rule) + one real
    nodelet, so the sampling loop exercises the genuine scrape fan-out.
    The driver process's default registry is the head's own metrics
    page, so a gauge set here is a real cluster series."""
    from ray_tpu.core.head import Head
    from ray_tpu.core.nodelet import Nodelet

    session_dir = str(tmp_path_factory.mktemp("wt_session"))
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    rules = [WatchRule("queue-ramp", metric="serve_llm_queue_depth",
                       kind="rate", agg="sum", op=">", threshold=0.5,
                       window_s=6.0, for_s=0.4, severity="warning",
                       description="test ramp")]
    head = Head(watchtower_period_s=0.2, watchtower_rules=rules).start()
    nodelet = Nodelet(head.address, {"CPU": 2.0},
                      session_dir=session_dir).start()
    yield head
    # debug_dump's serve_status step auto-inits a runtime against this
    # head; release it or every later module's init() sees "called
    # twice"
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    nodelet.stop()
    head.stop()


def test_cluster_alert_fires_and_surfaces_agree(watch_cluster, capsys):
    from ray_tpu.scripts import cli
    from ray_tpu.util import state
    from ray_tpu.util.metrics import Gauge

    head = watch_cluster
    g = Gauge("serve_llm_queue_depth", "waiting requests")
    g.set(0.0)
    # drive a deliberate queue ramp; the rule must transition
    # pending -> firing within a couple of evaluation periods
    deadline = time.monotonic() + 20.0
    v = 0.0
    fired = None
    while time.monotonic() < deadline:
        v += 1.0
        g.set(v)
        time.sleep(0.2)
        data = state.alerts(address=head.address)
        firing = [a for a in data["alerts"] if a["state"] == "firing"]
        if firing:
            fired = firing[0]
            break
    assert fired is not None, "queue ramp never fired"
    assert fired["rule"] == "queue-ramp"
    assert fired["value"] > 0.5

    # face 2: the CLI sees the same alert
    rc = cli.main(["alerts", "--address", head.address])
    out = capsys.readouterr().out
    assert rc == 0
    assert "queue-ramp" in out and "firing" in out
    rc = cli.main(["alerts", "--address", head.address, "--json"])
    cli_data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert any(a["rule"] == "queue-ramp" and a["state"] == "firing"
               for a in cli_data["alerts"])

    # face 3: the metrics catalog gauge on the cluster page
    text = state.cluster_metrics(address=head.address)
    line = next(l for l in text.splitlines()
                if l.startswith("watchtower_alerts_firing")
                and 'severity="warning"' in l)
    assert float(line.rsplit(" ", 1)[1]) >= 1.0
    assert 'watchtower_alerts_total{rule="queue-ramp"' in text

    # face 4: transitions land as watchtower-category spans on the
    # merged timeline
    tl = state.cluster_timeline(address=head.address)
    spans = [e for e in tl if e.get("cat") == "watchtower"]
    assert any(e["name"] == "watchtower.queue-ramp" for e in spans)

    # and it RESOLVES once the condition clears (queue stops ramping)
    deadline = time.monotonic() + 20.0
    resolved = False
    while time.monotonic() < deadline:
        time.sleep(0.3)
        data = state.alerts(address=head.address)
        if not data["alerts"]:
            resolved = True
            break
    assert resolved, "alert never resolved after the ramp stopped"
    tos = [e["to"] for e in data["history"]
           if e["rule"] == "queue-ramp"]
    assert tos[:3] == ["pending", "firing", "resolved"]

    # metric history: the substrate holds a real sampled window of the
    # series that drove the rule, with bounds bookkeeping attached
    h = state.cluster_metrics_history(
        names=["serve_llm_queue_depth"], address=head.address)
    series = [s for s in h["series"]
              if s["name"] == "serve_llm_queue_depth"]
    assert series and len(series[0]["samples"]) >= 5
    ts = [t for t, _ in series[0]["samples"]]
    assert ts == sorted(ts)
    assert h["samples_total"] >= 5
    assert h["series_dropped"] >= 0


def test_debug_dump_includes_alerts_artifact(watch_cluster, tmp_path):
    from ray_tpu.util import state

    out = state.debug_dump(out_dir=str(tmp_path / "dump"),
                           address=watch_cluster.address,
                           deadline_s=45)
    with open(os.path.join(out, "alerts.json")) as f:
        data = json.load(f)
    assert "alerts" in data and "history" in data and "rules" in data
    assert any(r["name"] == "queue-ramp" for r in data["rules"])
    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert "alerts" in summary["artifacts"]
