"""ZeRO-1 sharded weight update (train/spmd.py shard_optimizer).

Gates the three tentpole claims:
- loss parity with the unsharded step (atol 1e-5, several steps) on
  gpt2 and llama — sharding is layout, not arithmetic. The strict gate
  uses an elementwise-stable optimizer (sgd+momentum: param-shaped
  state, no ulp amplification, parity is exact); the adamw case
  documents the mu/sqrt(nu) amplification of cross-program
  reduction-order noise and gates the first steps plus the byte win.
- per-chip optimizer bytes shrink ~1/data-axis-size.
- the compiled program is structurally restructured: the ZeRO-1 step
  carries the extra resharding collectives (XLA:CPU realizes the
  scatter as allreduce + slice and the param regather as all-gathers;
  TPU forms true reduce-scatter) — plus the waterfall's split-phase
  and census plumbing (the PR's collective-attribution satellite).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.gpt2 import (
    GPT2Config,
    gpt2_loss,
    gpt2_partition_rules,
    init_gpt2,
)
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.train import spmd
from ray_tpu.train.spmd import (
    batch_shardings,
    init_sharded_state,
    make_train_step,
    optimizer_state_bytes,
)

DATA = 4  # data-axis size the byte-shrink assertions divide by


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(MeshSpec(data=DATA, tensor=2))


def _batch(mesh, vocab, B=8, T=64, seed=0):
    toks = np.random.RandomState(seed).randint(
        0, vocab, (B, T + 1)).astype(np.int32)
    b = {"tokens": jnp.asarray(toks[:, :-1]),
         "targets": jnp.asarray(toks[:, 1:])}
    return jax.device_put(b, batch_shardings(mesh, b))


def _run(mesh, rules, init_fn, loss_fn, tx, batch, shard, steps):
    state = init_sharded_state(init_fn, tx, mesh, rules,
                               shard_optimizer=shard)
    step = make_train_step(loss_fn, tx, shard_optimizer=shard,
                           mesh=mesh if shard else None,
                           rules=rules if shard else None)
    losses = []
    with mesh:
        for _ in range(steps):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
    return state, losses


def test_gpt2_loss_parity_sharded_vs_replicated(mesh):
    cfg = GPT2Config.tiny()
    rules = gpt2_partition_rules()
    tx = optax.sgd(0.05, momentum=0.9)
    batch = _batch(mesh, cfg.vocab_size)

    def init_fn():
        return init_gpt2(jax.random.PRNGKey(0), cfg)

    def loss_fn(p, b):
        return gpt2_loss(p, b, cfg)

    s_r, l_r = _run(mesh, rules, init_fn, loss_fn, tx, batch, False, 5)
    s_z, l_z = _run(mesh, rules, init_fn, loss_fn, tx, batch, True, 5)
    assert l_r[0] > l_r[-1]  # it actually trains
    np.testing.assert_allclose(l_r, l_z, atol=1e-5)
    # params track too — same update arithmetic, different layout
    for a, b in zip(jax.tree.leaves(s_r.params),
                    jax.tree.leaves(s_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)


def test_llama_loss_parity_sharded_vs_replicated(mesh):
    from ray_tpu.models.llama import (
        LlamaConfig,
        init_llama,
        llama_loss,
        llama_partition_rules,
    )

    cfg = LlamaConfig.tiny()
    rules = llama_partition_rules()
    tx = optax.sgd(0.05, momentum=0.9)
    batch = _batch(mesh, cfg.vocab_size, T=32, seed=1)

    def init_fn():
        return init_llama(jax.random.PRNGKey(0), cfg)

    def loss_fn(p, b):
        return llama_loss(p, b, cfg)

    _, l_r = _run(mesh, rules, init_fn, loss_fn, tx, batch, False, 5)
    _, l_z = _run(mesh, rules, init_fn, loss_fn, tx, batch, True, 5)
    np.testing.assert_allclose(l_r, l_z, atol=1e-5)


def test_optimizer_bytes_shrink_one_over_data_axis(mesh):
    """The memory claim itself: per-chip optimizer bytes under ZeRO-1
    ~1/DATA of replicated (small slack for the scalar/indivisible
    leaves that stay replicated), and the gauge shows both layouts."""
    cfg = GPT2Config.tiny()
    rules = gpt2_partition_rules()
    tx = optax.adamw(3e-4)  # two param-shaped moments — the real shape

    def init_fn():
        return init_gpt2(jax.random.PRNGKey(0), cfg)

    s_r = init_sharded_state(init_fn, tx, mesh, rules)
    s_z = init_sharded_state(init_fn, tx, mesh, rules,
                             shard_optimizer=True)
    b_r = optimizer_state_bytes(s_r.opt_state)
    b_z = optimizer_state_bytes(s_z.opt_state)
    assert b_r > 0
    ratio = b_z / b_r
    assert ratio <= 1.0 / DATA * 1.25, (b_r, b_z, ratio)
    assert ratio >= 1.0 / DATA * 0.75, (b_r, b_z, ratio)
    from ray_tpu.train.spmd import _optimizer_bytes_gauge

    exposed = "\n".join(_optimizer_bytes_gauge().expose())
    assert 'layout="replicated"' in exposed
    assert 'layout="zero1"' in exposed


def test_adamw_sharded_update_tracks_and_shrinks(mesh):
    """adamw: first-step loss identical, later steps track loosely
    (mu/sqrt(nu) amplifies cross-program reduction-order ulps — see
    TRAINING.md), and the byte win still holds end-to-end."""
    cfg = GPT2Config.tiny()
    rules = gpt2_partition_rules()
    tx = optax.adamw(1e-3)
    batch = _batch(mesh, cfg.vocab_size, seed=2)

    def init_fn():
        return init_gpt2(jax.random.PRNGKey(0), cfg)

    def loss_fn(p, b):
        return gpt2_loss(p, b, cfg)

    s_r, l_r = _run(mesh, rules, init_fn, loss_fn, tx, batch, False, 4)
    s_z, l_z = _run(mesh, rules, init_fn, loss_fn, tx, batch, True, 4)
    assert abs(l_r[0] - l_z[0]) <= 1e-5  # same params -> same loss
    np.testing.assert_allclose(l_r, l_z, atol=5e-3)
    assert l_z[0] > l_z[-1]
    assert optimizer_state_bytes(s_z.opt_state) \
        < 0.5 * optimizer_state_bytes(s_r.opt_state)


def test_zero1_program_restructures_collectives(mesh):
    """Structural census: the ZeRO-1 program carries the resharding
    collectives the replicated step doesn't (param all-gathers; true
    reduce-scatter where the backend forms it)."""
    from ray_tpu.parallel.ops import collective_op_counts

    cfg = GPT2Config.tiny()
    rules = gpt2_partition_rules()
    tx = optax.sgd(0.05, momentum=0.9)
    batch = _batch(mesh, cfg.vocab_size)

    def loss_fn(p, b):
        return gpt2_loss(p, b, cfg)

    def census(shard):
        state = init_sharded_state(
            lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh,
            rules, shard_optimizer=shard)
        step = make_train_step(loss_fn, tx, shard_optimizer=shard,
                               mesh=mesh if shard else None,
                               rules=rules if shard else None,
                               donate=False)
        with mesh:
            txt = step.jitted.lower(state, batch).compile().as_text()
        return collective_op_counts(txt)

    plain, zero1 = census(False), census(True)
    assert plain.get("allreduce", 0) > 0  # DP grad reduction exists
    assert (zero1.get("reduce_scatter", 0) > 0
            or zero1.get("all_gather", 0) > plain.get("all_gather", 0)), \
        (plain, zero1)


def test_waterfall_splits_collective_phase_and_censuses():
    """The attribution satellite, mechanically: (a) collective_seconds
    carries the canonical op labels and sums_by_tag groups them; (b) an
    attributed ZeRO-1 step records the program collective census and
    the table prints it; (c) split collective.<op> phases render."""
    from ray_tpu.util.collective import _OP_LABELS, _collective_seconds

    # (a) canonical labels: the host path maps its round kinds
    assert _OP_LABELS["allgather"] == "all_gather"
    assert _OP_LABELS["reducescatter"] == "reduce_scatter"
    h = _collective_seconds()
    base = h.sums_by_tag("op")
    h.observe(0.25, tags={"op": "all_gather"})
    h.observe(0.5, tags={"op": "reduce_scatter"})
    now = h.sums_by_tag("op")
    assert now.get("all_gather", 0) - base.get("all_gather", 0) \
        == pytest.approx(0.25)
    assert now.get("reduce_scatter", 0) - base.get("reduce_scatter", 0) \
        == pytest.approx(0.5)

    # (b) attributed zero1 step -> census lands in the waterfall
    cfg = GPT2Config.tiny()
    mesh = build_mesh(MeshSpec(data=4, tensor=2))
    rules = gpt2_partition_rules()
    tx = optax.sgd(0.05, momentum=0.9)
    batch = _batch(mesh, cfg.vocab_size)
    state = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh, rules,
        shard_optimizer=True)
    step = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), tx,
                           shard_optimizer=True, mesh=mesh, rules=rules)
    spmd.waterfall.reset()
    spmd.enable_step_waterfall(True)
    try:
        with mesh:
            state, m = step(state, batch)
            state, m = step(state, batch)
    finally:
        spmd.enable_step_waterfall(False)
    s = spmd.waterfall.summary()
    census = s.get("program_collectives", {})
    assert census, s
    assert census.get("all_gather", 0) > 0 or \
        census.get("reduce_scatter", 0) > 0, census
    assert "in-program collectives" in spmd.waterfall.table()
    # census survives the reset a timed bench window performs
    spmd.waterfall.reset()
    assert spmd.waterfall.summary().get("program_collectives") == census

    # (c) split phases render through add/summary/table
    spmd.waterfall.reset()
    spmd.waterfall.add({"compute": 0.8, "collective.reduce_scatter": 0.15,
                        "collective.all_gather": 0.05})
    out = spmd.waterfall.summary()
    assert out["phases"]["collective.reduce_scatter"] == \
        pytest.approx(0.15)
    table = spmd.waterfall.table()
    assert "collective.reduce_scatter" in table
    assert "collective.all_gather" in table
    spmd.waterfall.reset()
