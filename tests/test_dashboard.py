"""Dashboard-lite endpoint tests."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import dashboard
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    port = dashboard.start_dashboard(c.address, port=0)
    yield c, port
    dashboard.stop_dashboard()
    ray_tpu.shutdown()
    c.shutdown()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.read().decode()


def test_html_page(cluster):
    _, port = cluster
    body = _get(port, "/")
    assert "ray_tpu cluster" in body


def test_api_endpoints(cluster):
    _, port = cluster
    s = json.loads(_get(port, "/api/state"))
    assert s["nodes_alive"] == 1
    nodes = json.loads(_get(port, "/api/nodes"))
    assert nodes[0]["alive"]

    @ray_tpu.remote
    class D:
        def p(self):
            return 1

    a = D.remote()
    assert ray_tpu.get(a.p.remote(), timeout=60) == 1
    actors = json.loads(_get(port, "/api/actors"))
    assert any(x["state"] == "ALIVE" for x in actors)
    assert "# TYPE" in _get(port, "/metrics") or _get(port, "/metrics") == "\n"


def test_objects_memory_history_endpoints(cluster):
    """VERDICT r3 item 10: /api/objects, /api/memory, /api/history."""
    import time

    import numpy as np

    _, port = cluster

    @ray_tpu.remote(num_cpus=0.1)
    def produce():
        return np.zeros(1 << 20, np.uint8)  # store-resident

    ref = produce.remote()
    assert ray_tpu.get(ref, timeout=60).nbytes == 1 << 20

    objs = json.loads(_get(port, "/api/objects"))
    mine = [o for o in objs if o["object_id"] == ref.id.hex()]
    assert mine, f"driver-owned object missing from {len(objs)} rows"
    assert mine[0]["size"] >= 1 << 20
    assert mine[0]["ready"] and not mine[0]["error"]

    mem = json.loads(_get(port, "/api/memory"))
    assert mem["objects_total"] >= 1
    assert mem["nodes"] and mem["nodes"][0]["store_capacity"] > 0
    assert mem["nodes"][0]["store_bytes_allocated"] >= 1 << 20
    assert mem["by_owner"], "per-owner aggregation empty"

    # the sampler ticks every 5s; wait for at least one sample
    deadline = time.monotonic() + 15
    hist = []
    while time.monotonic() < deadline:
        hist = json.loads(_get(port, "/api/history"))
        if hist:
            break
        time.sleep(0.5)
    assert hist, "history ring buffer never sampled"
    assert hist[-1]["nodes_alive"] == 1
    assert "time" in hist[-1]


def test_node_stats_agent_endpoint(cluster):
    """Tier-2 per-node agent: loadavg + per-worker RSS + store usage
    through the nodelet (reference: dashboard/agent.py)."""
    c, port = cluster
    node_hex = c.nodelets[0].node_id.hex()

    # make sure at least one worker process exists
    @ray_tpu.remote
    def touch():
        return 1

    assert ray_tpu.get(touch.remote(), timeout=60) == 1
    s = json.loads(_get(port, f"/api/node_stats?node={node_hex}"))
    assert s["node_id"] == node_hex
    assert len(s["loadavg"]) == 3
    assert s["store"]["capacity"] > 0
    assert s["num_workers"] >= 1
    assert any(w["rss_kb"] > 0 for w in s["workers"])


def test_train_view_shows_live_run(cluster):
    """VERDICT done-criterion: a JaxTrainer run is visible under
    /api/train."""
    import sys

    import cloudpickle

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    from ray_tpu import train
    from ray_tpu.train.trainer import JaxTrainer, RunConfig, ScalingConfig

    def loop():
        for i in range(3):
            train.report({"loss": 1.0 / (i + 1), "step": i})

    trainer = JaxTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=1, use_tpu=False),
        run_config=RunConfig(name="dash_run"),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 2
    runs = json.loads(_get(port := cluster[1], "/api/train"))
    mine = [r for r in runs if r["name"] == "dash_run"]
    assert mine and mine[0]["status"] == "FINISHED"
    assert mine[0]["iteration"] == 3
    assert mine[0]["metrics"]["step"] == 2


def test_data_and_serve_views(cluster):
    c, port = cluster
    from ray_tpu import data as rd

    ds = rd.from_items(list(range(100)), parallelism=4)
    assert ds.map(lambda x: x + 1).count() == 100
    execs = json.loads(_get(port, "/api/data"))
    assert execs and execs[0]["status"] == "FINISHED"
    assert execs[0]["yielded"] >= 4
    serve_view = json.loads(_get(port, "/api/serve"))
    assert isinstance(serve_view, dict)  # empty control plane is fine
