"""Dashboard-lite endpoint tests."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import dashboard
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    port = dashboard.start_dashboard(c.address, port=0)
    yield c, port
    dashboard.stop_dashboard()
    ray_tpu.shutdown()
    c.shutdown()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.read().decode()


def test_html_page(cluster):
    _, port = cluster
    body = _get(port, "/")
    assert "ray_tpu cluster" in body


def test_api_endpoints(cluster):
    _, port = cluster
    s = json.loads(_get(port, "/api/state"))
    assert s["nodes_alive"] == 1
    nodes = json.loads(_get(port, "/api/nodes"))
    assert nodes[0]["alive"]

    @ray_tpu.remote
    class D:
        def p(self):
            return 1

    a = D.remote()
    assert ray_tpu.get(a.p.remote(), timeout=60) == 1
    actors = json.loads(_get(port, "/api/actors"))
    assert any(x["state"] == "ALIVE" for x in actors)
    assert "# TYPE" in _get(port, "/metrics") or _get(port, "/metrics") == "\n"
