"""Dashboard-lite endpoint tests."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import dashboard
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    port = dashboard.start_dashboard(c.address, port=0)
    yield c, port
    dashboard.stop_dashboard()
    ray_tpu.shutdown()
    c.shutdown()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.read().decode()


def test_html_page(cluster):
    _, port = cluster
    body = _get(port, "/")
    assert "ray_tpu cluster" in body


def test_api_endpoints(cluster):
    _, port = cluster
    s = json.loads(_get(port, "/api/state"))
    assert s["nodes_alive"] == 1
    nodes = json.loads(_get(port, "/api/nodes"))
    assert nodes[0]["alive"]

    @ray_tpu.remote
    class D:
        def p(self):
            return 1

    a = D.remote()
    assert ray_tpu.get(a.p.remote(), timeout=60) == 1
    actors = json.loads(_get(port, "/api/actors"))
    assert any(x["state"] == "ALIVE" for x in actors)
    assert "# TYPE" in _get(port, "/metrics") or _get(port, "/metrics") == "\n"


def test_objects_memory_history_endpoints(cluster):
    """VERDICT r3 item 10: /api/objects, /api/memory, /api/history."""
    import time

    import numpy as np

    _, port = cluster

    @ray_tpu.remote(num_cpus=0.1)
    def produce():
        return np.zeros(1 << 20, np.uint8)  # store-resident

    ref = produce.remote()
    assert ray_tpu.get(ref, timeout=60).nbytes == 1 << 20

    objs = json.loads(_get(port, "/api/objects"))
    mine = [o for o in objs if o["object_id"] == ref.id.hex()]
    assert mine, f"driver-owned object missing from {len(objs)} rows"
    assert mine[0]["size"] >= 1 << 20
    assert mine[0]["ready"] and not mine[0]["error"]

    mem = json.loads(_get(port, "/api/memory"))
    assert mem["objects_total"] >= 1
    assert mem["nodes"] and mem["nodes"][0]["store_capacity"] > 0
    assert mem["nodes"][0]["store_bytes_allocated"] >= 1 << 20
    assert mem["by_owner"], "per-owner aggregation empty"

    # the sampler ticks every 5s; wait for at least one sample
    deadline = time.monotonic() + 15
    hist = []
    while time.monotonic() < deadline:
        hist = json.loads(_get(port, "/api/history"))
        if hist:
            break
        time.sleep(0.5)
    assert hist, "history ring buffer never sampled"
    assert hist[-1]["nodes_alive"] == 1
    assert "time" in hist[-1]
