"""MoE layer + expert parallelism tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models.moe import MoEConfig, init_moe, moe_layer
from ray_tpu.parallel.mesh import MeshSpec, build_mesh


@pytest.fixture(scope="module")
def expert_mesh():
    return build_mesh(MeshSpec(data=2, expert=4, tensor=1))


def _cfg(**kw):
    base = dict(num_experts=4, top_k=2, d_model=32, d_ff=64,
                capacity_factor=2.0, dtype=jnp.float32)
    base.update(kw)
    return MoEConfig(**base)


def test_moe_forward_shapes_and_aux():
    cfg = _cfg()
    params = init_moe(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
    out, aux = moe_layer(params, x, cfg)
    assert out.shape == x.shape
    assert float(aux) > 0.0  # balanced loss is ~1.0, must be finite


def test_moe_matches_dense_single_expert():
    """With one expert and top_k=1, MoE reduces to a plain MLP."""
    cfg = _cfg(num_experts=1, top_k=1, capacity_factor=4.0)
    params = init_moe(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, cfg.d_model))
    out, _ = moe_layer(params, x, cfg)
    h = jax.nn.gelu(x.reshape(-1, cfg.d_model) @ params["wi"][0])
    ref = (h @ params["wo"][0]).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_moe_differentiable():
    cfg = _cfg()
    params = init_moe(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.d_model))

    def loss(p):
        out, aux = moe_layer(p, x, cfg)
        return jnp.mean(out ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    norms = [float(jnp.linalg.norm(leaf)) for leaf in
             jax.tree_util.tree_leaves(g)]
    assert all(n == n for n in norms)  # no NaNs
    assert any(n > 0 for n in norms)


def test_moe_sharded_over_expert_axis(expert_mesh):
    """Same numbers under jit with experts sharded over the mesh (GSPMD
    inserts the dispatch all-to-all)."""
    cfg = _cfg(num_experts=8)
    params = init_moe(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, cfg.d_model))

    ref_out, ref_aux = moe_layer(params, x, cfg)

    with expert_mesh:
        sharded_params = {
            "gate": {"kernel": jax.device_put(
                params["gate"]["kernel"],
                NamedSharding(expert_mesh, P()))},
            "wi": jax.device_put(params["wi"],
                                 NamedSharding(expert_mesh, P("expert"))),
            "wo": jax.device_put(params["wo"],
                                 NamedSharding(expert_mesh, P("expert"))),
        }
        xs = jax.device_put(x, NamedSharding(expert_mesh, P("data")))
        out, aux = jax.jit(
            lambda p, xx: moe_layer(p, xx, cfg))(sharded_params, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)
