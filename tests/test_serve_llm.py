"""serve.llm tests: block pool, decode parity, scheduler preemption /
EOS, bounded recompilation, and the serve-deployment integration
(8 concurrent streamed requests, zero drops).

Decode parity is THE correctness gate: prefill + N single-token paged
decode steps must reproduce the full-sequence forward's logits (atol
1e-4, f32 tiny configs) for both model families — any drift in the
cache layout, rope positions, or masking shows up here first.
"""

import dataclasses
import sys
import threading

import cloudpickle
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.serve.llm import (
    BlockPool,
    EngineConfig,
    LLMEngine,
    ModelRunner,
    SamplingParams,
    Scheduler,
    SeqState,
    Sequence,
)
from ray_tpu.serve.llm.cache import CacheExhausted
from ray_tpu.serve.llm.runner import DecodeItem, adapters
from ray_tpu.serve.llm.scheduler import DecodeWork, PrefillWork

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ------------------------------------------------------------- block pool


def test_block_pool_alloc_free_and_null_page():
    pool = BlockPool(num_blocks=8, block_size=4)
    assert pool.usable_blocks == 7  # page 0 reserved
    a = pool.alloc(3)
    assert 0 not in a and len(set(a)) == 3
    assert pool.num_free() == 4
    with pytest.raises(CacheExhausted):
        pool.alloc(5)
    assert pool.num_free() == 4  # all-or-nothing
    pool.free(a)
    assert pool.num_free() == 7
    with pytest.raises(ValueError):
        pool.free(a)  # double free
    assert pool.blocks_for_tokens(1) == 1
    assert pool.blocks_for_tokens(4) == 1
    assert pool.blocks_for_tokens(5) == 2


def test_block_pool_prefix_index_refcount_and_lru():
    """The tentpole's bookkeeping invariants: content-registered pages
    survive release in an LRU, match_prefix revives + refcounts them,
    shared pages outlive any single owner, and eviction recycles the
    coldest cached page first."""
    from ray_tpu.serve.llm.cache import chain_hashes

    pool = BlockPool(num_blocks=6, block_size=4)  # 5 usable
    toks = list(range(1, 13))  # 3 full pages worth
    h = chain_hashes(toks, 4, 3)
    assert h == chain_hashes(toks, 4, 3)  # deterministic
    assert h[:2] == chain_hashes(toks[:8] + [99, 98, 97, 96], 4, 3)[:2]

    a = pool.alloc(2)
    pool.register(a[0], h[0])
    pool.register(a[1], h[1])
    # a second sequence with the same prefix shares the pages
    m = pool.match_prefix(h[:2])
    assert m == a
    assert pool.refcount(a[0]) == 2
    pool.free(a)  # first owner leaves: pages stay pinned by the second
    assert pool.refcount(a[0]) == 1
    assert pool.num_cached() == 0
    pool.free(m)  # last ref: registered pages PARK, not free
    assert pool.num_cached() == 2
    assert pool.num_free() == 5  # still allocatable (evictable)
    assert pool.num_used() == 0

    # revival out of the LRU
    m2 = pool.match_prefix(h)  # 3rd hash unknown: partial match
    assert m2 == a and pool.num_cached() == 0
    pool.free(m2)

    # eviction order: coldest first, and a freed chain parks TAIL-first
    # so eviction shrinks a cached prefix from its tail, never orphaning
    # the pages behind a missing head. Allocate 4 of 5 usable pages —
    # the 3 truly-free pages go first, then the LRU's oldest (a[1]).
    b = pool.alloc(4)
    assert a[1] in b and a[0] not in b
    assert pool.evictions == 1
    # the chain HEAD survives: a fresh match still reuses the first
    # page and stops at the evicted tail
    m3 = pool.match_prefix(h[:2])
    assert m3 == [a[0]]
    # first-writer-wins: a[0] still owns h[0]; re-registering that hash
    # on another page is a no-op and the original stays matchable
    pool.register(b[0], h[0])
    assert pool.match_prefix([h[0]]) == [a[0]]
    pool.free([a[0]])  # ref from the h[:2] match
    pool.free([a[0]])  # ref from the [h[0]] match
    pool.free(b)


# ----------------------------------------------------------- decode parity


def _parity_case(name, cfg, forward):
    ad = adapters()[name]
    params = ad.init_fn(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    n_prompt, n_dec = 13, 8
    toks = rng.randint(1, cfg.vocab_size, size=n_prompt + n_dec).tolist()
    full = np.asarray(forward(params, jnp.asarray([toks], jnp.int32),
                              cfg))[0]
    runner = ModelRunner(ad, cfg, params, block_size=8, num_blocks=16,
                         max_model_len=32, max_batch_size=2)
    pool = BlockPool(16, 8)
    table = pool.alloc(pool.blocks_for_tokens(n_prompt))
    _, last = runner.prefill(toks[:n_prompt], table, 0.0)
    np.testing.assert_allclose(last, full[n_prompt - 1], atol=1e-4)
    # teacher-forced decode: feed the reference token at each position,
    # compare logits against the full-sequence forward at that position
    for t in range(n_prompt, n_prompt + n_dec):
        need = pool.blocks_for_tokens(t + 1)
        if len(table) < need:
            table += pool.alloc(need - len(table))
        _, logits = runner.decode([DecodeItem(toks[t], t, table, 0.0)])
        np.testing.assert_allclose(logits[0], full[t], atol=1e-4)


def test_decode_parity_gpt2():
    from ray_tpu.models import gpt2

    cfg = dataclasses.replace(gpt2.GPT2Config.tiny(), dtype=jnp.float32,
                              remat=False)
    _parity_case("gpt2", cfg, gpt2.gpt2_forward)


def test_decode_parity_llama():
    from ray_tpu.models import llama

    _parity_case("llama", llama.LlamaConfig.tiny(), llama.llama_forward)


def test_decode_batch_parity_independent_sequences():
    """Batched decode lanes must not leak across sequences: two
    different prompts decoded in one batch match their solo runs."""
    from ray_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    ad = adapters()["llama"]
    params = ad.init_fn(jax.random.PRNGKey(1), cfg)
    runner = ModelRunner(ad, cfg, params, block_size=4, num_blocks=32,
                         max_model_len=32, max_batch_size=4)
    pool = BlockPool(32, 4)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (5, 9)]
    tables, nexts = [], []
    for p in prompts:
        t = pool.alloc(pool.blocks_for_tokens(len(p) + 1))
        nxt, _ = runner.prefill(p, t, 0.0)
        tables.append(t)
        nexts.append(nxt)
    batch = [DecodeItem(nexts[i], len(prompts[i]), tables[i], 0.0)
             for i in range(2)]
    joint_toks, joint_logits = runner.decode(batch)
    for i in range(2):
        solo_toks, solo_logits = runner.decode([batch[i]])
        assert joint_toks[i] == solo_toks[0]
        np.testing.assert_allclose(joint_logits[i], solo_logits[0],
                                   atol=1e-4)


# ---------------------------------------------------- bounded recompilation


def test_prefill_bucketing_bounds_compiles():
    from ray_tpu.models import gpt2

    cfg = dataclasses.replace(gpt2.GPT2Config.tiny(), dtype=jnp.float32,
                              remat=False)
    ad = adapters()["gpt2"]
    params = ad.init_fn(jax.random.PRNGKey(0), cfg)
    runner = ModelRunner(ad, cfg, params, block_size=8, num_blocks=64,
                         max_model_len=64, max_batch_size=4,
                         prefill_bucket_min=16)
    assert runner.prefill_bucket(3) == 16
    assert runner.prefill_bucket(17) == 32
    assert runner.prefill_bucket(64) == 64
    with pytest.raises(ValueError):
        runner.prefill_bucket(65)
    pool = BlockPool(64, 8)
    for n in (3, 5, 9, 14, 16):  # five lengths, ONE bucket
        table = pool.alloc(pool.blocks_for_tokens(n))
        runner.prefill(list(range(1, n + 1)), table, 0.0)
        pool.free(table)
    sigs = runner.compiled_signatures()
    assert sigs in (-1, 1), f"expected 1 compiled prefill program: {sigs}"


# ---------------------------------------------------------- scheduler unit


def _mk_seq(i, n_prompt, max_tokens=4):
    return Sequence(seq_id=i, prompt=list(range(1, n_prompt + 1)),
                    sampling=SamplingParams(max_tokens=max_tokens))


def test_scheduler_admission_waits_for_pages():
    pool = BlockPool(num_blocks=5, block_size=4)  # 4 usable pages
    sched = Scheduler(pool, max_batch_size=4, max_model_len=16)
    s1, s2 = _mk_seq(0, 12), _mk_seq(1, 12)  # 3 pages each
    sched.add(s1)
    sched.add(s2)
    w = sched.schedule()
    assert isinstance(w, PrefillWork) and w.seq is s1
    # s2 needs 3 pages, only 1 free: decode continues, no admission
    w2 = sched.schedule()
    assert isinstance(w2, DecodeWork) and w2.seqs == [s1]
    sched.commit_token(s1, 99)
    assert s1.state is SeqState.RUNNING
    # finishing s1 releases pages; s2 admits next
    sched._retire(s1, "test")
    w3 = sched.schedule()
    assert isinstance(w3, PrefillWork) and w3.seq is s2


def test_scheduler_preempts_lifo_and_requeues_front():
    pool = BlockPool(num_blocks=5, block_size=4)
    sched = Scheduler(pool, max_batch_size=4, max_model_len=16)
    s1, s2 = _mk_seq(0, 8, max_tokens=8), _mk_seq(1, 7, max_tokens=8)
    sched.add(s1)
    sched.add(s2)
    assert isinstance(sched.schedule(), PrefillWork)  # s1: 2 pages
    assert isinstance(sched.schedule(), PrefillWork)  # s2: 2 pages
    sched.commit_token(s1, 5)
    sched.commit_token(s2, 5)
    # s1 at pos 9 needs page 3; pool empty -> LIFO victim is s2
    w = sched.schedule()
    assert isinstance(w, DecodeWork)
    assert w.seqs == [s1]
    assert s2.state is SeqState.WAITING and s2.preemptions == 1
    assert sched.waiting[0] is s2  # requeued at the FRONT
    assert s2.table == []  # pages released
    assert s2.refill_tokens == s2.prompt + [5]  # resume keeps progress


# ---------------------------------------------------- scheduler chunking


def test_scheduler_chunks_interleave_with_decode():
    """A long prompt prefills in page-aligned chunks and continuation
    chunks ALTERNATE with decode steps — one admission can no longer
    monopolize consecutive engine steps."""
    pool = BlockPool(num_blocks=64, block_size=4)
    sched = Scheduler(pool, max_batch_size=4, max_model_len=64,
                      chunk_size=8)
    s1 = _mk_seq(0, 6, max_tokens=8)
    sched.add(s1)
    w = sched.schedule()
    assert isinstance(w, PrefillWork) and w.seq is s1
    assert (w.start, w.end, w.is_last) == (0, 6, True)  # fits one chunk
    sched.commit_token(s1, 42)  # decode-ready

    # a DISTINCT prompt (no shared prefix, so no pages get skipped)
    s2 = Sequence(seq_id=1, prompt=list(range(100, 124)),
                  sampling=SamplingParams(max_tokens=8))
    sched.add(s2)
    w = sched.schedule()  # admission is still prefill-first
    assert isinstance(w, PrefillWork) and w.seq is s2
    assert (w.start, w.end, w.is_last) == (0, 8, False)
    w = sched.schedule()  # decode slips in between chunks
    assert isinstance(w, DecodeWork) and w.seqs == [s1]
    sched.commit_token(s1, 43)
    w = sched.schedule()
    assert isinstance(w, PrefillWork) and (w.start, w.end) == (8, 16)
    w = sched.schedule()
    assert isinstance(w, DecodeWork) and w.seqs == [s1]
    sched.commit_token(s1, 44)
    w = sched.schedule()
    assert isinstance(w, PrefillWork) and (w.start, w.end) == (16, 24)
    assert w.is_last
    sched.commit_token(s2, 45)
    w = sched.schedule()  # both lanes decode together now
    assert isinstance(w, DecodeWork) and w.seqs == [s1, s2]


# ------------------------------------------------------------ engine level


def _f32_engine(num_blocks, max_batch_size=4, seed=0, chunk=256,
                prefix_cache=True, max_model_len=32):
    from ray_tpu.models import gpt2

    cfg = dataclasses.replace(gpt2.GPT2Config.tiny(), dtype=jnp.float32,
                              remat=False)
    return LLMEngine(EngineConfig(
        model="gpt2", model_config=cfg, block_size=4,
        num_blocks=num_blocks, max_model_len=max_model_len,
        max_batch_size=max_batch_size, seed=seed,
        prefill_chunk_size=chunk, enable_prefix_cache=prefix_cache))


def _drive(engine, streams):
    import time

    deadline = time.monotonic() + 120
    while any(s.final() is None for s in streams):
        if not engine.step():
            pass
        assert time.monotonic() < deadline, "engine made no progress"
    return [s.final() for s in streams]


def test_engine_greedy_matches_model_teacher_forced():
    """ENGINE-level parity (not just runner-level): greedy engine
    output must equal the teacher-forced argmax of the full-sequence
    forward. This is the test that catches engine<->runner position
    convention bugs (e.g. feeding the last token at pos instead of
    pos-1), which runner-level parity cannot see."""
    from ray_tpu.models import gpt2

    eng = _f32_engine(num_blocks=64)
    prompt = list(range(1, 11))
    out = eng.generate(prompt, SamplingParams(max_tokens=8), drive=True)
    gen = out["token_ids"]
    cfg = eng.model_cfg
    toks = prompt + gen
    full = np.asarray(gpt2.gpt2_forward(
        eng.runner.params, jnp.asarray([toks], jnp.int32), cfg))[0]
    ref = [int(np.argmax(full[t][:cfg.vocab_size]))
           for t in range(len(prompt) - 1, len(toks) - 1)]
    assert gen == ref, (gen, ref)


def test_cache_exhaustion_preempts_and_completes_identically():
    """The acceptance gate: under a pool too small for both sequences,
    one gets preempted and STILL produces exactly the tokens it would
    have produced unpreempted (greedy, f32, recompute-style resume)."""
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 500, size=10).tolist(),
               rng.randint(1, 500, size=11).tolist()]
    sp = SamplingParams(max_tokens=12)

    roomy = _f32_engine(num_blocks=64)
    want = [roomy.generate(p, sp, drive=True)["token_ids"]
            for p in prompts]

    tight = _f32_engine(num_blocks=11)  # 10 usable: forces preemption
    streams = [tight.add_request(p, sp) for p in prompts]
    finals = _drive(tight, streams)
    assert tight.scheduler.preemption_count > 0, \
        "pool was sized to force preemption"
    assert sum(f["preemptions"] for f in finals) > 0
    # the Prometheus counter must see them too (it increments around
    # schedule(), where preemption actually happens)
    from ray_tpu.util.metrics import prometheus_text

    line = [l for l in prometheus_text().splitlines()
            if l.startswith("serve_llm_preemptions_total{")]
    assert line and float(line[0].rsplit(" ", 1)[1]) > 0, line
    for f, expect in zip(finals, want):
        assert f["finish_reason"] == "length"
        assert f["token_ids"] == expect, \
            "preempted sequence diverged after requeue"


def test_eos_completion():
    eng = _f32_engine(num_blocks=64)
    free = eng.generate([7, 8, 9], SamplingParams(max_tokens=8),
                        drive=True)
    toks = free["token_ids"]
    assert len(toks) == 8 and free["finish_reason"] == "length"
    eos = toks[3]
    stopped = eng.generate(
        [7, 8, 9], SamplingParams(max_tokens=8, eos_token_id=eos),
        drive=True)
    assert stopped["finish_reason"] == "eos"
    # generation halts at the FIRST occurrence of the eos token
    first = toks.index(eos)
    assert stopped["token_ids"] == toks[:first + 1]


def test_chunked_prefill_parity_vs_monolithic():
    """Chunked prefill (page-aligned chunks via the prefill-from-offset
    program) must reproduce monolithic prefill bit-identically under
    greedy sampling, for both model families."""
    from ray_tpu.models import llama

    rng = np.random.RandomState(17)
    prompt = rng.randint(1, 500, size=21).tolist()
    sp = SamplingParams(max_tokens=8)

    mono = _f32_engine(num_blocks=64, chunk=0, prefix_cache=False)
    want = mono.generate(prompt, sp, drive=True)["token_ids"]
    chunked = _f32_engine(num_blocks=64, chunk=8, prefix_cache=False)
    got = chunked.generate(prompt, sp, drive=True)["token_ids"]
    assert got == want, "gpt2 chunked prefill diverged from monolithic"

    lcfg = llama.LlamaConfig.tiny()
    lp = rng.randint(1, lcfg.vocab_size, size=19).tolist()

    def llama_eng(chunk):
        return LLMEngine(EngineConfig(
            model="llama", model_config=lcfg, block_size=4,
            num_blocks=64, max_model_len=32, max_batch_size=4,
            prefill_chunk_size=chunk, enable_prefix_cache=False))

    lw = llama_eng(0).generate(lp, sp, drive=True)["token_ids"]
    lg = llama_eng(8).generate(lp, sp, drive=True)["token_ids"]
    assert lg == lw, "llama chunked prefill diverged from monolithic"


def test_prefix_cache_hit_parity_and_counters():
    """Warm-cache generation (prefix pages shared, prefill skipped) is
    bit-identical to the cold greedy run, and the hit/skip shows up in
    the engine's counters."""
    rng = np.random.RandomState(23)
    shared = rng.randint(1, 500, size=16).tolist()  # 4 full pages
    suffixes = [rng.randint(1, 500, size=3).tolist() for _ in range(3)]
    sp = SamplingParams(max_tokens=8)

    # cold references from per-prompt fresh engines (no reuse possible)
    want = [
        _f32_engine(num_blocks=96, chunk=8).generate(
            shared + sfx, sp, drive=True)["token_ids"]
        for sfx in suffixes]

    eng = _f32_engine(num_blocks=96, chunk=8)
    got, cached = [], []
    for sfx in suffixes:
        stream = eng.add_request(shared + sfx, sp)
        _drive(eng, [stream])
        fin = stream.final()
        got.append(fin["token_ids"])
        cached.append(fin["cached_tokens"])
    st = eng.stats()
    assert got == want, "warm prefix-cache output diverged from cold"
    # 2nd and 3rd requests each match the 4 shared full pages, and the
    # final event reports the reused tokens
    assert cached == [0, 16, 16], cached
    assert st["prefix_hit_pages"] >= 8, st
    assert st["blocks_used"] == 0  # all refs released
    assert st["blocks_cached"] > 0  # ...but pages parked for reuse
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    for name in ("serve_llm_prefix_cache_hits_total",
                 "serve_llm_prefix_cache_misses_total",
                 "serve_llm_prefill_chunks_total"):
        assert name in text, f"missing metric {name}"


def test_preemption_while_prefix_shared():
    """Two sequences share prefix pages; cache pressure preempts one.
    The victim's dropped refs must not invalidate the survivor's shared
    pages, and BOTH must finish bit-identical to an unconstrained run
    (the refcounting acceptance gate)."""
    rng = np.random.RandomState(29)
    shared = rng.randint(1, 500, size=12).tolist()  # 3 pages, 2 matchable
    prompts = [shared + rng.randint(1, 500, size=2).tolist(),
               shared + rng.randint(1, 500, size=3).tolist()]
    sp = SamplingParams(max_tokens=10)

    want = [
        _f32_engine(num_blocks=64, chunk=8).generate(
            p, sp, drive=True)["token_ids"] for p in prompts]

    tight = _f32_engine(num_blocks=10, chunk=8)  # 9 usable pages
    streams = [tight.add_request(p, sp) for p in prompts]
    finals = _drive(tight, streams)
    assert tight.scheduler.preemption_count > 0, \
        "pool was sized to force preemption under sharing"
    assert tight.scheduler.prefix_hit_pages > 0, \
        "second sequence should share the prefix pages"
    for f, expect in zip(finals, want):
        assert f["token_ids"] == expect, \
            "sharing + preemption changed greedy output"
    st = tight.stats()
    assert st["blocks_used"] == 0


def test_compile_misses_bounded_after_warmup():
    """The recompilation acceptance gate: after warmup() no request mix
    (short, long/chunked, warm-prefix, preempting) may trigger another
    XLA compile — serve_llm_compile_misses_total must not move."""
    from ray_tpu.util.metrics import prometheus_text

    def misses():
        total = 0.0
        for line in prometheus_text().splitlines():
            if line.startswith("serve_llm_compile_misses_total{"):
                total += float(line.rsplit(" ", 1)[1])
        return total

    eng = _f32_engine(num_blocks=24, chunk=8, max_batch_size=2)
    eng.warmup()
    base = misses()
    rng = np.random.RandomState(31)
    shared = rng.randint(1, 500, size=10).tolist()
    sp = SamplingParams(max_tokens=6)
    for n in (3, 17, 25):  # one-chunk, multi-chunk, multi-chunk
        eng.generate(rng.randint(1, 500, size=n).tolist(), sp,
                     drive=True)
    for _ in range(2):  # warm-prefix path (prefill from offset)
        eng.generate(shared + rng.randint(1, 500, size=2).tolist(), sp,
                     drive=True)
    streams = [eng.add_request(
        rng.randint(1, 500, size=12).tolist(),
        SamplingParams(max_tokens=12)) for _ in range(2)]
    _drive(eng, streams)  # small pool: decode growth under pressure
    assert misses() == base, \
        "a request mix recompiled after warmup (unbounded programs)"


def test_topk_topp_sampling():
    """Satellite gate: top-k/top-p run in-jit. Degenerate settings
    reduce to greedy (bit-identical), and a top-k=2 stream only ever
    emits tokens from the greedy top-2 at each step."""
    from ray_tpu.models import gpt2

    prompt = list(range(1, 9))
    base = _f32_engine(num_blocks=64)
    want = base.generate(prompt, SamplingParams(max_tokens=6),
                         drive=True)["token_ids"]
    k1 = _f32_engine(num_blocks=64).generate(
        prompt, SamplingParams(max_tokens=6, temperature=1.0, top_k=1),
        drive=True)["token_ids"]
    assert k1 == want, "top_k=1 must reduce to greedy"
    p0 = _f32_engine(num_blocks=64).generate(
        prompt, SamplingParams(max_tokens=6, temperature=1.0,
                               top_p=1e-9), drive=True)["token_ids"]
    assert p0 == want, "top_p->0 must reduce to greedy"

    eng = _f32_engine(num_blocks=64, seed=7)
    out = eng.generate(prompt, SamplingParams(
        max_tokens=8, temperature=1.5, top_k=2), drive=True)
    cfg = eng.model_cfg
    toks = list(prompt)
    for tok in out["token_ids"]:
        full = np.asarray(gpt2.gpt2_forward(
            eng.runner.params, jnp.asarray([toks], jnp.int32), cfg))[0]
        logits = full[-1][:cfg.vocab_size]
        top2 = set(np.argsort(logits)[-2:].tolist())
        assert tok in top2, (tok, top2)
        toks.append(tok)


def test_engine_concurrent_requests_zero_drops():
    """8 concurrent requests through one engine, interleaved prefill/
    decode, every request completes with its full token budget."""
    eng = _f32_engine(num_blocks=128, max_batch_size=8)
    rng = np.random.RandomState(11)
    lens = [3, 5, 7, 9, 11, 13, 15, 16]
    streams = [eng.add_request(rng.randint(1, 500, size=n).tolist(),
                               SamplingParams(max_tokens=6))
               for n in lens]
    finals = _drive(eng, streams)
    assert len(finals) == 8
    for f in finals:
        assert f["done"] and f["finish_reason"] == "length"
        assert f["num_generated"] == 6
    st = eng.stats()
    assert st["waiting"] == 0 and st["running"] == 0
    assert st["blocks_used"] == 0  # everything released


def test_metrics_exported():
    from ray_tpu.util.metrics import prometheus_text

    eng = _f32_engine(num_blocks=64)
    eng.generate([1, 2, 3], SamplingParams(max_tokens=3), drive=True)
    text = prometheus_text()
    for name in ("serve_llm_tokens_generated_total",
                 "serve_llm_requests_total", "serve_llm_ttft_ms",
                 "serve_llm_cache_utilization"):
        assert name in text, f"missing metric {name}"


# ------------------------------------------------------ serve integration


@pytest.fixture(scope="module")
def llm_cluster():
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    serve.shutdown()
    ray_tpu.shutdown()
    c.shutdown()


def test_llm_deployment_8_concurrent_streams(llm_cluster):
    """The serving acceptance gate: >= 8 concurrent requests stream
    token-by-token through a serve deployment on CPU jax with zero
    dropped requests, and engine metrics surface via the state API."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    app = build_llm_app(
        model="gpt2", preset="tiny",
        engine_config={"block_size": 8, "num_blocks": 96,
                       "max_model_len": 64, "max_batch_size": 8},
        max_ongoing_requests=16)
    handle = serve.run(app, name="llm")
    try:
        sh = handle.options(stream=True, generator_backpressure=64)
        rng = np.random.RandomState(5)
        n_req, n_tok = 8, 5
        gens = [sh.remote({"prompt": rng.randint(1, 500, size=4 + i)
                           .tolist(),
                           "max_tokens": n_tok})
                for i in range(n_req)]

        results = [None] * n_req
        errors = []

        def consume(i, gen):
            try:
                events = [ray_tpu.get(r, timeout=120) for r in gen]
                results[i] = events
            except Exception as e:  # noqa: BLE001
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=consume, args=(i, g))
                   for i, g in enumerate(gens)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, f"dropped/errored requests: {errors}"
        for events in results:
            assert events is not None
            *toks, final = events
            assert len(toks) == n_tok  # one event per token, streamed
            assert [e["index"] for e in toks] == list(range(n_tok))
            assert final["done"] and final["finish_reason"] == "length"
            assert final["num_generated"] == n_tok

        from ray_tpu.util.state import llm_status

        stats = llm_status("llm")
        assert len(stats) == 1
        assert stats[0]["model"] == "gpt2"
        assert stats[0]["running"] == 0 and stats[0]["waiting"] == 0
    finally:
        serve.delete("llm")


def test_affinity_routing_concentrates_shared_prefix(llm_cluster):
    """Prefix-affinity routing: requests sharing a prompt prefix carry
    the same affinity key, rendezvous onto ONE of two replicas, and
    that replica's prefix cache serves the shared pages — the hits show
    up on exactly one engine."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app, prompt_affinity_key

    app = build_llm_app(
        model="gpt2", preset="tiny", num_replicas=2,
        engine_config={"block_size": 8, "num_blocks": 96,
                       "max_model_len": 64, "max_batch_size": 8,
                       "prefill_chunk_size": 16},
        max_ongoing_requests=16)
    handle = serve.run(app, name="llm-aff")
    try:
        rng = np.random.RandomState(9)
        shared = rng.randint(1, 500, size=24).tolist()  # 3 full pages
        for _ in range(4):
            p = shared + rng.randint(1, 500, size=2).tolist()
            sh = handle.options(stream=True,
                                affinity_key=prompt_affinity_key(p))
            events = [ray_tpu.get(r, timeout=120)
                      for r in sh.remote({"prompt": p, "max_tokens": 3})]
            assert events[-1]["done"]

        from ray_tpu.util.state import llm_status

        stats = llm_status("llm-aff")
        assert len(stats) == 2
        hits = [s.get("prefix_hit_pages", 0) for s in stats]
        # 3 warm requests x 3 shared pages, all on the SAME replica
        assert sum(hits) >= 9, stats
        assert max(hits) == sum(hits), \
            f"affinity routing scattered a shared prefix: {hits}"
    finally:
        serve.delete("llm-aff")
