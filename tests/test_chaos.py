"""Deterministic RPC fault injection (reference model:
src/ray/rpc/rpc_chaos.h:23 + RAY_testing_rpc_failure env — drop the
first N sends of a method and assert the retry path recovers)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import rpc


@pytest.fixture
def chaos_cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    rpc.set_chaos("")  # disarm
    ray_tpu.shutdown()
    c.shutdown()


def test_task_submit_survives_dropped_schedule_rpc(chaos_cluster):
    """submit_task sends schedule_task with retries=2; dropping the first
    send must be invisible to the caller, and the nodelet-side dedup must
    not double-run the task when both the dropped-then-retried and any
    slow duplicate arrive."""

    @ray_tpu.remote(num_cpus=0.1)
    def bump(x):
        return x + 1

    # warm up: function export + worker spawn happen without chaos
    assert ray_tpu.get(bump.remote(1), timeout=60) == 2

    rpc.set_chaos("schedule_task=1")
    assert ray_tpu.get(bump.remote(10), timeout=60) == 11


def test_actor_call_survives_dropped_rpc(chaos_cluster):
    """Dropping the first actor_call send exercises the submit retry
    loop; the worker-side task_id dedup keeps actor state correct even
    when a retry races a slow (not lost) original."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1

    # actor calls are at-most-once by default; retries are opt-in
    # (reference: max_task_retries, python/ray/actor.py) — and the
    # worker-side task_id dedup makes the opt-in retry exactly-once.
    rpc.set_chaos("actor_call=1")
    assert ray_tpu.get(c.incr.options(max_task_retries=2).remote(),
                       timeout=120) == 2
    rpc.set_chaos("")
    # exactly-once effect: no hidden duplicate increment
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 3


def test_resolve_retry_after_drop(chaos_cluster):
    """Borrower resolve path retries after a dropped resolve RPC."""

    @ray_tpu.remote(num_cpus=0.1)
    def make():
        return np.arange(10)

    @ray_tpu.remote(num_cpus=0.1)
    def consume(a):
        return int(a.sum())

    ref = make.remote()
    assert ray_tpu.get(ref, timeout=60) is not None
    rpc.set_chaos("resolve=1")
    # worker resolving the borrowed arg hits its own (worker-process)
    # chaos budget only via env; driver-side drop exercises our wait path
    assert ray_tpu.get(consume.remote(ref), timeout=90) == 45


def test_lease_request_drop_falls_back(chaos_cluster):
    """Dropping the lease grant forces the classic scheduling path —
    the task still completes (submitter-side fallback)."""

    @ray_tpu.remote(num_cpus=0.2)
    def val(x):
        return x * 3

    assert ray_tpu.get(val.remote(2), timeout=60) == 6  # warm
    rpc.set_chaos("request_lease=2")
    assert ray_tpu.get(val.remote(5), timeout=90) == 15
    rpc.set_chaos("")


def test_leased_push_drop_recovered_by_ack_sweeper(chaos_cluster):
    """A dropped execute_leased push never reaches the worker; the
    submitter's ack sweeper resends after the (shortened) ack timeout,
    and worker-side dedup keeps it exactly-once."""
    import os

    os.environ["RAY_TPU_ACK_TIMEOUT_S"] = "2"  # env reads are uncached

    @ray_tpu.remote(num_cpus=0.2)
    def bump(x):
        return x + 100

    try:
        assert ray_tpu.get(bump.remote(1), timeout=60) == 101  # warm lease
        rpc.set_chaos("execute_leased=1")
        assert ray_tpu.get(bump.remote(7), timeout=90) == 107
    finally:
        rpc.set_chaos("")
        os.environ.pop("RAY_TPU_ACK_TIMEOUT_S", None)
