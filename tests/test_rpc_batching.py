"""Submit/return-path coalescing (ISSUE 11) + oneway small-message
coalescing (reference role: gRPC stream batching for high-frequency
control messages — VERDICT r4 weak item 3: the transport must
aggregate small messages under concurrency).

The submit coalescer packs pending task/actor-call submissions to the
same peer into one batched RPC frame (actor_calls / schedule_tasks /
multi-spec execute_leased) and the return path batches workers'
per-task task_done oneways symmetrically (task_done_batch)."""

import threading
import time

import pytest

from ray_tpu.core.rpc import Batcher, RpcClient, RpcServer


def test_oneway_batching_delivers_all_with_fewer_sends():
    server = RpcServer(name="batch-test").start()
    got = []
    server.register("inc", lambda msg, frames: got.append(msg["i"]),
                    oneway=True)
    client = RpcClient()  # private instance: do not disturb the shared one
    try:
        peer = client._peer(server.address)
        sends = []
        orig = peer.send

        def counting_send(parts):
            sends.append(len(parts))
            return orig(parts)

        peer.send = counting_send
        for i in range(100):
            client.send_oneway(server.address, "inc", {"i": i})
        deadline = time.time() + 10
        while time.time() < deadline and len(got) < 100:
            time.sleep(0.01)
        assert sorted(got) == list(range(100))
        # coalesced: far fewer zmq messages than oneways
        assert 0 < len(sends) < 50, len(sends)
    finally:
        client.close()
        server.stop()


def test_oneway_flushed_before_call():
    """Wire ordering: a oneway buffered before a call to the same peer
    leaves first."""
    server = RpcServer(name="order-test").start()
    order = []
    server.register("mark", lambda msg, frames: order.append("oneway"),
                    oneway=True)

    def ping(msg, frames):
        # the oneway was dispatched to the pool before this call; give
        # its handler a moment to run
        t0 = time.time()
        while "oneway" not in order and time.time() - t0 < 5:
            time.sleep(0.005)
        order.append("call")
        return {}

    server.register("ping", ping)
    client = RpcClient()
    try:
        client.send_oneway(server.address, "mark", {})
        client.call(server.address, "ping", {}, timeout=30)
        assert order == ["oneway", "call"]
    finally:
        client.close()
        server.stop()


def test_large_or_framed_oneways_bypass_batching():
    server = RpcServer(name="big-test").start()
    got = []
    server.register("blob", lambda msg, frames: got.append(
        (len(msg.get("data", b"")), len(frames))), oneway=True)
    client = RpcClient()
    try:
        client.send_oneway(server.address, "blob",
                           {"data": b"x" * (64 * 1024)})
        client.send_oneway(server.address, "blob", {}, frames=[b"frame"])
        deadline = time.time() + 10
        while time.time() < deadline and len(got) < 2:
            time.sleep(0.01)
        assert sorted(got) == [(0, 1), (64 * 1024, 0)]
    finally:
        client.close()
        server.stop()


def test_batcher_size_triggered_inline_flush(monkeypatch):
    """A buffer reaching SUBMIT_BATCH_MAX flushes on the appending
    thread — a tight submit loop never waits for the window."""
    monkeypatch.setenv("RAY_TPU_SUBMIT_BATCH_MAX", "8")
    flushed = []
    b = Batcher("t", lambda key, entries: flushed.append(
        (key, list(entries))))
    for i in range(8):
        b.append("k", i)
    assert flushed == [("k", list(range(8)))]
    assert b.pending_count() == 0
    b.close()


def test_batcher_window_flushes_stragglers(monkeypatch):
    monkeypatch.setenv("RAY_TPU_SUBMIT_BATCH_MAX", "1000")
    flushed = []
    b = Batcher("t", lambda key, entries: flushed.append(list(entries)))
    b.append("k", 1)
    b.append("k", 2)
    deadline = time.time() + 5
    while time.time() < deadline and not flushed:
        time.sleep(0.005)
    assert flushed == [[1, 2]]  # idle window swept the partial batch
    b.close()


def test_batcher_force_flush_and_per_key_order(monkeypatch):
    monkeypatch.setenv("RAY_TPU_SUBMIT_BATCH_MAX", "1000")
    flushed = []
    b = Batcher("t", lambda key, entries: flushed.append(
        (key, list(entries))))
    for i in range(5):
        b.append("a", i)
    b.append("b", 99)
    b.flush("a")  # only a's buffer leaves
    assert flushed == [("a", [0, 1, 2, 3, 4])]
    b.flush()
    assert flushed[1] == ("b", [99])
    b.close()


def test_batcher_window_zero_sends_immediately(monkeypatch):
    """SUBMIT_BATCH_WINDOW_MS=0 = send each immediately (the config
    flag's documented contract, same as the oneway batcher's)."""
    monkeypatch.setenv("RAY_TPU_SUBMIT_BATCH_WINDOW_MS", "0")
    flushed = []
    b = Batcher("t", lambda key, entries: flushed.append(list(entries)))
    b.append("k", 1)
    b.append("k", 2)
    assert flushed == [[1], [2]]  # no buffering, no sweeper involved
    b.close()


def test_batcher_flush_fn_error_never_wedges():
    calls = []

    def boom(key, entries):
        calls.append(list(entries))
        raise RuntimeError("flush boom")

    b = Batcher("t", boom)
    b.append("k", 1)
    b.flush()
    b.append("k", 2)
    b.flush()
    assert calls == [[1], [2]]  # second flush still ran
    assert b.pending_count() == 0
    b.close()


# ------------------------------------------------- cluster-level batching


@pytest.fixture(scope="module")
def batch_cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_actor_call_burst_coalesces_and_stays_ordered(batch_cluster):
    """A burst of pipelined actor calls rides actor_calls frames (the
    driver's coalesce counter moves) while per-actor submission order
    is preserved on a serial actor, and the return path delivers every
    result (task_done_batch dispatches on the driver's own server)."""
    import ray_tpu
    from ray_tpu.core.api import _global_runtime
    from ray_tpu.util.metrics import prometheus_text

    @ray_tpu.remote(num_cpus=0)
    class Seq:
        def __init__(self):
            self.log = []

        def mark(self, i):
            self.log.append(i)
            return i

        def read(self):
            return list(self.log)

    # earlier suites may clear_registry(): reset the lazy counter so it
    # re-registers into the current registry
    from ray_tpu.core import cluster_runtime as cr

    cr._coalesced_counter = None
    rt = _global_runtime()
    a = Seq.remote()
    ray_tpu.get(a.read.remote())

    def counts():
        out = {}
        for line in prometheus_text().splitlines():
            if line.startswith("core_submit_coalesced_total"):
                name, v = line.rsplit(" ", 1)
                out[name] = float(v)
        return out

    before = counts()
    n = 400
    refs = [a.mark.remote(i) for i in range(n)]
    assert ray_tpu.get(refs, timeout=120) == list(range(n))
    # order preserved end to end through the batched frames
    assert ray_tpu.get(a.read.remote(), timeout=60) == list(range(n))
    after = counts()
    key = 'core_submit_coalesced_total{kind="actor_call"}'
    assert after.get(key, 0) - before.get(key, 0) > 0, (before, after)
    # the return path coalesced too: the driver's server dispatched
    # task_done_batch frames, far fewer than one per call
    stats = rt.server.event_stats()
    assert stats.get("task_done_batch", {}).get("count", 0) > 0
    ray_tpu.kill(a)


def test_plain_task_burst_rides_schedule_tasks_frames(batch_cluster):
    """Tasks off the lease path (here: soft label selector) coalesce
    into schedule_tasks frames on the nodelet — far fewer scheduling
    dispatches than tasks — and every result lands."""
    import ray_tpu
    from ray_tpu.core.api import _global_runtime
    from ray_tpu.util.scheduling_strategies import SOFT_AFFINITY_LABEL

    rt = _global_runtime()
    nodelet = rt._booted[1]

    @ray_tpu.remote(num_cpus=0.1,
                    label_selector={"no-such-label": "x",
                                    SOFT_AFFINITY_LABEL: "1"})
    def double(x):
        return x * 2

    assert ray_tpu.get(double.remote(1), timeout=60) == 2  # warm path
    before = nodelet.server.event_stats()
    n = 100
    refs = [double.remote(i) for i in range(n)]
    assert ray_tpu.get(refs, timeout=120) == [i * 2 for i in range(n)]
    after = nodelet.server.event_stats()
    batched = after.get("schedule_tasks", {}).get("count", 0) - \
        before.get("schedule_tasks", {}).get("count", 0)
    singles = after.get("schedule_task", {}).get("count", 0) - \
        before.get("schedule_task", {}).get("count", 0)
    assert batched >= 1
    # the burst rode batch frames, not per-task round trips
    assert batched + singles < n / 2, (batched, singles)


def test_oneway_batch_size_histogram_observes():
    from ray_tpu.util.metrics import prometheus_text

    # earlier suites may clear_registry(): reset the lazy histogram so
    # it re-registers into the current registry
    import ray_tpu.core.rpc as rpc_mod

    rpc_mod._batch_size_hist = None
    server = RpcServer(name="hist-test").start()
    server.register("tick", lambda msg, frames: None, oneway=True)
    client = RpcClient()
    try:
        for i in range(50):
            client.send_oneway(server.address, "tick", {"i": i})
        client.flush_oneways()
        text = prometheus_text()
        assert "rpc_oneway_batch_size_count" in text
    finally:
        client.close()
        server.stop()


def test_event_stats_track_handlers_and_lag():
    """Reference: common/event_stats.h — per-handler duration + queue
    lag visible on the server."""
    server = RpcServer(name="stats-test").start()
    server.register("work", lambda msg, frames: time.sleep(0.02) or {})
    client = RpcClient()
    try:
        for _ in range(3):
            client.call(server.address, "work", {}, timeout=30)
        stats = server.event_stats()
        assert stats["work"]["count"] == 3
        assert stats["work"]["total_ms"] >= 3 * 20
        assert stats["work"]["max_ms"] >= 20
        assert stats["work"]["max_lag_ms"] >= 0
    finally:
        client.close()
        server.stop()
