"""Oneway small-message coalescing (reference role: gRPC stream
batching for high-frequency control messages — VERDICT r4 weak item 3:
the transport must aggregate small messages under concurrency)."""

import time

from ray_tpu.core.rpc import RpcClient, RpcServer


def test_oneway_batching_delivers_all_with_fewer_sends():
    server = RpcServer(name="batch-test").start()
    got = []
    server.register("inc", lambda msg, frames: got.append(msg["i"]),
                    oneway=True)
    client = RpcClient()  # private instance: do not disturb the shared one
    try:
        peer = client._peer(server.address)
        sends = []
        orig = peer.send

        def counting_send(parts):
            sends.append(len(parts))
            return orig(parts)

        peer.send = counting_send
        for i in range(100):
            client.send_oneway(server.address, "inc", {"i": i})
        deadline = time.time() + 10
        while time.time() < deadline and len(got) < 100:
            time.sleep(0.01)
        assert sorted(got) == list(range(100))
        # coalesced: far fewer zmq messages than oneways
        assert 0 < len(sends) < 50, len(sends)
    finally:
        client.close()
        server.stop()


def test_oneway_flushed_before_call():
    """Wire ordering: a oneway buffered before a call to the same peer
    leaves first."""
    server = RpcServer(name="order-test").start()
    order = []
    server.register("mark", lambda msg, frames: order.append("oneway"),
                    oneway=True)

    def ping(msg, frames):
        # the oneway was dispatched to the pool before this call; give
        # its handler a moment to run
        t0 = time.time()
        while "oneway" not in order and time.time() - t0 < 5:
            time.sleep(0.005)
        order.append("call")
        return {}

    server.register("ping", ping)
    client = RpcClient()
    try:
        client.send_oneway(server.address, "mark", {})
        client.call(server.address, "ping", {}, timeout=30)
        assert order == ["oneway", "call"]
    finally:
        client.close()
        server.stop()


def test_large_or_framed_oneways_bypass_batching():
    server = RpcServer(name="big-test").start()
    got = []
    server.register("blob", lambda msg, frames: got.append(
        (len(msg.get("data", b"")), len(frames))), oneway=True)
    client = RpcClient()
    try:
        client.send_oneway(server.address, "blob",
                           {"data": b"x" * (64 * 1024)})
        client.send_oneway(server.address, "blob", {}, frames=[b"frame"])
        deadline = time.time() + 10
        while time.time() < deadline and len(got) < 2:
            time.sleep(0.01)
        assert sorted(got) == [(0, 1), (64 * 1024, 0)]
    finally:
        client.close()
        server.stop()


def test_event_stats_track_handlers_and_lag():
    """Reference: common/event_stats.h — per-handler duration + queue
    lag visible on the server."""
    server = RpcServer(name="stats-test").start()
    server.register("work", lambda msg, frames: time.sleep(0.02) or {})
    client = RpcClient()
    try:
        for _ in range(3):
            client.call(server.address, "work", {}, timeout=30)
        stats = server.event_stats()
        assert stats["work"]["count"] == 3
        assert stats["work"]["total_ms"] >= 3 * 20
        assert stats["work"]["max_ms"] >= 20
        assert stats["work"]["max_lag_ms"] >= 0
    finally:
        client.close()
        server.stop()
