"""Logical plan, optimizer rules, Datasource ABC (VERDICT r3 missing
item 6; reference model: data/_internal/logical tests + datasource
contract)."""

import sys

import cloudpickle
import pytest

from ray_tpu.data.datasource import (
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONLDatasource,
    RangeDatasource,
    ReadTask,
    TextDatasource,
)
from ray_tpu.data.plan import (
    FilterRows,
    Fused,
    Limit,
    LimitPushdown,
    LogicalPlan,
    MapFusion,
    MapRows,
    RedundantLimitElimination,
)

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------------------
# optimizer rules
# ---------------------------------------------------------------------------

def test_limit_pushes_past_one_to_one_maps():
    ops = [MapRows(lambda x: x * 2), MapRows(lambda x: x + 1), Limit(3)]
    out = LimitPushdown().apply(ops)
    assert isinstance(out[0], Limit)
    # semantics preserved
    plan = LogicalPlan(ops)
    assert plan.compile()(list(range(10))) == [1, 3, 5]


def test_limit_blocked_by_filter():
    ops = [FilterRows(lambda x: x % 2 == 0), Limit(2)]
    out = LimitPushdown().apply(ops)
    assert isinstance(out[0], FilterRows), "limit must not cross a filter"
    assert LogicalPlan(ops).compile()(list(range(10))) == [0, 2]


def test_adjacent_limits_collapse():
    out = RedundantLimitElimination().apply([Limit(5), Limit(2), Limit(9)])
    assert len(out) == 1 and out[0].n == 2


def test_map_fusion_single_operator():
    ops = [MapRows(lambda x: x + 1), FilterRows(lambda x: x > 2),
           MapRows(lambda x: x * 10)]
    fused = MapFusion().apply(ops)
    assert len(fused) == 1 and isinstance(fused[0], Fused)
    assert fused[0].block_fn()([0, 1, 2, 3]) == [30, 40]


def test_plan_describe_and_global_limit():
    plan = LogicalPlan([MapRows(lambda x: x), Limit(7)])
    assert "Limit" in plan.describe()
    assert plan.global_limit() == 7
    assert LogicalPlan([Limit(7), FilterRows(lambda x: True)]) \
        .global_limit() is None


def test_empty_plan_identity():
    assert LogicalPlan([]).compile()([1, 2]) == [1, 2]


# ---------------------------------------------------------------------------
# datasources
# ---------------------------------------------------------------------------

def test_range_datasource_partitions():
    tasks = RangeDatasource(10).get_read_tasks(3)
    rows = [r for t in tasks for r in t()]
    assert rows == list(range(10))
    assert RangeDatasource(10).estimate_inmemory_data_size() == 80


def test_items_datasource():
    tasks = ItemsDatasource(["a", "b", "c"]).get_read_tasks(2)
    assert sorted(r for t in tasks for r in t()) == ["a", "b", "c"]


def test_file_datasources(tmp_path):
    (tmp_path / "a.txt").write_text("x\ny\n")
    (tmp_path / "b.csv").write_text("k,v\n1,2\n3,4\n")
    (tmp_path / "c.jsonl").write_text('{"n": 1}\n{"n": 2}\n')

    t = TextDatasource(str(tmp_path / "a.txt"))
    assert [r for task in t.get_read_tasks(4) for r in task()] == ["x", "y"]
    assert t.estimate_inmemory_data_size() == 4

    c = CSVDatasource(str(tmp_path / "b.csv"))
    rows = [r for task in c.get_read_tasks(1) for r in task()]
    assert rows == [{"k": "1", "v": "2"}, {"k": "3", "v": "4"}]

    j = JSONLDatasource(str(tmp_path / "c.jsonl"))
    rows = [r for task in j.get_read_tasks(1) for r in task()]
    assert rows == [{"n": 1}, {"n": 2}]


def test_file_datasource_grouping_honors_parallelism(tmp_path):
    for i in range(6):
        (tmp_path / f"f{i}.txt").write_text(f"{i}\n")
    tasks = TextDatasource(str(tmp_path)).get_read_tasks(2)
    assert len(tasks) == 2
    assert sorted(r for t in tasks for r in t()) == [str(i) for i in
                                                    range(6)]
    assert all(t.input_files for t in tasks)


def test_custom_datasource_contract():
    class Fib(Datasource):
        def get_read_tasks(self, parallelism):
            return [ReadTask(lambda: [1, 1, 2, 3, 5])]

    rows = [r for t in Fib().get_read_tasks(1) for r in t()]
    assert rows == [1, 1, 2, 3, 5]


def test_missing_files_error():
    with pytest.raises(FileNotFoundError):
        TextDatasource("/definitely/not/here/*.txt")


def test_read_parallelism_defaults_to_one_task_per_file(tmp_path):
    import ray_tpu
    from ray_tpu import data as rd

    ray_tpu.init(num_cpus=2)
    try:
        for i in range(12):
            (tmp_path / f"f{i}.txt").write_text(f"{i}\n")
        ds = rd.read_text(str(tmp_path))
        assert ds.num_blocks() == 12  # one task per file by default
        ds2 = rd.read_text(str(tmp_path), parallelism=3)
        assert ds2.num_blocks() == 3
        assert sorted(ds2.take_all()) == sorted(str(i) for i in range(12))
    finally:
        ray_tpu.shutdown()
