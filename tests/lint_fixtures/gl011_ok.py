"""GL011 non-firing fixture: handled errors + two-way raisers."""


class Service:
    def __init__(self, server):
        self.server = server
        server.register("task_done", self._h_task_done, oneway=True)
        server.register("resolve", self._h_resolve)  # two-way: raise ok

    def _h_task_done(self, msg, frames):
        try:
            if "task_id" not in msg:
                raise ValueError("missing task_id")  # caught below
            self._done = msg["task_id"]
        except Exception as e:  # noqa: BLE001
            self._log(e)  # handled locally: the sanctioned idiom

    def _h_resolve(self, msg, frames):
        def helper():
            raise RuntimeError("nested scope, not the handler")

        if not msg:
            raise KeyError("two-way handlers reply with errors")
        return helper()

    def _h_unregistered(self, msg, frames):
        raise RuntimeError("never registered oneway: quiet")

    def _log(self, e):
        self.last_error = repr(e)
