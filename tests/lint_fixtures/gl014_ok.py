"""GL014 ok fixture: fan-outs, gathers, retries, oneways stay quiet."""


class Clean:
    def __init__(self, client, nodelet):
        self.client = client
        self.nodelet = nodelet

    def gather(self, oids):
        # sanctioned: one shared deadline across the fan-out
        return self.client.call_gather(
            [(self.nodelet, "free_object", {"oid": o}) for o in oids])

    def per_peer(self, leases):
        for le in leases:  # loop-variant peer: a genuine fan-out
            self.client.call(le.nodelet, "return_lease",
                             {"lease_id": le.lease_id})

    def derived_peer(self, args):
        for a in args:
            loc = a.location or self.nodelet  # bound in the loop body
            self.client.call(loc, "object_meta", {"oid": a.oid})

    def retry(self, addr, msg):
        for attempt in range(3):  # range loop: sequential is the point
            try:
                return self.client.call(addr, "actor_call", msg)
            except Exception:  # noqa: BLE001
                continue

    def oneways(self, oids):
        for oid in oids:  # oneway batcher already coalesces these
            self.client.send_oneway(self.nodelet, "free_object",
                                    {"oid": oid})
