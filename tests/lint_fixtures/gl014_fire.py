"""GL014 fire fixture: per-item blocking RPCs to a loop-invariant peer."""
from ray_tpu.core.rpc import RpcClient


class Freer:
    def __init__(self, client, nodelet):
        self.client = client
        self.nodelet = nodelet

    def free_all(self, oids):
        for oid in oids:  # same peer every iteration: one frame would do
            self.client.call(self.nodelet, "free_object", {"oid": oid})

    def probe_all(self, task_ids, head):
        for tid in task_ids:
            RpcClient.shared().call_frames(head, "task_state",
                                           {"task_id": tid})

    def nested_collection_loop(self, groups):
        for group in groups:
            for item in group:  # peer fixed across both loops
                self.client.call(self.nodelet, "touch", {"item": item})
