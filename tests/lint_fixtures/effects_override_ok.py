"""``# effects:`` override ok twin: an annotation FREEZES a
function's effect set.

``_observe`` statically reaches open() through ``_read``, so without
the annotation GL012.inter would fire on the call under the guarded
lock. ``# effects: none`` declares the function inert (here: the read
is served from an in-memory fake in every deployment that matters),
and inference neither adds to nor propagates through it.
"""

import threading


class HookRunner:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}  # guarded_by(_lock)

    # effects: none
    def _observe(self):
        return self._read()

    def _read(self):
        with open("/proc/self/stat") as f:
            return f.read()

    def update(self, key):
        with self._lock:
            self._state[key] = self._observe()
