"""GL005 non-firing fixture: every mutation holds the lock (or is in
a caller-holds-the-lock helper)."""
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}  # guarded_by(_lock)
        self._hits = 0  # guarded_by(_lock)

    def put(self, k, v):
        with self._lock:
            self._entries[k] = v
            self._hits += 1

    def evict_locked(self, k):
        self._entries.pop(k, None)  # *_locked suffix: caller holds it

    def drop(self, k):
        """Caller holds self._lock (documented convention)."""
        del self._entries[k]

    def size(self):
        return len(self._entries)  # reads are never flagged
