"""GL016 firing fixture: raw console output in package code."""

import sys
from sys import stderr


def announce(value):
    print(f"computed {value}")  # FIRE: bare print in library code


def warn_raw(msg):
    sys.stderr.write(f"warning: {msg}\n")  # FIRE: raw stderr write


def warn_aliased(msg):
    stderr.write(f"warning: {msg}\n")  # FIRE: aliased stderr write
