"""GL008 non-firing fixture: clean oneway and two-way handlers."""


class Service:
    def __init__(self, server):
        self.server = server
        server.register("task_done", self._h_task_done, oneway=True)
        server.register("resolve", self._h_resolve)  # two-way: replies fine
        server.register("ping", lambda m, f: "pong")  # two-way lambda
        server.register("noop", lambda m, f: None, oneway=True)

    def _h_task_done(self, msg, frames):
        if not msg:
            return  # bare early exits are the oneway idiom
        self._last = msg
        return None  # explicit None: nothing dropped

    def _h_resolve(self, msg, frames):
        def helper():
            return {"nested": True}  # nested fn, not the handler

        return helper()  # two-way handler replying is the whole point

    def _h_mixed(self, msg, frames):
        return {"ok": True}  # never registered oneway: quiet
