"""GL012 firing fixture: blocking calls under a guarded_by lock."""
import time
import threading

import ray_tpu


class Controller:
    def __init__(self, client):
        self._lock = threading.Lock()
        self._replicas = []  # guarded_by(_lock)
        self.client = client

    def probe(self):
        with self._lock:
            for r in self._replicas:
                ray_tpu.get(r)  # FIRE: remote result under the lock

    def settle(self):
        with self._lock:
            time.sleep(0.5)  # FIRE: timer under the lock
            self._replicas.clear()

    def scrape(self, address):
        with self._lock:
            return self.client.call(address, "stats", {})  # FIRE: RPC
