"""GL006 firing fixture: bare except and swallowed cancellation."""


def drain(q):
    try:
        q.flush()
    except:  # FIRE: bare except
        pass


def run(fn):
    try:
        fn()
    except BaseException:  # FIRE: swallowed, nothing recorded
        return None


def poll(task):
    try:
        task.step()
    except KeyboardInterrupt:  # FIRE: ^C vanishes outside main()
        pass
