"""GL010 fire fixture: module globals annotated guarded_by(<lock>) but
mutated bare at some sites (locked at others — the inconsistency that
makes the locked sites useless)."""

import threading

_LOCK = threading.Lock()
_TABLE = {}  # guarded_by(_LOCK)
# guarded_by(_LOCK)
_COUNT = 0


def locked_site(k, v):
    with _LOCK:
        _TABLE[k] = v


def bare_item_write(k, v):
    _TABLE[k] = v  # fires: same global, no lock


def bare_mutator_call(k):
    _TABLE.pop(k, None)  # fires


def bare_rebind():
    global _COUNT
    _COUNT += 1  # fires
