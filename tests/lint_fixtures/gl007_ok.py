"""GL007 non-firing fixture: every pin is released or handed off."""


class Nodelet:
    def __init__(self, store):
        self.store = store
        self.meta = {}

    def read(self, oid):
        buf = self.store.get(oid)
        try:
            return bytes(buf)
        finally:
            self.store.release(oid)

    def open_view(self, oid):
        """Zero-copy hand-off; caller releases via store.release(oid)."""
        return self.store.get(oid)

    def borrow_unreleased(self, oid):
        return self.store.get(oid)  # *_unreleased suffix: hand-off

    def config(self, r):
        store = r.get("store", {})  # a dict named store: not a pin
        return store.get("capacity", 0)

    def nested_release(self, oid):
        view = self.store.get(oid)

        def done():
            self.store.release(oid)

        return view, done
