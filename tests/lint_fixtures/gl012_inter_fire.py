"""GL012.inter fire: the blocking call hides behind a helper.

The per-file pass sees only a plain method call under the lock and
stays quiet; the indexed effect closure sees that the callee
transitively reaches open() / time.sleep() and fires at the call
site, with the chain as evidence.
"""

import threading
import time


class SpillManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}  # guarded_by(_lock)

    def _read_disk(self, path):
        with open(path, "rb") as f:
            return f.read()

    def _nap(self):
        time.sleep(0.01)

    def lookup(self, key, path):
        with self._lock:
            if key not in self._table:
                self._table[key] = self._read_disk(path)  # GL012.inter
            return self._table[key]

    def touch(self, key):
        with self._lock:
            self._nap()  # GL012.inter
            self._table[key] = 1
