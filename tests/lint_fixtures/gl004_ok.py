"""GL004 non-firing fixture: transfers only at the host boundary."""
import jax
import numpy as np


@jax.jit
def train_step(params, batch):
    return (params - batch).sum()  # stays on device


def report(metrics):
    # explicit host boundary, not reachable from the trace root
    return float(np.asarray(metrics).item())
