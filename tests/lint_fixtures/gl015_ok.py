"""GL015 clean fixture: timestamps, monotonic durations, the anchor."""

import time

# the sanctioned epoch anchor: one wall operand, one monotonic operand
_WALL_ANCHOR = time.time() - time.monotonic()


def work():
    pass


def stamp() -> dict:
    # timestamps without subtraction are what time.time() is FOR
    return {"time": time.time(), "session": f"s_{int(time.time())}"}


def elapsed() -> float:
    t0 = time.monotonic()
    work()
    return time.monotonic() - t0  # monotonic duration: correct


def remaining(deadline: float) -> float:
    # unknown provenance on `deadline`: only known-wall operands fire
    return deadline - time.time()


def cpu_elapsed() -> float:
    c0 = time.thread_time()
    work()
    return time.thread_time() - c0
