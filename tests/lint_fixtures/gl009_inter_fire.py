"""GL009.inter fire: lock-order inversions invisible per-file.

Two inversion pairs: (1) Engine nests Engine._lock -> Pool._pool_lock
lexically, while Reaper nests the same pair the other way around —
different classes, so the per-file (per-class-scope) pass never pairs
them; (2) Cache.put HOLDS Cache._cache_lock while calling a Registry
method that ACQUIRES Registry._reg_lock (the lock-held-in-caller /
acquired-in-callee shape), while Sweeper nests the opposite order
lexically. Attribute types are statically evident (constructor
assignments), so the index unifies ``self.pool._pool_lock`` with
Pool's own ``_pool_lock``.
"""

import threading


class Pool:
    def __init__(self):
        self._pool_lock = threading.Lock()
        self.stats = {}

    def add(self, key):
        with self._pool_lock:
            self.stats[key] = 1


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.pool = Pool()

    def submit(self, key):
        with self._lock:
            with self.pool._pool_lock:
                self.pool.stats[key] = 1


class Reaper:
    def __init__(self):
        self.engine = Engine()
        self.pool = Pool()

    def drain(self):
        with self.pool._pool_lock:
            with self.engine._lock:  # GL009.inter (vs Engine.submit)
                return dict(self.pool.stats)


class Registry:
    def __init__(self):
        self._reg_lock = threading.Lock()
        self.items = {}

    def note(self, key):
        with self._reg_lock:
            self.items[key] = 1


class Cache:
    def __init__(self):
        self._cache_lock = threading.Lock()
        self.registry = Registry()

    def put(self, key):
        with self._cache_lock:
            self.registry.note(key)  # acquires Registry._reg_lock


class Sweeper:
    def __init__(self):
        self.registry = Registry()
        self.cache = Cache()

    def sweep(self):
        with self.registry._reg_lock:
            with self.cache._cache_lock:  # GL009.inter (vs Cache.put)
                return len(self.registry.items)
