"""GL002 firing fixture: .remote() futures thrown away."""


def kick(actor, f):
    f.remote(1)  # FIRE: bare statement discards the ObjectRef
    actor.step.options(num_cpus=1).remote()  # FIRE: options chain too
