"""GL019 firing fixture: per-iteration device->host syncs in a step loop."""

import jax
import jax.numpy as jnp
import numpy as np


class Engine:
    def __init__(self, params):
        self._decode_jit = jax.jit(lambda p, t: (t, t))
        self._params = params

    def _step_loop(self):
        tokens = jnp.zeros((8,), jnp.int32)
        while True:
            logits, tokens = self._decode_jit(self._params, tokens)
            tok = int(tokens[0])  # FIRE: cast of a device value
            prob = logits.max().item()  # FIRE: .item() sync per step
            host = np.asarray(logits)  # FIRE: asarray of device value
            stats = jax.device_get(logits)  # FIRE: device_get in loop
            self._emit(tok, prob, host, stats)

    def _emit(self, *parts):
        pass
