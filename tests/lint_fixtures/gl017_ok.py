"""GL017 ok twin: every annotation resolves to a real lock.

Tracker defines its lock; SubTracker inherits it through a base the
index can resolve; External's base escapes the index entirely, so the
rule stays conservative (the lock may live there); the module-level
annotation names a real module global.
"""

import threading

from some_external_pkg import BaseStore


class Tracker:
    def __init__(self):
        self._items_lock = threading.Lock()
        self.items = {}  # guarded_by(_items_lock)


class SubTracker(Tracker):
    def __init__(self):
        super().__init__()
        self.extra = {}  # guarded_by(_items_lock)


class External(BaseStore):
    def __init__(self):
        super().__init__()
        self.data = {}  # guarded_by(_store_lock)


_counts_lock = threading.Lock()
_counts = {}  # guarded_by(_counts_lock)
