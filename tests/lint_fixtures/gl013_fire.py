"""GL013 firing fixture: handlers calling back into their own server."""


class Service:
    def __init__(self, server, client):
        self.server = server
        self.client = client
        self.address = server.address
        server.register("stats", self._h_stats)
        server.register("chain", self._h_chain)
        server.register("fan", self._h_fan)
        server.register("leaf", self._h_leaf)

    def _h_stats(self, msg, frames):
        # FIRE: synchronous self-call — needs a second pool thread
        return self.client.call(self.address, "leaf", {})

    def _h_chain(self, msg, frames):
        # FIRE: same deadlock through the server's own address attribute
        value, fr = self.client.call_frames(self.server.address,
                                            "leaf", {}, timeout=5)
        return value

    def _h_fan(self, msg, frames):
        # FIRE: gather list that includes this server itself
        return self.client.call_gather(
            [(self.address, "leaf", {})], timeout=5)

    def _h_leaf(self, msg, frames):
        return {}
