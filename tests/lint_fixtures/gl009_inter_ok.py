"""GL009.inter ok twin: every path takes the locks in ONE global
order (coordination lock before leaf lock), so the global graph has
edges but no cycles."""

import threading


class Pool:
    def __init__(self):
        self._pool_lock = threading.Lock()
        self.stats = {}

    def add(self, key):
        with self._pool_lock:
            self.stats[key] = 1


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.pool = Pool()

    def submit(self, key):
        with self._lock:
            with self.pool._pool_lock:
                self.pool.stats[key] = 1


class Reaper:
    def __init__(self):
        self.engine = Engine()
        self.pool = Pool()

    def drain(self):
        with self.engine._lock:
            with self.pool._pool_lock:
                return dict(self.pool.stats)


class Registry:
    def __init__(self):
        self._reg_lock = threading.Lock()
        self.items = {}

    def note(self, key):
        with self._reg_lock:
            self.items[key] = 1


class Cache:
    def __init__(self):
        self._cache_lock = threading.Lock()
        self.registry = Registry()

    def put(self, key):
        with self._cache_lock:
            self.registry.note(key)


class Sweeper:
    def __init__(self):
        self.registry = Registry()
        self.cache = Cache()

    def sweep(self):
        with self.cache._cache_lock:
            with self.registry._reg_lock:
                return len(self.registry.items)
