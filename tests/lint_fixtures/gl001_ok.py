"""GL001 non-firing fixture: the sanctioned async/handler patterns."""
import asyncio

import ray_tpu


class Worker:
    async def poll(self, ref):
        loop = asyncio.get_running_loop()
        # offloaded to an executor: the loop thread never blocks
        return await loop.run_in_executor(None, ray_tpu.get, [ref])

    async def nap(self, ev):
        await asyncio.sleep(1)
        await ev.wait()  # awaited asyncio form, not a thread block


class Nodelet:
    def _h_fetch(self, msg, frames):
        self.ready.wait(timeout=60)  # bounded: ok
        return {}

    def helper(self, ref):
        return ray_tpu.get([ref])  # plain sync code: ok
