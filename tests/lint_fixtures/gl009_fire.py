"""GL009 firing fixture: inverted nested lock acquisition orders."""

import threading


class Engine:
    def __init__(self, store):
        self._lock = threading.Lock()
        self.store = store

    def submit(self, item):
        with self._lock:
            with self.store._store_lock:  # defines _lock -> store lock
                self.store.put(item)

    def drain(self):
        with self.store._store_lock:
            with self._lock:  # FIRE: inverted vs submit
                return list(self.store.items)

    def stats(self):
        with self.store._store_lock:
            with self._lock:  # FIRE: same inversion, second site
                return len(self.store.items)


class Pool:
    def __init__(self):
        self._alloc_lock = threading.Lock()
        self._evict_lock = threading.Lock()

    def grow(self):
        with self._alloc_lock:
            with self._evict_lock:  # defines alloc -> evict
                self.pages += 1

    def shrink(self):
        with self._evict_lock:
            with self._alloc_lock:  # FIRE: inverted vs grow
                self.pages -= 1
