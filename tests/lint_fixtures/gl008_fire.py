"""GL008 firing fixture: oneway handlers that return dropped values."""


class Service:
    def __init__(self, server):
        self.server = server
        server.register("task_done", self._h_task_done, oneway=True)
        server.register("heartbeat", self._h_heartbeat, oneway=True)
        server.register("ping", lambda m, f: "pong", oneway=True)  # FIRE

    def _h_task_done(self, msg, frames):
        if not msg:
            return  # bare early exit: fine
        return {"ok": True}  # FIRE: reply silently dropped

    def _h_heartbeat(self, msg, frames):
        self._beat = msg["t"]
        return msg["t"]  # FIRE: oneway via positional-style keyword


def wire(server):
    server.register("free_object", handler, True)  # positional oneway
    return server


def handler(msg, frames):
    return len(msg)  # FIRE: registered oneway positionally above
