"""GL013 clean twin: handlers that talk to PEERS (or not at all)."""

import threading


class Service:
    def __init__(self, server, client):
        self.server = server
        self.client = client
        self.address = server.address
        server.register("relay", self._h_relay)
        server.register("notify", self._h_notify)
        server.register("snapshot", self._h_snapshot)
        self._gathered = {}

    def _h_relay(self, msg, frames):
        # ok: a DIFFERENT peer answers from its own pool
        return self.client.call(msg["peer"], "leaf", {})

    def _h_notify(self, msg, frames):
        # ok: oneway has no reply — nothing parks on the pool
        self.client.send_oneway(self.address, "event", {})
        return {}

    def _h_snapshot(self, msg, frames):
        # ok: reads state a non-handler thread gathered
        return dict(self._gathered)

    def _refresh_loop(self):
        # ok: not a handler — a dedicated thread may call its own
        # server (one parked thread, pool still drains)
        while True:
            self._gathered = self.client.call(self.address,
                                              "snapshot", {})

    def start(self):
        threading.Thread(target=self._refresh_loop, daemon=True).start()
