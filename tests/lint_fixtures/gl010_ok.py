"""GL010 clean twin: every mutation of an annotated module global
holds the lock, uses a caller-holds convention, or is not actually the
global at all."""

import threading

_LOCK = threading.Lock()
_TABLE = {}  # guarded_by(_LOCK)
_COUNT = 0  # guarded_by(_LOCK)
_PLAIN = {}  # unannotated: not checked

_TABLE["boot"] = 1  # import time: happens-before sharing


def locked_sites(k, v):
    with _LOCK:
        _TABLE[k] = v
        _TABLE.pop(k, None)


def locked_rebind():
    global _COUNT
    with _LOCK:
        _COUNT += 1


def _flush_locked():
    _TABLE.clear()  # *_locked suffix: caller holds the lock


def documented_helper():
    """caller holds _lock... specifically holds _LOCK."""
    _TABLE.update({})


def local_shadow():
    _TABLE = {}  # a LOCAL, not the module global
    _TABLE["x"] = 1
    return _TABLE


def shadowing_param(_TABLE):
    _TABLE["x"] = 1  # parameter, not the module global


def unannotated(k):
    _PLAIN[k] = 1
