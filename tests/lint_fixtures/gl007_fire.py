"""GL007 firing fixture: store.get() pins with no release()."""


class Nodelet:
    def __init__(self, store):
        self.store = store

    def read_once(self, oid):
        buf = self.store.get(oid)  # FIRE: no release in this function
        return bytes(buf)

    def checksum(self, oid):
        view = self.store.get(oid)  # FIRE: released on the WRONG store
        other_store = object()
        other_store.release(oid)
        return sum(view)


def copy_out(store, oid, dst):
    view = store.get(oid)  # FIRE: module-level helper, never releases
    dst[:] = view
