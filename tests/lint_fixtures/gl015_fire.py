"""GL015 firing fixture: time.time() deltas used as durations."""

import time


def work():
    pass


def elapsed_direct():
    t0 = time.time()
    work()
    return time.time() - t0  # FIRE: wall call minus wall-assigned name


class Timer:
    def begin(self):
        self._start = time.time()

    def end(self):
        self._end = time.time()
        return self._end - self._start  # FIRE: both attrs wall-assigned


def spin_budget():
    start = time.time()
    while time.time() - start < 5.0:  # FIRE: wall-vs-wall loop budget
        work()
