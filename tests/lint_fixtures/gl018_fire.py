"""GL018 firing fixture: unbounded accumulation on traffic paths."""


class LeakyHead:
    def __init__(self):
        self._events = []
        self._peers = set()
        self._rows = []

    def _h_task_event(self, msg):
        self._events.append(msg)  # FIRE: handler append, no consumer

    def _h_register(self, msg):
        self._peers.add(msg["node_id"])  # FIRE: handler add, no discard

    def poll_loop(self):
        while True:
            self._rows.extend(self._scrape())  # FIRE: loop extend

    def _scrape(self):
        return []
