"""GL001 firing fixture: blocking calls in async + handler contexts.

Never imported — parsed by graftlint in tests only.
"""
import time

import ray_tpu


class Worker:
    async def poll(self, ref):
        return ray_tpu.get([ref])  # FIRE: blocking get in async method

    async def nap(self):
        time.sleep(1)  # FIRE: time.sleep parks the event loop


class Nodelet:
    def _h_fetch(self, msg, frames):
        self.ready.wait()  # FIRE: no-timeout wait in an RPC handler
        return {}
