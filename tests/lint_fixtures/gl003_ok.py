"""GL003 non-firing fixture: keyed jax.random, host code off-trace."""
import random
import time

import jax


@jax.jit
def step(key, x):
    return x + jax.random.normal(key, x.shape)  # deterministic: ok


def host_side():
    # wall clock + RNG are fine outside any trace root
    return time.time(), random.random()
