"""GL019 clean fixture: values stay on device; syncs sit at the boundary."""

import time

import jax
import jax.numpy as jnp
import numpy as np


class Engine:
    def __init__(self, params):
        self._decode_jit = jax.jit(lambda p, t: t)
        self._params = params
        self._queue = []

    def _step_loop(self):
        tokens = jnp.zeros((8,), jnp.int32)
        deadline = float(time.monotonic()) + 5.0  # host value: quiet
        batch = np.asarray(self._queue)  # python list: quiet
        del batch
        while time.monotonic() < deadline:
            tokens = self._decode_jit(self._params, tokens)
            self._stash(tokens)  # stays on device across iterations
        self._publish(tokens)

    def _stash(self, tok):
        self._queue.append(tok)

    def _publish(self, tokens):
        # one sync at the loop boundary, not one per iteration
        return jax.device_get(tokens).tolist()
