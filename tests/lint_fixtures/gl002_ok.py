"""GL002 non-firing fixture: refs are bound, returned, or passed."""
import ray_tpu


def kick(actor, f):
    ref = f.remote(1)
    refs = [actor.step.remote() for _ in range(2)]
    return ray_tpu.get([ref] + refs)
