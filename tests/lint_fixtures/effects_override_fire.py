"""``# effects:`` override fire: dynamic dispatch the closure cannot
see, declared blocking by annotation.

``_run_hook`` calls through a stored callable — statically inert, so
without the annotation the index would infer no effects. The
``# effects: blocking`` line declares what dispatch hides, and
GL012.inter fires on the call under the guarded lock.
"""

import threading


class HookRunner:
    def __init__(self, hook):
        self._lock = threading.Lock()
        self._hook = hook
        self._state = {}  # guarded_by(_lock)

    # effects: blocking
    def _run_hook(self):
        return self._hook()

    def update(self, key):
        with self._lock:
            self._state[key] = self._run_hook()  # GL012.inter
