"""GL017 fire: guarded_by annotations naming locks nobody defines.

Tracker annotates with ``_items_lock`` but only ever creates
``_lock``; the module-level annotation names ``_counts_lock`` which no
module assignment (or import) provides. Both annotations guard
nothing — the guarded-by rules silently enforce a lock that cannot be
held.
"""

import threading


class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = {}  # guarded_by(_items_lock)   GL017: never defined


_counts = {}  # guarded_by(_counts_lock)   GL017: never defined
