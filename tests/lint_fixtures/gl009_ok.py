"""GL009 non-firing fixture: one consistent order, non-lock contexts,
distinct classes, and sequential (non-nested) acquisitions."""

import threading


class Engine:
    def __init__(self, store):
        self._lock = threading.Lock()
        self.store = store

    def submit(self, item):
        with self._lock:
            with self.store._store_lock:  # same order everywhere
                self.store.put(item)

    def drain(self):
        with self._lock:
            with self.store._store_lock:
                return list(self.store.items)

    def reopen(self, path):
        with self._lock:
            with open(path) as f:  # not a lock: ignored
                return f.read()


class Other:
    def reversed_names_other_class(self):
        # the same NAMES as Engine's pair, but a different class means
        # different lock objects — not an inversion of Engine's order
        with self.store._store_lock:
            with self._lock:
                return self.snapshot()


def flat(a_lock, b_lock):
    with a_lock:
        pass
    with b_lock:  # sequential, not nested: no ordering constraint
        pass
