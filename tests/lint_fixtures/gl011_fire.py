"""GL011 firing fixture: exceptions escaping oneway handlers."""


class Service:
    def __init__(self, server):
        self.server = server
        server.register("task_done", self._h_task_done, oneway=True)
        server.register("heartbeat", self._h_heartbeat, oneway=True)

    def _h_task_done(self, msg, frames):
        if "task_id" not in msg:
            raise ValueError("missing task_id")  # FIRE: nobody sees it
        self._done = msg["task_id"]

    def _h_heartbeat(self, msg, frames):
        assert msg.get("node_id"), "beat without node"  # FIRE: swallowed
        try:
            self._beat = float(msg["t"])
        except KeyError:
            raise RuntimeError("no timestamp")  # FIRE: escapes the except


def wire(server):
    server.register("free_object", handler, True)  # positional oneway
    return server


def handler(msg, frames):
    if not msg:
        raise KeyError("empty free")  # FIRE: registered oneway above
    return None
