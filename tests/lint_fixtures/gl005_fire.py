"""GL005 firing fixture: guarded state mutated without its lock."""
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}  # guarded_by(_lock)
        self._hits = 0  # guarded_by(_lock)

    def put(self, k, v):
        self._entries[k] = v  # FIRE: subscript assign, no lock

    def bump(self):
        self._hits += 1  # FIRE: augassign, no lock

    def evict(self, k):
        self._entries.pop(k, None)  # FIRE: mutator call, no lock
