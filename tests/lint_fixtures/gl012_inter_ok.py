"""GL012.inter ok twin: snapshot under the lock, block outside it.

Same helpers as the fire fixture, but every transitively blocking
call happens with the guarded lock released.
"""

import threading
import time


class SpillManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}  # guarded_by(_lock)

    def _read_disk(self, path):
        with open(path, "rb") as f:
            return f.read()

    def _nap(self):
        time.sleep(0.01)

    def lookup(self, key, path):
        with self._lock:
            cached = self._table.get(key)
        if cached is not None:
            return cached
        data = self._read_disk(path)
        with self._lock:
            self._table[key] = data
        return data

    def touch(self, key):
        self._nap()
        with self._lock:
            self._table[key] = 1
