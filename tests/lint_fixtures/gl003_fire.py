"""GL003 firing fixture: host RNG / wall clock in traced code."""
import random
import time

import jax
import numpy as np


@jax.jit
def step(x):
    return x * random.random()  # FIRE: host RNG in a trace root


def helper(x):
    return x + time.time()  # FIRE: reachable from the jitted loss


@jax.jit
def loss(x):
    return helper(x)


def update(x):
    return x * np.random.rand()  # FIRE: np RNG, root via jax.jit(update)


train = jax.jit(update)
