"""GL013.inter ok twin: each fire shape, defused the sanctioned way.

Alpha -> Beta stays one-directional because Beta's callback rides
send_oneway (no reply to park on). Delta's same-class call is the
peer-to-peer idiom (another NODE's instance of the same service) and
is not a cycle edge. Epsilon's transitive self-call is fine because
the handler is registered slow=True — the slow pool can park without
starving the service loop.
"""


class Alpha:
    def __init__(self, server, client, beta_addr):
        self.server = server
        self.client = client
        self.beta_addr = beta_addr
        server.register("alpha_step", self._h_step)
        server.register("alpha_note", self._h_note, oneway=True)

    def _h_note(self, msg, frames):
        self.last = msg

    def _h_step(self, msg, frames):
        return self._forward(msg)

    def _forward(self, msg):
        return self.client.call(self.beta_addr, "beta_pull", msg,
                                timeout=5)


class Beta:
    def __init__(self, server, client, alpha_addr):
        self.server = server
        self.client = client
        self.alpha_addr = alpha_addr
        server.register("beta_pull", self._h_pull)

    def _h_pull(self, msg, frames):
        self.client.send_oneway(self.alpha_addr, "alpha_note", msg)
        return {"ok": True}


class Delta:
    def __init__(self, server, client, peer_addr):
        self.client = client
        self.peer_addr = peer_addr
        server.register("delta_pull", self._h_pull)

    def _h_pull(self, msg, frames):
        return self._fetch(msg)

    def _fetch(self, msg):
        # same service class on a DIFFERENT node: peer-to-peer pull
        return self.client.call(self.peer_addr, "delta_pull", msg,
                                timeout=5)


class Epsilon:
    def __init__(self, server, client):
        self.client = client
        self.address = server.address
        server.register("eps_gather", self._h_gather, slow=True)

    def _h_gather(self, msg, frames):
        return self._pull(msg)

    def _pull(self, msg):
        return self.client.call(self.address, "eps_ping", msg,
                                timeout=5)
