"""GL012 non-firing fixture: snapshot under the lock, block outside;
blocking under an UN-annotated lock is someone else's contract."""
import time
import threading

import ray_tpu


class Controller:
    def __init__(self, client):
        self._lock = threading.Lock()
        self._replicas = []  # guarded_by(_lock)
        self._io_lock = threading.Lock()  # not guarded_by-annotated
        self.client = client

    def probe(self):
        with self._lock:
            replicas = list(self._replicas)  # snapshot...
        return [ray_tpu.get(r) for r in replicas]  # ...block outside

    def settle(self):
        with self._lock:
            self._replicas.clear()
        time.sleep(0.5)  # timer outside the critical section

    def scrape(self, address):
        with self._io_lock:  # a plain serialization lock is fine
            return self.client.call(address, "stats", {})

    def sized_read(self, fut):
        with self._lock:
            n = len(self._replicas)
        return fut.result(), n  # future join outside the lock
