"""Suppression fixture: real violations silenced two ways."""


def kick(f):
    f.remote(1)  # graftlint: disable=discarded-future
    # graftlint: disable=GL002
    f.remote(2)
    f.remote(3)  # graftlint: disable=all
