"""GL013.inter fire: reentry the single pass cannot see.

Three shapes: (1) a 2-hop cycle across two service classes — Alpha's
handler synchronously calls a method of Beta whose handler calls back
into a method of Alpha (both edges reported, one per direction); (2) a
self-targeted synchronous RPC reached through a helper call instead of
sitting in the handler body. No handler body contains a self-addressed
call, so the per-file GL013 pass is quiet on this file.
"""


class Alpha:
    def __init__(self, server, client, beta_addr):
        self.server = server
        self.client = client
        self.beta_addr = beta_addr
        server.register("alpha_step", self._h_step)
        server.register("alpha_info", self._h_info)

    def _h_info(self, msg, frames):
        return {"ok": True}

    def _h_step(self, msg, frames):
        return self._forward(msg)

    def _forward(self, msg):
        return self.client.call(self.beta_addr, "beta_pull", msg,
                                timeout=5)  # GL013.inter (cycle)


class Beta:
    def __init__(self, server, client, alpha_addr):
        self.server = server
        self.client = client
        self.alpha_addr = alpha_addr
        server.register("beta_pull", self._h_pull)

    def _h_pull(self, msg, frames):
        return self.client.call(self.alpha_addr, "alpha_info", msg,
                                timeout=5)  # GL013.inter (cycle)


class Gamma:
    def __init__(self, server, client):
        self.server = server
        self.client = client
        self.address = server.address
        server.register("gamma_sync", self._h_sync)

    def _h_sync(self, msg, frames):  # GL013.inter (transitive self)
        return self._refresh(msg)

    def _refresh(self, msg):
        # self-targeted, but one call hop away from the handler body
        return self.client.call(self.address, "gamma_sync", msg,
                                timeout=5)
