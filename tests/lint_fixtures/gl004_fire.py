"""GL004 firing fixture: implicit host transfers in a training step."""
import jax
import numpy as np


@jax.jit
def train_step(params, batch):
    loss = (params - batch).sum()
    log_val = loss.item()  # FIRE: device->host sync per step
    host = np.asarray(batch)  # FIRE: materializes on host under trace
    return jax.device_get(loss), log_val, host  # FIRE: device_get
