"""GL006 non-firing fixture: narrow catches, recorded or re-raised."""


def drain(q):
    try:
        q.flush()
    except ValueError:
        pass


def run(fn, sink):
    try:
        fn()
    except BaseException as e:  # recorded for a supervisor: ok
        sink.error = e


def guard(fn):
    try:
        fn()
    except BaseException:
        raise  # re-raised: ok


def main():
    try:
        guard(None)
    except KeyboardInterrupt:  # clean ^C exit in a CLI main: ok
        pass
