"""GL018 clean fixture: every accumulation path carries a bound."""

import collections


class BoundedHead:
    def __init__(self):
        self._events = collections.deque(maxlen=10_000)  # bounded ctor
        self._peers = set()
        self._outbox = []
        self._rows = []
        self._staging = []

    def _h_task_event(self, msg):
        self._events.append(msg)  # deque(maxlen=...) never grows past cap

    def _h_register(self, msg):
        self._peers.add(msg["node_id"])

    def _h_unregister(self, msg):
        self._peers.discard(msg["node_id"])  # a consumer exists

    def _h_enqueue(self, msg):
        if len(self._outbox) < 5000:
            self._outbox.append(msg)

    def flush_loop(self):
        batch, self._outbox = self._outbox, []  # drain-by-reassignment
        return batch

    def _h_retire(self, msg):
        self._rows.append(msg)
        del self._rows[:-100]  # trimmed in place

    def record(self, item):
        # not a handler or loop: builders/one-shot setup may append
        self._staging.append(item)
