"""GL016 clean fixture: structured logging and non-console writes."""

import logging

_log = logging.getLogger("ray_tpu.fixture")


def announce(value):
    _log.info("computed %s", value)  # the sanctioned path


def warn(msg):
    _log.warning("warning: %s", msg)


def persist(path, data):
    with open(path, "w") as f:
        f.write(data)  # a file's write is not a console write


class Sink:
    def write(self, chunk):  # defining write is fine
        return len(chunk)


def drain(sink: Sink, chunk):
    sink.write(chunk)  # and so is calling a non-sys stream's write


def sanctioned_handshake(address):
    # protocol output a parent process parses from stdout — the
    # justified-suppression shape
    print(f"ADDR {address}", flush=True)  # graftlint: disable=bare-print
