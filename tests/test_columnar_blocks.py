"""Columnar (zero-copy) data blocks + union/zip.

Reference model: data/_internal/arrow_block.py — blocks move between
map stages as columnar tables whose payload never passes through
pickle; here the audit rides the serialization layer's byte counters
(core/serialization.STATS).
"""

import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.cluster_utils import Cluster
from ray_tpu.data.block import (
    concat_batches,
    is_columnar,
    slice_block,
    split_columnar,
    to_batch,
    to_rows,
)

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 8,
                                "store_capacity": 512 * 1024 * 1024})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


# ----------------------------------------------------------- block unit


def test_block_format_roundtrip():
    col = {"x": np.arange(10), "y": np.ones(10)}
    assert is_columnar(col)
    assert not is_columnar([{"x": 1}])
    rows = to_rows(col)
    assert rows[3]["x"] == 3
    back = to_batch(rows)
    np.testing.assert_array_equal(back["x"], col["x"])
    sl = slice_block(col, 2, 5)
    assert sl["x"].base is col["x"]  # a VIEW, not a copy
    cat = concat_batches([slice_block(col, 0, 4), slice_block(col, 4, 10)])
    np.testing.assert_array_equal(cat["x"], col["x"])
    parts = split_columnar(col, 3)
    assert [len(p["x"]) for p in parts] == [4, 3, 3]


# ------------------------------------------------------------- pipeline


def test_from_numpy_blocks_stay_columnar(cluster):
    arr = np.arange(1000, dtype=np.float32).reshape(250, 4)
    ds = rd.from_numpy(arr, parallelism=4)
    blocks = [ray_tpu.get(r, timeout=60) for r in ds._block_refs]
    assert all(isinstance(b, np.ndarray) for b in blocks)
    out = np.concatenate(list(ds.iter_batches(batch_size=50)))
    np.testing.assert_array_equal(out, arr)


def test_map_batches_numpy_keeps_columnar_blocks(cluster):
    ds = rd.from_numpy({"x": np.arange(100, dtype=np.float64)},
                       parallelism=4)
    out = ds.map_batches(lambda b: {"x": b["x"] * 2, "sq": b["x"] ** 2})
    blocks = [ray_tpu.get(r, timeout=120)
              for r in out._execute()]
    assert all(is_columnar(b) and isinstance(b, dict) for b in blocks)
    got = concat_batches(blocks)
    np.testing.assert_array_equal(got["x"], np.arange(100) * 2.0)
    # row ops still work downstream of columnar blocks
    rows = out.filter(lambda r: r["sq"] < 9).take_all()
    assert [r["x"] for r in rows] == [0.0, 2.0, 4.0]


def test_zero_pickle_of_block_payloads(cluster):
    """VERDICT done-criterion: map_batches over big numeric blocks moves
    payload exclusively through out-of-band buffers — the pickle stream
    carries only envelopes (counter-instrumented at both the driver and
    inside the worker)."""
    from ray_tpu.core import serialization as ser

    n = 4_000_000  # 32 MB of float64 payload
    ser.reset_stats()
    ds = rd.from_numpy({"x": np.random.default_rng(0).random(n)},
                       parallelism=8)
    put_pickle = ser.STATS["pickle_bytes"]
    put_buffer = ser.STATS["buffer_bytes"]
    assert put_buffer >= n * 8
    assert put_pickle < 64 * 1024  # envelopes only

    def audited_double(batch):
        # runs in the WORKER: its deserialize of the input block must
        # have ridden buffers, not the pickle stream
        from ray_tpu.core import serialization as wser

        s = wser.STATS
        assert s["buffer_bytes"] >= batch["x"].nbytes, s
        assert s["pickle_bytes"] < 0.01 * max(s["buffer_bytes"], 1), s
        return {"x": batch["x"] * 2.0}

    out = ds.map_batches(audited_double)
    ser.reset_stats()
    total = 0
    for batch in out.iter_batches(batch_size=500_000):
        total += len(batch["x"])
    assert total == n
    # driver-side read of the mapped blocks: payload via buffers
    assert ser.STATS["buffer_bytes"] >= n * 8
    assert ser.STATS["pickle_bytes"] < 0.01 * ser.STATS["buffer_bytes"]


def test_repartition_columnar(cluster):
    ds = rd.from_numpy({"x": np.arange(90)}, parallelism=9)
    rp = ds.repartition(3)
    blocks = [ray_tpu.get(r, timeout=60) for r in rp._block_refs]
    assert len(blocks) == 3
    assert all(is_columnar(b) for b in blocks)
    np.testing.assert_array_equal(concat_batches(blocks)["x"],
                                  np.arange(90))


def test_union(cluster):
    a = rd.from_numpy({"x": np.arange(10)})
    b = rd.from_numpy({"x": np.arange(10, 30)})
    c = rd.from_items([{"x": 99}]).map(lambda r: {"x": r["x"] + 1})
    u = a.union(b, c)
    assert u.count() == 31
    xs = sorted(int(r["x"]) for r in u.take_all())
    assert xs == list(range(30)) + [100]


def test_zip_columnar_and_rows(cluster):
    left = rd.from_numpy({"a": np.arange(20)}, parallelism=3)
    right = rd.from_numpy({"b": np.arange(20) * 10,
                           "a": np.arange(20) + 5}, parallelism=5)
    z = left.zip(right)
    rows = z.take_all()
    assert len(rows) == 20
    assert rows[7]["a"] == 7 and rows[7]["b"] == 70
    assert rows[7]["a_1"] == 12  # right-side duplicate renamed
    # row-format zip pairs into tuples
    z2 = rd.from_items(list("abcd")).zip(rd.from_items([1, 2, 3, 4]))
    assert z2.take_all() == [("a", 1), ("b", 2), ("c", 3), ("d", 4)]
    with pytest.raises(ValueError, match="equal row counts"):
        rd.from_items([1, 2]).zip(rd.from_items([1, 2, 3])).take_all()


def test_union_applies_pending_actor_stage(cluster):
    """Regression: union/zip must not silently drop a pending
    map_batches(compute="actors") stage."""
    a = rd.from_items([{"a": 1}, {"a": 2}]).map_batches(
        lambda b: {"a": b["a"] * 10}, compute="actors")
    u = a.union(rd.from_items([{"a": 3}]))
    xs = sorted(int(r["a"]) for r in u.take_all())
    assert xs == [3, 10, 20]


def test_write_jsonl_and_parquet_columnar(cluster, tmp_path):
    """Regression: writers must emit ROWS from columnar blocks, not
    column names."""
    import json

    ds = rd.from_numpy({"x": np.arange(3)}, parallelism=1)
    paths = ds.write_jsonl(str(tmp_path / "j"))
    rows = [json.loads(line) for p in paths for line in open(p)]
    assert rows == [{"x": 0}, {"x": 1}, {"x": 2}]
    try:
        import pyarrow.parquet as pq
    except ImportError:
        return
    ppaths = ds.write_parquet(str(tmp_path / "p"))
    table = pq.read_table(ppaths[0])
    assert table.to_pylist() == [{"x": 0}, {"x": 1}, {"x": 2}]


def test_mixed_columnar_union_repartition_falls_back_to_rows(cluster):
    u = rd.from_numpy(np.arange(4)).union(
        rd.from_numpy({"a": np.arange(4)}))
    rp = u.repartition(2)
    assert rp.count() == 8


def test_iter_jax_batches_from_columnar(cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    ds = rd.from_numpy({"x": np.arange(64, dtype=np.float32)},
                       parallelism=4)
    batches = list(ds.iter_jax_batches(batch_size=16))
    assert len(batches) == 4
    assert all(isinstance(b["x"], jax.Array) for b in batches)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["x"]) for b in batches]),
        np.arange(64, dtype=np.float32))


def test_join_inner_and_left(cluster):
    users = rd.from_items([{"uid": i, "name": f"u{i}"} for i in range(8)],
                          parallelism=3)
    orders = rd.from_items(
        [{"uid": i % 4, "amount": 10 * i, "name": f"o{i}"}
         for i in range(6)], parallelism=2)
    inner = users.join(orders, on="uid").take_all()
    assert len(inner) == 6  # every order matches a user (uids 0-3)
    row = next(r for r in inner if r["amount"] == 50)
    assert row["uid"] == 1 and row["name"] == "u1" and row["name_1"] == "o5"

    left = users.join(orders, on="uid", how="left").take_all()
    # users 4..7 have no orders but survive with their own columns
    unmatched = [r for r in left if r["uid"] >= 4]
    assert len(unmatched) == 4
    assert all("amount" not in r for r in unmatched)
    assert len(left) == 10  # 6 matches + 4 left-only

    # joins compose with pending ops and columnar sources
    big = rd.from_numpy({"uid": np.arange(8), "score": np.arange(8) * 1.0})
    j = users.filter(lambda r: r["uid"] < 3).join(big, on="uid")
    rows = sorted(j.take_all(), key=lambda r: r["uid"])
    assert [int(r["uid"]) for r in rows] == [0, 1, 2]
    assert rows[2]["score"] == 2.0

    with pytest.raises(ValueError, match="how must be"):
        users.join(orders, on="uid", how="outer")
