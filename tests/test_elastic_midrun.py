"""Mid-run elastic Train scaling (VERDICT r2 weak item 4).

Reference parity: continuous scaling decisions in Train v2
(train/v2/_internal/execution/scaling_policy/scaling_policy.py:26) —
the gang GROWS while running when capacity appears, restarting from the
latest checkpoint at a result boundary.
"""

import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_midrun_elastic_grows_gang(tmp_path):
    """A gang running at capacity 1 GROWS to 2 when a node joins mid-run
    (continuous scaling decision, not just start-time sizing)."""
    from ray_tpu.train import (
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.train.checkpoint import CheckpointConfig

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        def loop(config):
            import time as _t

            from ray_tpu import train

            ctx = train.get_context()
            start = 0
            ck = train.get_checkpoint()
            if ck is not None:
                with ck.as_directory() as d:
                    with open(f"{d}/step") as f:
                        start = int(f.read())
            for step in range(start, 12):
                _t.sleep(0.5)
                ckpt = None
                if ctx.get_world_rank() == 0:
                    d = f"{ctx.get_trial_dir()}/ck{step}"
                    import os as _os

                    _os.makedirs(d, exist_ok=True)
                    with open(f"{d}/step", "w") as f:
                        f.write(str(step + 1))
                    ckpt = train.Checkpoint(d)
                train.report({"step": step,
                              "world": ctx.get_world_size()}, checkpoint=ckpt)

        trainer = JaxTrainer(
            loop,
            train_loop_config={},
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1, elastic_interval_s=1.0,
                resources_per_worker={"CPU": 1.0}),
            run_config=RunConfig(
                name="elastic_midrun", storage_path=str(tmp_path),
                checkpoint_config=CheckpointConfig(num_to_keep=3)),
        )
        import threading

        def add_node_later():
            time.sleep(3.0)
            c.add_node(num_cpus=1)

        threading.Thread(target=add_node_later, daemon=True).start()
        result = trainer.fit()
        worlds = [m["world"] for m in result.metrics_history]
        assert worlds[0] == 1, worlds  # started at capacity
        assert worlds[-1] == 2, worlds  # grew mid-run after the join
        assert result.metrics_history[-1]["step"] == 11
    finally:
        ray_tpu.shutdown()
        c.shutdown()
