"""Collective API tests (reference model:
python/ray/util/collective/tests/ — groups of actors reducing numpy
arrays; plus in-program XLA collectives on the virtual mesh)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col


@pytest.fixture
def ray_local():
    ray_tpu.init(local_mode=True, num_cpus=8)
    yield
    ray_tpu.shutdown()
    col._groups.clear()


@ray_tpu.remote
class Member:
    def __init__(self, rank, world):
        self.rank = rank
        self.world = world

    def setup(self, group):
        col.init_collective_group(self.world, self.rank, group_name=group)
        return True

    def do_allreduce(self, group):
        x = np.full((4,), float(self.rank + 1))
        return col.allreduce(x, group_name=group)

    def do_allgather(self, group):
        return col.allgather(np.array([self.rank]), group_name=group)

    def do_broadcast(self, group):
        x = np.arange(3.0) if self.rank == 0 else None
        return col.broadcast(x, src_rank=0, group_name=group)

    def do_reducescatter(self, group):
        x = np.arange(8.0)
        return col.reducescatter(x, group_name=group)

    def do_sendrecv(self, group):
        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=1, group_name=group)
            return None
        return col.recv(src_rank=0, group_name=group)


def _mk_group(n, group):
    members = [Member.remote(i, n) for i in range(n)]
    ray_tpu.get([m.setup.remote(group) for m in members])
    return members


def test_allreduce_sum(ray_local):
    ms = _mk_group(4, "g1")
    outs = ray_tpu.get([m.do_allreduce.remote("g1") for m in ms])
    for o in outs:
        assert np.array_equal(o, np.full((4,), 1.0 + 2 + 3 + 4))


def test_allgather(ray_local):
    ms = _mk_group(3, "g2")
    outs = ray_tpu.get([m.do_allgather.remote("g2") for m in ms])
    for o in outs:
        assert [int(x[0]) for x in o] == [0, 1, 2]


def test_broadcast(ray_local):
    ms = _mk_group(3, "g3")
    outs = ray_tpu.get([m.do_broadcast.remote("g3") for m in ms])
    for o in outs:
        assert np.array_equal(o, np.arange(3.0))


def test_reducescatter(ray_local):
    ms = _mk_group(2, "g4")
    outs = ray_tpu.get([m.do_reducescatter.remote("g4") for m in ms])
    # sum over 2 ranks of arange(8) = 2*arange(8); rank i gets half i
    assert np.array_equal(outs[0], 2 * np.arange(4.0))
    assert np.array_equal(outs[1], 2 * np.arange(4.0, 8.0))


def test_send_recv(ray_local):
    ms = _mk_group(2, "g5")
    outs = ray_tpu.get([m.do_sendrecv.remote("g5") for m in ms])
    assert outs[0] is None
    assert np.array_equal(outs[1], np.array([42.0]))


def test_allreduce_pytree(ray_local):
    ms = _mk_group(2, "g6")

    @ray_tpu.remote
    def member_tree(rank):
        col.init_collective_group(2, rank, group_name="g6t")
        tree = {"w": np.ones((2, 2)) * (rank + 1), "b": np.array([rank])}
        return col.allreduce(tree, group_name="g6t")

    outs = ray_tpu.get([member_tree.remote(i) for i in range(2)])
    for o in outs:
        assert np.array_equal(o["w"], np.full((2, 2), 3.0))
        assert np.array_equal(o["b"], np.array([1]))


# ---------------------------------------------------------------- in-program


def test_in_program_collectives_on_mesh(cpu_mesh8):
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel import ops

    mesh = cpu_mesh8

    def f(x):
        s = ops.psum(x, ("data", "fsdp", "tensor"))
        g = ops.all_gather(x, "tensor", axis=0)
        return s, g

    x = np.arange(8.0).reshape(8, 1)
    fm = ops.shard_map(f, mesh, in_specs=P(("data", "fsdp", "tensor")),
                       out_specs=(P(), P(("data", "fsdp"))))
    s, g = fm(x)
    assert float(np.asarray(s)[0]) == x.sum()
    assert np.asarray(g).shape == (8, 1)


def test_ring_shift(cpu_mesh8):
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel import ops

    mesh = cpu_mesh8

    def f(x):
        return ops.ring_shift(x, "data", 1)

    x = np.arange(2.0).reshape(2, 1)
    fm = ops.shard_map(f, mesh, in_specs=P("data"), out_specs=P("data"))
    out = np.asarray(fm(x)).ravel()
    assert out.tolist() == [1.0, 0.0]
