"""Fault tolerance: actor restart, node death, placement groups.

Reference model: python/ray/tests/test_actor_failures.py,
test_placement_group*.py, test_gcs_fault_tolerance.py.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
)


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=4, labels={"ray.io/tpu-slice": "slice-0"})
    c.add_node(num_cpus=4, labels={"ray.io/tpu-slice": "slice-0"})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_actor_restart(cluster):
    @ray_tpu.remote(max_restarts=2)
    class Phoenix:
        def __init__(self):
            self.calls = 0

        def crash(self):
            import os

            os._exit(1)

        def ping(self):
            self.calls += 1
            return self.calls

    p = Phoenix.remote()
    assert ray_tpu.get(p.ping.remote()) == 1
    try:
        ray_tpu.get(p.crash.remote(), timeout=15)
    except Exception:
        pass
    # actor restarts with fresh state; calls eventually succeed
    deadline = time.monotonic() + 30
    result = None
    while time.monotonic() < deadline:
        try:
            result = ray_tpu.get(p.ping.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.5)
    assert result == 1  # fresh state after restart


def test_actor_no_restart_dies(cluster):
    @ray_tpu.remote(max_restarts=0)
    class Mortal:
        def crash(self):
            import os

            os._exit(1)

        def ping(self):
            return "ok"

    m = Mortal.remote()
    assert ray_tpu.get(m.ping.remote()) == "ok"
    try:
        ray_tpu.get(m.crash.remote(), timeout=15)
    except Exception:
        pass
    from ray_tpu.core import exceptions as exc

    deadline = time.monotonic() + 20
    saw_dead = False
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(m.ping.remote(), timeout=10)
            time.sleep(0.3)
        except (exc.ActorDiedError, exc.ActorUnavailableError, exc.TaskError):
            saw_dead = True
            break
    assert saw_dead


def test_pg_strict_spread(cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}, {"CPU": 1}],
                         strategy="STRICT_SPREAD")
    assert pg.wait(30)
    info = placement_group_table(pg)
    assert info["state"] == "CREATED"
    assert len(set(info["nodes"])) == 3  # three distinct nodes
    remove_placement_group(pg)


def test_pg_strict_pack_tasks_colocate(cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
    assert pg.wait(30)
    info = placement_group_table(pg)
    assert len(set(info["nodes"])) == 1

    @ray_tpu.remote(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0))
    def where():
        return ray_tpu.get_runtime_context().node_id.hex()

    nodes = set(ray_tpu.get([where.remote() for _ in range(4)]))
    assert nodes == {info["nodes"][0]}
    remove_placement_group(pg)


def test_pg_infeasible_stays_pending(cluster):
    pg = placement_group([{"CPU": 64}], strategy="PACK")
    assert not pg.wait(2)
    info = placement_group_table(pg)
    assert info["state"] == "PENDING"
    remove_placement_group(pg)


def test_pg_pending_created_after_node_add(cluster):
    """VERDICT done-criterion: infeasible PG becomes CREATED when a
    feasible node joins (head-side pending replanning — reference:
    gcs_placement_group_manager pending queue)."""
    c = cluster
    pg2 = placement_group([{"bigres": 1}], strategy="PACK")
    assert not pg2.wait(1.5)
    extra = c.add_node(num_cpus=2, resources={"bigres": 2.0})
    assert pg2.wait(20)
    info = placement_group_table(pg2)
    assert info["state"] == "CREATED"
    remove_placement_group(pg2)
    c.remove_node(extra)
    # don't leak a mid-death node into the next test's resource snapshots
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 3:
            break
        time.sleep(0.3)
    assert sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 3


def test_pg_bundle_metering_serializes_tasks(cluster):
    """Tasks inside a PG cannot exceed the bundle reservation: two 1-CPU
    tasks against a 1-CPU bundle must run one after the other."""
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray_tpu.remote(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0))
    def stamp():
        import time as _t

        start = _t.monotonic()
        _t.sleep(0.5)
        return (start, _t.monotonic())

    spans = ray_tpu.get([stamp.remote(), stamp.remote()], timeout=90)
    (s0, e0), (s1, e1) = sorted(spans)
    assert s1 >= e0 - 0.05, f"overlapping spans: {spans}"
    remove_placement_group(pg)


def test_pg_bundle_rejects_oversized_task(cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray_tpu.remote(
        num_cpus=2,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0))
    def too_big():
        return "ran"

    with pytest.raises(Exception) as ei:
        ray_tpu.get(too_big.remote(), timeout=60)
    assert "bundle" in str(ei.value)
    remove_placement_group(pg)


def test_pg_releases_resources_on_remove(cluster):
    # let releases from earlier tests settle so the snapshots are stable
    before = ray_tpu.available_resources().get("CPU", 0)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        time.sleep(1.0)
        now = ray_tpu.available_resources().get("CPU", 0)
        if now == before:
            break
        before = now
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(30)
    time.sleep(1.2)  # heartbeat propagation
    during = ray_tpu.available_resources().get("CPU", 0)
    assert during <= before - 2
    remove_placement_group(pg)
    time.sleep(1.2)
    after = ray_tpu.available_resources().get("CPU", 0)
    assert after >= during + 2


def test_node_death_marks_cluster(cluster):
    c = cluster
    extra = c.add_node(num_cpus=2)
    c.wait_for_nodes()
    assert sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 4
    # hard-stop the nodelet (heartbeats cease)
    extra.stop()
    c.nodelets.remove(extra)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 3:
            break
        time.sleep(0.3)
    assert sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 3


def test_actor_on_dead_node_restarts_elsewhere(cluster):
    c = cluster
    extra = c.add_node(num_cpus=2, resources={"special": 1.0})
    c.wait_for_nodes()

    @ray_tpu.remote(resources={"special": 1.0}, num_cpus=0, max_restarts=1)
    class Pinned:
        def node(self):
            return ray_tpu.get_runtime_context().node_id.hex()

    p = Pinned.remote()
    first = ray_tpu.get(p.node.remote(), timeout=30)
    assert first == extra.node_id.hex()
    extra.stop()
    c.nodelets.remove(extra)
    # Node death → actor restart attempted; 'special' exists nowhere else,
    # so the actor must end up DEAD (no silent hang).
    from ray_tpu.core import exceptions as exc

    deadline = time.monotonic() + 90
    saw_dead = False
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(p.node.remote(), timeout=15)
            time.sleep(0.5)
        except (exc.ActorDiedError, exc.ActorUnavailableError, exc.TaskError):
            saw_dead = True
            break
    assert saw_dead
