"""Speculative decoding + paged attention (ISSUE 19): the invariant
matrix.

Speculation is a pure *throughput* transform — every test here pins the
semantics side of that claim: greedy outputs bit-identical spec-on vs
spec-off (both model families, dense and paged attention), and the
speculative path composing with every other serving feature without
changing outputs: preemption-recompute, prefix-cache warm hits,
mid-stream replica kill (failover replay), update_weights hot-swap,
and page-refcount hygiene when drafts get rejected. The pallas paged-
attention kernel gets its own parity gates (kernel-level vs the dense
reference, engine-level vs the dense gather path) at atol 1e-4.
"""

import dataclasses
import sys
import threading

import cloudpickle
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.serve.llm import (
    EngineConfig,
    LLMEngine,
    NGramProposer,
    SamplingParams,
    SpeculativeConfig,
)

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ------------------------------------------------------------- proposer


def test_ngram_proposer_prompt_lookup():
    p = NGramProposer()
    # trailing [5, 6] seen earlier, continuation follows it
    assert p.propose([1, 5, 6, 7, 8, 9, 5, 6], 4) == [7, 8, 9, 5]
    # most RECENT earlier occurrence wins
    assert p.propose([5, 6, 1, 5, 6, 2, 5, 6], 2) == [2, 5]
    # no earlier occurrence of any trailing n-gram: no draft
    assert p.propose([1, 2, 3, 4, 5], 3) == []
    # the copy is self-extending: a period-1 cycle yields the full k
    # even though only one real token follows the matched n-gram
    assert p.propose([7, 7, 7, 7], 2) == [7, 7]
    assert p.propose([7, 7, 7, 7], 6) == [7] * 6
    # period-2 cycle extends with the right phase
    assert p.propose([9, 4, 9, 4, 9, 4], 5) == [9, 4, 9, 4, 9]
    assert p.propose([], 4) == []


def test_speculative_config_validation():
    assert SpeculativeConfig.from_payload(None) is None
    cfg = SpeculativeConfig.from_payload({"num_draft_tokens": 3})
    assert cfg.num_draft_tokens == 3 and cfg.method == "ngram"
    same = SpeculativeConfig(num_draft_tokens=2)
    assert SpeculativeConfig.from_payload(same) is same
    with pytest.raises(ValueError):
        SpeculativeConfig.from_payload({"num_draft_tokens": 0})
    with pytest.raises(ValueError):
        SpeculativeConfig.from_payload({"bogus_key": 1})
    with pytest.raises(ValueError):
        SpeculativeConfig(num_draft_tokens=2, method="eagle")
    with pytest.raises(ValueError):
        SpeculativeConfig(num_draft_tokens=2, max_ngram=1, min_ngram=2)


# ------------------------------------------------------- kernel parity


def test_paged_attention_kernel_matches_dense_reference():
    """The kernel-level gate: pallas (interpret mode on CPU) vs the
    dense jnp oracle, covering W=1 (decode) and W=5 (verify window),
    GQA head grouping, and the ctx_len edges (0 = nothing cached,
    full = every mapped slot valid)."""
    from ray_tpu.ops.paged_attention import (
        paged_attention,
        paged_attention_reference,
    )

    rng = np.random.RandomState(0)
    S, H, HK, D, bs, maxB, npages = 3, 4, 2, 16, 4, 6, 32
    k_pages = rng.normal(size=(npages, bs, HK, D)).astype(np.float32)
    v_pages = rng.normal(size=(npages, bs, HK, D)).astype(np.float32)
    perm = rng.permutation(np.arange(1, npages))
    tables = perm[:S * maxB].reshape(S, maxB).astype(np.int32)
    ctx_len = np.asarray([0, 7, maxB * bs], np.int32)  # the edges
    for W in (1, 5):
        q = rng.normal(size=(S, W, H, D)).astype(np.float32)
        ok = rng.normal(size=(S, W, HK, D)).astype(np.float32)
        ov = rng.normal(size=(S, W, HK, D)).astype(np.float32)
        out = paged_attention(q, ok, ov, k_pages, v_pages, tables,
                              ctx_len, interpret=True)
        ref = paged_attention_reference(q, ok, ov, k_pages, v_pages,
                                        tables, ctx_len)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)


# ----------------------------------------------------------- engine level


def _engine(model="gpt2", num_blocks=64, *, spec=None, paged=False,
            max_batch_size=4, chunk=256, prefix_cache=True, seed=0):
    if model == "gpt2":
        from ray_tpu.models import gpt2

        cfg = dataclasses.replace(gpt2.GPT2Config.tiny(),
                                  dtype=jnp.float32, remat=False)
    else:
        from ray_tpu.models import llama

        cfg = llama.LlamaConfig.tiny()
    return LLMEngine(EngineConfig(
        model=model, model_config=cfg, block_size=4,
        num_blocks=num_blocks, max_model_len=32,
        max_batch_size=max_batch_size, seed=seed,
        prefill_chunk_size=chunk, enable_prefix_cache=prefix_cache,
        speculative=spec, use_paged_attention=paged))


def _drive(engine, streams):
    import time

    deadline = time.monotonic() + 120
    while any(s.final() is None for s in streams):
        if not engine.step():
            pass
        assert time.monotonic() < deadline, "engine made no progress"
    return [s.final() for s in streams]


def _repetitive_prompt(seed, n=12):
    """A motif-tiled prompt: the shape the n-gram proposer predicts."""
    rng = np.random.RandomState(seed)
    motif = rng.randint(1, 500, size=4).tolist()
    return (motif * ((n + 3) // 4))[:n]


@pytest.mark.parametrize("model", ["gpt2", "llama"])
def test_spec_greedy_bit_identical_all_attention_paths(model):
    """THE spec gate, both families: greedy output is bit-identical
    across {spec off, spec on} x {dense, paged attention}, and the
    spec arms actually exercised the verify program."""
    prompt = _repetitive_prompt(3)
    sp = SamplingParams(max_tokens=16)
    want = _engine(model).generate(prompt, sp, drive=True)["token_ids"]
    assert len(want) == 16
    for label, kwargs in (
            ("spec", {"spec": {"num_draft_tokens": 4}}),
            ("paged", {"paged": True}),
            ("spec+paged", {"spec": {"num_draft_tokens": 4},
                            "paged": True})):
        eng = _engine(model, **kwargs)
        got = eng.generate(prompt, sp, drive=True)["token_ids"]
        assert got == want, f"{model}/{label} diverged from plain greedy"
        st = eng.stats()
        if "spec" in kwargs:
            assert st["spec_proposed"] > 0, \
                f"{model}/{label}: verify program never ran"
            assert st["spec_accepted"] > 0, \
                f"{model}/{label}: nothing accepted on a cyclic prompt"
        if kwargs.get("paged"):
            assert st["paged_attention"] is True


def test_spec_with_preemption_recompute_bit_identical():
    """Spec x preemption: a pool too small for both sequences preempts
    one mid-decode; recompute-resume under speculation still produces
    exactly the unconstrained spec-off outputs."""
    prompts = [_repetitive_prompt(5, n=10), _repetitive_prompt(6, n=11)]
    sp = SamplingParams(max_tokens=12)
    want = [_engine(num_blocks=64).generate(p, sp, drive=True)
            ["token_ids"] for p in prompts]

    tight = _engine(num_blocks=11, spec={"num_draft_tokens": 4})
    streams = [tight.add_request(p, sp) for p in prompts]
    finals = _drive(tight, streams)
    assert tight.scheduler.preemption_count > 0, \
        "pool was sized to force preemption"
    for f, expect in zip(finals, want):
        assert f["token_ids"] == expect, \
            "speculative sequence diverged after preemption-requeue"
    assert tight.stats()["blocks_used"] == 0


def test_spec_with_prefix_cache_warm_hit_bit_identical():
    """Spec x prefix cache: a warm admission (shared pages, prefill
    skipped) followed by speculative decode matches the cold run, and
    the hit is real (cached_tokens > 0)."""
    rng = np.random.RandomState(41)
    shared = _repetitive_prompt(8, n=16)  # 4 full pages
    suffixes = [rng.randint(1, 500, size=3).tolist() for _ in range(2)]
    sp = SamplingParams(max_tokens=10)
    want = [_engine(num_blocks=96, chunk=8).generate(
        shared + sfx, sp, drive=True)["token_ids"] for sfx in suffixes]

    eng = _engine(num_blocks=96, chunk=8, spec={"num_draft_tokens": 4})
    got, cached = [], []
    for sfx in suffixes:
        fin = eng.generate(shared + sfx, sp, drive=True)
        got.append(fin["token_ids"])
        cached.append(fin["cached_tokens"])
    assert got == want, "warm-prefix speculative output diverged"
    assert cached[0] == 0 and cached[1] == 16, cached
    assert eng.stats()["spec_accepted"] > 0


def test_spec_rejected_runs_leak_no_pages():
    """Rejected drafts must not leak pages: rejected window slots stay
    mere garbage past the frontier, and after every stream retires the
    pool is back to zero pages used. Hot-temperature sampling forces
    the rejections — the proposer copies history, the target samples
    near-uniform over 512 tokens, so drafts die at the first mismatch
    (a greedy tiny model would just keep agreeing with its own loop)."""
    rng = np.random.RandomState(43)
    eng = _engine(num_blocks=96, spec={"num_draft_tokens": 8})
    # random prompts with an internal repeat so the proposer FINDS a
    # draft (a proposal must happen for a rejection to happen)
    prompts = []
    for _ in range(4):
        half = rng.randint(1, 500, size=5).tolist()
        prompts.append(half + half)
    streams = [eng.add_request(p, SamplingParams(max_tokens=8,
                                                 temperature=1.5))
               for p in prompts]
    finals = _drive(eng, streams)
    assert all(f["num_generated"] == 8 for f in finals)
    st = eng.stats()
    assert st["spec_proposed"] > 0, "no drafts were ever proposed"
    assert st["spec_accepted"] < st["spec_proposed"], \
        "expected at least one rejected draft token on random prompts"
    assert st["blocks_used"] == 0, \
        "rejected speculative runs leaked page refs"
    assert st["waiting"] == 0 and st["running"] == 0


def test_spec_with_weight_hot_swap_mid_generation():
    """Spec x update_weights: a hot-swap lands between speculative
    steps (never inside one), every stream completes its budget, and
    the per-token version tags stay monotonic — multi-token commits
    must tag every committed token with the version its verify step
    ran on."""
    from ray_tpu.serve.llm.runner import adapters

    eng = _engine(num_blocks=96, max_batch_size=8,
                  spec={"num_draft_tokens": 4})
    sp = SamplingParams(max_tokens=16, logprobs=True)
    streams = [eng.add_request(_repetitive_prompt(50 + i), sp)
               for i in range(8)]
    # all admitted, a spec step or two in — but well short of the 16-
    # token budget: at ~K+1 tokens per verify commit the streams race
    # to completion, and the swap must land while all 8 are in flight
    for _ in range(3):
        eng.step()
    new_params = adapters()["gpt2"].init_fn(jax.random.PRNGKey(7),
                                            eng.model_cfg)
    stats = eng.update_weights(1, new_params)
    assert stats["in_flight_streams"] == 8
    finals = _drive(eng, streams)
    assert all(f is not None and f["done"] for f in finals)
    assert all(f["num_generated"] == 16 for f in finals)
    swapped = [f for f in finals if len(f["weight_versions"]) > 1]
    assert swapped, "swap landed after every stream finished"
    for f in swapped:
        assert f["stale"], "mid-generation swap must tag the stream"
        assert f["weight_versions"] == sorted(set(f["weight_versions"]))
    assert eng.stats()["spec_proposed"] > 0
    assert eng.stats()["blocks_used"] == 0


def test_spec_stream_indices_contiguous_with_logprobs():
    """Multi-token commits emit one event per token with explicit,
    contiguous indices, and logprob events line up with their token
    (the base-index arithmetic gate)."""
    eng = _engine(num_blocks=64, spec={"num_draft_tokens": 4})
    stream = eng.add_request(_repetitive_prompt(9),
                             SamplingParams(max_tokens=12,
                                            logprobs=True))
    _drive(eng, [stream])
    events = list(stream)
    final = stream.final()
    toks = [e for e in events if not e.get("done")]
    assert [e["index"] for e in toks] == list(range(12))
    assert [e["token"] for e in toks] == final["token_ids"]
    assert len(final["logprobs"]) == 12
    assert all(np.isfinite(final["logprobs"]))


@pytest.mark.parametrize("model", ["gpt2", "llama"])
def test_paged_attention_engine_logit_parity(model):
    """Engine-level paged parity beyond token identity: greedy
    logprobs from the paged path match the dense path to 1e-4 — the
    numerics gate argmax equality alone cannot see."""
    prompt = _repetitive_prompt(11)
    sp = SamplingParams(max_tokens=8, logprobs=True)
    dense = _engine(model).generate(prompt, sp, drive=True)
    paged = _engine(model, paged=True).generate(prompt, sp, drive=True)
    assert paged["token_ids"] == dense["token_ids"]
    np.testing.assert_allclose(paged["logprobs"], dense["logprobs"],
                               atol=1e-4)


# ------------------------------------------------- failover (cluster)


@pytest.fixture(scope="module")
def spec_cluster():
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    serve.shutdown()
    ray_tpu.shutdown()
    c.shutdown()


def _tiny64_cfg():
    from ray_tpu.models import gpt2

    return gpt2.GPT2Config(
        vocab_size=64, n_layer=1, n_head=2, n_embd=32, block_size=64,
        vocab_pad_multiple=64, dtype=jnp.float32, remat=False)


def test_spec_streams_survive_replica_kill(spec_cluster):
    """Spec x failover: concurrent speculative greedy streams, one
    replica killed mid-generation — zero client-visible failures and
    outputs bit-identical to the unkilled run (the failover replay
    re-feeds prompt+generated, so committed speculative tokens must
    replay exactly)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app
    from ray_tpu.util import chaos

    n_streams, n_tok = 4, 24
    app = build_llm_app(
        model="gpt2",
        engine_config={"model_config": _tiny64_cfg(), "block_size": 8,
                       "num_blocks": 96, "max_model_len": 64,
                       "max_batch_size": 8,
                       "speculative": {"num_draft_tokens": 4}},
        num_replicas=2, max_ongoing_requests=16)
    handle = serve.run(app, name="llm-spec")
    try:
        rng = np.random.RandomState(13)
        prompts = [(rng.randint(1, 64, size=4).tolist() * 3)[:10]
                   for _ in range(n_streams)]

        def run(on_second_event=None):
            sh = handle.options(stream=True, generator_backpressure=8)
            results = [None] * n_streams
            errors: list = []
            barrier = (threading.Barrier(n_streams + 1, timeout=180)
                       if on_second_event else None)
            resume = threading.Event()
            if on_second_event is None:
                resume.set()

            def consume(i, gen):
                try:
                    evs = []
                    for r in gen:
                        evs.append(ray_tpu.get(r, timeout=180))
                        if barrier is not None and len(evs) == 2:
                            barrier.wait()
                            resume.wait(timeout=180)
                    results[i] = evs
                except Exception as e:  # noqa: BLE001
                    errors.append((i, repr(e)))

            gens = [sh.remote({"prompt": p, "max_tokens": n_tok})
                    for p in prompts]
            threads = [threading.Thread(target=consume, args=(i, g))
                       for i, g in enumerate(gens)]
            for t in threads:
                t.start()
            if barrier is not None:
                barrier.wait()
                on_second_event()
                resume.set()
            for t in threads:
                t.join(timeout=300)
            return results, errors

        ref, errors = run()
        assert not errors, errors
        want = [evs[-1]["token_ids"] for evs in ref]
        assert all(len(w) == n_tok for w in want)

        results, errors = run(
            on_second_event=lambda: chaos.kill_replica(
                "llm-spec", busiest=True))
        assert not errors, f"client-visible failures: {errors}"
        failovers = 0
        for i, evs in enumerate(results):
            assert evs is not None, f"stream {i} never finished"
            final = evs[-1]
            toks = evs[:-1]
            assert [e["index"] for e in toks] == \
                list(range(len(toks)))
            assert final["token_ids"] == want[i], \
                f"speculative stream {i} diverged after failover"
            failovers += final.get("failovers", 0)
        assert failovers >= 1, "the kill never landed on a live stream"
    finally:
        serve.delete("llm-spec")
