"""Cluster-wide tracing + metrics plane (ISSUE 3): merged timeline with
epoch-aligned cross-node spans, trace_id correlation across a
driver→actor→task chain, and the head's cluster /metrics aggregation
(reference model: `ray timeline` over the task-event pipeline +
the dashboard's Prometheus surface)."""

import sys
import time
import urllib.request

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import state, tracing

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster2():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 4,
                                "resources": {"n1": 2.0}})
    c.add_node(num_cpus=4, resources={"n2": 2.0})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _timeline_spans(want_names, timeout=25.0):
    """Poll the merged timeline until every wanted span name arrived
    (worker span flushes are periodic)."""
    deadline = time.monotonic() + timeout
    while True:
        tl = ray_tpu.timeline()
        spans = [e for e in tl if e.get("ph") == "X"]
        names = {e["name"] for e in spans}
        if set(want_names) <= names or time.monotonic() > deadline:
            return tl, spans


def test_merged_timeline_cross_node_epoch_aligned(cluster2):
    @ray_tpu.remote(num_cpus=0.1, resources={"n1": 0.1})
    def t3_on_n1():
        with tracing.span("t3-inner-n1"):
            time.sleep(0.01)
        return ray_tpu.get_runtime_context().node_id.hex()

    @ray_tpu.remote(num_cpus=0.1, resources={"n2": 0.1})
    def t3_on_n2():
        return ray_tpu.get_runtime_context().node_id.hex()

    t0_us = time.time() * 1e6
    n1 = ray_tpu.get(t3_on_n1.remote(), timeout=60)
    n2 = ray_tpu.get(t3_on_n2.remote(), timeout=60)
    assert n1 != n2

    tl, spans = _timeline_spans({"t3_on_n1", "t3_on_n2", "t3-inner-n1"})
    by_name = {e["name"]: e for e in spans}
    a, b = by_name["t3_on_n1"], by_name["t3_on_n2"]
    # pid = node: the two task spans render as different processes
    assert a["pid"] != b["pid"]
    # both nodes named in the process metadata
    proc_names = {e["args"]["name"] for e in tl
                  if e.get("ph") == "M" and e["name"] == "process_name"}
    assert any(n1[:16] in p for p in proc_names), proc_names
    assert any(n2[:16] in p for p in proc_names), proc_names
    # epoch anchoring: ts is wall-clock-comparable across processes (a
    # monotonic-only ts would sit at machine-uptime scale, far away)
    now_us = time.time() * 1e6
    for ev in (a, b, by_name["t3-inner-n1"]):
        assert t0_us - 120e6 < ev["ts"] < now_us + 120e6, ev
    # cross-process ordering: n1 ran (and was awaited) before n2 was
    # submitted, so the epoch-aligned timestamps must order them
    assert a["ts"] < b["ts"] + 1e3  # 1ms NTP-class slack (same host: 0)
    # the nested user span sits inside its task span's window
    inner = by_name["t3-inner-n1"]
    assert a["ts"] - 1e3 <= inner["ts"] <= a["ts"] + a["dur"] + 1e3


def test_trace_id_correlates_driver_actor_task_chain(cluster2):
    @ray_tpu.remote(num_cpus=0.1, resources={"n1": 0.1})
    def t3_leaf():
        with tracing.span("t3-leaf-work"):
            pass
        return tracing.current_trace()["trace_id"]

    @ray_tpu.remote(num_cpus=0.1, resources={"n2": 0.1})
    class T3Chain:
        def call(self):
            return ray_tpu.get(t3_leaf.remote(), timeout=60)

    with tracing.span("t3-root") as root:
        a = T3Chain.remote()
        leaf_trace_id = ray_tpu.get(a.call.remote(), timeout=60)
    # context propagated driver -> actor (node2) -> task (node1)
    assert leaf_trace_id == root["trace_id"]

    tl, spans = _timeline_spans({"t3-root", "T3Chain.call",
                                 "t3-leaf-work"})
    chain = [e for e in spans
             if e.get("args", {}).get("trace_id") == root["trace_id"]]
    names = {e["name"] for e in chain}
    assert {"t3-root", "T3Chain.call", "t3-leaf-work"} <= names, names
    # the one trace crosses >= 2 processes of the merged timeline
    assert len({e["pid"] for e in chain}) >= 2, chain
    # and parent links chain: the actor span's parent is the root span
    call = next(e for e in chain if e["name"] == "T3Chain.call")
    assert call["args"]["parent_id"] == root["span_id"]


def _t3_train_steps():
    """Tiny jitted train loop through make_train_step — populates the
    train_step_seconds histogram + compile-miss counter in THIS worker
    process's registry."""
    import jax.numpy as jnp
    import optax

    from ray_tpu.train.spmd import TrainState, make_train_step

    tx = optax.sgd(0.1)
    state0 = TrainState.create({"w": jnp.zeros(4)}, tx)

    def loss_fn(params, batch):
        return jnp.sum((params["w"] - batch["x"]) ** 2)

    step = make_train_step(loss_fn, tx, donate=False)
    s = state0
    for _ in range(3):
        s, m = step(s, {"x": jnp.ones(4)})
    return float(m["loss"])


def test_cluster_metrics_aggregates_train_metrics_by_node(cluster2):
    t3_train_n1 = ray_tpu.remote(num_cpus=0.5,
                                 resources={"n1": 0.1})(_t3_train_steps)
    t3_train_n2 = ray_tpu.remote(num_cpus=0.5,
                                 resources={"n2": 0.1})(_t3_train_steps)
    ray_tpu.get([t3_train_n1.remote(), t3_train_n2.remote()], timeout=120)

    text = state.cluster_metrics()
    # acceptance: train step-time histogram + compile-miss counter on
    # the head page, tagged by node — from BOTH nodes' workers
    assert "# TYPE train_step_seconds histogram" in text
    miss_nodes = set()
    for line in text.splitlines():
        if line.startswith("train_compile_misses_total{"):
            tags = line.split("{", 1)[1].split("}", 1)[0]
            node = [t for t in tags.split(",") if t.startswith('node="')]
            assert node, line
            miss_nodes.add(node[0])
    assert len(miss_nodes) >= 2, text
    # object-plane metrics ride the same page
    assert "object_store_bytes_allocated" in text


def test_head_metrics_http_endpoint(cluster2):
    port = cluster2.head.start_metrics_http(0)
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=15) as r:
        body = r.read().decode()
    assert 'node="' in body
    assert "object_store_bytes_allocated" in body


def test_cli_metrics_and_timeline(cluster2, tmp_path):
    import json
    import os
    import subprocess

    addr = cluster2.address
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "metrics",
         "--address", addr],
        capture_output=True, text=True, timeout=120, cwd="/root/repo",
        env=env)
    assert out.returncode == 0, out.stderr
    assert 'node="' in out.stdout

    trace_file = str(tmp_path / "tl.json")
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "timeline",
         "--address", addr, "-o", trace_file],
        capture_output=True, text=True, timeout=120, cwd="/root/repo",
        env=env)
    assert out.returncode == 0, out.stderr
    with open(trace_file) as f:
        events = json.load(f)
    assert isinstance(events, list) and events
