"""CQL offline RL (VERDICT r3 item 8a).

Reference parity: rllib/algorithms/cql/cql.py — conservative Q-learning
over recorded continuous-control data. The learning assertion is CQL's
defining property: dataset actions end up with HIGHER Q than
out-of-distribution random actions (the conservative penalty pushes
OOD Q down), plus return improvement over the random behavior policy's
evaluation is not required at CPU-test scale.
"""

import sys

import cloudpickle
import numpy as np
import pytest

from ray_tpu.rllib import CQL, CQLConfig, record_continuous_experiences

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def offline_pendulum(tmp_path_factory):
    """Recorded dataset + a LIVE runtime for the whole module: CQL's
    dataset loading submits read tasks, and letting it auto-init a
    runtime after this fixture shut one down would leak a cluster into
    every later test module."""
    import ray_tpu

    out = str(tmp_path_factory.mktemp("cql") / "pendulum")
    ray_tpu.init(num_cpus=4)
    try:
        record_continuous_experiences("Pendulum-v1", 600, out, seed=3)
        yield out
    finally:
        ray_tpu.shutdown()


def _build(offline_pendulum, **kw):
    cfg = (CQLConfig()
           .offline_data(offline_pendulum)
           .environment("Pendulum-v1")
           .training(hidden=(64, 64), train_batch_size=128, lr=1e-3,
                     updates_per_iteration=32, **kw))
    return cfg.build()


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_cql_conservative_property(offline_pendulum):
    """After training, Q(dataset actions) > Q(random OOD actions): the
    penalty explicitly minimizes logsumexp_a Q - Q(a_data)."""
    algo = _build(offline_pendulum, cql_alpha=10.0, seed=0)
    for _ in range(10):
        r = algo.train()
    assert np.isfinite(r["learner/bellman_loss"])
    gap = algo.ood_gap()
    assert gap > 0.0, f"dataset-action Q advantage {gap} not positive"


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_cql_alpha_zero_is_plain_sac_critic(offline_pendulum):
    """With cql_alpha=0 the conservative pressure is gone — the OOD gap
    stays near zero (sanity that the knob drives the property)."""
    algo = _build(offline_pendulum, cql_alpha=0.0, seed=0)
    for _ in range(10):
        algo.train()
    algo10 = _build(offline_pendulum, cql_alpha=10.0, seed=0)
    for _ in range(10):
        algo10.train()
    assert algo10.ood_gap() > algo.ood_gap(), \
        "conservative penalty did not widen the OOD gap vs alpha=0"


def test_cql_metrics_and_eval(offline_pendulum):
    algo = _build(offline_pendulum, seed=1)
    r = algo.train()
    for k in ("learner/bellman_loss", "learner/conservative_gap",
              "learner/actor_loss", "alpha"):
        assert k in r, f"missing metric {k}"
    ev = algo.evaluate(num_episodes=1)
    assert np.isfinite(ev["episode_return_mean"])


def test_cql_checkpoint_roundtrip(offline_pendulum, tmp_path):
    algo = _build(offline_pendulum, seed=2)
    algo.train()
    path = algo.save_to_path(str(tmp_path / "ck"))
    algo2 = _build(offline_pendulum, seed=7)
    algo2.restore_from_path(path)
    import jax

    a = jax.tree.leaves(algo.params)
    b = jax.tree.leaves(algo2.params)
    assert all(np.allclose(x, y) for x, y in zip(a, b))
