"""Autoscaler tests with the fake provider (reference model:
autoscaler e2e over fake_multi_node — no cloud)."""

import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeNodeProvider,
    StandardAutoscaler,
)
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_scale_up_on_queued_demand_and_down_when_idle(cluster, tmp_path):
    provider = FakeNodeProvider(
        cluster.address,
        {"worker": {"resources": {"CPU": 4.0}}},
        session_dir=str(tmp_path / "as"))
    scaler = StandardAutoscaler(
        cluster.address, provider,
        AutoscalerConfig(min_workers=0, max_workers=2,
                         idle_timeout_s=2.0, poll_interval_s=0.5))

    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(1.0)
        return ray_tpu.get_runtime_context().node_id.hex()

    # 6 one-CPU tasks against a 1-CPU cluster: queue builds up
    refs = [slow.remote() for _ in range(6)]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not provider.non_terminated_nodes():
        scaler.reconcile()
        time.sleep(0.3)
    assert provider.non_terminated_nodes(), "no scale-up despite queue"
    assert scaler.num_launches >= 1

    nodes = set(ray_tpu.get(refs, timeout=120))
    assert len(nodes) >= 2  # new capacity actually ran work

    # drain, then idle nodes are reclaimed after the timeout
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline and provider.non_terminated_nodes():
        scaler.reconcile_down()
        time.sleep(0.5)
    assert not provider.non_terminated_nodes(), "idle node never reclaimed"
    assert scaler.num_terminations >= 1


def test_min_workers_kept(cluster, tmp_path):
    provider = FakeNodeProvider(
        cluster.address, {"worker": {"resources": {"CPU": 2.0}}},
        session_dir=str(tmp_path / "as2"))
    scaler = StandardAutoscaler(
        cluster.address, provider,
        AutoscalerConfig(min_workers=1, max_workers=3, idle_timeout_s=0.5))
    scaler.start()
    try:
        assert len(provider.non_terminated_nodes()) == 1
        time.sleep(2.5)  # well past idle timeout
        assert len(provider.non_terminated_nodes()) == 1  # floor holds
    finally:
        scaler.stop()
        for h in provider.non_terminated_nodes():
            provider.terminate_node(h)
