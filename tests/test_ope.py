"""Off-policy evaluation estimators (reference model:
rllib/offline/estimators/tests — on-policy identity + sanity)."""

import jax
import numpy as np
import pytest

from ray_tpu.rllib import models
from ray_tpu.rllib.ope import (
    DoublyRobust,
    ImportanceSampling,
    WeightedImportanceSampling,
    split_episodes,
)


def _make_rows(params, n_episodes=8, T=12, gamma=0.97, seed=0):
    """Synthetic logged episodes sampled FROM the given policy (so the
    logged logp is exact)."""
    rng = np.random.RandomState(seed)
    key = jax.random.PRNGKey(seed)
    rows = []
    for _ in range(n_episodes):
        for t in range(T):
            obs = rng.randn(4).astype(np.float32)
            key, k = jax.random.split(key)
            a, logp, _v = models.sample_actions(
                params, obs[None], k)
            rows.append({
                "obs": obs.tolist(), "action": int(a[0]),
                "reward": float(rng.rand()), "done": t == T - 1,
                "truncated": False, "logp": float(logp[0]),
            })
    return rows


@pytest.fixture(scope="module")
def policy():
    return models.init_mlp_policy(jax.random.PRNGKey(1), 4, 2, (16,))


def test_split_episodes():
    rows = [{"done": False, "truncated": False},
            {"done": True, "truncated": False},
            {"done": False, "truncated": True},
            {"done": False, "truncated": False}]
    eps = split_episodes(rows)
    assert [len(e) for e in eps] == [2, 1, 1]


def test_on_policy_identity(policy):
    """Evaluating the BEHAVIOR policy itself: all importance ratios are
    exactly 1, so IS and WIS reduce to the mean discounted return, and
    DR telescopes to it (terminal value is zeroed)."""
    gamma = 0.97
    rows = _make_rows(policy, gamma=gamma)
    behavior_return = np.mean([
        sum(gamma ** t * r["reward"] for t, r in enumerate(ep))
        for ep in split_episodes(rows)])
    for cls in (ImportanceSampling, WeightedImportanceSampling):
        est = cls(policy, gamma=gamma).estimate(rows)
        np.testing.assert_allclose(est["v_target"], behavior_return,
                                   rtol=1e-4)
        np.testing.assert_allclose(est["v_gain"], 1.0, rtol=1e-4)
    dr = DoublyRobust(policy, gamma=gamma).estimate(rows)
    np.testing.assert_allclose(dr["v_target"], behavior_return, rtol=1e-4)


def test_off_policy_weights_move_the_estimate(policy):
    """A DIFFERENT target policy produces non-unit weights; estimates
    stay finite and differ from the behavior value."""
    rows = _make_rows(policy)
    other = models.init_mlp_policy(jax.random.PRNGKey(99), 4, 2, (16,))
    for cls in (ImportanceSampling, WeightedImportanceSampling,
                DoublyRobust):
        est = cls(other, gamma=0.97).estimate(rows)
        assert np.isfinite(est["v_target"])
        assert est["num_episodes"] == 8
    # WIS is self-normalized: bounded by the max single-episode return
    wis = WeightedImportanceSampling(other, gamma=0.97).estimate(rows)
    max_ret = max(sum(0.97 ** t * r["reward"] for t, r in enumerate(ep))
                  for ep in split_episodes(rows))
    assert wis["v_target"] <= max_ret * 2.5


def test_estimators_over_recorded_dataset(tmp_path, policy):
    """End-to-end: rows written by record_experiences round-trip through
    the dataset layer into the estimators."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.rllib.offline import (
        load_offline_dataset,
        record_experiences,
    )

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        out = str(tmp_path / "exp")
        record_experiences("CartPole-v1", num_episodes=4, out_dir=out,
                           seed=3)
        rows = load_offline_dataset(out).take_all()
        est = ImportanceSampling(policy, gamma=0.99).estimate(rows)
        assert np.isfinite(est["v_target"])
        assert est["v_behavior"] > 0
        assert est["num_episodes"] >= 4
    finally:
        ray_tpu.shutdown()
        c.shutdown()
