"""Object spilling, push transfer, pull admission (VERDICT r2 item 5/6).

Reference parity: raylet/local_object_manager.h:41 (spill pinned
primaries under pressure, restore on access),
object_manager/push_manager.h:30 (proactive transfer toward consumers),
pull_manager.h:52 (bounded pull admission).
"""

import os
import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture
def small_store():
    """Driver with a deliberately tiny (32MB) local store."""
    ray_tpu.init(num_cpus=4, store_capacity=32 * 1024 * 1024)
    yield ray_tpu
    ray_tpu.shutdown()


def test_put_beyond_capacity_spills_and_reads_back(small_store):
    """Put 10 x 8MB (2.5x store capacity) with all refs held: earlier
    primaries spill to disk; every object reads back intact."""
    refs, arrays = [], []
    for i in range(10):
        a = np.full(8 << 20, i, np.uint8)
        arrays.append(a)
        refs.append(ray_tpu.put(a))
    rt = ray_tpu.core.api._global_runtime()
    spilled = [b for b, st in rt._owned.items() if st.spilled_path]
    assert spilled, "no object was spilled despite store pressure"
    for i, r in enumerate(refs):
        out = ray_tpu.get(r)
        assert out[0] == i and out[-1] == i and len(out) == 8 << 20


def test_spilled_object_usable_as_task_arg(small_store):
    """A spilled primary is restored when a worker borrows it."""
    refs = [ray_tpu.put(np.full(8 << 20, i, np.uint8)) for i in range(8)]
    rt = ray_tpu.core.api._global_runtime()
    spilled = [b for b, st in rt._owned.items() if st.spilled_path]
    assert spilled

    @ray_tpu.remote(num_cpus=1)
    def head_byte(a):
        return int(a[0])

    vals = ray_tpu.get([head_byte.remote(r) for r in refs], timeout=120)
    assert vals == list(range(8))


def test_spill_files_cleaned_on_free(small_store):
    refs = [ray_tpu.put(np.full(8 << 20, i, np.uint8)) for i in range(8)]
    rt = ray_tpu.core.api._global_runtime()
    paths = [st.spilled_path for st in rt._owned.values() if st.spilled_path]
    assert paths and all(os.path.exists(p) for p in paths)
    del refs
    import gc

    gc.collect()
    assert all(not os.path.exists(p) for p in paths)


def test_push_transfer_prefetches_arg():
    """Submitting a task whose big arg lives on node A while the task is
    pinned to node B triggers an owner-directed push: B's store holds the
    bytes without the worker having to pull them at exec time."""
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    nl_b = c.add_node(num_cpus=2, resources={"b": 2.0})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        arr = np.arange(1 << 20, dtype=np.uint8)
        ref = ray_tpu.put(arr)  # primary on the driver's node (A)
        oid = ref.id.binary()

        @ray_tpu.remote(resources={"b": 1.0}, num_cpus=0.1)
        def consume(a):
            return int(a[-1])

        assert ray_tpu.get(consume.remote(ref), timeout=60) == arr[-1]
        # the push landed a secondary copy in B's store
        assert nl_b.store.contains(oid)
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_reader_crash_mid_get_does_not_wedge_store(small_store):
    """Kill a worker while it holds a zero-copy read view; the store must
    keep serving and the object must remain readable (weak item r2#8:
    crashed-reader refcount)."""
    big = ray_tpu.put(np.zeros(4 << 20, np.uint8))

    @ray_tpu.remote(num_cpus=1)
    def crash_while_reading(a):
        # `a` aliases the store; die without releasing the view
        os._exit(1)

    with pytest.raises(ray_tpu.core.exceptions.RayTpuError):
        ray_tpu.get(crash_while_reading.remote(big), timeout=60)
    # store still serves reads and accepts new objects
    assert ray_tpu.get(big)[0] == 0
    for i in range(8):  # churn past capacity: eviction/spill still works
        ray_tpu.get(ray_tpu.put(np.full(4 << 20, i, np.uint8)))
