"""Lineage reconstruction: lost task outputs are re-executed
(reference model: python/ray/tests/test_reconstruction*.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def two_node_cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_lost_object_reconstructed_after_node_death(two_node_cluster):
    c = two_node_cluster
    volatile = c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 1.0},
                    max_retries=2)
    def produce():
        # large result -> lives in the producing node's store
        return np.arange(500_000, dtype=np.int64)

    ref = produce.remote()
    # wait until the task completed (location recorded) WITHOUT fetching
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    # the producing node dies; its store bytes are gone
    c.remove_node(volatile)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 1:
            break
        time.sleep(0.3)
    # re-add capacity so the reconstructed task can run somewhere
    c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    arr = ray_tpu.get(ref, timeout=180)
    assert int(arr.sum()) == 124999750000


def test_put_objects_are_not_reconstructable(two_node_cluster):
    """put() has no lineage — a lost put-object must raise, not hang
    (reference semantics)."""
    c = two_node_cluster
    rt = ray_tpu.core.api._runtime
    ref = ray_tpu.put(np.arange(200_000))
    b = ref.id.binary()
    with rt._lock:
        st = rt._owned[b]
    # simulate loss: wipe the local store copy behind the runtime's back
    st.has_cached = False
    st.value_cached = None
    rt.store.release(b)
    rt.store.delete(b)
    with pytest.raises(ray_tpu.core.exceptions.ObjectLostError):
        ray_tpu.get(ref, timeout=30)
