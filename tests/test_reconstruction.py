"""Lineage reconstruction: lost task outputs are re-executed
(reference model: python/ray/tests/test_reconstruction*.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def _module_cluster():
    """ONE head + stable node + driver for the whole module (tier-1
    wall-time lever, see ROADMAP): cluster boot + init + worker warmup
    are paid once instead of per test."""
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@pytest.fixture
def two_node_cluster(_module_cluster):
    c = _module_cluster
    yield c
    # tests add (and kill) volatile nodes; strip everything but the
    # stable head node and wait for the head to age the dead ones out,
    # so every test starts from the same 1-alive-node state a fresh
    # cluster would give it
    for nl in list(c.nodelets[1:]):
        try:
            c.remove_node(nl)
        except Exception:  # noqa: BLE001
            pass
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 1:
            return
        time.sleep(0.2)
    raise RuntimeError("extra nodes did not age out of the cluster view")


def test_lost_object_reconstructed_after_node_death(two_node_cluster):
    c = two_node_cluster
    volatile = c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 1.0},
                    max_retries=2)
    def produce():
        # large result -> lives in the producing node's store
        return np.arange(500_000, dtype=np.int64)

    ref = produce.remote()
    # wait until the task completed (location recorded) WITHOUT fetching
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    # the producing node dies; its store bytes are gone
    c.remove_node(volatile)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 1:
            break
        time.sleep(0.3)
    # re-add capacity so the reconstructed task can run somewhere
    c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    arr = ray_tpu.get(ref, timeout=180)
    assert int(arr.sum()) == 124999750000


def test_put_objects_are_not_reconstructable(two_node_cluster):
    """put() has no lineage — a lost put-object must raise, not hang
    (reference semantics)."""
    c = two_node_cluster
    rt = ray_tpu.core.api._runtime
    ref = ray_tpu.put(np.arange(200_000))
    b = ref.id.binary()
    with rt._lock:
        st = rt._owned[b]
    # simulate loss: wipe the local store copy behind the runtime's back
    st.has_cached = False
    st.value_cached = None
    rt.store.release(b)
    rt.store.delete(b)
    with pytest.raises(ray_tpu.core.exceptions.ObjectLostError):
        ray_tpu.get(ref, timeout=30)


# ---------------------------------------------------------------------------
# r4 hardening (VERDICT item 5): nested chains, racing borrowers, chaos,
# actor-result semantics, retry-budget exhaustion
# ---------------------------------------------------------------------------

import sys

import cloudpickle

cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _kill_volatile_and_recover(c, handle):
    """Remove the volatile node, wait for death detection, re-add
    capacity for reconstructed tasks."""
    c.remove_node(handle)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 1:
            break
        time.sleep(0.3)
    c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_nested_lost_chain_reconstructed(two_node_cluster):
    """A → B → C all on the dying node: getting C forces C's
    re-execution, whose lost ARG (B) is reconstructed owner-side when
    the executing worker reports the dead location, recursively down to
    A (reference: test_reconstruction.py chained-dependency cases)."""
    c = two_node_cluster
    volatile = c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 0.1},
                    max_retries=4)
    def produce():
        return np.arange(300_000, dtype=np.int64)  # store-resident

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 0.1},
                    max_retries=4)
    def bump(a):
        return a + 1

    a = produce.remote()
    b = bump.remote(a)
    c3 = bump.remote(b)
    ready, _ = ray_tpu.wait([c3], timeout=60)
    assert ready
    _kill_volatile_and_recover(c, volatile)
    arr = ray_tpu.get(c3, timeout=180)
    assert int(arr[0]) == 2 and int(arr[-1]) == 300_001


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_reconstruction_racing_concurrent_borrowers(two_node_cluster):
    """Two consumers hit the same lost object concurrently: exactly one
    reconstruction runs (event-guarded) and both complete."""
    c = two_node_cluster
    volatile = c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 0.1},
                    max_retries=4)
    def produce():
        return np.ones(300_000, dtype=np.int64)

    @ray_tpu.remote(num_cpus=0.1, max_retries=4)
    def consume(a, tag):
        return int(a.sum()) + tag

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    _kill_volatile_and_recover(c, volatile)
    outs = ray_tpu.get([consume.remote(ref, 1), consume.remote(ref, 2)],
                       timeout=180)
    assert sorted(outs) == [300_001, 300_002]


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_reconstruction_under_rpc_chaos(two_node_cluster):
    """Reconstruction still converges when the resubmission RPCs drop
    their first attempts (deterministic chaos budgets, ref
    rpc/rpc_chaos.h)."""
    from ray_tpu.core import rpc as rpc_mod

    c = two_node_cluster
    volatile = c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 0.1},
                    max_retries=4)
    def produce():
        return np.full(300_000, 7, dtype=np.int64)

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    _kill_volatile_and_recover(c, volatile)
    # drop the next schedule_task send from THIS (owner) process: the
    # reconstruction submission itself must retry through the drop
    rpc_mod.set_chaos("schedule_task=1")
    try:
        arr = ray_tpu.get(ref, timeout=180)
        assert int(arr.sum()) == 7 * 300_000
    finally:
        rpc_mod.set_chaos("")


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_actor_results_not_reconstructable(two_node_cluster):
    """Actor task outputs carry no lineage (reference: actor task
    results are not rebuilt by the recovery manager) — a lost one
    surfaces ObjectLostError instead of hanging."""
    c = two_node_cluster
    volatile = c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 0.1})
    class Holder:
        def big(self):
            return np.zeros(300_000, dtype=np.int64)

    h = Holder.remote()
    ref = h.big.remote()
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    _kill_volatile_and_recover(c, volatile)
    with pytest.raises(ray_tpu.core.exceptions.RayTpuError):
        ray_tpu.get(ref, timeout=30)


def test_retry_budget_exhaustion_raises(two_node_cluster):
    """max_retries=0: a lost output must raise ObjectLostError promptly
    rather than loop (budget is consumed by reconstruction attempts)."""
    c = two_node_cluster
    volatile = c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 0.1},
                    max_retries=0)
    def produce():
        return np.arange(300_000, dtype=np.int64)

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    _kill_volatile_and_recover(c, volatile)
    with pytest.raises(ray_tpu.core.exceptions.RayTpuError):
        ray_tpu.get(ref, timeout=60)


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_nested_chain_with_consumer_on_stable_node(two_node_cluster):
    """The dead node held ONLY the intermediates; a stable-node consumer
    task transparently waits out the owner-driven reconstruction of its
    borrowed arg (lost_at report path)."""
    c = two_node_cluster
    volatile = c.add_node(num_cpus=2, resources={"volatile": 2.0})
    c.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0.1, resources={"volatile": 0.1},
                    max_retries=4)
    def produce():
        return np.arange(300_000, dtype=np.int64)

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    _kill_volatile_and_recover(c, volatile)

    @ray_tpu.remote(num_cpus=0.1, max_retries=2)
    def total(a):
        return int(a.sum())

    assert ray_tpu.get(total.remote(ref), timeout=180) == 44999850000
