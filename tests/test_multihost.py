"""RPC endpoints on routable (non-loopback) addresses.

Reference parity: address selection/plumbing in
python/ray/_private/services.py and node.py:1227 — the runtime must be
able to span hosts. Tested with a loopback alias (127.0.0.2), the
standard single-box stand-in for a second interface."""

import os
import socket

import pytest

import ray_tpu
from ray_tpu.core import rpc


def _alias_usable() -> bool:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("127.0.0.2", 0))
        s.close()
        return True
    except OSError:
        return False


@pytest.mark.skipif(not _alias_usable(), reason="no loopback alias")
def test_cluster_on_nonloopback_address(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NODE_IP", "127.0.0.2")
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        assert c.address.startswith("127.0.0.2:")
        c.wait_for_nodes()
        assert c.nodelets[0].address.startswith("127.0.0.2:")
        ray_tpu.init(address=c.address)

        @ray_tpu.remote(num_cpus=0.1)
        def who():
            return ray_tpu.get_runtime_context().node_id.hex()

        assert ray_tpu.get(who.remote(), timeout=60) == \
            c.nodelets[0].node_id.hex()

        # worker env carries head/nodelet addresses on the alias
        @ray_tpu.remote(num_cpus=0.1)
        def addrs():
            return (os.environ["RAY_TPU_HEAD_ADDR"],
                    os.environ["RAY_TPU_NODELET_ADDR"])

        head_addr, nodelet_addr = ray_tpu.get(addrs.remote(), timeout=60)
        assert head_addr.startswith("127.0.0.2:")
        assert nodelet_addr.startswith("127.0.0.2:")
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_node_ip_autodetect(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NODE_IP", "auto")
    ip = rpc.node_ip()
    # any syntactically valid IPv4 is fine; must not crash offline
    parts = ip.split(".")
    assert len(parts) == 4 and all(p.isdigit() for p in parts)


def test_default_is_loopback(monkeypatch):
    monkeypatch.delenv("RAY_TPU_NODE_IP", raising=False)
    assert rpc.node_ip() == "127.0.0.1"
