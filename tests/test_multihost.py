"""RPC endpoints on routable (non-loopback) addresses.

Reference parity: address selection/plumbing in
python/ray/_private/services.py and node.py:1227 — the runtime must be
able to span hosts. Tested with a loopback alias (127.0.0.2), the
standard single-box stand-in for a second interface."""

import os
import socket

import pytest

import ray_tpu
from ray_tpu.core import rpc


def _alias_usable() -> bool:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("127.0.0.2", 0))
        s.close()
        return True
    except OSError:
        return False


@pytest.mark.skipif(not _alias_usable(), reason="no loopback alias")
def test_cluster_on_nonloopback_address(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NODE_IP", "127.0.0.2")
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        assert c.address.startswith("127.0.0.2:")
        c.wait_for_nodes()
        assert c.nodelets[0].address.startswith("127.0.0.2:")
        ray_tpu.init(address=c.address)

        @ray_tpu.remote(num_cpus=0.1)
        def who():
            return ray_tpu.get_runtime_context().node_id.hex()

        assert ray_tpu.get(who.remote(), timeout=60) == \
            c.nodelets[0].node_id.hex()

        # worker env carries head/nodelet addresses on the alias
        @ray_tpu.remote(num_cpus=0.1)
        def addrs():
            return (os.environ["RAY_TPU_HEAD_ADDR"],
                    os.environ["RAY_TPU_NODELET_ADDR"])

        head_addr, nodelet_addr = ray_tpu.get(addrs.remote(), timeout=60)
        assert head_addr.startswith("127.0.0.2:")
        assert nodelet_addr.startswith("127.0.0.2:")
    finally:
        ray_tpu.shutdown()
        c.shutdown()


@pytest.mark.skipif(not _alias_usable(), reason="no loopback alias")
def test_two_hosts_object_transfer_and_death(monkeypatch):
    """True multi-host behavior on distinct interfaces: a second "host"
    on 127.0.0.3 joins a head on 127.0.0.2; objects created on one host
    are pulled node-to-node for a consumer pinned to the other; killing
    the second host is detected and its node leaves the live set
    (reference: multi-node object transfer + node failure handling,
    object_manager + gcs health check)."""
    import sys
    import time

    import cloudpickle

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("127.0.0.3", 0))
        s.close()
    except OSError:
        pytest.skip("no 127.0.0.3 alias")
    monkeypatch.setenv("RAY_TPU_NODE_IP", "127.0.0.2")
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        # second host on a DIFFERENT interface
        monkeypatch.setenv("RAY_TPU_NODE_IP", "127.0.0.3")
        nl2 = c.add_node(num_cpus=2)
        assert nl2.address.startswith("127.0.0.3:")
        c.wait_for_nodes()
        ray_tpu.init(address=c.address)

        import numpy as np

        host1 = c.nodelets[0].node_id.hex()
        host2 = nl2.node_id.hex()

        @ray_tpu.remote(num_cpus=0.1)
        def make():
            return np.arange(200_000, dtype=np.int64)

        @ray_tpu.remote(num_cpus=0.1)
        def consume(arr):
            return (int(arr.sum()),
                    ray_tpu.get_runtime_context().node_id.hex())

        # produce on host1, consume pinned to host2: the 1.6 MB payload
        # crosses interfaces through the chunked node-to-node pull
        ref = make.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                host1)).remote()
        total, where = ray_tpu.get(consume.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                host2)).remote(ref), timeout=120)
        assert total == 199_999 * 200_000 // 2
        assert where == host2

        # host death: stop the second nodelet, the head notices
        nl2.stop()
        deadline = time.time() + 30
        while time.time() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["Alive"]]
            if len(alive) == 1:
                break
            time.sleep(0.5)
        assert [n["NodeID"] for n in alive] == [host1]
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_node_ip_autodetect(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NODE_IP", "auto")
    ip = rpc.node_ip()
    # any syntactically valid IPv4 is fine; must not crash offline
    parts = ip.split(".")
    assert len(parts) == 4 and all(p.isdigit() for p in parts)


def test_default_is_loopback(monkeypatch):
    monkeypatch.delenv("RAY_TPU_NODE_IP", raising=False)
    assert rpc.node_ip() == "127.0.0.1"
