"""Unified Algorithm/Trainable/searcher stack tests.

Reference model: rllib/tests/test_algorithm* (Algorithm as a Tune
Trainable), tune/tests/test_trainable.py (class API checkpoint cycle),
tune/tests/test_searchers.py (model-based search beats random), and
tune/tests/test_pb2.py.
"""

import json
import os
import sys

import cloudpickle
import jax
import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.trainer import RunConfig

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


# ------------------------------------------------------------- RLModule


def test_rl_module_contract():
    """forward_inference is greedy/deterministic; forward_exploration
    samples with logp; explore() matches the env-runner signature."""
    from ray_tpu.rllib.rl_module import DefaultActorCriticModule

    mod = DefaultActorCriticModule(4, 3, {"hidden": (16,)})
    params = mod.init(jax.random.PRNGKey(0))
    obs = np.random.RandomState(0).randn(8, 4).astype(np.float32)

    inf1 = mod.forward_inference(params, {"obs": obs})
    inf2 = mod.forward_inference(params, {"obs": obs})
    np.testing.assert_array_equal(np.asarray(inf1["actions"]),
                                  np.asarray(inf2["actions"]))
    assert inf1["actions"].shape == (8,)

    exp = mod.forward_exploration(params, {"obs": obs},
                                  jax.random.PRNGKey(1))
    assert exp["actions"].shape == (8,)
    assert exp["action_logp"].shape == (8,)
    assert np.all(np.asarray(exp["action_logp"]) <= 0)

    a, logp, v = mod.explore(params, obs, jax.random.PRNGKey(2))
    assert a.shape == (8,) and logp.shape == (8,) and v.shape == (8,)


def test_algorithm_shared_step_and_eval():
    """The SHARED Algorithm.step drives PPO/DQN/IMPALA; periodic
    evaluation comes from the base (reference: Algorithm.step :959)."""
    from ray_tpu.rllib import DQNConfig, PPOConfig

    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                         rollout_fragment_length=16)
            .training(num_sgd_iter=1, minibatch_size=32)
            .evaluation(evaluation_interval=2, evaluation_duration=1)
            ).build()
    r1 = algo.train()
    assert "evaluation" not in r1
    r2 = algo.train()
    assert "episode_return_mean" in r2["evaluation"]
    assert r2["training_iteration"] == 2
    # the same train() skeleton runs DQN — family only supplies
    # training_step (checked via the shared bookkeeping keys)
    dqn = (DQNConfig().environment("CartPole-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                        rollout_fragment_length=8)).build()
    rd = dqn.train()
    assert rd["training_iteration"] == 1 and "time_this_iter_s" in rd
    algo.stop()
    dqn.stop()


def test_algorithm_checkpoint_roundtrip(tmp_path):
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                         rollout_fragment_length=16)
            .training(num_sgd_iter=1, minibatch_size=32)).build()
    algo.train()
    algo.train()
    state = algo.save_checkpoint()
    w0 = algo.get_weights()
    algo2 = (PPOConfig().environment("CartPole-v1")
             .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                          rollout_fragment_length=16)
             .training(num_sgd_iter=1, minibatch_size=32)).build()
    algo2.load_checkpoint(state)
    w1 = algo2.get_weights()
    jax.tree.map(np.testing.assert_array_equal, w0, w1)
    # the Checkpointable state carries the iteration clock too
    assert algo2._iteration == 2
    r = algo2.train()
    assert r["training_iteration"] == 3
    algo.stop()
    algo2.stop()


# ------------------------------------------- Tuner over AlgorithmConfig


def test_tuner_drives_algorithm_config_with_asha(cluster, tmp_path):
    """VERDICT done-criterion: Tuner(PPOConfig().training(
    lr=grid_search([...]))) runs trial actors and ASHA stops losers."""
    from ray_tpu.rllib import PPOConfig

    config = (PPOConfig().environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                           rollout_fragment_length=32)
              .training(num_sgd_iter=2, minibatch_size=64,
                        lr=tune.grid_search([3e-4, 3e-3, 1e-5])))
    tuner = tune.Tuner(
        config,
        tune_config=tune.TuneConfig(
            metric="episode_return_mean", mode="max",
            scheduler=tune.ASHAScheduler(max_t=6, grace_period=2,
                                         reduction_factor=2),
            max_concurrent_trials=3),
        run_config=RunConfig(name="ppo_asha", storage_path=str(tmp_path),
                             stop={"training_iteration": 6}),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    assert not grid.errors
    lrs = sorted(r.config["lr"] for r in grid)
    assert lrs == [1e-5, 3e-4, 3e-3]
    best = grid.get_best_result()
    assert best.metrics["episode_return_mean"] == \
        best.metrics["episode_return_mean"]  # not NaN
    # every trial ran through the shared Algorithm.train clock
    assert all(r.metrics.get("training_iteration", 0) >= 2 for r in grid)
    # checkpoints were shipped (Algorithm state through the session)
    assert any(f.startswith("ckpt_") for f in
               os.listdir(os.path.join(tmp_path, "ppo_asha")))


# ------------------------------------------------- class Trainable API


class _Quad(tune.Trainable):
    def setup(self, config):
        self.lr = config["lr"]
        self.x = 0.0
        self.restored = False

    def step(self):
        self.x -= self.lr * 2 * (self.x - 3.0)
        return {"objective": (self.x - 3.0) ** 2, "restored": self.restored}

    def save_checkpoint(self):
        return {"x": self.x}

    def load_checkpoint(self, state):
        self.x = state["x"]
        self.restored = True


def test_class_trainable_under_asha(cluster, tmp_path):
    tuner = tune.Tuner(
        _Quad,
        param_space={"lr": tune.grid_search([0.02, 0.1, 0.4])},
        tune_config=tune.TuneConfig(
            metric="objective", mode="min",
            scheduler=tune.ASHAScheduler(max_t=15, grace_period=3,
                                         reduction_factor=2)),
        run_config=RunConfig(name="quad_asha", storage_path=str(tmp_path),
                             stop={"training_iteration": 15}),
    )
    grid = tuner.fit()
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["objective"] < 0.1
    assert best.config["lr"] == 0.4


def test_class_trainable_resume_from_checkpoint(cluster, tmp_path):
    """Interrupted trials restart FROM THEIR CHECKPOINT, not from
    scratch (reference: Trainable save/restore driving Tuner.restore)."""
    name = "quad_resume"
    tuner = tune.Tuner(
        _Quad, param_space={"lr": tune.grid_search([0.1])},
        tune_config=tune.TuneConfig(metric="objective", mode="min"),
        run_config=RunConfig(name=name, storage_path=str(tmp_path),
                             stop={"training_iteration": 5}),
    )
    grid = tuner.fit()
    assert grid[0].metrics["training_iteration"] == 5
    exp = os.path.join(tmp_path, name)
    # simulate an interruption: mark the finished trial RUNNING again
    # with a later stop, as if the driver died mid-flight
    with open(os.path.join(exp, "tuner_state.json")) as f:
        state = json.load(f)
    state["trials"][0]["status"] = "RUNNING"
    with open(os.path.join(exp, "tuner_state.json"), "w") as f:
        json.dump(state, f)
    restored = tune.Tuner.restore(exp, _Quad)
    restored.run_config.stop = {"training_iteration": 9}
    grid2 = restored.fit()
    last = grid2[0].metrics
    # resumed: iteration clock continued (6..9, not 1..9) and
    # load_checkpoint ran
    assert last["training_iteration"] == 9
    assert last["restored"] is True


# -------------------------------------------------------- TPE searcher


def _bowl(config):
    tune.report({"loss": (config["x"] - 0.3) ** 2 +
                 (config["y"] - 0.7) ** 2})


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_tpe_beats_random_on_bowl(cluster, tmp_path):
    space = {"x": tune.uniform(0.0, 1.0), "y": tune.uniform(0.0, 1.0)}
    n = 30

    random_grid = tune.Tuner(
        _bowl, param_space=dict(space),
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    num_samples=n, seed=3,
                                    max_concurrent_trials=4),
        run_config=RunConfig(name="bowl_rand", storage_path=str(tmp_path)),
    ).fit()
    tpe_grid = tune.Tuner(
        _bowl,
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=n,
            search_alg=tune.TPESearcher(space, n_initial=10, seed=3),
            max_concurrent_trials=1),  # sequential: condition on history
        run_config=RunConfig(name="bowl_tpe", storage_path=str(tmp_path)),
    ).fit()
    rand_best = random_grid.get_best_result().metrics["loss"]
    tpe_best = tpe_grid.get_best_result().metrics["loss"]
    assert len(tpe_grid) == n and not tpe_grid.errors
    assert tpe_best < rand_best, (tpe_best, rand_best)
    assert tpe_best < 0.02


# ---------------------------------------------------------------- PB2


class _NoisyHill(tune.Trainable):
    """Reward rate peaks at x=0.75; population starts near 0.05 so
    multiplicative PBT perturbation crawls while PB2's GP-UCB can jump
    across the box."""

    def setup(self, config):
        self.x = config["x"]
        self.score = 0.0
        self.rng = np.random.RandomState(int(config.get("noise_seed", 0)))

    def step(self):
        self.score += 1.0 - (self.x - 0.75) ** 2 + \
            self.rng.normal(0.0, 0.05)
        return {"score": self.score, "x": self.x}

    def save_checkpoint(self):
        return {"score": self.score}

    def load_checkpoint(self, state):
        self.score = state["score"]


def _run_population(scheduler, name, tmp_path, seed):
    rng = np.random.RandomState(seed)
    tuner = tune.Tuner(
        _NoisyHill,
        param_space={"x": tune.uniform(0.01, 0.1),
                     "noise_seed": tune.randint(0, 10_000)},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    num_samples=4, seed=seed,
                                    scheduler=scheduler,
                                    max_concurrent_trials=4),
        run_config=RunConfig(name=name, storage_path=str(tmp_path),
                             stop={"training_iteration": 24}),
    )
    del rng
    grid = tuner.fit()
    assert not grid.errors
    return max(r.metrics["score"] for r in grid
               if "score" in r.metrics)


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_pb2_beats_pbt_on_noisy_hill(cluster, tmp_path):
    # {"x": None} selects PBT's numeric path: current value * 0.8/1.2
    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=4,
        hyperparam_mutations={"x": None}, seed=11)
    pb2 = tune.PB2(metric="score", mode="max", perturbation_interval=4,
                   hyperparam_bounds={"x": (0.0, 1.0)}, seed=11)
    pbt_best = _run_population(pbt, "hill_pbt", tmp_path, seed=5)
    pb2_best = _run_population(pb2, "hill_pb2", tmp_path, seed=5)
    assert pb2_best > pbt_best, (pb2_best, pbt_best)


def test_resource_changing_scheduler(cluster, tmp_path):
    """The best trial gets more CPUs mid-flight; the trial restarts
    from its own checkpoint and keeps its iteration clock (reference:
    ResourceChangingScheduler + DistributeResourcesToTopJob)."""
    sched = tune.ResourceChangingScheduler(
        reallocation_interval=3, base_cpus=1.0, top_cpus=2.0)
    tuner = tune.Tuner(
        _Quad,
        param_space={"lr": tune.grid_search([0.05, 0.4])},
        tune_config=tune.TuneConfig(metric="objective", mode="min",
                                    scheduler=sched,
                                    max_concurrent_trials=2),
        run_config=RunConfig(name="rcs", storage_path=str(tmp_path),
                             stop={"training_iteration": 14}),
    )
    grid = tuner.fit()
    assert not grid.errors
    assert sched.realloc_count >= 1
    best = grid.get_best_result()
    # iteration clock survived the resize restart
    assert best.metrics["training_iteration"] == 14
    assert best.config["lr"] == 0.4
    # the resized trial actually resumed from its checkpoint
    restarted = [r for r in grid if r.metrics.get("restored")]
    assert restarted
