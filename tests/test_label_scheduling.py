"""Task-level label selectors + soft node affinity.

Reference model: node_affinity_scheduling_policy.h:29 (hard pins fail
when the node is gone, soft falls back) and the label-match scheduling
tests. Actors already honored selectors via head placement; these cover
the TASK path through the nodelet scheduler (`_place` + dispatch
guard).
"""

import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
)

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2, labels={"zone": "b"})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote
def where():
    return ray_tpu.get_runtime_context().node_id.hex()


def test_task_hard_node_affinity_lands_on_target(cluster):
    nodes = [n for n in ray_tpu.nodes() if n["Alive"]]
    assert len(nodes) == 2
    for n in nodes:
        ref = where.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            n["NodeID"])).remote()
        assert ray_tpu.get(ref, timeout=60) == n["NodeID"]


def test_task_label_selector_routes_to_matching_node(cluster):
    zone_b = [n for n in ray_tpu.nodes()
              if n["Labels"].get("zone") == "b"][0]
    refs = [where.options(scheduling_strategy=NodeLabelSchedulingStrategy(
        {"zone": "b"})).remote() for _ in range(4)]
    assert set(ray_tpu.get(refs, timeout=60)) == {zone_b["NodeID"]}


def test_task_soft_affinity_falls_back_when_node_gone(cluster):
    dead_id = "ff" * 14  # no such node
    ref = where.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        dead_id, soft=True)).remote()
    out = ray_tpu.get(ref, timeout=60)
    assert out in {n["NodeID"] for n in ray_tpu.nodes()}


def test_task_hard_affinity_to_dead_node_waits(cluster):
    dead_id = "ff" * 14
    ref = where.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        dead_id)).remote()
    with pytest.raises(Exception):  # noqa: B017 — timeout-class error
        ray_tpu.get(ref, timeout=3)
    # the cluster keeps working around the held task
    t0 = time.time()
    assert ray_tpu.get(where.remote(), timeout=60)
    assert time.time() - t0 < 60


def test_actor_soft_affinity_falls_back(cluster):
    @ray_tpu.remote
    class A:
        def whereami(self):
            return ray_tpu.get_runtime_context().node_id.hex()

    a = A.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        "ff" * 14, soft=True)).remote()
    out = ray_tpu.get(a.whereami.remote(), timeout=60)
    assert out in {n["NodeID"] for n in ray_tpu.nodes()}
    ray_tpu.kill(a)
