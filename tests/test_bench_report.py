"""Bench trajectory index drift gate.

Same contract as the dashboard gate in test_observability4: every
bench JSON artifact at the repo root must parse into a shape
``ray_tpu.devtools.bench_report`` understands, and the committed
BENCH_INDEX.md must byte-match a regeneration. Adding a bench round
without re-running ``python -m ray_tpu.devtools.bench_report`` fails
here, not three PRs later when someone reads a stale table."""

import glob
import json
import os

from ray_tpu.devtools import bench_report

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_every_bench_artifact_parses():
    paths = (glob.glob(os.path.join(ROOT, "BENCH_r*.json"))
             + glob.glob(os.path.join(ROOT, "MULTICHIP_r*.json"))
             + [os.path.join(ROOT, n) for n in
                ("CORE_BENCH.json", "SERVE_BENCH.json", "RL_BENCH.json")
                if os.path.exists(os.path.join(ROOT, n))])
    assert paths, "no bench artifacts found at the repo root"
    for p in paths:
        with open(p, encoding="utf-8") as f:
            json.load(f)  # raises on corruption
    data = bench_report.collect(ROOT)  # raises on unknown shape
    assert len(data["files"]) == len(paths)
    assert data["rounds"], "no bench rounds collected"
    for r in data["rounds"]:
        rec = r["record"]
        if rec is not None:
            assert rec.get("metric") and rec.get("value") is not None, r


def test_index_has_every_artifact_and_primary_metric():
    text = bench_report.build_index(ROOT)
    data = bench_report.collect(ROOT)
    for name in data["files"]:
        assert name in text, f"{name} missing from index"
    for r in data["rounds"]:
        if r["record"] is not None:
            assert r["record"]["metric"] in text


def test_train_bubble_regression():
    """The interleaved-1F1B perf claim, gated on the committed bench
    artifact: in the newest round carrying the pipeline schedule-
    emulation A/B, the interleaved measured bubble must sit strictly
    below flat at equal S/M. (The emulated lane models op latency
    through the real driver/actor path, so the comparison is immune to
    single-core CPU contention — see bench.py `_pipeline_bench`.)"""
    check = bench_report.bubble_regression(ROOT)
    assert check is not None, (
        "no bench round records the pipeline emulation A/B — rerun "
        "`python bench.py` and commit the new BENCH_r<N>.json")
    assert check["ok"], (
        f"interleaved bubble regressed: {check['interleaved']} >= "
        f"{check['flat']} (flat) in {check['source']}")
    # the index surfaces the same verdict
    assert "Interleaved below flat (emulated lane): yes" in \
        bench_report.build_index(ROOT)


def test_zero_ladder_indexed():
    """The newest round's ZeRO ladder renders into the index with its
    byte-ratio summary — the bytes-win trajectory stays readable."""
    text = bench_report.build_index(ROOT)
    assert "## ZeRO ladder" in text
    assert "Sharded/replicated byte ratios" in text


def test_committed_index_matches_regeneration():
    committed = os.path.join(ROOT, "BENCH_INDEX.md")
    assert os.path.exists(committed), (
        "BENCH_INDEX.md missing — run "
        "`python -m ray_tpu.devtools.bench_report`")
    with open(committed, encoding="utf-8") as f:
        on_disk = f.read()
    assert on_disk == bench_report.build_index(ROOT), (
        "BENCH_INDEX.md is stale — regenerate with "
        "`python -m ray_tpu.devtools.bench_report`")
