"""Head restart recovery via pluggable storage (reference model:
gcs_client_reconnection_test.cc / GCS-restarts-from-Redis)."""

import pytest

import ray_tpu
from ray_tpu.core.head import Head
from ray_tpu.core.head_storage import FileHeadStore
from ray_tpu.core.nodelet import Nodelet
from ray_tpu.core.rpc import RpcClient


def test_file_store_roundtrip(tmp_path):
    st = FileHeadStore(str(tmp_path / "hs"))
    st.put("t", b"\x01\x02", b"value1")
    st.put("t", "strkey", b"value2")
    assert st.get("t", b"\x01\x02") == b"value1"
    assert dict(st.scan("t")) == {b"\x01\x02": b"value1",
                                  "strkey": b"value2"}
    st.delete("t", "strkey")
    assert st.get("t", "strkey") is None


def test_head_restart_recovers_kv_and_actor_registry(tmp_path):
    storage_dir = str(tmp_path / "head_meta")
    client = RpcClient.shared()

    head = Head(storage=FileHeadStore(storage_dir)).start()
    nl = Nodelet(head.address, {"CPU": 4},
                 session_dir=str(tmp_path / "sess")).start()
    try:
        ray_tpu.init(address=head.address)

        client.call(head.address, "kv_put",
                    {"ns": "app", "key": "cfg", "overwrite": True},
                    frames=[b"persisted-bytes"], timeout=30)

        @ray_tpu.remote
        class Named:
            def ping(self):
                return "ok"

        a = Named.options(name="survivor").remote()
        assert ray_tpu.get(a.ping.remote(), timeout=60) == "ok"
    finally:
        ray_tpu.shutdown()
        nl.stop()
        head.stop()

    # new head incarnation on the same storage
    head2 = Head(storage=FileHeadStore(storage_dir)).start()
    try:
        v, frames = client.call_frames(
            head2.address, "kv_get", {"ns": "app", "key": "cfg"}, timeout=30)
        assert v["found"] and frames[0] == b"persisted-bytes"
        actors = client.call(head2.address, "list_actors", {},
                             timeout=30)["actors"]
        assert any(x["name"] == "survivor" and x["state"] == "DEAD"
                   for x in actors)
    finally:
        head2.stop()
