"""Compiled DAGs, offline RL (BC/MARWIL), multi-agent PPO — closing the
r2 coverage table's remaining 'no' rows.

Reference parity: python/ray/dag/compiled_dag_node.py:711 (channel-backed
compiled execution), rllib/offline/offline_data.py:22 + algorithms/bc +
algorithms/marwil, rllib/core/rl_module/multi_rl_module.py:49 +
env/multi_agent_env.py.
"""

import sys
import time

import cloudpickle
import numpy as np
import pytest

import ray_tpu

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def ray_boot():
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


# ---------------------------------------------------------------- DAG

def test_compiled_dag_chain_and_errors(ray_boot):
    from ray_tpu.dag import InputNode

    @ray_tpu.remote(num_cpus=0.5)
    class Stage:
        def __init__(self, add):
            self.add = add

        def step(self, x):
            if x == "boom":
                raise ValueError("dag boom")
            return x + self.add

    a, b = Stage.remote(1), Stage.remote(10)
    ray_tpu.get([a.step.remote(0), b.step.remote(0)])
    with InputNode() as inp:
        y = b.step.bind(a.step.bind(inp))
    dag = y.experimental_compile()
    try:
        assert dag.execute(5).get() == 16
        # pipelined executions come back in order
        refs = [dag.execute(i) for i in range(50)]
        assert [r.get() for r in refs] == [i + 11 for i in range(50)]
        # errors propagate through the pipeline to the caller — the
        # SAME TaskError the eager .remote() chain raises (bit-parity
        # gated in tests/test_compiled_dag.py)
        from ray_tpu.core.exceptions import TaskError

        with pytest.raises(TaskError, match="boom"):
            dag.execute("boom").get()
    finally:
        dag.teardown()
        ray_tpu.kill(a)
        ray_tpu.kill(b)


def test_compiled_dag_beats_actor_calls(ray_boot):
    """The point of compiling: repeated execution costs channel ops, not
    per-call task submission (compiled_dag_node.py:711)."""
    from ray_tpu.dag import InputNode

    @ray_tpu.remote(num_cpus=0.5)
    class Echo:
        def step(self, x):
            return x

    e = Echo.remote()
    ray_tpu.get(e.step.remote(0))
    with InputNode() as inp:
        y = e.step.bind(inp)
    dag = y.experimental_compile()
    try:
        n = 500
        t0 = time.perf_counter()
        refs = [dag.execute(i) for i in range(n)]
        assert [r.get() for r in refs] == list(range(n))
        dag_rate = n / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        m = 200
        for i in range(m):
            ray_tpu.get(e.step.remote(i))
        call_rate = m / (time.perf_counter() - t0)
        assert dag_rate > 3 * call_rate, (dag_rate, call_rate)
    finally:
        dag.teardown()
        ray_tpu.kill(e)


def test_compiled_dag_multi_output(ray_boot):
    from ray_tpu.dag import InputNode, MultiOutputNode

    @ray_tpu.remote(num_cpus=0.5)
    class Mul:
        def __init__(self, k):
            self.k = k

        def step(self, x):
            return x * self.k

    a, b = Mul.remote(2), Mul.remote(3)
    ray_tpu.get([a.step.remote(0), b.step.remote(0)])
    with InputNode() as inp:
        out = MultiOutputNode([a.step.bind(inp), b.step.bind(inp)])
    dag = out.experimental_compile()
    try:
        assert dag.execute(7).get() == [14, 21]
    finally:
        dag.teardown()
        ray_tpu.kill(a)
        ray_tpu.kill(b)


# ---------------------------------------------------------------- offline RL

@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_offline_record_bc_marwil(ray_boot, tmp_path):
    """Record expert experiences -> parquet -> BC clones the policy to
    eval-solve CartPole; MARWIL's advantage weighting also learns."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.offline import BCConfig, MARWILConfig, record_experiences
    from ray_tpu.rllib.ppo import PPOConfig

    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=128)
            .training(lr=1e-3)).build()
    best = 0.0
    t0 = time.time()
    while time.time() - t0 < 180:
        r = algo.train()
        m = r["episode_return_mean"]
        if m == m:
            best = max(best, m)
        if best > 300:
            break
    expert = algo.get_weights()
    algo.stop()
    assert best > 150, f"expert failed to train ({best})"

    out = str(tmp_path / "exp")
    paths = record_experiences("CartPole-v1", 40, out, params=expert,
                               fmt="parquet")
    assert paths

    bc = BCConfig().offline_data(out).training(lr=1e-3).build()
    losses = [bc.train()["learner/loss"] for _ in range(30)]
    assert losses[-1] < losses[0]
    ev = bc.evaluate("CartPole-v1", num_episodes=10)
    assert ev["episode_return_mean"] > 150, ev

    mw = MARWILConfig().offline_data(out).training(lr=1e-3).build()
    for _ in range(30):
        mw.train()
    assert mw.evaluate("CartPole-v1",
                       num_episodes=10)["episode_return_mean"] > 150


# ---------------------------------------------------------------- multi-agent

@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_multi_agent_shared_policy_learns():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.multi_agent import MultiAgentPPOConfig

    algo = MultiAgentPPOConfig().build()
    first = algo.train()["episode_return_mean"]
    last = first
    for _ in range(20):
        last = algo.train()["episode_return_mean"]
    assert last > first + 5, (first, last)  # coordination emerges
    assert last > 20  # near-perfect (max 25)


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_multi_agent_independent_policies():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.multi_agent import MultiAgentPPOConfig, MultiRLModule

    algo = (MultiAgentPPOConfig()
            .multi_agent(policies=["p0", "p1"],
                         policy_mapping_fn=lambda a: "p0" if a == "a0"
                         else "p1")
            .build())
    assert isinstance(algo.module, MultiRLModule)
    assert set(algo.module.get_weights()) == {"p0", "p1"}
    for _ in range(25):
        r = algo.train()
    assert r["episode_return_mean"] > 20
    assert "learner/p0/total_loss" in r and "learner/p1/total_loss" in r
