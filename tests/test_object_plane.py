"""Object-plane hardening tests: borrow release + chunked transfer.

Reference model: python/ray/tests/test_reference_counting*.py (borrower
release frees the owner's memory) and the object manager's chunked
transfer (object_manager.h:117)."""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=4)
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _store_objects(nodelet) -> int:
    return nodelet.store.stats()["num_objects"]


def test_borrow_then_drop_frees_owner_memory(cluster):
    """A worker that borrowed (and released) a big object must not pin it
    in the owner's store forever: when the driver also drops its ref, the
    bytes are reclaimed (VERDICT r1: served_borrow leaked forever)."""
    nl = cluster.nodelets[0]

    @ray_tpu.remote(num_cpus=0.1)
    def consume(a):
        return int(a[0]) + int(a[-1])

    before = _store_objects(nl)
    big = ray_tpu.put(np.arange(1_000_000))  # ~8MB -> store path
    assert ray_tpu.get(consume.remote(big), timeout=60) == 999999
    # drop the driver's last reference
    del big
    gc.collect()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if _store_objects(nl) <= before:
            break
        time.sleep(0.2)
    assert _store_objects(nl) <= before, (
        f"object leaked in store: {nl.store.stats()}")


def test_chunked_node_to_node_transfer(cluster):
    """A result bigger than the pull chunk size transfers node-to-node in
    bounded chunks and arrives intact."""
    target = cluster.nodelets[1]

    @ray_tpu.remote(num_cpus=0.1, resources={"maker": 1.0})
    def make_big():
        return np.arange(3_000_000, dtype=np.int64)  # 24MB > 4MB chunk

    # pin production to a third node so the driver (attached to node 0)
    # must pull across nodes
    maker = cluster.add_node(num_cpus=2, resources={"maker": 2.0})
    cluster.wait_for_nodes()
    try:
        before_chunks = maker._pull_chunks_served
        ref = make_big.remote()
        arr = ray_tpu.get(ref, timeout=120)
        assert arr.shape == (3_000_000,)
        assert int(arr[12345]) == 12345
        assert int(arr.sum()) == 4499998500000
        # the driver-side fetch went through its local nodelet's chunked
        # pull (6 chunks for 24MB at 4MB)
        assert cluster.nodelets[0]._pull_chunks_served >= 6
        del before_chunks, target
    finally:
        cluster.remove_node(maker)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 2:
                break
            time.sleep(0.3)


def test_large_roundtrip_through_store(cluster):
    """Zero-copy write + read of a large array via put/get."""
    a = np.random.RandomState(0).rand(2_000_000)  # 16MB
    ref = ray_tpu.put(a)
    b = ray_tpu.get(ref)
    np.testing.assert_array_equal(a, b)
