"""Autoscaler v2 — instance lifecycle + reconciler (reference model:
python/ray/autoscaler/v2/tests — state-machine legality, idempotent
reconciliation, stuck-instance handling, demand-driven convergence)."""

import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.autoscaler import FakeNodeProvider
from ray_tpu.autoscaler_v2 import (
    ALLOCATED,
    ALLOCATION_FAILED,
    QUEUED,
    RAY_RUNNING,
    REQUESTED,
    TERMINATED,
    TERMINATING,
    InstanceStorage,
    InvalidTransitionError,
    Reconciler,
)
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------------------
# storage state machine
# ---------------------------------------------------------------------------

def test_legal_lifecycle_and_history():
    st = InstanceStorage()
    inst = st.add("worker")
    assert inst.status == QUEUED
    st.transition(inst.instance_id, REQUESTED)
    st.transition(inst.instance_id, ALLOCATED, node_id=b"n1")
    st.transition(inst.instance_id, RAY_RUNNING)
    st.transition(inst.instance_id, TERMINATING)
    got = st.transition(inst.instance_id, TERMINATED)
    assert [s for s, _ in got.history] == [
        QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING, TERMINATING, TERMINATED]


def test_illegal_edges_raise():
    st = InstanceStorage()
    inst = st.add("worker")
    with pytest.raises(InvalidTransitionError):
        st.transition(inst.instance_id, RAY_RUNNING)  # QUEUED -> RUNNING
    st.transition(inst.instance_id, REQUESTED)
    st.transition(inst.instance_id, ALLOCATION_FAILED)
    with pytest.raises(InvalidTransitionError):
        st.transition(inst.instance_id, REQUESTED)  # terminal


def test_version_cas_conflict():
    st = InstanceStorage()
    inst = st.add("worker")
    v = inst.version
    st.transition(inst.instance_id, REQUESTED, expected_version=v)
    with pytest.raises(InvalidTransitionError):
        st.transition(inst.instance_id, ALLOCATED, expected_version=v)


def test_subscribers_see_every_transition():
    st = InstanceStorage()
    seen = []
    st.subscribe(lambda i: seen.append(i.status))
    inst = st.add("worker")
    st.transition(inst.instance_id, REQUESTED)
    assert seen == [QUEUED, REQUESTED]


# ---------------------------------------------------------------------------
# reconciler against a live head + fake provider
# ---------------------------------------------------------------------------

@pytest.fixture
def cluster(tmp_path):
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _mk(cluster, tmp_path, **kw):
    provider = FakeNodeProvider(
        cluster.address, {"worker": {"resources": {"CPU": 4.0}}},
        session_dir=str(tmp_path / "v2"))
    return Reconciler(cluster.address, provider, node_type="worker", **kw)


def test_scale_up_converges_to_ray_running(cluster, tmp_path):
    rec = _mk(cluster, tmp_path, min_workers=1, max_workers=3)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        rec.reconcile()
        if rec.storage.list(RAY_RUNNING):
            break
        time.sleep(0.2)
    running = rec.storage.list(RAY_RUNNING)
    assert len(running) == 1
    assert running[0].node_id is not None
    assert rec.summary()["launches"] == 1
    # idempotence: further ticks change nothing at steady state
    for _ in range(3):
        rec.reconcile()
    assert rec.summary()["launches"] == 1
    assert len(rec.storage.list(RAY_RUNNING)) == 1


def test_demand_drives_scale_up_then_idle_scale_down(cluster, tmp_path):
    rec = _mk(cluster, tmp_path, min_workers=0, max_workers=2,
              idle_timeout_s=1.5)

    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(1.0)
        return 1

    refs = [slow.remote() for _ in range(6)]  # 1-CPU head: queue builds
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline:
        rec.reconcile()
        if rec.storage.list(RAY_RUNNING):
            break
        time.sleep(0.2)
    assert rec.storage.list(RAY_RUNNING), "no scale-up under demand"
    assert ray_tpu.get(refs, timeout=120) == [1] * 6

    # reclaim: RAY_RUNNING → TERMINATING → (provider+head agree it is
    # gone, head death-detection ~5s) → TERMINATED
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        rec.reconcile()
        if rec.storage.list(TERMINATED):
            break
        time.sleep(0.3)
    assert not rec.storage.list(RAY_RUNNING), "idle node never reclaimed"
    assert rec.storage.list(TERMINATED), "termination never converged"
    assert rec.summary()["terminations"] >= 1


def test_stuck_requested_instance_reclaimed_not_leaked(cluster, tmp_path):
    """A stuck REQUESTED whose provider call SUCCEEDED must be
    terminated (the cloud node may materialize later and bill forever
    behind a terminal record), not marked ALLOCATION_FAILED."""
    terminated = []

    class StuckProvider(FakeNodeProvider):
        def create_node(self, node_type):
            return object()  # a handle that never yields a node_id

        def node_id(self, handle):
            return b""

        def terminate_node(self, handle):
            terminated.append(handle)

    provider = StuckProvider(
        cluster.address, {"worker": {"resources": {"CPU": 2.0}}},
        session_dir=str(tmp_path / "stuck"))
    rec = Reconciler(cluster.address, provider, node_type="worker",
                     min_workers=1, max_workers=2,
                     stuck_timeouts={"REQUESTED": 0.5})
    rec.reconcile()
    assert rec.storage.list(REQUESTED)
    time.sleep(0.7)
    rec.reconcile()  # stuck → TERMINATING (terminate issued)
    rec.reconcile()  # provider agrees it is gone → TERMINATED
    assert terminated, "stuck launch never terminated at the provider"
    assert rec.storage.list(TERMINATED)
    assert not rec.storage.list(ALLOCATION_FAILED)
    # a partial stuck_timeouts override must keep the other defaults
    assert "ALLOCATED" in rec.stuck_timeouts
    assert "TERMINATING" in rec.stuck_timeouts


def test_provider_create_failure_records_allocation_failed(cluster,
                                                          tmp_path):
    class FailingProvider(FakeNodeProvider):
        def create_node(self, node_type):
            raise RuntimeError("stockout")

    provider = FailingProvider(
        cluster.address, {"worker": {"resources": {"CPU": 2.0}}},
        session_dir=str(tmp_path / "fail"))
    rec = Reconciler(cluster.address, provider, node_type="worker",
                     min_workers=1, max_workers=2)
    rec.reconcile()
    assert rec.storage.list(ALLOCATION_FAILED)
    assert not rec.storage.list(RAY_RUNNING)


def test_gcp_slice_adoption(cluster, tmp_path):
    """One GCP create_node yields N slice hosts; the reconciler matches
    the requesting instance to one host and ADOPTS the others as
    managed instances (reference: reconciler cloud-instance adoption)."""
    from ray_tpu.autoscaler_gcp import GCPTPUNodeProvider

    provider = GCPTPUNodeProvider(
        cluster.address,
        {"tpu": {"accelerator_type": "v4-8", "cpus_per_host": 1}},
        session_dir=str(tmp_path / "gcpv2"))
    rec = Reconciler(cluster.address, provider, node_type="tpu",
                     min_workers=1, max_workers=4)
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline:
        rec.reconcile()
        if len(rec.storage.list(RAY_RUNNING)) >= 2:
            break
        time.sleep(0.3)
    running = rec.storage.list(RAY_RUNNING)
    assert len(running) == 2, rec.summary()  # both v4-8 hosts managed
    assert len({i.node_id for i in running}) == 2
    assert rec.summary()["launches"] == 1  # ONE provider request
    for h in list(provider.non_terminated_nodes()):
        provider.terminate_node(h)


def test_stockout_backoff_bounds_failed_records(cluster, tmp_path):
    class FailingProvider(FakeNodeProvider):
        def create_node(self, node_type):
            raise RuntimeError("stockout")

    provider = FailingProvider(
        cluster.address, {"worker": {"resources": {"CPU": 2.0}}},
        session_dir=str(tmp_path / "stockout"))
    rec = Reconciler(cluster.address, provider, node_type="worker",
                     min_workers=1, max_workers=2)
    for _ in range(10):
        rec.reconcile()
    # backoff: 10 ticks produce ONE failed record, not ten
    assert len(rec.storage.list(ALLOCATION_FAILED)) == 1


def test_dead_ray_running_node_replaced(cluster, tmp_path):
    """A RAY_RUNNING instance whose node dies must leave RAY_RUNNING
    (via TERMINATING) so min_workers replacement fires — a crashed node
    must not count as live capacity forever."""
    rec = _mk(cluster, tmp_path, min_workers=1, max_workers=3)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        rec.reconcile()
        if rec.storage.list(RAY_RUNNING):
            break
        time.sleep(0.2)
    inst = rec.storage.list(RAY_RUNNING)[0]
    inst.provider_handle.stop()  # kill the nodelet behind the provider's back
    rec.provider._nodes.remove(inst.provider_handle)
    deadline = time.monotonic() + 40
    replaced = False
    while time.monotonic() < deadline:
        rec.reconcile()
        running = rec.storage.list(RAY_RUNNING)
        if running and running[0].instance_id != inst.instance_id:
            replaced = True
            break
        time.sleep(0.3)
    assert replaced, rec.summary()
    assert rec.summary()["launches"] == 2
