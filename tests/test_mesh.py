import jax
import pytest

from ray_tpu.parallel.mesh import MeshSpec, build_mesh, slice_groups


def test_mesh_spec_resolve_infer():
    sizes = MeshSpec(data=-1, tensor=2).resolve(8)
    assert sizes["data"] == 4 and sizes["tensor"] == 2


def test_mesh_spec_resolve_exact():
    sizes = MeshSpec(data=2, fsdp=2, tensor=2).resolve(8)
    assert sizes["data"] * sizes["fsdp"] * sizes["tensor"] == 8


def test_mesh_spec_mismatch_raises():
    with pytest.raises(ValueError):
        MeshSpec(data=3, tensor=3).resolve(8)


def test_mesh_spec_two_unknown_raises():
    with pytest.raises(ValueError):
        MeshSpec(data=-1, tensor=-1).resolve(8)


def test_build_mesh_canonical_order(cpu_mesh8):
    names = cpu_mesh8.axis_names
    assert names.index("data") < names.index("fsdp") < names.index("tensor")
    assert dict(cpu_mesh8.shape)["data"] == 2


def test_build_mesh_all_devices():
    mesh = build_mesh(MeshSpec(data=-1))
    assert dict(mesh.shape)["data"] == len(jax.devices())


def test_slice_groups_cpu_single_domain():
    groups = slice_groups()
    assert sum(len(v) for v in groups.values()) == len(jax.devices())
