"""Tuner tests (reference model: tune/tests — controller, schedulers,
restore)."""

import sys

import cloudpickle
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.trainer import RunConfig

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _quadratic(config):
    # minimum at x=3; lr controls convergence speed
    x = 0.0
    for _ in range(20):
        x -= config["lr"] * 2 * (x - 3.0)
        tune.report({"objective": (x - 3.0) ** 2, "x": x})


def test_random_sweep_20_trials(cluster, tmp_path):
    tuner = tune.Tuner(
        _quadratic,
        param_space={"lr": tune.loguniform(1e-3, 0.5)},
        tune_config=tune.TuneConfig(metric="objective", mode="min",
                                    num_samples=20, seed=7,
                                    max_concurrent_trials=4),
        run_config=RunConfig(name="sweep20", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 20
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["objective"] < 0.5
    assert best.config["lr"] > 0.01  # higher lr converges further in 20 steps


def test_grid_search_cross_product(cluster, tmp_path):
    tuner = tune.Tuner(
        _quadratic,
        param_space={"lr": tune.grid_search([0.01, 0.1, 0.4])},
        tune_config=tune.TuneConfig(metric="objective", mode="min"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    lrs = sorted(r.config["lr"] for r in grid)
    assert lrs == [0.01, 0.1, 0.4]


def test_asha_stops_bad_trials(cluster, tmp_path):
    def slow_loss(config):
        for i in range(30):
            tune.report({"loss": config["level"] + 0.001 * i})

    tuner = tune.Tuner(
        slow_loss,
        param_space={"level": tune.grid_search([1.0, 2.0, 3.0, 4.0,
                                                5.0, 6.0, 7.0, 8.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", max_concurrent_trials=8,
            scheduler=tune.ASHAScheduler(max_t=30, grace_period=5,
                                         reduction_factor=2)),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    stopped = [r for r in grid
               if r.metrics.get("training_iteration", 0) < 30]
    finished = [r for r in grid
                if r.metrics.get("training_iteration", 0) == 30]
    assert finished, "some trials must survive to max_t"
    assert stopped, "ASHA must cut some underperformers early"
    # the best level should be among the finishers
    assert min(r.config["level"] for r in finished) == 1.0


def test_tuner_restore_completes_pending(cluster, tmp_path):
    """Simulate an interrupted sweep: state on disk has a PENDING trial;
    restore() runs it and the grid is complete."""
    tuner = tune.Tuner(
        _quadratic,
        param_space={"lr": tune.grid_search([0.05, 0.2])},
        tune_config=tune.TuneConfig(metric="objective", mode="min"),
        run_config=RunConfig(name="resume", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 2

    # forge an interruption: mark one trial pending again
    import json
    import os

    state_file = os.path.join(str(tmp_path), "resume", "tuner_state.json")
    with open(state_file) as f:
        state = json.load(f)
    state["trials"][1]["status"] = "RUNNING"  # as if it died mid-flight
    with open(state_file, "w") as f:
        json.dump(state, f)

    restored = tune.Tuner.restore(os.path.join(str(tmp_path), "resume"),
                                  _quadratic)
    grid2 = restored.fit()
    assert len(grid2) == 2
    assert not grid2.errors
    assert all(r.metrics for r in grid2)


def test_gpt2_tiny_lr_sweep(cluster, tmp_path):
    """The VERDICT done-criterion: sweep the GPT-2-tiny learning rate on
    CPU; best config reported (scaled to 4 trials for suite runtime)."""

    def train_gpt2(config):
        import jax
        import numpy as np
        import optax

        from ray_tpu.models.gpt2 import (
            GPT2Config,
            gpt2_loss,
            gpt2_partition_rules,
            init_gpt2,
        )
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.train.spmd import init_sharded_state, make_train_step

        jax.config.update("jax_platforms", "cpu")
        cfg = GPT2Config.tiny(vocab_size=256, block_size=32)
        mesh = build_mesh(MeshSpec(data=-1), devices=jax.devices())
        tx = optax.adamw(config["lr"])
        state = init_sharded_state(
            lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh,
            gpt2_partition_rules())
        rng = np.random.RandomState(0)
        toks = rng.randint(0, cfg.vocab_size, (2, cfg.block_size + 1)
                           ).astype(np.int32)
        batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
        step_fn = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), tx)
        with mesh:
            for _ in range(5):
                state, metrics = step_fn(state, batch)
                tune.report({"loss": float(np.asarray(metrics["loss"]))})

    tuner = tune.Tuner(
        train_gpt2,
        param_space={"lr": tune.grid_search([1e-5, 1e-3, 5e-2, 0.5])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    max_concurrent_trials=2),
        run_config=RunConfig(name="gpt2lr", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    assert not grid.errors
    best = grid.get_best_result()
    assert best.config["lr"] in (1e-3, 5e-2)
