"""Tuner tests (reference model: tune/tests — controller, schedulers,
restore)."""

import sys

import cloudpickle
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.trainer import RunConfig

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    """Module-scoped on purpose (tier-1 wall-time lever, see ROADMAP):
    every test shares one head + nodelet + driver; trials only ever add
    actors, never nodes, so no per-test cluster surgery is needed."""
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _quadratic(config):
    # minimum at x=3; lr controls convergence speed
    x = 0.0
    for _ in range(20):
        x -= config["lr"] * 2 * (x - 3.0)
        tune.report({"objective": (x - 3.0) ** 2, "x": x})


def test_random_sweep_20_trials(cluster, tmp_path):
    tuner = tune.Tuner(
        _quadratic,
        param_space={"lr": tune.loguniform(1e-3, 0.5)},
        tune_config=tune.TuneConfig(metric="objective", mode="min",
                                    num_samples=20, seed=7,
                                    max_concurrent_trials=4),
        run_config=RunConfig(name="sweep20", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 20
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["objective"] < 0.5
    assert best.config["lr"] > 0.01  # higher lr converges further in 20 steps


def test_grid_search_cross_product(cluster, tmp_path):
    tuner = tune.Tuner(
        _quadratic,
        param_space={"lr": tune.grid_search([0.01, 0.1, 0.4])},
        tune_config=tune.TuneConfig(metric="objective", mode="min"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    lrs = sorted(r.config["lr"] for r in grid)
    assert lrs == [0.01, 0.1, 0.4]


def test_asha_stops_bad_trials(cluster, tmp_path):
    def slow_loss(config):
        for i in range(30):
            tune.report({"loss": config["level"] + 0.001 * i})

    tuner = tune.Tuner(
        slow_loss,
        param_space={"level": tune.grid_search([1.0, 2.0, 3.0, 4.0,
                                                5.0, 6.0, 7.0, 8.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", max_concurrent_trials=8,
            scheduler=tune.ASHAScheduler(max_t=30, grace_period=5,
                                         reduction_factor=2)),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    stopped = [r for r in grid
               if r.metrics.get("training_iteration", 0) < 30]
    finished = [r for r in grid
                if r.metrics.get("training_iteration", 0) == 30]
    assert finished, "some trials must survive to max_t"
    assert stopped, "ASHA must cut some underperformers early"
    # the best level should be among the finishers
    assert min(r.config["level"] for r in finished) == 1.0


def test_tuner_restore_completes_pending(cluster, tmp_path):
    """Simulate an interrupted sweep: state on disk has a PENDING trial;
    restore() runs it and the grid is complete."""
    tuner = tune.Tuner(
        _quadratic,
        param_space={"lr": tune.grid_search([0.05, 0.2])},
        tune_config=tune.TuneConfig(metric="objective", mode="min"),
        run_config=RunConfig(name="resume", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 2

    # forge an interruption: mark one trial pending again
    import json
    import os

    state_file = os.path.join(str(tmp_path), "resume", "tuner_state.json")
    with open(state_file) as f:
        state = json.load(f)
    state["trials"][1]["status"] = "RUNNING"  # as if it died mid-flight
    with open(state_file, "w") as f:
        json.dump(state, f)

    restored = tune.Tuner.restore(os.path.join(str(tmp_path), "resume"),
                                  _quadratic)
    grid2 = restored.fit()
    assert len(grid2) == 2
    assert not grid2.errors
    assert all(r.metrics for r in grid2)


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_gpt2_tiny_lr_sweep(cluster, tmp_path):
    """The VERDICT done-criterion: sweep the GPT-2-tiny learning rate on
    CPU; best config reported (scaled to 4 trials for suite runtime)."""

    def train_gpt2(config):
        import jax
        import numpy as np
        import optax

        from ray_tpu.models.gpt2 import (
            GPT2Config,
            gpt2_loss,
            gpt2_partition_rules,
            init_gpt2,
        )
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.train.spmd import init_sharded_state, make_train_step

        jax.config.update("jax_platforms", "cpu")
        cfg = GPT2Config.tiny(vocab_size=256, block_size=32)
        mesh = build_mesh(MeshSpec(data=-1), devices=jax.devices())
        tx = optax.adamw(config["lr"])
        state = init_sharded_state(
            lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh,
            gpt2_partition_rules())
        rng = np.random.RandomState(0)
        toks = rng.randint(0, cfg.vocab_size, (2, cfg.block_size + 1)
                           ).astype(np.int32)
        batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
        step_fn = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), tx)
        with mesh:
            for _ in range(5):
                state, metrics = step_fn(state, batch)
                tune.report({"loss": float(np.asarray(metrics["loss"]))})

    tuner = tune.Tuner(
        train_gpt2,
        param_space={"lr": tune.grid_search([1e-5, 1e-3, 5e-2, 0.5])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    max_concurrent_trials=2),
        run_config=RunConfig(name="gpt2lr", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    assert not grid.errors
    best = grid.get_best_result()
    assert best.config["lr"] in (1e-3, 5e-2)


# ---------------------------------------------------------------------------
# Population Based Training (VERDICT r2 item 2 / BASELINE "PBT sweep")
# ---------------------------------------------------------------------------

def _pbt_progress(config):
    """Synthetic PBT objective: score is accumulated progress `x`; good
    `lr` trials advance fast. Exploit clones x (the checkpoint) so a bad
    trial teleports to the leader's state; explore perturbs lr."""
    import time as _t

    state = tune.get_checkpoint() or {"x": 0.0}
    x = state["x"]
    for _ in range(24):
        x += config["lr"]
        tune.report({"score": x}, checkpoint={"x": x})
        _t.sleep(0.03)


_PBT_LRS = [0.001, 0.002, 0.005, 1.0]


def _run_population(scheduler, tmp_path, name):
    tuner = tune.Tuner(
        _pbt_progress,
        param_space={"lr": tune.grid_search(_PBT_LRS)},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=scheduler,
                                    max_concurrent_trials=4),
        run_config=RunConfig(name=name, storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert not grid.errors
    return sorted(r.metrics["score"] for r in grid)


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_pbt_beats_fixed_hyperparams(cluster, tmp_path):
    """PBT's exploit/explore lifts the population: the mean final score
    beats the same population with fixed hyperparameters."""
    fixed = _run_population(None, tmp_path, "pbt_fixed")
    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=6,
        hyperparam_mutations={"lr": [0.5, 1.0, 2.0]}, seed=3)
    evolved = _run_population(pbt, tmp_path, "pbt_evolved")
    assert pbt.exploit_count >= 1
    assert sum(evolved) > sum(fixed) * 2, (fixed, evolved)
    # the exploited stragglers specifically must have been lifted
    assert evolved[0] > fixed[0] * 10


def test_pbt_over_jax_training_smoke(cluster, tmp_path):
    """PBT over a real jitted jax train loop: checkpoints are param
    pytrees cloned across trial actors (BASELINE north star: PBT sweep
    over pod slices — here the single-host smoke)."""

    def jax_trainable(config):
        import jax
        import jax.numpy as jnp

        w = tune.get_checkpoint()
        w = jnp.asarray(w["w"]) if w else jnp.zeros(4)
        target = jnp.arange(4.0)

        @jax.jit
        def step(w, lr):
            g = 2 * (w - target)
            return w - lr * g

        for _ in range(10):
            w = step(w, config["lr"])
            loss = float(jnp.sum((w - target) ** 2))
            tune.report({"loss": loss}, checkpoint={"w": list(map(float, w))})

    pbt = tune.PopulationBasedTraining(
        metric="loss", mode="min", perturbation_interval=4,
        hyperparam_mutations={"lr": [0.05, 0.2]}, seed=0)
    tuner = tune.Tuner(
        jax_trainable,
        param_space={"lr": tune.grid_search([0.001, 0.2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    scheduler=pbt,
                                    max_concurrent_trials=2),
        run_config=RunConfig(name="pbt_jax", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert not grid.errors
    assert grid.get_best_result().metrics["loss"] < 0.1


def test_median_stopping_rule_cuts_stragglers(cluster, tmp_path):
    sched = tune.MedianStoppingRule(metric="score", mode="max",
                                    grace_period=3)
    scores = _run_population(sched, tmp_path, "median_stop")
    assert scores[-1] > 20  # leader ran to completion
