import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.sharding import PartitionRules, shard_pytree


RULES = PartitionRules(
    [
        (r"kernel$", P("fsdp", "tensor")),
        (r"bias$", P("tensor")),
        (r".*", P()),
    ]
)


def test_first_match_wins():
    rules = PartitionRules([(r"a/kernel$", P("tensor")), (r"kernel$", P("fsdp"))])
    assert rules.spec_for("x/a/kernel") == P("tensor")
    assert rules.spec_for("b/kernel") == P("fsdp")


def test_no_match_replicates():
    assert RULES.spec_for("whatever") == P()


def test_prune_missing_axis(cpu_mesh8):
    rules = PartitionRules([(r"k$", P("data", "nonexistent"))])
    assert rules.spec_for("k", cpu_mesh8) == P("data", None)


def test_prune_size_one_axis(cpu_mesh8):
    # 'seq' exists in the mesh but has size 1 -> dropped
    rules = PartitionRules([(r"k$", P("seq", "tensor"))])
    assert rules.spec_for("k", cpu_mesh8) == P(None, "tensor")


def test_shard_pytree(cpu_mesh8):
    tree = {"layer": {"kernel": jnp.ones((8, 8)), "bias": jnp.ones((8,))}}
    sharded = shard_pytree(tree, RULES, cpu_mesh8)
    k = sharded["layer"]["kernel"]
    assert k.sharding.spec == P("fsdp", "tensor")
    assert sharded["layer"]["bias"].sharding.spec == P("tensor")
    # round-trips values
    assert jnp.allclose(jax.device_get(k), 1.0)


# ---------------------------------------------- add_axis_to_spec edges
# The ZeRO ladder's "+replica axis" transformation (zero_shardings in
# train/spmd.py maps it over whole state trees): documented edge cases.

def test_add_axis_scalar_leaf_unchanged(cpu_mesh8):
    from ray_tpu.parallel.sharding import add_axis_to_spec

    assert add_axis_to_spec(P(), (), cpu_mesh8, "data") == P()


def test_add_axis_no_divisible_dim_falls_back_replicated(cpu_mesh8):
    """No dim divides by the shard count -> the leaf stays replicated
    over the new axis (the caller's ~1/N byte assertions carry slack
    for exactly these leaves)."""
    from ray_tpu.parallel.sharding import add_axis_to_spec

    assert add_axis_to_spec(P(), (3, 5), cpu_mesh8, "data") == P()


def test_add_axis_already_sharded_on_axis_skipped(cpu_mesh8):
    """A leaf already touching the axis comes back unchanged — mapping
    zero_shardings over an already-ZeRO tree is idempotent."""
    from ray_tpu.parallel.sharding import add_axis_to_spec

    assert add_axis_to_spec(P("data"), (8, 8), cpu_mesh8, "data") \
        == P("data")
    assert add_axis_to_spec(P(("fsdp", "data")), (8, 8), cpu_mesh8,
                            "data") == P(("fsdp", "data"))


def test_add_axis_picks_first_evenly_divisible_dim(cpu_mesh8):
    from ray_tpu.parallel.sharding import add_axis_to_spec

    # dim0 (3) does not divide by data=2; dim1 (8) does
    assert add_axis_to_spec(P(), (3, 8), cpu_mesh8, "data") \
        == P(None, "data")


def test_add_axis_composes_with_existing_axes(cpu_mesh8):
    """Divisibility accounts for shards already on the dim: a
    tensor(2)-sharded dim of 8 takes data(2) too (8 % 4 == 0), a dim
    of 6 does not (6 % 4 != 0) and stays as-is."""
    from ray_tpu.parallel.sharding import add_axis_to_spec

    assert add_axis_to_spec(P("tensor"), (8, 4), cpu_mesh8, "data") \
        == P(("tensor", "data"), None)
    assert add_axis_to_spec(P("tensor"), (6,), cpu_mesh8, "data") \
        == P("tensor")


def test_add_axis_absent_mesh_axis_is_noop(cpu_mesh8):
    from ray_tpu.parallel.sharding import add_axis_to_spec

    assert add_axis_to_spec(P(), (8, 8), cpu_mesh8, "nonexistent") \
        == P()
