import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.sharding import PartitionRules, shard_pytree


RULES = PartitionRules(
    [
        (r"kernel$", P("fsdp", "tensor")),
        (r"bias$", P("tensor")),
        (r".*", P()),
    ]
)


def test_first_match_wins():
    rules = PartitionRules([(r"a/kernel$", P("tensor")), (r"kernel$", P("fsdp"))])
    assert rules.spec_for("x/a/kernel") == P("tensor")
    assert rules.spec_for("b/kernel") == P("fsdp")


def test_no_match_replicates():
    assert RULES.spec_for("whatever") == P()


def test_prune_missing_axis(cpu_mesh8):
    rules = PartitionRules([(r"k$", P("data", "nonexistent"))])
    assert rules.spec_for("k", cpu_mesh8) == P("data", None)


def test_prune_size_one_axis(cpu_mesh8):
    # 'seq' exists in the mesh but has size 1 -> dropped
    rules = PartitionRules([(r"k$", P("seq", "tensor"))])
    assert rules.spec_for("k", cpu_mesh8) == P(None, "tensor")


def test_shard_pytree(cpu_mesh8):
    tree = {"layer": {"kernel": jnp.ones((8, 8)), "bias": jnp.ones((8,))}}
    sharded = shard_pytree(tree, RULES, cpu_mesh8)
    k = sharded["layer"]["kernel"]
    assert k.sharding.spec == P("fsdp", "tensor")
    assert sharded["layer"]["bias"].sharding.spec == P("tensor")
    # round-trips values
    assert jnp.allclose(jax.device_get(k), 1.0)
