"""Self-healing serve (ISSUE 9): controller health loop + replica
replacement, handle failover (unary + mid-stream LLM replay),
weight-version catch-up, restart backoff/cap, and the chaos plane.

The acceptance gate lives in test_llm_kill_mid_stream_* — 8 concurrent
greedy streams, one replica killed mid-generation, zero client-visible
failures, bit-identical outputs vs the unkilled run, and the
replacement serving at the fleet's current weight version before it
takes traffic.
"""

import os
import sys
import threading
import time

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import chaos

cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _failovers(app: str) -> float:
    from ray_tpu.util.metrics import prometheus_text

    for line in prometheus_text().splitlines():
        if line.startswith(
                f'serve_request_failovers_total{{app="{app}"}}'):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _wait_healed(app: str, target: int, min_restarts: int = 1,
                 timeout: float = 120.0) -> dict:
    """Block until the app is back to `target` replicas with no
    replacement in flight (and at least `min_restarts` heals done)."""
    deadline = time.monotonic() + timeout
    hl: dict = {}
    while time.monotonic() < deadline:
        hl = serve.status()["health"].get(app, {})
        if hl.get("restarts", 0) >= min_restarts and \
                hl.get("healthy") == target and \
                hl.get("replacing") == 0:
            return hl
        time.sleep(0.3)
    raise AssertionError(f"{app} never healed: {hl}")


# ---------------------------------------------------------------------------
# RPC chaos: delay injection (no cluster)
# ---------------------------------------------------------------------------

def test_rpc_chaos_delay_injection():
    """"method=delayN" delivers the first N sends LATE (timer thread),
    so a caller with a shorter timeout sees exactly what a slow network
    produces: a timeout racing an in-flight straggler — then full speed
    once the budget is spent."""
    from ray_tpu.core import rpc

    server = rpc.RpcServer(name="chaos-delay").start()
    server.register("slowmo", lambda msg, frames: {"ok": True})
    client = rpc.RpcClient()
    os.environ["RAY_TPU_TESTING_RPC_DELAY_S"] = "0.6"
    try:
        assert client.call(server.address, "slowmo", {},
                           timeout=10)["ok"]  # warm, undelayed
        rpc.set_chaos("slowmo=delay2")
        for _ in range(2):
            with pytest.raises(rpc.PeerUnavailableError):
                client.call(server.address, "slowmo", {}, timeout=0.2)
        t0 = time.monotonic()
        assert client.call(server.address, "slowmo", {},
                           timeout=10)["ok"]  # budget spent: fast again
        assert time.monotonic() - t0 < 0.5
        # a delayed send with a GENEROUS timeout still succeeds — the
        # message was late, not lost
        rpc.set_chaos("slowmo=delay1")
        t0 = time.monotonic()
        assert client.call(server.address, "slowmo", {},
                           timeout=10)["ok"]
        assert time.monotonic() - t0 >= 0.5
    finally:
        rpc.set_chaos("")
        os.environ.pop("RAY_TPU_TESTING_RPC_DELAY_S", None)
        client.close()
        server.stop()


# ---------------------------------------------------------------------------
# cluster fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    serve.shutdown()
    ray_tpu.shutdown()
    c.shutdown()


def _tiny_llm_cfg():
    import jax.numpy as jnp

    from ray_tpu.models import gpt2

    return gpt2.GPT2Config(
        vocab_size=64, n_layer=1, n_head=2, n_embd=32, block_size=64,
        vocab_pad_multiple=64, dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def llm_app(cluster):
    """One 2-replica tiny LLM app shared by the LLM heal tests (they
    run in order; weight versions only ever move forward)."""
    from ray_tpu.serve.llm import build_llm_app

    app = build_llm_app(
        model="gpt2",
        engine_config={"model_config": _tiny_llm_cfg(), "block_size": 8,
                       "num_blocks": 96, "max_model_len": 64,
                       "max_batch_size": 8, "prefill_chunk_size": 8},
        num_replicas=2, max_ongoing_requests=16)
    handle = serve.run(app, name="llm-heal")
    yield handle
    serve.delete("llm-heal")


def _tiny_llm_params(seed: int = 0):
    import jax

    from ray_tpu.serve.llm.runner import adapters

    return adapters()["gpt2"].init_fn(jax.random.PRNGKey(seed),
                                      _tiny_llm_cfg())


# ---------------------------------------------------------------------------
# generic apps: heal loop, failover, backoff/cap, affinity, idle handles
# ---------------------------------------------------------------------------

def test_health_loop_replaces_killed_replica(cluster):
    """Kill → DEAD detection → routing-set removal → replacement, with
    the lifecycle history visible through serve_status()."""

    @serve.deployment(num_replicas=2, health_check_period_s=0.3)
    class Echo:
        def __call__(self, x):
            return ("ok", x)

    h = serve.run(Echo.bind(), name="heal")
    try:
        assert ray_tpu.get(h.remote(1), timeout=60) == ("ok", 1)
        ident = chaos.kill_replica("heal")
        # requests keep succeeding through the whole heal window
        for i in range(5):
            assert ray_tpu.get(h.remote(i), timeout=60) == ("ok", i)
            time.sleep(0.2)
        hl = _wait_healed("heal", target=2)
        assert not hl["degraded"], hl
        assert ident not in {r["ident"] for r in hl["replicas"]}, \
            "dead replica still in the routing set"
        events = [e["event"] for e in hl["lifecycle"]]
        assert "dead" in events and "replaced" in events, hl["lifecycle"]
        dead = [e for e in hl["lifecycle"] if e["event"] == "dead"][0]
        assert dead["replica"] == ident and dead["detail"]  # reason kept
        # the state-API face debug-dump persists shows the same thing
        from ray_tpu.util.state import serve_status

        st = serve_status()
        assert st["health"]["heal"]["restarts"] >= 1
        # probe/restart metrics reached the controller's /metrics page
        from ray_tpu.util.state import cluster_metrics

        text = cluster_metrics()
        assert "serve_replica_restarts_total" in text
        assert 'serve_replica_health_checks_total{app="heal"' in text
    finally:
        serve.delete("heal")


def test_unary_failover_single_replica_rides_out_heal(cluster):
    """ActorDiedError on a unary call is transparent: the relay retries
    with backoff until the replacement takes traffic — even when the
    dead replica was the ONLY one."""

    @serve.deployment(num_replicas=1, health_check_period_s=0.3)
    class Solo:
        def __call__(self, x):
            return x * 3

    h = serve.run(Solo.bind(), name="solo")
    try:
        assert ray_tpu.get(h.remote(2), timeout=60) == 6
        before = _failovers("solo")
        chaos.kill_replica("solo")
        # submitted into the outage window: must converge, not error
        assert ray_tpu.get(h.remote(5), timeout=120) == 15
        assert _failovers("solo") > before
        _wait_healed("solo", target=1)
    finally:
        serve.delete("solo")


def test_restart_backoff_cap_no_hot_loop(cluster, tmp_path):
    """A replica that crashes in __init__ repeatedly burns its
    max_replica_restarts budget and stops — degraded, not hot-looping."""
    sentinel = str(tmp_path / "crash-on-init")

    @serve.deployment(num_replicas=1, health_check_period_s=0.3,
                      max_replica_restarts=2)
    class Crashy:
        def __init__(self, path):
            if os.path.exists(path):
                raise RuntimeError("flagged to crash in __init__")
            self.path = path

        def __call__(self, x):
            return x

    h = serve.run(Crashy.bind(sentinel), name="crashy")
    try:
        assert ray_tpu.get(h.remote(7), timeout=60) == 7
        with open(sentinel, "w") as f:
            f.write("boom")
        chaos.kill_replica("crashy")
        deadline = time.monotonic() + 90
        hl = {}
        while time.monotonic() < deadline:
            hl = serve.status()["health"].get("crashy", {})
            if hl.get("degraded_reason"):
                break
            time.sleep(0.3)
        assert hl.get("degraded_reason"), hl
        assert "max_replica_restarts" in hl["degraded_reason"]
        assert hl["restart_attempts"] == 2  # the cap, exactly
        assert hl["healthy"] == 0 and hl["replacing"] == 0
        events = [e["event"] for e in hl["lifecycle"]]
        assert events.count("restart_failed") == 2
        assert "restart_cap" in events
        # no hot loop: attempts do not grow once the cap is hit
        time.sleep(2.0)
        hl2 = serve.status()["health"]["crashy"]
        assert hl2["restart_attempts"] == 2
        assert [e["event"] for e in hl2["lifecycle"]].count(
            "restart_failed") == 2
        # the app still exists (never flaps to deletion); an explicit
        # redeploy recovers it
        os.unlink(sentinel)
        h2 = serve.run(Crashy.bind(sentinel), name="crashy")
        assert ray_tpu.get(h2.remote(9), timeout=60) == 9
    finally:
        serve.delete("crashy")


def test_affinity_falls_back_when_primary_dies(cluster):
    """Rendezvous routing re-ranks over the LIVE set: when a key's
    chosen replica dies, the key deterministically lands on the
    next-ranked survivor instead of erroring."""
    import hashlib

    from ray_tpu.serve.api import _replica_ident

    @serve.deployment(num_replicas=2, health_check_period_s=0.3)
    class Aff:
        def __init__(self):
            self.pid = os.getpid()

        def __call__(self, x):
            return self.pid

    h = serve.run(Aff.bind(), name="aff")
    try:
        replicas = chaos.list_replicas("aff")

        def score(key, r):
            return hashlib.blake2b(
                f"{key}:{_replica_ident(r)}".encode(),
                digest_size=8).digest()

        # a key whose rendezvous primary is replica 0
        key = next(f"k{i}" for i in range(64)
                   if max(replicas, key=lambda r: score(f"k{i}", r))
                   is replicas[0])
        pid_primary = ray_tpu.get(
            h.options(affinity_key=key).remote(0), timeout=60)
        chaos.kill_replica("aff", index=0)
        # routed during/after the outage: must land on the survivor
        pid_after = ray_tpu.get(
            h.options(affinity_key=key).remote(1), timeout=120)
        assert pid_after != pid_primary
        _wait_healed("aff", target=2)
    finally:
        serve.delete("aff")


def test_idle_handle_converges_after_heal(cluster):
    """A handle created before the kill and next used after the heal
    routes straight to the replacement — no submit to the dead
    replica's stub first. The push-refresh usually converges idle
    handles in <100ms, but pushes are best-effort oneways; the HARD
    bound is the anti-entropy window (_REFRESH_S): past it, the next
    call refreshes synchronously before picking, so this assertion is
    deterministic even if every push was lost."""

    @serve.deployment(num_replicas=2, health_check_period_s=0.3)
    class Idle:
        def __call__(self, x):
            return x + 1

    h = serve.run(Idle.bind(), name="idle")
    try:
        assert ray_tpu.get(h.remote(0), timeout=60) == 1  # primed
        chaos.kill_replica("idle")
        _wait_healed("idle", target=2)
        time.sleep(serve.api.DeploymentHandle._REFRESH_S + 0.5)
        before = _failovers("idle")
        for i in range(3):
            assert ray_tpu.get(h.remote(i), timeout=60) == i + 1
        assert _failovers("idle") == before, \
            "post-heal call still hit the dead replica's stub"
    finally:
        serve.delete("idle")


# ---------------------------------------------------------------------------
# the chaos gate: LLM streams survive a mid-generation replica kill
# ---------------------------------------------------------------------------

N_STREAMS, N_TOK = 8, 40


def _llm_prompts():
    rng = np.random.RandomState(5)
    return [rng.randint(1, 64, size=6 + i).tolist()
            for i in range(N_STREAMS)]


def _run_streams(handle, prompts, on_second_event=None):
    """Consume N_STREAMS concurrently. With `on_second_event`, every
    consumer parks after its 2nd event until the hook has run — so the
    hook (the kill) fires while every stream is provably in flight
    (no final event delivered anywhere), regardless of box speed."""
    sh = handle.options(stream=True, generator_backpressure=8)
    results = [None] * len(prompts)
    errors: list = []
    barrier = (threading.Barrier(len(prompts) + 1, timeout=180)
               if on_second_event else None)
    resume = threading.Event()
    if on_second_event is None:
        resume.set()

    def consume(i, gen):
        try:
            evs = []
            for r in gen:
                evs.append(ray_tpu.get(r, timeout=180))
                if barrier is not None and len(evs) == 2:
                    barrier.wait()
                    resume.wait(timeout=180)
            results[i] = evs
        except Exception as e:  # noqa: BLE001
            errors.append((i, repr(e)))

    gens = [sh.remote({"prompt": p, "max_tokens": N_TOK})
            for p in prompts]
    threads = [threading.Thread(target=consume, args=(i, g))
               for i, g in enumerate(gens)]
    for t in threads:
        t.start()
    if barrier is not None:
        barrier.wait()  # every stream has exactly 2 delivered events
        on_second_event()
        resume.set()
    for t in threads:
        t.join(timeout=300)
    return results, errors


def test_llm_kill_mid_stream_bit_identical_and_catchup(llm_app):
    """THE gate: 8 concurrent greedy streams, one replica killed
    mid-generation → zero failed requests, outputs bit-identical to the
    unkilled run, final events carry failover counts, and the
    replacement reports the fleet's current weight version before
    taking traffic."""
    prompts = _llm_prompts()

    # reference run (no chaos): both replicas share one weight seed, so
    # greedy outputs are replica-independent
    ref, errors = _run_streams(llm_app, prompts)
    assert not errors, errors
    want = [evs[-1]["token_ids"] for evs in ref]
    assert all(len(w) == N_TOK for w in want)

    # bump the fleet to weight version 1 (same values: outputs stay
    # comparable; the VERSION is what catch-up must preserve)
    out = llm_app.update_weights(1, _tiny_llm_params(0))
    assert {o.get("version") for o in out} == {1}

    killed = []
    results, errors = _run_streams(
        llm_app, prompts,
        on_second_event=lambda: killed.append(
            chaos.kill_replica("llm-heal", busiest=True)))
    assert not errors, f"client-visible failures: {errors}"
    failovers = 0
    for i, evs in enumerate(results):
        assert evs is not None, f"stream {i} never finished"
        final = evs[-1]
        toks = evs[:-1]
        # one seamless index sequence across the failover
        assert [e["index"] for e in toks] == list(range(len(toks)))
        assert [e["token"] for e in toks] == final["token_ids"]
        assert final["token_ids"] == want[i], \
            f"stream {i} diverged after failover"
        failovers += final.get("failovers", 0)
    assert failovers >= 1, "the kill never landed on an active stream"
    assert _failovers("llm-heal") >= failovers

    # the replacement entered the routing set at the current version
    hl = _wait_healed("llm-heal", target=2)
    assert hl["weight_version"] == 1
    assert killed and killed[0] not in \
        {r["ident"] for r in hl["replicas"]}
    from ray_tpu.util.state import llm_status

    stats = llm_status("llm-heal")
    assert [s["weight_version"] for s in stats] == [1, 1], stats


def test_llm_update_weights_during_replacement_window(llm_app):
    """An update_weights broadcast issued while the replacement is
    still warming is NOT lost: the controller records it and replays it
    before the replacement enters the routing set."""
    from ray_tpu.util.state import llm_status

    restarts0 = serve.status()["health"]["llm-heal"]["restarts"]
    chaos.kill_replica("llm-heal")
    time.sleep(0.1)  # inside the replacement window
    out = llm_app.update_weights(2, _tiny_llm_params(0))
    # the broadcast covers whatever the routing set held; the heal path
    # owns delivery to the replacement
    assert any(o.get("version") == 2 and "error" not in o or
               o.get("already_installed") for o in out) or out == []
    _wait_healed("llm-heal", target=2, min_restarts=restarts0 + 1)
    stats = llm_status("llm-heal")
    assert [s["weight_version"] for s in stats] == [2, 2], stats
    assert serve.status()["health"]["llm-heal"]["weight_version"] == 2


def test_rl_rollout_survives_replica_kill(llm_app):
    """The RL flywheel's rollout lap rides the same failover: kill an
    engine replica mid-rollout, every trajectory group completes and
    gets scored."""
    from ray_tpu.rllib.llm.rollout import RolloutConfig, RolloutWorker

    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 64, size=8).tolist() for _ in range(4)]
    worker = RolloutWorker(
        handle=llm_app,
        reward_fn=lambda p, toks: float(len(toks)) / 32.0,
        config=RolloutConfig(group_size=2, max_tokens=24,
                             temperature=1.0))
    restarts0 = serve.status()["health"]["llm-heal"]["restarts"]
    # fires unconditionally: even a too-fast rollout leaves a kill for
    # _wait_healed to account for (no cancel — the heal must happen)
    killer = threading.Timer(
        0.3, lambda: chaos.kill_replica("llm-heal", busiest=True))
    killer.start()
    trajs = worker.rollout(prompts)
    killer.join(timeout=60)
    assert len(trajs) == 8
    assert all(len(t.tokens) > 0 and t.reward > 0 for t in trajs)
    _wait_healed("llm-heal", target=2, min_restarts=restarts0 + 1)
