"""Actor concurrency groups + async actors (VERDICT r2 item 9).

Reference parity: ConcurrencyGroupManager
(src/ray/core_worker/transport/concurrency_group_manager.h:34 — named
groups with independent executor pools) and out-of-order async-actor
execution (out_of_order_actor_scheduling_queue.h).
"""

import sys
import time

import cloudpickle
import pytest

import ray_tpu

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def ray_boot():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_slow_group_does_not_block_fast_group(ray_boot):
    @ray_tpu.remote(concurrency_groups={"io": 1, "compute": 1})
    class Worker:
        @ray_tpu.method(concurrency_group="io")
        def slow(self):
            time.sleep(3.0)
            return "slow"

        @ray_tpu.method(concurrency_group="compute")
        def fast(self):
            return "fast"

    w = Worker.remote()
    slow_ref = w.slow.remote()
    t0 = time.monotonic()
    assert ray_tpu.get(w.fast.remote(), timeout=30) == "fast"
    fast_latency = time.monotonic() - t0
    assert fast_latency < 1.5, \
        f"fast group stuck behind slow group ({fast_latency:.1f}s)"
    assert ray_tpu.get(slow_ref, timeout=30) == "slow"
    ray_tpu.kill(w)


def test_ordering_within_group(ray_boot):
    @ray_tpu.remote(concurrency_groups={"serial": 1})
    class Seq:
        def __init__(self):
            self.log = []

        @ray_tpu.method(concurrency_group="serial")
        def mark(self, i):
            self.log.append(i)
            return i

        @ray_tpu.method(concurrency_group="serial")
        def read(self):
            return list(self.log)

    s = Seq.remote()
    refs = [s.mark.remote(i) for i in range(20)]
    ray_tpu.get(refs, timeout=60)
    assert ray_tpu.get(s.read.remote(), timeout=30) == list(range(20))
    ray_tpu.kill(s)


def test_per_call_group_override(ray_boot):
    @ray_tpu.remote(concurrency_groups={"g1": 1})
    class A:
        def where(self):
            import threading

            return threading.current_thread().name

    a = A.remote()
    default_thread = ray_tpu.get(a.where.remote(), timeout=30)
    g1_thread = ray_tpu.get(
        a.where.options(concurrency_group="g1").remote(), timeout=30)
    assert "_default" in default_thread
    assert "g1" in g1_thread
    ray_tpu.kill(a)


def test_async_actor_out_of_order_completion(ray_boot):
    """An async method awaiting a long sleep must not block later short
    calls — completions land out of submission order."""

    @ray_tpu.remote
    class AsyncActor:
        async def wait_for(self, delay, tag):
            import asyncio

            await asyncio.sleep(delay)
            return tag

    a = AsyncActor.remote()
    slow = a.wait_for.remote(3.0, "slow")
    fast = a.wait_for.remote(0.05, "fast")
    t0 = time.monotonic()
    assert ray_tpu.get(fast, timeout=30) == "fast"
    assert time.monotonic() - t0 < 1.5, "async method blocked the actor"
    assert ray_tpu.get(slow, timeout=30) == "slow"
    ray_tpu.kill(a)


def test_async_actor_error_propagates(ray_boot):
    @ray_tpu.remote
    class Bad:
        async def boom(self):
            raise ValueError("async boom")

    b = Bad.remote()
    with pytest.raises(ray_tpu.core.exceptions.TaskError):
        ray_tpu.get(b.boom.remote(), timeout=30)
