"""Object store + serialization tests.

Reference model: plasma store tests exercise create/seal/get/evict on a
local segment without any cluster (src/ray/object_manager/plasma/).
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from ray_tpu.core import serialization as ser
from ray_tpu.core.object_store import (
    ObjectStoreFullError,
    SharedMemoryStore,
    native_lib,
    open_store,
)

needs_native = pytest.mark.skipif(native_lib() is None, reason="no g++ toolchain")


@pytest.fixture
def store():
    s = open_store(capacity=32 * 1024 * 1024)
    yield s
    s.close()
    s.unlink()


def test_put_get_roundtrip(store):
    oid = os.urandom(16)
    store.put(oid, b"payload")
    v = store.get(oid)
    assert bytes(v) == b"payload"
    del v
    store.release(oid)


def test_get_absent_returns_none(store):
    assert store.get(os.urandom(16)) is None
    assert not store.contains(os.urandom(16))


def test_create_seal_visibility(store):
    oid = os.urandom(16)
    buf = store.create(oid, 4)
    # unsealed objects are not gettable (plasma semantics)
    assert store.get(oid) is None
    buf[:] = b"abcd"
    del buf
    store.seal(oid)
    v = store.get(oid)
    assert bytes(v) == b"abcd"
    del v


def test_duplicate_create_raises(store):
    oid = os.urandom(16)
    store.put(oid, b"x")
    with pytest.raises(KeyError):
        store.create(oid, 1)


@needs_native
def test_eviction_under_pressure():
    s = SharedMemoryStore(capacity=8 * 1024 * 1024)
    try:
        ids = []
        for _ in range(40):
            oid = os.urandom(16)
            s.put(oid, bytes(1024 * 1024))
            ids.append(oid)
        st = s.stats()
        assert st["evictions"] > 0
        # newest objects survive (LRU evicts oldest)
        assert s.contains(ids[-1])
        assert not s.contains(ids[0])
    finally:
        s.close()
        s.unlink()


@needs_native
def test_referenced_objects_not_evicted():
    s = SharedMemoryStore(capacity=8 * 1024 * 1024)
    try:
        pinned = os.urandom(16)
        s.put(pinned, bytes(1024 * 1024))
        v = s.get(pinned)  # hold a ref
        for _ in range(40):
            s.put(os.urandom(16), bytes(1024 * 1024))
        assert s.contains(pinned)
        assert bytes(v[:1]) == b"\x00"
        del v
        s.release(pinned)
    finally:
        s.close()
        s.unlink()


@needs_native
def test_oversize_object_raises():
    s = SharedMemoryStore(capacity=4 * 1024 * 1024)
    try:
        with pytest.raises(ObjectStoreFullError):
            s.put(os.urandom(16), bytes(32 * 1024 * 1024))
    finally:
        s.close()
        s.unlink()


def _child_read(store_name: str, oid: bytes, q):
    from ray_tpu.core.object_store import open_store

    s = open_store(name=store_name, create=False)
    v = s.get(oid)
    q.put(bytes(v) if v is not None else None)
    del v
    s.release(oid)
    s.close()


@needs_native
def test_cross_process_get():
    s = SharedMemoryStore(capacity=8 * 1024 * 1024)
    try:
        oid = os.urandom(16)
        s.put(oid, b"cross-process")
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_read, args=(s.name, oid, q))
        p.start()
        assert q.get(timeout=30) == b"cross-process"
        p.join(timeout=10)
    finally:
        s.close()
        s.unlink()


# ---------------------------------------------------------------- serde


def test_serialize_numpy_zero_copy(store):
    arr = np.arange(1 << 18, dtype=np.float32)
    head, views, total = ser.serialize({"x": arr})
    oid = os.urandom(16)
    buf = store.create(oid, total)
    ser.write_into(buf, head, views)
    del buf
    store.seal(oid)
    out = ser.deserialize(store.get(oid))
    assert np.array_equal(out["x"], arr)


def test_dumps_loads_plain():
    for obj in [1, "s", [1, 2], {"k": (3, 4)}, None, b"bytes"]:
        assert ser.loads(ser.dumps(obj)) == obj


def test_serialize_jax_array():
    import jax.numpy as jnp

    x = jnp.arange(128, dtype=jnp.float32)
    out = ser.loads(ser.dumps({"x": x}))
    assert np.array_equal(np.asarray(out["x"]), np.asarray(x))
