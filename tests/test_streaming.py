"""Streaming generators (num_returns="streaming" / ObjectRefStream).

Reference model: python/ray/tests/test_streaming_generator.py —
consume-as-produced semantics, backpressure, early termination GC,
producer death mid-stream, borrower iteration from another process.
"""

import sys
import tempfile
import time
import os

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------- local mode


def test_local_stream_basic():
    ray_tpu.init(local_mode=True)
    try:
        @ray_tpu.remote
        def gen(n):
            for i in range(n):
                yield i * 10

        out = [ray_tpu.get(ref) for ref in
               gen.options(num_returns="streaming").remote(5)]
        assert out == [0, 10, 20, 30, 40]
    finally:
        ray_tpu.shutdown()


def test_local_stream_error_and_consume_as_produced():
    ray_tpu.init(local_mode=True)
    try:
        @ray_tpu.remote
        def gen():
            yield 1
            yield 2
            raise ValueError("boom")

        g = gen.options(num_returns="streaming").remote()
        assert ray_tpu.get(next(g)) == 1
        assert ray_tpu.get(next(g)) == 2
        with pytest.raises(Exception, match="boom"):
            next(g)

        # consume-as-produced: first item arrives before the producer ends
        @ray_tpu.remote
        def slow():
            yield "fast"
            time.sleep(5)
            yield "slow"

        g2 = slow.options(num_returns="streaming").remote()
        t0 = time.monotonic()
        assert ray_tpu.get(next(g2)) == "fast"
        assert time.monotonic() - t0 < 3.0
        g2.close()
    finally:
        ray_tpu.shutdown()


def test_local_stream_actor_and_close():
    ray_tpu.init(local_mode=True)
    try:
        @ray_tpu.remote
        class Counter:
            def stream(self, n):
                for i in range(n):
                    yield i

        c = Counter.remote()
        g = c.stream.options(num_returns="streaming").remote(3)
        assert [ray_tpu.get(r) for r in g] == [0, 1, 2]

        # early close stops the producer promptly (backpressure-bounded)
        produced = []

        @ray_tpu.remote
        def endless():
            i = 0
            while True:
                produced.append(i)
                yield i
                i += 1

        g2 = endless.options(
            num_returns="streaming",
            generator_backpressure_num_objects=4).remote()
        assert ray_tpu.get(next(g2)) == 0
        g2.close()
        time.sleep(0.5)
        n_after_close = len(produced)
        time.sleep(0.5)
        assert len(produced) == n_after_close  # producer stopped
        assert n_after_close <= 8
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=4)
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_stream_task_basic(cluster):
    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield {"i": i}

    g = gen.options(num_returns="streaming").remote(7)
    out = [ray_tpu.get(ref)["i"] for ref in g]
    assert out == list(range(7))


def test_stream_consume_before_producer_done(cluster):
    @ray_tpu.remote
    def slow_gen():
        yield "first"
        time.sleep(8)
        yield "second"

    g = slow_gen.options(num_returns="streaming").remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(g))
    dt = time.monotonic() - t0
    assert first == "first"
    assert dt < 5.0, f"first item took {dt:.1f}s — not streamed"
    assert ray_tpu.get(next(g)) == "second"
    with pytest.raises(StopIteration):
        next(g)


def test_stream_large_items_via_store(cluster):
    import numpy as np

    @ray_tpu.remote
    def blocks(n):
        for i in range(n):
            yield np.full((1 << 16,), i, dtype=np.float32)  # 256 KiB

    g = blocks.options(num_returns="streaming").remote(4)
    for i, ref in enumerate(g):
        arr = ray_tpu.get(ref)
        assert arr.shape == (1 << 16,)
        assert arr[0] == i


def test_stream_actor_method(cluster):
    @ray_tpu.remote
    class Producer:
        def chunks(self, n):
            for i in range(n):
                yield f"chunk-{i}"

    p = Producer.remote()
    g = p.chunks.options(num_returns="streaming").remote(5)
    assert [ray_tpu.get(r) for r in g] == [f"chunk-{i}" for i in range(5)]


def test_stream_borrower_iterates(cluster):
    """A generator handle passed to another process: the consumer task
    iterates items as the producer yields them (owner = driver)."""

    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i * i

    @ray_tpu.remote
    def consume(g):
        return [ray_tpu.get(r) for r in g]

    g = gen.options(num_returns="streaming").remote(6)
    assert ray_tpu.get(consume.remote(g)) == [i * i for i in range(6)]


def test_stream_producer_death_mid_stream(cluster):
    """Producer actor dies mid-stream: already-consumed items stay
    valid; iteration past the last delivered item raises."""

    @ray_tpu.remote(max_restarts=0)
    class Doomed:
        def stream(self):
            yield 1
            yield 2
            time.sleep(0.3)  # let the item oneways flush before dying
            os._exit(1)

    d = Doomed.remote()
    g = d.stream.options(num_returns="streaming").remote()
    assert ray_tpu.get(next(g)) == 1
    assert ray_tpu.get(next(g)) == 2
    with pytest.raises(Exception):
        # either the death error, or StopIteration converted by iteration
        for _ in range(10):
            ray_tpu.get(next(g))


def test_stream_backpressure(cluster):
    """Producer must stall once produced-consumed hits the cap."""
    progress = tempfile.mktemp()

    @ray_tpu.remote
    def gen(path, n):
        for i in range(n):
            with open(path, "w") as f:
                f.write(str(i + 1))
            yield i

    g = gen.options(
        num_returns="streaming",
        generator_backpressure_num_objects=3).remote(progress, 50)
    first = ray_tpu.get(next(g))
    assert first == 0
    time.sleep(2.0)  # producer would finish all 50 in ms without BP
    with open(progress) as f:
        produced = int(f.read())
    assert produced <= 10, f"produced {produced} with backpressure=3"
    # drain; producer unblocks as we consume
    rest = [ray_tpu.get(r) for r in g]
    assert rest == list(range(1, 50))


def test_stream_early_close_cancels_producer(cluster):
    progress = tempfile.mktemp()

    @ray_tpu.remote
    def endless(path):
        i = 0
        while True:
            with open(path, "w") as f:
                f.write(str(i))
            yield i
            i += 1
            time.sleep(0.01)

    g = endless.options(num_returns="streaming").remote(progress)
    assert ray_tpu.get(next(g)) == 0
    g.close()
    time.sleep(1.5)  # close propagates via the sweeper + cancel oneway
    with open(progress) as f:
        at_close = int(f.read())
    time.sleep(1.0)
    with open(progress) as f:
        later = int(f.read())
    assert later - at_close <= 5, "producer kept running after close"


def test_data_streaming_read_consumes_as_produced(cluster):
    """read_datasource(streaming=True): iter_batches yields rows from
    block 0 while the producer is still sleeping before block 1."""
    from ray_tpu.data import Datasource, read_datasource

    class SlowSource(Datasource):
        def get_block_streams(self, parallelism):
            def gen():
                yield list(range(100))
                time.sleep(6)
                yield list(range(100, 200))

            return [gen]

    ds = read_datasource(SlowSource(), streaming=True)
    it = ds.iter_batches(batch_size=50, batch_format=None)
    t0 = time.monotonic()
    first = next(it)
    dt = time.monotonic() - t0
    assert first == list(range(50))
    assert dt < 4.0, f"first batch took {dt:.1f}s — read not streamed"
    rest = [row for b in it for row in b]
    assert rest == list(range(50, 200))


def test_data_streaming_read_files(cluster, tmp_path):
    """Grouped file read with streaming=True produces one block per
    file and survives transforms."""
    from ray_tpu.data import read_text

    for i in range(4):
        (tmp_path / f"f{i}.txt").write_text(
            "\n".join(f"l{i}-{j}" for j in range(10)) + "\n")
    ds = read_text(str(tmp_path), parallelism=2, streaming=True)
    rows = ds.map(lambda s: s.upper()).take_all()
    assert len(rows) == 40
    assert sorted(rows)[0] == "L0-0"


def test_stream_retry_on_worker_crash(cluster):
    """Streaming task whose worker dies is retried; the replayed items
    dedup at the owner and iteration completes."""
    marker = tempfile.mktemp()

    @ray_tpu.remote(max_retries=2, retry_exceptions=True)
    def flaky(n, path):
        first = not os.path.exists(path)
        if first:
            with open(path, "w") as f:
                f.write("x")
        for i in range(n):
            if first and i == 2:
                raise RuntimeError("synthetic mid-stream crash")
            yield i

    g = flaky.options(num_returns="streaming").remote(5, marker)
    out = [ray_tpu.get(r) for r in g]
    assert out == list(range(5))
