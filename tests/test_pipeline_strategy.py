"""1F1B pipeline-parallel train strategy (train/pipeline_strategy.py).

Schedule math is gated exactly (the per-stage fwd/bwd interleave and
the simulated bubble == (S-1)/(S-1+M)); the distributed strategy is
gated on loss parity against the single-program pipelined model and on
the bubble/microbatch metrics surfacing."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.parallel.pipeline import (
    interleaved_1f1b_submission_order,
    one_f_one_b_schedule,
    one_f_one_b_submission_order,
    simulate_1f1b,
    simulate_interleaved_1f1b,
    theoretical_bubble,
    theoretical_bubble_interleaved,
)


# ------------------------------------------------------------- schedule


def test_1f1b_exact_interleave_2x4():
    assert one_f_one_b_schedule(2, 4) == [
        [("fwd", 0), ("fwd", 1), ("bwd", 0), ("fwd", 2), ("bwd", 1),
         ("fwd", 3), ("bwd", 2), ("bwd", 3)],
        [("fwd", 0), ("bwd", 0), ("fwd", 1), ("bwd", 1), ("fwd", 2),
         ("bwd", 2), ("fwd", 3), ("bwd", 3)],
    ]


def test_1f1b_exact_interleave_4x4_warmup_depths():
    sched = one_f_one_b_schedule(4, 4)
    # stage s runs S-1-s warmup forwards (plus the first steady-state
    # forward) before its first backward
    for s, ops in enumerate(sched):
        warm = [k for k, _ in ops[:ops.index(("bwd", 0))]]
        assert warm == ["fwd"] * (4 - s), (s, ops)
        # steady state is strictly one-forward-one-backward
        kinds = [k for k, _ in ops]
        assert kinds.count("fwd") == kinds.count("bwd") == 4
    # last stage never waits: F0 B0 F1 B1 ...
    assert sched[3] == [("fwd", 0), ("bwd", 0), ("fwd", 1), ("bwd", 1),
                        ("fwd", 2), ("bwd", 2), ("fwd", 3), ("bwd", 3)]


@pytest.mark.parametrize("S,M", [(1, 1), (1, 4), (2, 4), (3, 5),
                                 (4, 8), (4, 2), (5, 3)])
def test_1f1b_schedule_complete_and_memory_bounded(S, M):
    sched = one_f_one_b_schedule(S, M)
    for s, ops in enumerate(sched):
        assert sorted(ops) == sorted(
            [("fwd", m) for m in range(M)] + [("bwd", m)
                                             for m in range(M)])
        # 1F1B memory bound: at most min(M, S-s) forwards outstanding
        live = peak = 0
        for kind, _ in ops:
            live += 1 if kind == "fwd" else -1
            peak = max(peak, live)
        assert peak <= min(M, S - s), (s, peak, ops)


@pytest.mark.parametrize("S,M", [(1, 2), (2, 4), (3, 5), (4, 8), (4, 2)])
def test_1f1b_submission_order_topological(S, M):
    order = one_f_one_b_submission_order(S, M)
    assert len(order) == 2 * S * M
    seen = set()
    per_stage = {s: [] for s in range(S)}
    for kind, s, m in order:
        if kind == "fwd" and s > 0:
            assert ("fwd", s - 1, m) in seen
        if kind == "bwd":
            assert ("fwd", s, m) in seen
            if s < S - 1:
                assert ("bwd", s + 1, m) in seen
        seen.add((kind, s, m))
        per_stage[s].append((kind, m))
    # per-stage projection IS the 1F1B interleave
    assert [per_stage[s] for s in range(S)] == one_f_one_b_schedule(S, M)


@pytest.mark.parametrize("S,M", [(2, 4), (3, 6), (4, 8), (4, 4), (2, 1)])
def test_simulated_bubble_matches_theoretical(S, M):
    sim = simulate_1f1b(S, M)
    assert sim["bubble_ratio"] == pytest.approx(
        theoretical_bubble(S, M), abs=1e-9)
    # unequal op costs still fill: bubble stays below the equal-cost
    # GPipe worst case of (S-1)/M utilization loss at these shapes
    assert 0.0 <= simulate_1f1b(S, M, 1.0, 2.0)["bubble_ratio"] < 1.0


# ------------------------------------------- interleaved schedule math


@pytest.mark.parametrize("S,M,R", [(2, 4, 2), (2, 2, 3), (3, 6, 2),
                                   (4, 8, 2), (2, 8, 4)])
def test_interleaved_submission_complete_and_topological(S, M, R):
    """Every (kind, virtual_stage, microbatch) appears once, and each
    op's dependencies precede it — FIFO workers realize the schedule."""
    order = interleaved_1f1b_submission_order(S, M, R)
    V = S * R
    assert len(order) == 2 * V * M
    assert sorted(order) == sorted(
        [("fwd", v, m) for v in range(V) for m in range(M)]
        + [("bwd", v, m) for v in range(V) for m in range(M)])
    seen = set()
    for kind, v, m in order:
        if kind == "fwd" and v > 0:
            assert ("fwd", v - 1, m) in seen, (kind, v, m)
        if kind == "bwd":
            assert ("fwd", v, m) in seen, (kind, v, m)
            if v < V - 1:
                assert ("bwd", v + 1, m) in seen, (kind, v, m)
        seen.add((kind, v, m))


def test_interleaved_submission_rejects_m_below_s():
    with pytest.raises(ValueError):
        interleaved_1f1b_submission_order(4, 3, 2)
    with pytest.raises(ValueError):
        interleaved_1f1b_submission_order(2, 4, 0)


@pytest.mark.parametrize("S,M,R", [(2, 4, 2), (2, 4, 3), (3, 6, 2),
                                   (4, 8, 2), (4, 4, 4)])
def test_interleaved_sim_matches_theory_and_beats_flat(S, M, R):
    """The discrete-event interleaved makespan reproduces the
    (S-1)/(R*M+S-1) floor exactly, strictly below flat 1F1B's
    (S-1)/(M+S-1) at equal S and M — the whole point of V virtual
    stages per worker."""
    sim = simulate_interleaved_1f1b(S, M, R)
    assert sim["bubble_ratio"] == pytest.approx(
        theoretical_bubble_interleaved(S, M, R), abs=1e-9)
    flat = simulate_1f1b(S, M)["bubble_ratio"]
    assert sim["bubble_ratio"] < flat, (sim, flat)
    # R=1 degrades to the flat schedule
    assert simulate_interleaved_1f1b(S, M, 1)["bubble_ratio"] == \
        pytest.approx(flat, abs=1e-9)


# ------------------------------------------------------- cluster parity


@pytest.fixture(scope="module")
def cluster():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _toy_batch(cfg, B, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "tokens": rs.randint(0, cfg.vocab_size,
                             (B, cfg.block_size)).astype(np.int32),
        "targets": rs.randint(0, cfg.vocab_size,
                              (B, cfg.block_size)).astype(np.int32),
    }


def test_pipeline_strategy_matches_single_program(cluster):
    """2 stage workers x 4 microbatches vs pipelined_train_step on a
    one-device mesh: same init, same lr, 3 SGD steps — losses and the
    merged params must track."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ray_tpu.models.pipelined import (
        PipelinedConfig,
        init_pipelined,
        pipelined_train_step,
    )
    from ray_tpu.train.pipeline_strategy import PipelineStrategy

    cfg = PipelinedConfig()
    batch = _toy_batch(cfg, B=8)
    params = init_pipelined(jax.random.PRNGKey(0), cfg)
    ref_mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                    ("pipe", "fsdp"))
    ref_step = pipelined_train_step(cfg, ref_mesh, lr=1e-2)
    jb = {k: jnp.asarray(v) for k, v in batch.items()}
    ref_params, ref_losses = params, []
    for _ in range(3):
        ref_params, loss = ref_step(ref_params, jb)
        ref_losses.append(float(loss))

    ps = PipelineStrategy(cfg, num_stages=2, num_microbatches=4,
                          lr=1e-2, seed=0)
    try:
        metrics = [ps.train_step(batch) for _ in range(3)]
        pipe_losses = [m["loss"] for m in metrics]
        np.testing.assert_allclose(ref_losses, pipe_losses, atol=1e-5)
        assert pipe_losses[0] > pipe_losses[-1]  # it trains
        merged = ps.full_params()
        for a, b in zip(jax.tree.leaves(ref_params),
                        jax.tree.leaves(merged)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)
        for m in metrics:
            assert 0.0 <= m["bubble_ratio"] < 1.0
            assert m["bubble_theoretical"] == pytest.approx(
                theoretical_bubble(2, 4))
            assert m["microbatches"] == 4
    finally:
        ps.shutdown()


def test_pipeline_metrics_surface(cluster):
    """bubble gauge + microbatch counter reach the metric registry."""
    from ray_tpu.models.pipelined import PipelinedConfig
    from ray_tpu.train.pipeline_strategy import (
        PipelineStrategy,
        _strategy_metrics,
    )

    cfg = PipelinedConfig(n_virtual_stages=2, d_model=32, d_ff=64,
                          block_size=16)
    ps = PipelineStrategy(cfg, num_stages=2, num_microbatches=2,
                          lr=1e-2)
    try:
        m_bubble, m_micro, m_virtual = _strategy_metrics()
        before = m_micro._values.get((), 0.0)
        out = ps.train_step(_toy_batch(cfg, B=4))
        assert m_micro._values.get((), 0.0) == before + 2
        exposed = "\n".join(m_bubble.expose())
        assert "train_pipeline_bubble_ratio" in exposed
        exposed_v = "\n".join(m_virtual.expose())
        assert "train_pipeline_virtual_stages" in exposed_v
        assert m_virtual._values.get((), 0.0) == 2.0  # flat: V == S
        assert out["loss"] > 0
    finally:
        ps.shutdown()


def test_jax_trainer_pipeline_strategy(cluster, tmp_path):
    """JaxTrainer(strategy='pipeline') drives the strategy end-to-end
    and returns a Result with per-step history."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    cfg_kwargs = dict(n_virtual_stages=2, d_model=32, d_ff=64,
                      block_size=16, num_microbatches=2)
    from ray_tpu.models.pipelined import PipelinedConfig

    batch = _toy_batch(PipelinedConfig(**cfg_kwargs), B=4)
    result = JaxTrainer(
        strategy="pipeline",
        train_loop_config={"model": cfg_kwargs, "batch": batch,
                           "steps": 2, "num_stages": 2, "lr": 1e-2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="pipe_t", storage_path=str(tmp_path)),
    ).fit()
    assert len(result.metrics_history) == 2
    assert result.metrics["loss"] > 0
    assert "bubble_ratio" in result.metrics


def test_pipeline_strategy_rejects_bad_shapes(cluster):
    from ray_tpu.models.pipelined import PipelinedConfig
    from ray_tpu.train.pipeline_strategy import PipelineStrategy

    cfg = PipelinedConfig(n_virtual_stages=2, d_model=32, d_ff=64,
                          block_size=16)
    with pytest.raises(ValueError):
        # more stages than blocks
        PipelineStrategy(cfg, num_stages=3, num_microbatches=2)
    ps = PipelineStrategy(cfg, num_stages=2, num_microbatches=3)
    try:
        with pytest.raises(ValueError):
            ps.train_step(_toy_batch(cfg, B=4))  # 4 % 3 != 0
    finally:
        ps.shutdown()


# --------------------------------------- interleaved + ZeRO composition


def _single_program_reference(cfg, batch, steps, lr=1e-2, seed=0):
    """pipelined_train_step on a 1-device mesh — the parity oracle all
    strategy configurations (flat, interleaved, composed) must match."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ray_tpu.models.pipelined import (
        init_pipelined,
        pipelined_train_step,
    )

    params = init_pipelined(jax.random.PRNGKey(seed), cfg)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                ("pipe", "fsdp"))
    step = pipelined_train_step(cfg, mesh, lr=lr)
    jb = {k: jnp.asarray(v) for k, v in batch.items()}
    losses = []
    for _ in range(steps):
        params, loss = step(params, jb)
        losses.append(float(loss))
    return params, losses


def test_interleaved_strategy_matches_single_program(cluster):
    """num_repeats=2 (V=4 virtual stages on 2 workers): the circular
    schedule must be numerically invisible — same losses and merged
    params as the single-program oracle, and the metrics surface the
    interleaved theoretical floor."""
    import jax

    from ray_tpu.models.pipelined import PipelinedConfig
    from ray_tpu.train.pipeline_strategy import PipelineStrategy

    cfg = PipelinedConfig()
    batch = _toy_batch(cfg, B=8, seed=2)
    ref_params, ref_losses = _single_program_reference(cfg, batch, 3)

    ps = PipelineStrategy(cfg, num_stages=2, num_microbatches=4,
                          lr=1e-2, seed=0, num_repeats=2)
    try:
        metrics = [ps.train_step(batch) for _ in range(3)]
        np.testing.assert_allclose(
            ref_losses, [m["loss"] for m in metrics], atol=1e-5)
        merged = ps.full_params()
        for a, b in zip(jax.tree.leaves(ref_params),
                        jax.tree.leaves(merged)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)
        for m in metrics:
            assert m["num_repeats"] == 2
            assert m["virtual_stages"] == 4
            assert m["bubble_theoretical"] == pytest.approx(
                theoretical_bubble_interleaved(2, 4, 2))
    finally:
        ps.shutdown()


def test_pipeline_zero_composition_parity_and_bytes(cluster):
    """One config, every axis: interleaved (R=2) pipeline with
    intra-stage ZeRO over data_parallel=2. Losses must still match the
    single-program oracle (ZeRO is a memory layout, not an algorithm
    change), and the per-stage resident grad/param bytes must land at
    ~1/D of the undistributed run's."""
    from ray_tpu.models.pipelined import PipelinedConfig
    from ray_tpu.train.pipeline_strategy import PipelineStrategy

    cfg = PipelinedConfig()
    batch = _toy_batch(cfg, B=8, seed=4)
    _, ref_losses = _single_program_reference(cfg, batch, 3)

    def run(zero_stage, data_parallel):
        ps = PipelineStrategy(cfg, num_stages=2, num_microbatches=4,
                              lr=1e-2, seed=0, num_repeats=2,
                              zero_stage=zero_stage,
                              data_parallel=data_parallel,
                              momentum=0.9)
        try:
            losses = [ps.train_step(batch)["loss"] for _ in range(3)]
            return losses, ps.last_stage_stats
        finally:
            ps.shutdown()

    base_losses, base_stats = run(0, 1)
    z_losses, z_stats = run(3, 2)
    # momentum=0.9 diverges from the momentum-0 oracle — compare the
    # two momentum runs to each other, and the first (pre-update) loss
    # to the oracle's
    assert z_losses[0] == pytest.approx(ref_losses[0], abs=1e-5)
    np.testing.assert_allclose(base_losses, z_losses, atol=1e-5)
    D, bound = 2, 1.25 / 2
    for b, z in zip(base_stats, z_stats):
        assert z["grad_state_bytes"] / b["grad_state_bytes"] <= bound
        assert z["param_state_bytes"] / b["param_state_bytes"] <= bound
        assert z["velocity_state_bytes"] / b["velocity_state_bytes"] \
            <= bound


def test_emulated_bubble_interleaved_below_flat(cluster):
    """The measured-bubble gate: in schedule-emulation mode (modeled op
    latency through the real submission/actor/accounting path — immune
    to single-core contention), interleaved R=2 must measure a strictly
    smaller bubble than flat at equal S and M."""
    from ray_tpu.models.pipelined import PipelinedConfig
    from ray_tpu.train.pipeline_strategy import PipelineStrategy

    cfg = PipelinedConfig(d_model=32, d_ff=64, block_size=16)
    batch = _toy_batch(cfg, B=8)

    def measure(R):
        # op times large vs dispatch overhead so a loaded CI box can't
        # blur the schedule-shape difference into the noise
        ps = PipelineStrategy(cfg, num_stages=2, num_microbatches=4,
                              lr=1e-2, seed=0, num_repeats=R,
                              emulate_ms=(60.0, 120.0))
        try:
            ps.train_step(batch)  # warm the dispatch path
            return np.mean([ps.train_step(batch)["bubble_ratio"]
                            for _ in range(3)])
        finally:
            ps.shutdown()

    flat, inter = measure(1), measure(2)
    assert inter < flat, (inter, flat)
    # both sit at/above their theoretical floors (sanity on the lane)
    assert flat > theoretical_bubble(2, 4) - 1e-6
    assert inter > theoretical_bubble_interleaved(2, 4, 2) - 1e-6


# ----------------------------------------------------------- checkpoint


def test_pipeline_checkpoint_round_trip(cluster, tmp_path):
    """save_checkpoint writes per-stage shards + manifest;
    load_pipeline_checkpoint reassembles the exact full param tree."""
    import jax

    from ray_tpu.models.pipelined import PipelinedConfig
    from ray_tpu.train.pipeline_strategy import (
        PipelineStrategy,
        load_pipeline_checkpoint,
    )

    cfg = PipelinedConfig(d_model=32, d_ff=64, block_size=16)
    ps = PipelineStrategy(cfg, num_stages=2, num_microbatches=2,
                          lr=1e-2, seed=0, num_repeats=2)
    try:
        ps.train_step(_toy_batch(cfg, B=4))
        ckpt = ps.save_checkpoint(str(tmp_path / "ck"))
        want = ps.full_params()
    finally:
        ps.shutdown()
    got, meta = load_pipeline_checkpoint(ckpt.path)
    assert meta["format"] == "pipeline-stage-shards-v1"
    assert meta["num_stages"] == 2 and meta["num_repeats"] == 2
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restore path: a fresh strategy seeded from the checkpoint params
    # continues from the same weights
    ps2 = PipelineStrategy(cfg, num_stages=2, num_microbatches=2,
                           lr=1e-2, params=got)
    try:
        for a, b in zip(jax.tree.leaves(got),
                        jax.tree.leaves(ps2.full_params())):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        ps2.shutdown()


def test_jax_trainer_pipeline_checkpoints(cluster, tmp_path):
    """JaxTrainer(strategy='pipeline') registers stage-shard
    checkpoints through CheckpointManager and hands back the latest."""
    from ray_tpu.train import (
        CheckpointConfig,
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.train.pipeline_strategy import load_pipeline_checkpoint

    cfg_kwargs = dict(n_virtual_stages=4, d_model=32, d_ff=64,
                      block_size=16, num_microbatches=2)
    from ray_tpu.models.pipelined import PipelinedConfig

    batch = _toy_batch(PipelinedConfig(**cfg_kwargs), B=4)
    result = JaxTrainer(
        strategy="pipeline",
        train_loop_config={"model": cfg_kwargs, "batch": batch,
                           "steps": 2, "num_stages": 2,
                           "num_repeats": 2, "lr": 1e-2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="pipe_ck", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(num_to_keep=1)),
    ).fit()
    assert result.checkpoint is not None
    params, meta = load_pipeline_checkpoint(result.checkpoint.path)
    assert meta["num_repeats"] == 2
    assert jax_leaf_count(params) > 0


def jax_leaf_count(tree):
    import jax

    return len(jax.tree.leaves(tree))
