"""Metrics registry, state API, CLI (reference model:
python/ray/tests/test_metrics_agent.py + util/state tests)."""

import json
import subprocess
import sys
import urllib.request

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import metrics as m
from ray_tpu.util import state


@pytest.fixture(autouse=True)
def fresh_registry():
    m.clear_registry()
    yield
    m.clear_registry()


def test_counter_gauge_exposition():
    c = m.Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    g = m.Gauge("queue_depth", "depth")
    g.set(7)
    text = m.prometheus_text()
    assert 'reqs_total{route="/a"} 1.0' in text
    assert 'reqs_total{route="/b"} 2.0' in text
    assert "queue_depth 7.0" in text
    assert "# TYPE reqs_total counter" in text


def test_histogram_buckets():
    h = m.Histogram("lat_s", "latency", boundaries=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    text = m.prometheus_text()
    assert 'lat_s_bucket{le="0.1"} 1' in text
    assert 'lat_s_bucket{le="1.0"} 2' in text
    assert 'lat_s_bucket{le="+Inf"} 3' in text
    assert "lat_s_count 3" in text


def test_metrics_http_endpoint():
    m.Counter("hits", "h").inc(3)
    port = m.serve_metrics_http(0)
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        body = r.read().decode()
    assert "hits 3.0" in body


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_state_api(cluster):
    @ray_tpu.remote
    class Marker:
        def ping(self):
            return "ok"

    a = Marker.options(name="state_marker").remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "ok"
    actors = state.list_actors()
    assert any(x["state"] == "ALIVE" for x in actors)
    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    s = state.summarize()
    assert s["nodes_alive"] == 1
    assert s["actors_alive"] >= 1


def test_cli_status_and_list(cluster):
    addr = cluster.address
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "status",
         "--address", addr],
        capture_output=True, text=True, timeout=60, cwd="/root/repo",
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "nodes: 1 alive" in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "list", "nodes",
         "--address", addr],
        capture_output=True, text=True, timeout=60, cwd="/root/repo",
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)
    assert rows and rows[0]["alive"]


def test_histogram_recreation_shares_state():
    h1 = m.Histogram("shared_lat", "l", boundaries=(1.0,))
    h1.observe(0.5)
    h2 = m.Histogram("shared_lat", "l", boundaries=(1.0,))
    h2.observe(0.7)  # must land in the registered instance's buckets
    text = m.prometheus_text()
    assert "shared_lat_count 2" in text


def test_list_tasks_state_api(cluster):
    @ray_tpu.remote(num_cpus=0.1)
    def traced(x):
        if x == 3:
            raise ValueError("boom")
        return x

    refs = [traced.remote(i) for i in range(4)]
    for i, r in enumerate(refs):
        try:
            ray_tpu.get(r, timeout=60)
        except Exception:
            assert i == 3
    import time as _t

    deadline = _t.monotonic() + 20
    while _t.monotonic() < deadline:
        tasks = state.list_tasks()
        finished = [t for t in tasks if t["name"] == "traced"]
        if len(finished) >= 4:
            break
        _t.sleep(0.2)
    states = sorted(t["state"] for t in finished)
    assert states.count("FINISHED") == 3
    assert states.count("FAILED") == 1
    assert all(t["duration_ms"] >= 0 for t in finished)
