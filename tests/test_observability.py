"""Metrics registry, state API, CLI (reference model:
python/ray/tests/test_metrics_agent.py + util/state tests)."""

import json
import subprocess
import sys
import urllib.request

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import metrics as m
from ray_tpu.util import state


@pytest.fixture(autouse=True)
def fresh_registry():
    m.clear_registry()
    yield
    m.clear_registry()


def test_counter_gauge_exposition():
    c = m.Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    g = m.Gauge("queue_depth", "depth")
    g.set(7)
    text = m.prometheus_text()
    assert 'reqs_total{route="/a"} 1.0' in text
    assert 'reqs_total{route="/b"} 2.0' in text
    assert "queue_depth 7.0" in text
    assert "# TYPE reqs_total counter" in text


def test_histogram_buckets():
    h = m.Histogram("lat_s", "latency", boundaries=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    text = m.prometheus_text()
    assert 'lat_s_bucket{le="0.1"} 1' in text
    assert 'lat_s_bucket{le="1.0"} 2' in text
    assert 'lat_s_bucket{le="+Inf"} 3' in text
    assert "lat_s_count 3" in text


def test_metrics_http_endpoint():
    m.Counter("hits", "h").inc(3)
    port = m.serve_metrics_http(0)
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        body = r.read().decode()
    assert "hits 3.0" in body


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_state_api(cluster):
    @ray_tpu.remote
    class Marker:
        def ping(self):
            return "ok"

    a = Marker.options(name="state_marker").remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "ok"
    actors = state.list_actors()
    assert any(x["state"] == "ALIVE" for x in actors)
    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    s = state.summarize()
    assert s["nodes_alive"] == 1
    assert s["actors_alive"] >= 1


def test_cli_status_and_list(cluster):
    addr = cluster.address
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "status",
         "--address", addr],
        capture_output=True, text=True, timeout=60, cwd="/root/repo",
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "nodes: 1 alive" in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "list", "nodes",
         "--address", addr],
        capture_output=True, text=True, timeout=60, cwd="/root/repo",
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)
    assert rows and rows[0]["alive"]


def test_inject_labels_forms():
    assert m.inject_labels("hits 3.0", {"node": "abc"}) == \
        'hits{node="abc"} 3.0'
    assert m.inject_labels('lat_bucket{le="0.1"} 1', {"node": "n"}) == \
        'lat_bucket{le="0.1",node="n"} 1'
    # two tags, sorted for stable output
    assert m.inject_labels("x 1", {"proc": "w", "node": "n"}) == \
        'x{node="n",proc="w"} 1'
    # a key the series already carries is NOT duplicated (duplicate
    # label names are invalid exposition format)
    assert m.inject_labels('x{proc="mine"} 1', {"proc": "w", "node": "n"}) \
        == 'x{proc="mine",node="n"} 1'


def test_merge_prometheus_dedupes_meta_and_tags_pages():
    page = ("# HELP hits h\n# TYPE hits counter\nhits 1.0\n")
    merged = m.merge_prometheus([({"node": "a"}, page),
                                 ({"node": "b"}, page)])
    assert merged.count("# TYPE hits counter") == 1
    assert 'hits{node="a"} 1.0' in merged
    assert 'hits{node="b"} 1.0' in merged


def test_merge_prometheus_groups_families_contiguously():
    """Standard parsers demote samples separated from their TYPE header
    to untyped: a family on 2+ pages must merge into ONE header with
    all samples directly under it (histograms especially — _bucket/_sum/
    _count lines carry suffixed names)."""
    h = m.Histogram("mp_lat", "l", boundaries=(1.0,))
    h.observe(0.5)
    c = m.Counter("mp_hits", "h")
    c.inc()
    page = m.prometheus_text()
    merged = m.merge_prometheus([({"node": "a"}, page),
                                 ({"node": "b"}, page)])
    lines = merged.splitlines()
    start = lines.index("# TYPE mp_lat histogram")
    block = lines[start + 1:start + 7]  # 3 sample lines x 2 pages
    assert all(l.startswith("mp_lat") for l in block), block
    assert sum(1 for l in lines if l.startswith("# TYPE mp_lat")) == 1
    # the counter family survives as its own contiguous block too
    assert 'mp_hits{node="a"} 1.0' in merged
    assert 'mp_hits{node="b"} 1.0' in merged


def test_nested_span_api_links_and_epoch_anchor(cluster):
    """util.tracing.span: nesting produces parent-linked spans sharing
    one trace_id, with epoch-anchored (wall-clock-comparable) ts."""
    import time as _t

    from ray_tpu.util import tracing

    with tracing.span("t_outer") as t_o:
        with tracing.span("t_inner") as t_i:
            pass
    assert t_i["trace_id"] == t_o["trace_id"]
    assert t_i["parent_id"] == t_o["span_id"]
    events = {e["name"]: e for e in ray_tpu.timeline()
              if e.get("ph") == "X"}
    assert events["t_inner"]["args"]["parent_id"] == \
        events["t_outer"]["args"]["span_id"]
    # the epoch-anchoring contract (the old monotonic-only ts bug):
    # span timestamps must be comparable to wall-clock time
    assert abs(events["t_outer"]["ts"] - _t.time() * 1e6) < 300e6


def test_span_context_threads_into_tasks(cluster):
    from ray_tpu.util import tracing

    @ray_tpu.remote(num_cpus=0.1)
    def probe():
        from ray_tpu.util import tracing as _tr

        return _tr.current_trace()

    with tracing.span("t_root") as root:
        child = ray_tpu.get(probe.remote(), timeout=60)
    assert child["trace_id"] == root["trace_id"]
    assert child["parent_id"] == root["span_id"]


def test_histogram_recreation_shares_state():
    h1 = m.Histogram("shared_lat", "l", boundaries=(1.0,))
    h1.observe(0.5)
    h2 = m.Histogram("shared_lat", "l", boundaries=(1.0,))
    h2.observe(0.7)  # must land in the registered instance's buckets
    text = m.prometheus_text()
    assert "shared_lat_count 2" in text


def test_list_tasks_state_api(cluster):
    @ray_tpu.remote(num_cpus=0.1)
    def traced(x):
        if x == 3:
            raise ValueError("boom")
        return x

    refs = [traced.remote(i) for i in range(4)]
    for i, r in enumerate(refs):
        try:
            ray_tpu.get(r, timeout=60)
        except Exception:
            assert i == 3
    import time as _t

    deadline = _t.monotonic() + 20
    while _t.monotonic() < deadline:
        tasks = state.list_tasks()
        finished = [t for t in tasks if t["name"] == "traced"]
        if len(finished) >= 4:
            break
        _t.sleep(0.2)
    states = sorted(t["state"] for t in finished)
    assert states.count("FINISHED") == 3
    assert states.count("FAILED") == 1
    assert all(t["duration_ms"] >= 0 for t in finished)
