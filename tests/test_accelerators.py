"""Accelerator plugin registry (reference: ray._private.accelerators —
AcceleratorManager ABC + per-type registry)."""

from ray_tpu import accelerators as acc


def test_registry_has_tpu_and_gpu():
    managers = acc.all_managers()
    assert managers["TPU"] is acc.TPUAcceleratorManager
    assert managers["GPU"] is acc.NvidiaGPUAcceleratorManager
    assert acc.get_manager("TPU").resource_name == "TPU"
    assert acc.get_manager("nope") is None


def test_tpu_env_handoff_roundtrip():
    env = {"PALLAS_AXON_POOL_IPS": "10.0.0.1", "JAX_PLATFORMS": "axon"}
    acc.TPUAcceleratorManager.configure_worker_env(env, claimed=False)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "PALLAS_AXON_POOL_IPS" not in env
    assert env["RAY_TPU_AXON_POOL_IPS"] == "10.0.0.1"  # parked
    # a TPU-claiming worker restores the device
    acc.TPUAcceleratorManager.configure_worker_env(env, claimed=True)
    assert env["PALLAS_AXON_POOL_IPS"] == "10.0.0.1"
    assert "JAX_PLATFORMS" not in env


def test_detect_node_resources(monkeypatch):
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.delenv("TPU_CHIPS_PER_HOST_BOUNDS", raising=False)
    assert "TPU" not in acc.detect_node_resources()
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    assert acc.detect_node_resources().get("TPU") == 1.0
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
    monkeypatch.setenv("TPU_CHIPS_PER_HOST", "4")
    assert acc.detect_node_resources().get("TPU") == 4.0


def test_gpu_masking():
    env = {"CUDA_VISIBLE_DEVICES": "0,1"}
    acc.NvidiaGPUAcceleratorManager.configure_worker_env(env, claimed=False)
    assert env["CUDA_VISIBLE_DEVICES"] == ""
    acc.NvidiaGPUAcceleratorManager.configure_worker_env(env, claimed=True)
    assert "CUDA_VISIBLE_DEVICES" not in env
