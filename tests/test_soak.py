"""Concurrent-churn mini-soak: leases+borrows (forced gc), compiled
DAGs, and serve HTTP hammered simultaneously for ~20s.

Guards the round-3 race surface (gc-reentrant releases, deferred-send
queue, DAG channel loops, proxy threads): the full-length version ran
7 minutes with 5M DAG executions / 53k HTTP requests / 112k borrows and
zero errors; this bounded variant keeps the class of regression visible
in the suite.
"""

import gc
import json
import sys
import threading
import time
import urllib.request

import cloudpickle
import numpy as np

import ray_tpu

cloudpickle.register_pickle_by_value(sys.modules[__name__])

# 8s keeps the regression class visible in tier-1 (the full-length run
# is the 7-minute variant described above); raise locally when hunting
SOAK_S = 8


def test_concurrent_subsystem_churn():
    from ray_tpu import serve
    from ray_tpu.dag import InputNode

    ray_tpu.init(num_cpus=8)
    dag = None
    errors: list = []
    counts: dict[str, int] = {}
    deadline = {"stop": 0.0}

    def guard(name, fn):
        def run():
            try:
                n = 0
                while time.monotonic() < deadline["stop"]:
                    fn()
                    n += 1
                counts[name] = n
            except Exception as e:  # noqa: BLE001
                errors.append((name, repr(e)))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t

    saved_thresholds = gc.get_threshold()
    try:
        # SETUP inside the try: a failure here must still tear the
        # runtime down or later test modules inherit a broken state
        @ray_tpu.remote(num_cpus=0.5)
        def make():
            return np.arange(1 << 16)

        @ray_tpu.remote(num_cpus=0.5)
        def consume(a):
            return int(a[0] + a[-1])

        def borrow_churn():
            refs = [make.remote() for _ in range(2)]
            outs = ray_tpu.get([consume.remote(r) for r in refs],
                               timeout=120)
            assert outs == [65535, 65535], outs

        @ray_tpu.remote(num_cpus=0.5)
        class Echo:
            def step(self, x):
                return x + 1

        echo = Echo.remote()
        ray_tpu.get(echo.step.remote(0))
        with InputNode() as inp:
            node = echo.step.bind(inp)
        dag = node.experimental_compile()

        def dag_churn():
            refs = [dag.execute(i) for i in range(20)]
            assert [r.get(timeout=60) for r in refs] == \
                [i + 1 for i in range(20)]

        @serve.deployment
        class Up:
            def __call__(self, s):
                return s.upper()

        serve.run(Up.bind(), name="soak")
        addr = serve.start_proxy(port=0)

        def serve_churn():
            req = urllib.request.Request(f"http://{addr}/soak",
                                         data=json.dumps("hi").encode())
            body = json.loads(
                urllib.request.urlopen(req, timeout=30).read())
            assert body["result"] == "HI"

        gc.set_threshold(50, 5, 5)
        # deadline starts AFTER setup so slow hosts get the full window
        deadline["stop"] = time.monotonic() + SOAK_S
        threads = [guard("borrow", borrow_churn), guard("dag", dag_churn),
                   guard("serve", serve_churn)]
        for t in threads:
            t.join(timeout=SOAK_S + 120)
    finally:
        gc.set_threshold(*saved_thresholds)
        if dag is not None:
            dag.teardown()
        serve.shutdown()
        ray_tpu.shutdown()
    assert not errors, errors
    assert all(counts.get(k, 0) > 0 for k in ("borrow", "dag", "serve")), \
        counts
