"""Task/actor/object API semantics in local mode (reference test model:
python/ray/tests/test_basic.py family)."""

import time

import pytest

from ray_tpu.core.exceptions import ActorDiedError, GetTimeoutError, TaskError


def test_task_roundtrip(ray_local):
    ray = ray_local

    @ray.remote
    def add(a, b):
        return a + b

    assert ray.get(add.remote(1, 2)) == 3


def test_put_get(ray_local):
    ray = ray_local
    ref = ray.put({"x": [1, 2, 3]})
    assert ray.get(ref) == {"x": [1, 2, 3]}


def test_objectref_args_resolved(ray_local):
    ray = ray_local

    @ray.remote
    def double(x):
        return 2 * x

    ref = ray.put(21)
    assert ray.get(double.remote(ref)) == 42
    # chained tasks
    assert ray.get(double.remote(double.remote(ref))) == 84


def test_num_returns(ray_local):
    ray = ray_local

    @ray.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(ray_local):
    ray = ray_local

    @ray.remote
    def boom():
        raise ValueError("nope")

    with pytest.raises(TaskError, match="nope"):
        ray.get(boom.remote())


def test_retry_exceptions(ray_local):
    ray = ray_local
    state = {"n": 0}

    @ray.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise RuntimeError("transient")
        return state["n"]

    assert ray.get(flaky.remote()) == 3


def test_wait(ray_local):
    ray = ray_local

    @ray.remote
    def fast():
        return "fast"

    @ray.remote
    def slow():
        time.sleep(5)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray.wait([f, s], num_returns=1, timeout=2)
    assert ready == [f] and not_ready == [s]


def test_get_timeout(ray_local):
    ray = ray_local

    @ray.remote
    def slow():
        time.sleep(10)

    with pytest.raises(GetTimeoutError):
        ray.get(slow.remote(), timeout=0.1)


def test_actor_state_and_order(ray_local):
    ray = ray_local

    @ray.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    refs = [c.inc.remote() for _ in range(5)]
    assert ray.get(refs) == [11, 12, 13, 14, 15]
    assert ray.get(c.value.remote()) == 15


def test_named_actor(ray_local):
    ray = ray_local

    @ray.remote
    class Store:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    Store.options(name="kv").remote()
    h = ray.get_actor("kv")
    ray.get(h.set.remote("a", 1))
    assert ray.get(h.get.remote("a")) == 1

    with pytest.raises(ValueError):
        Store.options(name="kv").remote()
    # get_if_exists returns the existing one
    h2 = Store.options(name="kv", get_if_exists=True).remote()
    assert ray.get(h2.get.remote("a")) == 1


def test_kill_actor(ray_local):
    ray = ray_local

    @ray.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray.get(a.ping.remote()) == "pong"
    ray.kill(a)
    with pytest.raises(ActorDiedError):
        ray.get(a.ping.remote())


def test_actor_error_propagates(ray_local):
    ray = ray_local

    @ray.remote
    class B:
        def bad(self):
            raise KeyError("missing")

    b = B.remote()
    with pytest.raises(TaskError, match="missing"):
        ray.get(b.bad.remote())


def test_nested_tasks(ray_local):
    ray = ray_local

    @ray.remote
    def inner(x):
        return x * 2

    @ray.remote
    def outer(x):
        import ray_tpu

        return ray_tpu.get(inner.remote(x)) + 1

    assert ray.get(outer.remote(10)) == 21


def test_actor_handle_passing(ray_local):
    ray = ray_local

    @ray.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    @ray.remote
    def bump(counter):
        import ray_tpu

        return ray_tpu.get(counter.inc.remote())

    c = Counter.remote()
    assert ray.get(bump.remote(c)) == 1
    assert ray.get(bump.remote(c)) == 2


def test_runtime_context(ray_local):
    ray = ray_local
    ctx = ray.get_runtime_context()
    assert len(ctx.get_node_id()) == 32


def test_options_validation(ray_local):
    ray = ray_local
    with pytest.raises(ValueError, match="invalid task option"):

        @ray.remote(bogus_option=1)
        def f():
            pass
