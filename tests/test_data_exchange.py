"""All-to-all Data ops: shuffle / sort / groupby / parquet (VERDICT r2
item 5). Reference parity: python/ray/data/dataset.py:1374
(random_shuffle), :2472 (sort), :2099 (groupby), arrow_block.py.
"""

import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_random_shuffle_preserves_multiset(ray_cluster):
    ds = rd.range(1000, parallelism=8)
    out = ds.random_shuffle(seed=7).take_all()
    assert sorted(out) == list(range(1000))
    assert out != list(range(1000))  # actually permuted


def test_random_shuffle_deterministic_with_seed(ray_cluster):
    a = rd.range(500, parallelism=4).random_shuffle(seed=3).take_all()
    b = rd.range(500, parallelism=4).random_shuffle(seed=3).take_all()
    assert a == b


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_sort_scalars_multi_block(ray_cluster):
    rng = np.random.RandomState(0)
    vals = [int(v) for v in rng.randint(0, 10_000, 2_000)]
    out = rd.from_items(vals, parallelism=8).sort().take_all()
    assert out == sorted(vals)


def test_sort_by_column_descending(ray_cluster):
    rows = [{"k": i % 17, "v": i} for i in range(400)]
    out = rd.from_items(rows, parallelism=6).sort("k", descending=True) \
            .take_all()
    assert [r["k"] for r in out] == sorted((r["k"] for r in rows),
                                           reverse=True)


def test_sort_after_map(ray_cluster):
    out = rd.range(100, parallelism=5).map(lambda x: 99 - x).sort().take_all()
    assert out == list(range(100))


def test_groupby_aggregate_matches_inmemory(ray_cluster):
    rng = np.random.RandomState(1)
    rows = [{"k": int(k), "v": float(v)}
            for k, v in zip(rng.randint(0, 13, 1_500),
                            rng.rand(1_500) * 10)]
    out = rd.from_items(rows, parallelism=8).groupby("k").aggregate(
        rd.Count(), rd.Sum("v"), rd.Mean("v"), rd.Min("v"), rd.Max("v"),
    ).take_all()
    by_k = {}
    for r in rows:
        by_k.setdefault(r["k"], []).append(r["v"])
    assert len(out) == len(by_k)
    for row in out:
        vs = by_k[row["k"]]
        assert row["count"] == len(vs)
        np.testing.assert_allclose(row["sum(v)"], sum(vs))
        np.testing.assert_allclose(row["mean(v)"], sum(vs) / len(vs))
        assert row["min(v)"] == min(vs) and row["max(v)"] == max(vs)


def test_groupby_map_groups(ray_cluster):
    rows = [{"k": i % 3, "v": i} for i in range(30)]
    out = rd.from_items(rows, parallelism=4).groupby("k").map_groups(
        lambda rs: {"k": rs[0]["k"], "n": len(rs)}).take_all()
    assert sorted((r["k"], r["n"]) for r in out) == [(0, 10), (1, 10), (2, 10)]


def test_parquet_round_trip(ray_cluster, tmp_path):
    rows = [{"a": i, "b": float(i) / 3, "s": f"row{i}"} for i in range(200)]
    paths = rd.from_items(rows, parallelism=4).write_parquet(
        str(tmp_path / "pq"))
    assert len(paths) == 4
    back = rd.read_parquet(str(tmp_path / "pq")).take_all()
    assert sorted(back, key=lambda r: r["a"]) == rows
    # column pruning
    only_a = rd.read_parquet(str(tmp_path / "pq"), columns=["a"]).take_all()
    assert set(only_a[0].keys()) == {"a"}


def test_pyarrow_batch_format(ray_cluster):
    import pyarrow as pa

    rows = [{"x": i} for i in range(100)]

    def double(table: "pa.Table") -> "pa.Table":
        import pyarrow.compute as pc

        return table.set_column(0, "x", pc.multiply(table["x"], 2))

    out = rd.from_items(rows, parallelism=4).map_batches(
        double, batch_format="pyarrow").take_all()
    assert sorted(r["x"] for r in out) == [2 * i for i in range(100)]
    batches = list(rd.from_items(rows, parallelism=2).iter_batches(
        batch_size=40, batch_format="pyarrow"))
    assert isinstance(batches[0], pa.Table)
    assert sum(b.num_rows for b in batches) == 100


def test_shuffled_train_ingestion(ray_cluster):
    """Shuffle -> shard -> iter_batches: every row exactly once across
    shards, shard contents differ from the unshuffled split (the Data ->
    Train ingestion contract, reference: DataParallelTrainer datasets=)."""
    ds = rd.range(512, parallelism=8).random_shuffle(seed=11)
    shards = ds.split(4)
    seen = []
    for sh in shards:
        for batch in sh.iter_batches(batch_size=32):
            seen.extend(int(v) for v in batch)
    assert sorted(seen) == list(range(512))
    plain_shard0 = rd.range(512, parallelism=8).split(4)[0].take_all()
    assert shards[0].take_all() != plain_shard0


# ---------------------------------------------------------------------------
# Streaming-executor backpressure (VERDICT r2 weak item 6)
# Reference: streaming_executor.py:48 + backpressure_policy.py:11
# ---------------------------------------------------------------------------

def test_memory_budget_bounds_buffered_bytes(ray_cluster):
    """A tiny memory budget keeps produced-but-unconsumed block bytes
    bounded: the executor waits instead of racing ahead of a slow
    consumer."""
    import time as _t

    big = rd.from_items(list(range(16)), parallelism=16).map_batches(
        lambda b: np.zeros((len(b), 64 * 1024), np.float32))  # 4MB/block
    ds = big
    it = ds._execute(max_in_flight=8, memory_budget=2 * (1 << 20))
    out = []
    for ref in it:
        _t.sleep(0.05)  # slow consumer
        out.append(ray_tpu.get(ref))
    ex = ds._last_executor
    assert len(out) == 16
    assert ex.stats.backpressure_waits > 0, "budget never engaged"
    # bytes buffered ahead of the consumer stayed near the budget, far
    # below the ~64MB the pipeline would produce unthrottled
    assert ex.stats.peak_buffered_bytes < 12 * (1 << 20), \
        ex.stats.peak_buffered_bytes


def test_executor_preserves_order_and_results(ray_cluster):
    ds = rd.range(200, parallelism=10).map(lambda x: x * 3)
    assert ds.take_all() == [x * 3 for x in range(200)]
    ex = ds._last_executor
    assert ex.stats.submitted == 10 and ex.stats.yielded == 10


def test_seeded_shuffle_not_position_aligned(ray_cluster):
    """r3 ADVICE: one shared seed stream made rows at equal positions in
    different blocks ALWAYS co-locate in the same output partition (a
    seeded shuffle far from uniform). Per-block seed derivation makes
    co-location ~1/P."""
    ds = rd.from_items(list(range(100)), parallelism=2).random_shuffle(seed=7)
    parts = ray_tpu.get(list(ds._block_refs), timeout=120)
    assert sorted(r for p in parts for r in p) == list(range(100))
    same = sum(1 for i in range(50)
               if any(i in p and i + 50 in p for p in parts))
    assert same < 45, f"position-aligned co-location: {same}/50"
