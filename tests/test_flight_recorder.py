"""Task flight recorder — the fifth observability pillar.

Covers the lifecycle ledger (bounded ring + transition cap + disk
spill), the waterfall phase breakdown, critical-path analysis, and
the acceptance gates end-to-end on a live 2-node cluster:

  (a) an unschedulable task is EXPLAINED — the verdict names the
      unsatisfiable constraint and the nodes considered;
  (b) a task stalled behind a saturated pool shows a ledger
      queue-wait matching the deliberate stall within 10%;
  (c) critical path over a 4-stage compiled DAG covers >= 90% of the
      measured end-to-end time and names the slow stage;
  (d) the armed ledger costs < 1% CPU of the busy window it records,
      and the ring/spill stay bounded under a 10k-task burst with
      every drop counted.
"""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.core.task_ledger import TaskLedger, waterfall
from ray_tpu.util import critpath

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


# ---------------------------------------------------------------------------
# waterfall: pure phase breakdown
# ---------------------------------------------------------------------------

def _rec(transitions, **extra):
    out = {"task_id": "ab" * 16, "name": "t", "type": "task",
           "state": transitions[-1][0] if transitions else "",
           "transitions": [{"state": s, "t": t} for s, t in transitions]}
    out.update(extra)
    return out


def test_waterfall_orders_out_of_order_transitions():
    """Producers flush on independent cadences, so events arrive out
    of time order — the waterfall must sort by recorded timestamp."""
    t0 = 1700000000.0
    rec = _rec([("RUNNING", t0 + 2.0), ("SUBMITTED", t0),
                ("FINISHED", t0 + 2.5), ("LEASED", t0 + 0.1),
                ("QUEUED", t0 + 0.05)])
    wf = waterfall(rec)
    assert [p["phase"] for p in wf["phases"]] == [
        "SUBMITTED→QUEUED", "QUEUED→LEASED", "LEASED→RUNNING",
        "RUNNING→FINISHED"]
    assert all(p["ms"] >= 0.0 for p in wf["phases"])
    assert wf["total_ms"] == pytest.approx(2500.0, abs=1.0)
    assert wf["queue_ms"] == pytest.approx(50.0, abs=1.0)
    assert wf["exec_ms"] == pytest.approx(500.0, abs=1.0)


def test_waterfall_queue_wait_ignores_preceding_spillback_hop():
    """A spillback SCHEDULED hop can be stamped BEFORE the target
    node's QUEUED — queue wait must anchor on the first hand-off at or
    after queueing, not the earlier hop."""
    t0 = 1700000000.0
    rec = _rec([("SUBMITTED", t0), ("SCHEDULED", t0 + 0.01),
                ("QUEUED", t0 + 0.02), ("DISPATCHED", t0 + 1.02),
                ("RUNNING", t0 + 1.03), ("FINISHED", t0 + 1.1)])
    wf = waterfall(rec)
    assert wf["queue_ms"] == pytest.approx(1000.0, abs=1.0)


def test_waterfall_queue_wait_spans_requeue_hops():
    """A task queued on one node and re-spilled to another mid-wait
    re-enters QUEUED there — the queue phase starts at the FIRST
    queueing, not the last hop's."""
    t0 = 1700000000.0
    rec = _rec([("SUBMITTED", t0), ("QUEUED", t0 + 0.001),
                ("SCHEDULED", t0 + 1.0), ("QUEUED", t0 + 1.001),
                ("DISPATCHED", t0 + 1.002), ("RUNNING", t0 + 1.003),
                ("FINISHED", t0 + 1.01)])
    wf = waterfall(rec)
    assert wf["queue_ms"] == pytest.approx(1001.0, abs=1.0)


def test_waterfall_retry_resets_queue_wait():
    """The waterfall describes the LAST attempt: a retry re-enters
    QUEUED and the queue phase restarts there."""
    t0 = 1700000000.0
    rec = _rec([("SUBMITTED", t0), ("QUEUED", t0 + 0.001),
                ("DISPATCHED", t0 + 0.002), ("RUNNING", t0 + 0.003),
                ("RETRIED", t0 + 5.0), ("QUEUED", t0 + 5.001),
                ("DISPATCHED", t0 + 5.201), ("RUNNING", t0 + 5.202),
                ("FINISHED", t0 + 5.3)])
    wf = waterfall(rec)
    assert wf["queue_ms"] == pytest.approx(200.0, abs=1.0)


def test_waterfall_exec_falls_back_to_reported_duration():
    t0 = 1700000000.0
    rec = _rec([("SUBMITTED", t0), ("FINISHED", t0 + 1.0)],
               duration_ms=400.0)
    assert waterfall(rec)["exec_ms"] == 400.0


# ---------------------------------------------------------------------------
# TaskLedger: join, caps, spill — gate (d) bounding discipline
# ---------------------------------------------------------------------------

def _ev(tid, state, t, **extra):
    out = {"task_id": tid, "state": state, "time": t}
    out.update(extra)
    return out


def test_ledger_joins_events_per_task():
    led = TaskLedger(capacity=100)
    t0 = 1700000000.0
    led.ingest([_ev("aa" * 16, "SUBMITTED", t0, name="f", type="task",
                    trace_id="tr1"),
                _ev("aa" * 16, "RUNNING", t0 + 0.1, node_id="n1",
                    worker_id="w1"),
                _ev("aa" * 16, "FINISHED", t0 + 0.2, duration_ms=95.0),
                _ev("bb" * 16, "SUBMITTED", t0)])
    rec = led.get("aa")  # unique prefix lookup
    assert rec["state"] == "FINISHED"
    assert rec["name"] == "f" and rec["trace_id"] == "tr1"
    assert rec["node_id"] == "n1" and rec["duration_ms"] == 95.0
    assert [t["state"] for t in rec["transitions"]] == [
        "SUBMITTED", "RUNNING", "FINISHED"]
    assert led.counts() == {"FINISHED": 1, "SUBMITTED": 1}
    assert led.stats()["events_total"] == 4
    # unknown state / missing task_id are ignored, not fatal
    led.ingest([{"state": "RUNNING", "time": t0},
                _ev("cc" * 16, "NOT_A_STATE", t0)])
    assert led.stats()["events_total"] == 4


def test_ledger_transition_cap_counts_drops_keeps_terminal():
    led = TaskLedger(capacity=10, max_transitions=8)
    tid = "dd" * 16
    t0 = 1700000000.0
    for i in range(20):  # a retry storm blows the history cap
        led.ingest([_ev(tid, "RETRIED" if i % 2 else "QUEUED",
                        t0 + i)])
    led.ingest([_ev(tid, "FAILED", t0 + 99, error="gave up")])
    rec = led.get(tid)
    assert len(rec["transitions"]) == 8
    # the terminal verdict stays visible in the overwritten last slot
    assert rec["transitions"][-1]["state"] == "FAILED"
    assert rec["state"] == "FAILED" and rec["error"] == "gave up"
    assert rec["dropped_transitions"] == 21 - 8
    assert led.stats()["dropped_transitions_total"] == 13


def test_ledger_bounded_under_10k_burst_with_spill(tmp_path):
    """Gate (d), bounding half: a 10k-task burst through a 1k ring
    stays bounded, evictions are counted and spill to disk, and an
    evicted task is still findable post-mortem."""
    led = TaskLedger(capacity=1_000, spill_dir=str(tmp_path))
    t0 = 1700000000.0
    batch = []
    for i in range(10_000):
        tid = f"{i:032x}"
        batch.append(_ev(tid, "SUBMITTED", t0 + i * 1e-3, name=f"burst{i}"))
        batch.append(_ev(tid, "FINISHED", t0 + i * 1e-3 + 5e-4))
        if len(batch) >= 256:
            led.ingest(batch)
            batch = []
    led.ingest(batch)
    st = led.stats()
    assert st["records"] == 1_000
    assert st["events_total"] == 20_000
    assert st["spilled_records_total"] == 9_000
    # live window answers from memory, an evicted task from the spill
    assert led.get(f"{9_500:032x}")["name"] == "burst9500"
    old = led.get(f"{3:032x}")
    assert old is not None and old["name"] == "burst3"
    assert [t["state"] for t in old["transitions"]] == [
        "SUBMITTED", "FINISHED"]


def test_ledger_armed_overhead_under_one_percent():
    """Gate (d), overhead half: producing + ingesting the full
    lifecycle of a task costs < 1% of the CPU the task itself burns
    (CPU-metered via thread_time, immune to wall-clock noise)."""
    led = TaskLedger(capacity=10_000)
    n = 200

    def busy_task():
        x = 0
        for k in range(100_000):
            x += k * k
        return x

    led_cpu = 0.0
    buf = []
    cpu0 = time.thread_time()
    for i in range(n):
        busy_task()
        t_a = time.thread_time()
        tid = f"{i:032x}"
        now = 1700000000.0 + i
        buf.extend(_ev(tid, s, now + j * 0.01, name=f"t{i}", type="task")
                   for j, s in enumerate(("SUBMITTED", "LEASED",
                                          "RUNNING", "FINISHED")))
        if len(buf) >= 128:  # the task_events lane flushes batches
            led.ingest(buf)
            buf = []
        led_cpu += time.thread_time() - t_a
    t_a = time.thread_time()
    led.ingest(buf)
    led_cpu += time.thread_time() - t_a
    busy_cpu = (time.thread_time() - cpu0) - led_cpu
    assert led.stats()["events_total"] == 4 * n
    assert led_cpu < 0.01 * busy_cpu, (led_cpu, busy_cpu)


# ---------------------------------------------------------------------------
# critical path: pure chain analysis
# ---------------------------------------------------------------------------

def _span(name, ts_us, dur_us, trace="tr"):
    return {"name": name, "cat": "dag", "ph": "X", "ts": ts_us,
            "dur": dur_us, "args": {"trace_id": trace}}


def test_critpath_chain_and_slack():
    t0 = 1_700_000_000_000_000.0
    spans = [_span("a", t0, 10_000), _span("b", t0 + 10_020, 50_000),
             _span("c", t0 + 61_000, 10_000)]
    r = critpath.critical_path(spans, "tr")
    assert [c["name"] for c in r["chain"]] == ["a", "b", "c"]
    assert r["slowest"] == "b"
    # slack: a→b handoff is sub-eps (contiguous), b→c has ~1ms idle
    assert r["chain"][1]["slack_ms"] == pytest.approx(0.02, abs=0.05)
    assert r["chain"][2]["slack_ms"] == pytest.approx(0.98, abs=0.1)
    assert r["coverage"] > 0.95


def test_critpath_coverage_does_not_double_count_overlap():
    """A covering parent span overlapping its children must not push
    coverage past 1.0 — covered time is a union of intervals."""
    t0 = 1_700_000_000_000_000.0
    spans = [_span("parent", t0, 100_000),
             _span("child1", t0 + 1_000, 40_000),
             _span("child2", t0 + 50_000, 45_000)]
    r = critpath.critical_path(spans, "tr")
    assert r["coverage"] <= 1.0
    assert r["e2e_ms"] == pytest.approx(100.0, abs=0.01)


def test_critpath_aggregate_across_traces():
    t0 = 1_700_000_000_000_000.0
    spans = []
    for i, tr in enumerate(("t1", "t2", "t3")):
        base = t0 + i * 1_000_000
        spans += [_span("load", base, 10_000, tr),
                  _span("compute", base + 10_050, 80_000, tr)]
    r = critpath.aggregate(spans)
    assert r["traces"] == 3
    by_name = {e["name"]: e for e in r["entries"]}
    assert by_name["compute"]["count"] == 3
    assert by_name["compute"]["total_ms"] > by_name["load"]["total_ms"]
    assert r["entries"][0]["name"] == "compute"  # sorted by total


def test_critpath_empty_trace():
    r = critpath.critical_path([], "nope")
    assert r["chain"] == [] and r["coverage"] == 0.0


# ---------------------------------------------------------------------------
# live cluster: gates (a), (b), (c) + degraded queries + debug dump
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster2():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 4, "labels": {"zone": "a"}})
    c.add_node(num_cpus=2, labels={"zone": "b"})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _ledger_record(frag, timeout=10.0, pred=None):
    """Poll the head ledger until a record whose name contains `frag`
    (and satisfies `pred`) lands — producers flush on 0.25-1s cadences."""
    from ray_tpu.util import state

    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        r = state.task_ledger(limit=500)
        for rec in r.get("records", ()):
            if frag in (rec.get("name") or ""):
                last = rec
                if pred is None or pred(rec):
                    return rec
        time.sleep(0.25)
    raise AssertionError(f"no ledger record for {frag!r}; last={last}")


def test_explain_names_infeasible_resource_constraint(cluster2):
    """Gate (a), resource flavor: a task demanding a resource no node
    has parks driver-side waiting for a lease — explain still names
    the unsatisfiable constraint and lists every node considered."""
    from ray_tpu.util import state

    @ray_tpu.remote(num_cpus=0.1, resources={"fr_nonexistent": 1.0})
    def fr_unsched():
        return 1

    fr_unsched.remote()  # never schedulable; left pending on purpose
    rec = _ledger_record("fr_unsched",
                         pred=lambda r: r.get("state") == "QUEUED")
    out = state.explain_task(rec["task_id"])
    assert out["record"]["state"] == "QUEUED"
    v = out.get("verdict") or {}
    assert "no node in the cluster has total capacity" in \
        v.get("constraint", ""), out
    assert "fr_nonexistent" in v["constraint"]
    considered = v.get("nodes_considered") or []
    assert len(considered) == 2
    assert all(not n.get("ok") for n in considered)
    assert all(n.get("reason") for n in considered)
    # the waterfall shows it never left the queue
    assert "RUNNING" not in (out.get("waterfall") or {}).get("states", [])


def test_explain_names_infeasible_label_selector(cluster2):
    """Gate (a), label flavor: a hard label selector no node matches
    queues at a nodelet with an infeasible-wait verdict that names the
    selector and the per-node reasons."""
    from ray_tpu.util import state

    @ray_tpu.remote(num_cpus=0.1, label_selector={"zone": "zz"})
    def fr_pinned():
        return 1

    fr_pinned.remote()  # never schedulable; left pending on purpose
    rec = _ledger_record(
        "fr_pinned",
        pred=lambda r: (r.get("verdict") or {}).get("decision")
        == "infeasible-wait")
    v = rec["verdict"]
    assert "label selector" in v["constraint"] and "zz" in v["constraint"]
    assert v.get("nodes_considered"), v
    out = state.explain_task(rec["task_id"])
    # the owning nodelet reports live queue state for the stuck task
    queued = [i for i in (out.get("nodes") or {}).values()
              if i.get("queued")]
    assert queued, out
    assert queued[0].get("queue_position") is not None
    assert not out.get("errors"), out


def test_queue_wait_matches_deliberate_stall(cluster2):
    """Gate (b): saturate the 2-CPU zone-b pool with a hog, then
    submit a waiter needing the whole pool — the waiter's ledger
    queue-wait must match the stall it actually sat through."""
    from ray_tpu.util import state

    stall_s = 1.5

    @ray_tpu.remote(num_cpus=2, label_selector={"zone": "b"})
    def fr_hog():
        time.sleep(stall_s)
        return "hogged"

    @ray_tpu.remote(num_cpus=2, label_selector={"zone": "b"})
    def fr_waiter():
        return "ran"

    href = fr_hog.remote()
    _ledger_record("fr_hog", pred=lambda r: r.get("state") == "RUNNING")
    t_submit = time.time()
    wref = fr_waiter.remote()
    assert ray_tpu.get(href, timeout=30) == "hogged"
    t_hog_done = time.time()
    assert ray_tpu.get(wref, timeout=30) == "ran"
    waiter_wall = time.time() - t_submit
    measured_stall = t_hog_done - t_submit

    rec = _ledger_record("fr_waiter",
                         pred=lambda r: r.get("state") == "FINISHED")
    out = state.explain_task(rec["task_id"])
    queue_s = (out["waterfall"].get("queue_ms") or 0.0) / 1e3
    # the ledger's queue-wait covers the stall within 10% (small
    # absolute floor for submit->enqueue transit); it may exceed the
    # hog's runtime when the scheduler re-spills the waiter onto the
    # freed node — that hop is still queue time — but never the
    # waiter's own observed latency
    assert queue_s >= 0.9 * measured_stall - 0.1, (queue_s, measured_stall)
    assert queue_s <= waiter_wall + 0.2, (queue_s, waiter_wall)


def test_critical_path_over_compiled_dag(cluster2):
    """Gate (c): a 4-stage compiled DAG with one deliberately slow
    stage — the critical path covers >= 90% of the measured e2e and
    names the slow stage."""
    from ray_tpu.core.rpc import RpcClient
    from ray_tpu.dag import InputNode
    from ray_tpu.util import state

    @ray_tpu.remote(num_cpus=0.2, label_selector={"zone": "a"})
    class FrStage:
        def fr_s1(self, x):
            time.sleep(0.05)
            return x + 1

        def fr_s2(self, x):  # the slow stage
            time.sleep(0.30)
            return x + 1

        def fr_s3(self, x):
            time.sleep(0.05)
            return x + 1

        def fr_s4(self, x):
            time.sleep(0.05)
            return x + 1

    s1, s2, s3, s4 = [FrStage.remote() for _ in range(4)]
    with InputNode() as inp:
        out = s4.fr_s4.bind(s3.fr_s3.bind(s2.fr_s2.bind(s1.fr_s1.bind(inp))))
    dag = out.compile()
    try:
        assert dag.execute(0).get() == 4  # warm the resident loops
        t0 = time.monotonic()
        assert dag.execute(10).get() == 14
        wall_ms = (time.monotonic() - t0) * 1e3

        # worker span flush rides the 1s event loop
        deadline = time.monotonic() + 10
        trace_id = None
        while time.monotonic() < deadline and trace_id is None:
            spans = RpcClient.shared().call(
                cluster2.address, "dump_timeline", {},
                timeout=30)["spans"]
            ours = [s for s in spans
                    if "fr_s" in s.get("name", "")
                    and ((s.get("args") or {}).get("trace_id") or ""
                         ).endswith(":1")]
            if len(ours) == 4:
                trace_id = ours[0]["args"]["trace_id"]
                break
            time.sleep(0.5)
        assert trace_id, "stage spans for execution 1 never flushed"

        r = state.critical_path(trace_id=trace_id)
        names = [c["name"] for c in r["chain"]]
        assert [n.split(":")[0] for n in names[:4]] == [
            "dag.fr_s1", "dag.fr_s2", "dag.fr_s3", "dag.fr_s4"], names
        assert r["coverage"] >= 0.9, r
        assert "fr_s2" in r["slowest"], r
        assert r["path_ms"] >= 0.9 * wall_ms * 0.9, (r["path_ms"], wall_ms)
        assert r["e2e_ms"] <= wall_ms + 100.0
    finally:
        dag.teardown()


def test_debug_dump_includes_ledger_artifact(cluster2, tmp_path):
    """The post-mortem dump carries the joined per-task state machines
    as tasks.jsonl next to the flat event view."""
    from ray_tpu.util import state

    out = state.debug_dump(out_dir=str(tmp_path / "dump"), deadline_s=60)
    files = set(os.listdir(out))
    assert "tasks.jsonl" in files, files
    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert "task_ledger" in summary["artifacts"], summary
    with open(os.path.join(out, "tasks.jsonl")) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines, "tasks.jsonl is empty"
    assert all("transitions" in rec and "state" in rec for rec in lines)
    # the gate (b) waiter's full lifecycle is greppable post-mortem
    waiters = [r for r in lines if "fr_waiter" in (r.get("name") or "")]
    assert waiters and waiters[0]["state"] == "FINISHED"


def test_ledger_queries_survive_dead_node(cluster2):
    """LAST test in the module: it stops a node. Ledger queries and
    explain's live fan-out must keep answering — a dead node becomes
    an `errors` entry (or is pruned), never a failed gather."""
    from ray_tpu.util import state

    rec = _ledger_record("fr_pinned")  # still pending from gate (a)
    victim = cluster2.nodelets[-1]
    cluster2.remove_node(victim)

    t0 = time.monotonic()
    out = state.explain_task(rec["task_id"], timeout=8)
    elapsed = time.monotonic() - t0
    assert elapsed < 15.0, elapsed
    assert out["record"]["task_id"] == rec["task_id"]
    assert isinstance(out.get("nodes"), dict)
    # a node that could not answer is an errors entry, never a raise
    assert all(isinstance(e, str) for e in out.get("errors", {}).values())
    r = state.task_ledger()
    assert r["counts"] and r["stats"]["events_total"] > 0
