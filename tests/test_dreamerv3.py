"""DreamerV3 (reference model: rllib/algorithms/dreamerv3/tests) —
world-model learning signal, imagination machinery, replay windows.

CPU-scale smoke: full learning-to-solve is out of budget here; what is
pinned down is (a) the world model FITS (its loss drops substantially
over replayed updates), (b) symlog/twohot invariants, (c) sequence
replay contiguity + episode-boundary flags, (d) checkpoint roundtrip.
"""

import sys

import cloudpickle
import numpy as np
import pytest

from ray_tpu.rllib.dreamerv3 import (
    BINS,
    DreamerV3Config,
    EpisodeSequenceBuffer,
    symexp,
    symlog,
    twohot,
    twohot_mean,
)

cloudpickle.register_pickle_by_value(sys.modules[__name__])


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------

def test_symlog_symexp_roundtrip():
    x = np.array([-100.0, -1.0, 0.0, 0.5, 3.0, 1000.0])
    np.testing.assert_allclose(np.asarray(symexp(symlog(x))), x, rtol=1e-5)


def test_twohot_is_distribution_and_invertible():
    y = np.array([-7.3, 0.0, 0.4, 12.0])
    th = np.asarray(twohot(y))
    assert th.shape == (4, len(BINS))
    np.testing.assert_allclose(th.sum(-1), 1.0, rtol=1e-5)
    # expected value through log-space decode recovers the input
    logits = np.log(th + 1e-9)
    np.testing.assert_allclose(np.asarray(twohot_mean(logits)), y,
                               rtol=0.05, atol=0.05)


def test_sequence_buffer_windows_contiguous():
    buf = EpisodeSequenceBuffer(1000, num_envs=2, seed=0)
    for t in range(30):
        buf.add_step({"obs": np.array([[t, 0], [t, 1]], np.float32),
                      "first": np.array([t % 10 == 0, False], np.float32)})
    assert buf.can_sample(4, 8)
    s = buf.sample_sequences(4, 8)
    assert s["obs"].shape == (4, 8, 2)
    for b in range(4):
        ts = s["obs"][b, :, 0]
        assert np.all(np.diff(ts) == 1), f"window not contiguous: {ts}"
        env = s["obs"][b, :, 1]
        assert len(set(env.tolist())) == 1, "window crossed env streams"


def test_sequence_buffer_capacity_evicts_oldest():
    buf = EpisodeSequenceBuffer(20, num_envs=2, seed=0)  # 10 per stream
    for t in range(25):
        buf.add_step({"obs": np.array([[t], [t]], np.float32)})
    s = buf.sample_sequences(8, 10)
    assert s["obs"].min() >= 15  # only the newest 10 survive


# ---------------------------------------------------------------------------
# algorithm
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def algo():
    a = (DreamerV3Config()
         .environment("CartPole-v1")
         .training(model_size="XS", training_ratio=8.0, batch_size_B=4,
                   batch_length_T=8, horizon_H=5, num_envs=4,
                   rollout_fragment_length=16, seed=0)).build()
    yield a
    a.stop()


def test_world_model_fits(algo):
    """The decisive smoke: wm total loss drops substantially as the
    world model sees replayed experience."""
    first = None
    last = None
    for _ in range(6):
        r = algo.train()
        if "wm/total" in r:
            if first is None:
                first = r["wm/total"]
            last = r["wm/total"]
    assert first is not None and last is not None, "no updates ran"
    assert np.isfinite(last)
    assert last < first * 0.8, (first, last)


def test_metrics_and_imagination_finite(algo):
    r = algo.train()
    for k in ("wm/decoder", "wm/reward", "wm/dyn", "wm/rep",
              "actor/entropy", "critic/value", "imagined_return"):
        assert k in r, f"missing {k}"
        assert np.isfinite(r[k]), (k, r[k])
    assert r["num_env_steps_sampled_lifetime"] > 0
    assert r["num_steps_replayed"] > 0


def test_checkpoint_roundtrip(algo, tmp_path):
    import jax

    algo.train()
    path = algo.save_to_path(str(tmp_path / "dv3"))
    algo2 = (DreamerV3Config()
             .environment("CartPole-v1")
             .training(model_size="XS", training_ratio=8.0,
                       batch_size_B=4, batch_length_T=8, horizon_H=5,
                       num_envs=4, rollout_fragment_length=16,
                       seed=99)).build()
    algo2.restore_from_path(path)
    a = jax.tree.leaves(algo.wm)
    b = jax.tree.leaves(algo2.wm)
    assert all(np.allclose(x, y) for x, y in zip(a, b))
    algo2.stop()


@pytest.mark.slow  # tier-1 budget (see ROADMAP): covered by faster siblings
def test_image_observations_conv_world_model():
    """DreamerV3 on a pixel env: the conv encoder + pixel decoder world
    model fits (reference: DreamerV3's headline domain is pixels)."""
    algo = (DreamerV3Config()
            .environment("PixelCatch-v0")
            .training(model_size="XS", training_ratio=8.0, batch_size_B=4,
                      batch_length_T=8, horizon_H=5, num_envs=4,
                      rollout_fragment_length=16, seed=0)).build()
    try:
        assert algo._image_obs
        assert "conv" in algo.wm["encoder"], "conv encoder not selected"
        first = last = None
        for _ in range(6):
            r = algo.train()
            if "wm/total" in r:
                first = first if first is not None else r["wm/total"]
                last = r["wm/total"]
        assert first is not None and np.isfinite(last)
        assert last < first * 0.8, (first, last)
        # replay holds uint8 pixels (4x memory), scaled only on device
        s = algo.buffer.sample_sequences(2, 4)
        assert s["obs"].dtype == np.uint8
    finally:
        algo.stop()
