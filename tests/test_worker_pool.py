"""Worker pool cap / reuse / prestart (reference model: WorkerPool,
raylet/worker_pool.h:216 — caps by cores, reuses idle workers)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def capped_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_MAX_WORKERS", "3")
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    monkeypatch.delenv("RAY_TPU_MAX_WORKERS", raising=False)


def test_burst_respects_pool_cap(capped_cluster):
    """A 60-task burst must not fork 60 interpreters: the pool is capped
    (here at 3) and workers are reused."""
    nl = capped_cluster.nodelets[0]

    @ray_tpu.remote(num_cpus=0.1)
    def work(i):
        return os.getpid()

    refs = [work.remote(i) for i in range(60)]
    pids = set(ray_tpu.get(refs, timeout=120))
    assert len(pids) <= 3, f"{len(pids)} distinct workers for a capped pool"
    with nl._lock:
        n_task_workers = sum(1 for w in nl._workers.values()
                             if w.actor_id is None)
    assert n_task_workers <= 3


def test_workers_reused_across_tasks(capped_cluster):
    @ray_tpu.remote(num_cpus=0.1)
    def pid():
        return os.getpid()

    first = ray_tpu.get([pid.remote() for _ in range(3)], timeout=60)
    second = ray_tpu.get([pid.remote() for _ in range(3)], timeout=60)
    assert set(first) & set(second), "idle workers were not reused"


def test_prestart_workers(monkeypatch):
    monkeypatch.setenv("RAY_TPU_PRESTART_WORKERS", "2")
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.wait_for_nodes()
    try:
        nl = c.nodelets[0]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with nl._lock:
                if len(nl._idle_workers) >= 2:
                    break
            time.sleep(0.2)
        with nl._lock:
            assert len(nl._idle_workers) >= 2
    finally:
        c.shutdown()
        monkeypatch.delenv("RAY_TPU_PRESTART_WORKERS", raising=False)
