"""ray_tpu.data tests (reference model: python/ray/data/tests —
transform semantics, streaming, actor compute, Train ingestion)."""

import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_range_count_sum(cluster):
    ds = rd.range(100, parallelism=8)
    assert ds.count() == 100
    assert ds.sum() == 4950
    assert ds.num_blocks() == 8


def test_map_filter_chain_fused(cluster):
    ds = rd.range(50).map(lambda x: x * 2).filter(lambda x: x % 4 == 0)
    out = sorted(ds.take_all())
    assert out == [x * 2 for x in range(50) if (x * 2) % 4 == 0]


def test_flat_map(cluster):
    ds = rd.from_items([1, 2, 3], parallelism=2).flat_map(
        lambda x: [x] * x)
    assert sorted(ds.take_all()) == [1, 2, 2, 3, 3, 3]


def test_map_batches_numpy(cluster):
    ds = rd.from_items([{"x": float(i)} for i in range(32)], parallelism=4)
    out = ds.map_batches(lambda b: {"y": b["x"] * 10}).take_all()
    assert sorted(r["y"] for r in out) == [i * 10.0 for i in range(32)]


def test_map_batches_actor_pool(cluster):
    class_state_marker = []  # noqa: F841

    def heavy(b):
        return {"y": b["x"] + 1}

    ds = rd.from_items([{"x": float(i)} for i in range(24)], parallelism=6)
    out = ds.map_batches(heavy, compute="actors", num_actors=2).take_all()
    assert sorted(r["y"] for r in out) == [i + 1.0 for i in range(24)]


def test_iter_batches_rebatching(cluster):
    ds = rd.range(25, parallelism=4)
    batches = list(ds.iter_batches(batch_size=10))
    sizes = [len(b) for b in batches]
    assert sizes == [10, 10, 5]
    assert int(np.concatenate(batches).sum()) == 300


def test_shard_for_train_ingestion(cluster):
    ds = rd.range(64, parallelism=8).map(lambda x: x + 1)
    shards = ds.split(2)
    all_rows = sorted(shards[0].take_all() + shards[1].take_all())
    assert all_rows == list(range(1, 65))
    assert shards[0].num_blocks() == 4


def test_repartition_and_materialize(cluster):
    ds = rd.range(40, parallelism=4).map(lambda x: x * 3)
    m = ds.materialize()
    assert m.num_blocks() == 4
    r = m.repartition(10)
    assert r.num_blocks() == 10
    assert sorted(r.take_all()) == [x * 3 for x in range(40)]


def test_take_streams_lazily(cluster):
    ds = rd.range(1000, parallelism=16).map(lambda x: x)
    assert len(ds.take(5)) == 5


def test_read_text_and_write_jsonl(cluster, tmp_path):
    for i in range(3):
        (tmp_path / f"f{i}.txt").write_text(f"line-{i}a\nline-{i}b\n")
    ds = rd.read_text(str(tmp_path / "*.txt"))
    rows = sorted(ds.take_all())
    assert rows == sorted(f"line-{i}{s}" for i in range(3) for s in "ab")
    out = ds.map(lambda line: {"text": line}).write_jsonl(
        str(tmp_path / "out"))
    assert len(out) == 3
    back = rd.read_json(str(tmp_path / "out")).take_all()
    assert sorted(r["text"] for r in back) == rows


def test_read_csv(cluster, tmp_path):
    (tmp_path / "d.csv").write_text("a,b\n1,x\n2,y\n")
    rows = rd.read_csv(str(tmp_path / "d.csv")).take_all()
    assert rows == [{"a": "1", "b": "x"}, {"a": "2", "b": "y"}]


def test_native_lineio_matches_python(tmp_path):
    """The native mmap line scanner (third C++ component) agrees with
    Python file iteration on edge cases."""
    from ray_tpu.data.lineio import _lineio_lib, read_lines

    cases = {
        "plain": "a\nbb\nccc\n",
        "no_trailing_newline": "x\ny",
        "empty_lines": "\n\na\n\n",
        "empty_file": "",
        "one_line": "only",
    }
    for name, content in cases.items():
        p = tmp_path / f"{name}.txt"
        p.write_text(content)
        expected = content.splitlines()
        assert read_lines(str(p)) == expected, name
    assert _lineio_lib() is not None, "native lineio failed to build"


def test_native_lineio_keep_newlines_and_errors(tmp_path):
    """strip_newline=False matches text-mode iteration exactly, and
    open errors surface like the fallback (no FileNotFoundError
    masking)."""
    import pytest as _pytest

    from ray_tpu.data.lineio import read_lines

    p = tmp_path / "t.txt"
    p.write_text("a\nb")  # unterminated final line
    assert read_lines(str(p), strip_newline=False) == ["a\n", "b"]
    p2 = tmp_path / "crlf.txt"
    p2.write_bytes(b"x\r\ny\r\n")
    assert read_lines(str(p2)) == ["x", "y"]
    with _pytest.raises(FileNotFoundError):
        read_lines(str(tmp_path / "missing.txt"))
    with _pytest.raises(IsADirectoryError):
        read_lines(str(tmp_path))


def test_iter_jax_batches_sharded_device_arrays(cluster):
    """VERDICT r3 item 9: the device-feed iterator yields GLOBAL jax
    arrays sharded over the mesh's replica axes, fixed batch shape."""
    import jax
    import numpy as np

    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=4, fsdp=2), devices=jax.devices()[:8])
    ds = rd.from_items(
        [{"x": np.full((4,), i, np.float32), "y": i} for i in range(50)],
        parallelism=5)
    seen = 0
    for batch in ds.iter_jax_batches(batch_size=16, mesh=mesh):
        assert isinstance(batch["x"], jax.Array)
        assert batch["x"].shape == (16, 4)
        assert batch["y"].shape == (16,)
        # batch dim actually sharded over data x fsdp = 8 devices
        assert len(batch["x"].sharding.device_set) == 8
        shard_rows = {s.data.shape[0] for s in batch["x"].addressable_shards}
        assert shard_rows == {2}  # 16 rows / 8 replicas
        seen += 1
    assert seen == 3  # 50 rows -> 3 full batches, partial dropped


def test_iter_jax_batches_unsharded_and_last_batch(cluster):
    import jax

    ds = rd.range(20, parallelism=2)
    batches = list(ds.iter_jax_batches(batch_size=8, drop_last=False))
    assert [b.shape[0] for b in batches] == [8, 8, 4]
    assert all(isinstance(b, jax.Array) for b in batches)


def test_limit_pushdown_and_global_cap(cluster):
    """Dataset.limit caps rows globally; the optimizer pushes it past
    1:1 maps (visible in explain()) so capped rows skip upstream work."""
    ds = rd.range(1000, parallelism=8).map(lambda x: x * 2).limit(5)
    assert "Limit" in ds.explain()
    assert ds.take_all() == [0, 2, 4, 6, 8]
    assert ds.count() == 5


def test_read_datasource_custom(cluster):
    class Squares(rd.Datasource):
        def get_read_tasks(self, parallelism):
            return [rd.ReadTask(lambda lo=lo: [x * x for x in
                                               builtins_range(lo, lo + 5)])
                    for lo in (0, 5)]

    from builtins import range as builtins_range

    ds = rd.read_datasource(Squares())
    assert sorted(ds.take_all()) == sorted(x * x for x in range(10))


def test_limit_global_before_non_one_to_one(cluster):
    """A limit FOLLOWED by non-1:1 ops must stay a GLOBAL cap — naive
    per-block limiting would leak n rows per block downstream."""
    out = (rd.range(20, parallelism=2).limit(5)
           .flat_map(lambda r: [r, r]).take_all())
    assert sorted(out) == sorted([r for x in range(5) for r in (x, x)])
    # and through an all-to-all exchange
    shuffled = rd.range(100, parallelism=4).limit(7).random_shuffle(seed=1)
    assert sorted(shuffled.take_all()) == list(range(7))
    assert rd.range(50, parallelism=4).limit(9).count() == 9


def test_limit_respected_by_writers_and_materialize(cluster, tmp_path):
    """write_*/materialize enforce the GLOBAL limit too (a per-block
    slice would write n rows per block)."""
    ds = rd.range(100, parallelism=8).limit(5)
    assert ds.materialize().count() == 5
    files = ds.write_jsonl(str(tmp_path / "j"))
    import json

    rows = [json.loads(line) for p in files for line in open(p)]
    assert sorted(rows) == list(range(5))
    assert repr(ds)  # plan repr uses operator names
