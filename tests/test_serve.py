"""Serve tests (reference model: serve/tests — deploy, route, scale,
HTTP ingress)."""

import json
import sys
import urllib.request

import cloudpickle
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 8})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    serve.shutdown()
    ray_tpu.shutdown()
    c.shutdown()


def test_deploy_and_call(cluster):
    @serve.deployment(num_replicas=2)
    class Doubler:
        def __init__(self, bias):
            self.bias = bias

        def __call__(self, x):
            return 2 * x + self.bias

    handle = serve.run(Doubler.bind(5), name="doubler")
    results = ray_tpu.get([handle.remote(i) for i in range(10)], timeout=60)
    assert results == [2 * i + 5 for i in range(10)]
    serve.delete("doubler")


def test_function_deployment(cluster):
    @serve.deployment
    def greeter(name):
        return f"hello {name}"

    handle = serve.run(greeter.bind(), name="greet")
    assert ray_tpu.get(handle.remote("tpu"), timeout=60) == "hello tpu"
    serve.delete("greet")


def test_replicas_share_load(cluster):
    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _):
            return self.pid

    handle = serve.run(WhoAmI.bind(), name="who")
    pids = set(ray_tpu.get([handle.remote(None) for _ in range(20)],
                           timeout=60))
    assert len(pids) == 2  # both replicas served traffic
    serve.delete("who")


def test_method_routing_and_handle_reacquire(cluster):
    @serve.deployment(num_replicas=1)
    class Store:
        def __init__(self):
            self.v = {}

        def put(self, k, val):
            self.v[k] = val
            return "ok"

        def get(self, k):
            return self.v.get(k)

    serve.run(Store.bind(), name="store")
    handle = serve.get_app_handle("store")
    assert ray_tpu.get(handle.method("put")("a", 1), timeout=60) == "ok"
    assert ray_tpu.get(handle.method("get")("a"), timeout=60) == 1
    serve.delete("store")


def test_http_ingress(cluster):
    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"echo": payload}

    serve.run(Echo.bind(), name="echo", http_port=18123)
    req = urllib.request.Request(
        "http://127.0.0.1:18123/echo",
        data=json.dumps({"msg": "hi"}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.loads(resp.read())
    assert body["result"]["echo"] == {"msg": "hi"}
    serve.delete("echo")


def test_autoscaling_up_and_down(cluster):
    """Replica count tracks load (reference: serve autoscaling on mean
    ongoing requests) and the handle's routing set refreshes."""
    @serve.deployment(
        num_replicas=1,
        max_ongoing_requests=32,
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_ongoing_requests": 2.0,
                            "downscale_idle_rounds": 2})
    class Slow:
        def __call__(self, _):
            import time as _t

            _t.sleep(0.4)
            return "ok"

    handle = serve.run(Slow.bind(), name="auto")
    import time

    ctrl = ray_tpu.get_actor("__serve_controller")

    def replica_count():
        return len(ray_tpu.get(ctrl.get_replicas.remote("auto"),
                               timeout=30)["replicas"])

    assert replica_count() == 1
    # sustained burst: keep ~12 requests in flight for a few seconds
    refs = []
    deadline = time.monotonic() + 15
    grew = False
    while time.monotonic() < deadline:
        refs = [r for r in refs
                if not ray_tpu.wait([r], timeout=0)[0]]
        while len(refs) < 12:
            refs.append(handle.remote(None))
        if replica_count() >= 2:
            grew = True
            break
        time.sleep(0.2)
    assert grew, "autoscaler never added a replica under load"
    for r in refs:
        try:
            ray_tpu.get(r, timeout=60)
        except Exception:
            pass
    # idle: scales back toward min
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if replica_count() == 1:
            break
        time.sleep(0.5)
    assert replica_count() == 1
    serve.delete("auto")


# ---------------------------------------------------------------------------
# App graphs / composition + proxy-actor ingress (VERDICT r2 item 10)
# Reference: serve/_private/build_app.py:68, _private/proxy.py
# ---------------------------------------------------------------------------

def test_deployment_composition_pipeline(cluster):
    """Model deployment receives a bound Preprocess app; its replicas
    call it via an injected DeploymentHandle."""

    @serve.deployment(num_replicas=2)
    class Preprocess:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre  # DeploymentHandle injected by the app graph

        def __call__(self, x):
            import ray_tpu as rt

            doubled = rt.get(self.pre.remote(x), timeout=60)
            return doubled + 1

    handle = serve.run(Model.bind(Preprocess.bind()), name="pipeline")
    out = ray_tpu.get([handle.remote(i) for i in range(5)], timeout=60)
    assert out == [2 * i + 1 for i in range(5)]
    serve.delete("pipeline")
    serve.delete("pipeline--Preprocess")


def test_http_ingress_via_proxy_actor(cluster):
    """Two-deployment pipeline served over HTTP by the PROXY ACTOR (a
    non-driver process bound on the node IP)."""
    import json
    import urllib.request

    @serve.deployment
    class Upper:
        def __call__(self, s):
            return s.upper()

    @serve.deployment
    class Greeter:
        def __init__(self, upper):
            self.upper = upper

        def __call__(self, name):
            import ray_tpu as rt

            loud = rt.get(self.upper.remote(name), timeout=60)
            return f"HELLO {loud}"

    serve.run(Greeter.bind(Upper.bind()), name="greet")
    addr = serve.start_proxy(port=0)
    req = urllib.request.Request(
        f"http://{addr}/greet",
        data=json.dumps("world").encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.loads(resp.read())
    assert body["result"] == "HELLO WORLD"
    # the proxy is a named actor in its own worker process, not the driver
    assert ray_tpu.get_actor("__serve_proxy") is not None
    serve.delete("greet")
    serve.delete("greet--Upper")


def test_push_updates_routing_staleness(cluster):
    """VERDICT r3 item 9: replica-set changes are PUSHED via the head's
    long-poll pubsub — a live handle converges on the new replica set in
    well under the old 2s poll interval."""
    import time

    @serve.deployment(num_replicas=1)
    class V1:
        def __call__(self, x):
            return "v1"

    @serve.deployment(num_replicas=1)
    class V2:
        def __call__(self, x):
            return "v2"

    handle = serve.run(V1.bind(), name="pushapp")
    assert ray_tpu.get(handle.remote(0), timeout=60) == "v1"

    # redeploy: the old replica dies, the version bumps, and a push (not
    # the 15s fallback poll) must update this existing handle
    serve.run(V2.bind(), name="pushapp")
    t0 = time.monotonic()
    deadline = t0 + 5.0
    got = None
    while time.monotonic() < deadline:
        try:
            got = ray_tpu.get(handle.remote(0), timeout=10)
            if got == "v2":
                break
        except Exception:  # noqa: BLE001
            pass  # old replica mid-teardown
        time.sleep(0.05)
    elapsed = time.monotonic() - t0
    assert got == "v2", f"handle still stale after {elapsed:.1f}s"
    assert elapsed < 4.0, f"push should beat the poll fallback: {elapsed:.1f}s"
    serve.delete("pushapp")


def test_handle_version_monotonic_across_redeploys(cluster):
    """Redeploying must not reset the version handles compare against
    (a version that restarts at 0 makes every handle ignore the new
    replica set forever)."""

    @serve.deployment(num_replicas=1)
    class App:
        def __call__(self, x):
            return x + 1

    serve.run(App.bind(), name="ver")
    ctrl = serve.api._controller()
    v1 = ray_tpu.get(ctrl.get_replicas.remote("ver"), timeout=30)["version"]
    serve.run(App.bind(), name="ver")
    v2 = ray_tpu.get(ctrl.get_replicas.remote("ver"), timeout=30)["version"]
    assert v2 > v1, (v1, v2)
    serve.delete("ver")


# ---------------------------------------------------------------------------
# Streaming responses + per-node proxy fleet (VERDICT r4 item 6)
# Reference: serve/_private/proxy.py (proxy per node, response
# streaming), serve/handle.py (handle.options(stream=True))


def test_streaming_handle(cluster):
    import time

    @serve.deployment
    class Tokens:
        def __call__(self, n):
            for i in range(n):
                yield f"tok{i}"
                time.sleep(0.05)

    h = serve.run(Tokens.bind(), name="tok")
    gen = h.options(stream=True).remote(4)
    t0 = time.monotonic()
    first = ray_tpu.get(next(gen))
    dt = time.monotonic() - t0
    assert first == "tok0"
    assert dt < 2.0, f"first chunk took {dt:.1f}s — not streamed"
    rest = [ray_tpu.get(r) for r in gen]
    assert rest == ["tok1", "tok2", "tok3"]
    serve.delete("tok")


def test_http_streaming_endpoint(cluster):
    import time

    @serve.deployment
    class Chunks:
        def __call__(self, body):
            for i in range(3):
                yield {"chunk": i}
                time.sleep(0.3)

    serve.run(Chunks.bind(), name="chunks")
    addr = serve.start_proxy(port=0)
    url = f"http://{addr}/chunks?stream=1"
    req = urllib.request.Request(
        url, data=b"null", headers={"Content-Type": "application/json"})
    t0 = time.monotonic()
    resp = urllib.request.urlopen(req, timeout=60)
    first_line = resp.readline()
    t_first = time.monotonic() - t0
    assert json.loads(first_line)["result"] == {"chunk": 0}
    assert t_first < 3.0, f"first chunk after {t_first:.1f}s — buffered"
    lines = [json.loads(l) for l in resp.read().splitlines() if l.strip()]
    assert [l["result"]["chunk"] for l in lines] == [1, 2]
    serve.delete("chunks")


def test_proxy_fleet_two_nodes_and_state_metrics(cluster):
    """Proxies on BOTH nodes (node-affinity pinned), each serving HTTP,
    with request metrics visible through the state API (reference:
    per-node proxies, _private/proxy.py + default_impl.py)."""
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, body):
            return {"echo": body}

    serve.run(Echo.bind(), name="fleet-echo")
    fleet = serve.start_proxy_fleet(port=0)
    assert len(fleet) >= 2, f"expected >=2 node proxies, got {fleet}"
    node_ids = set(fleet)
    assert len(node_ids) == len(fleet)  # one per distinct node
    for nid, addr in fleet.items():
        req = urllib.request.Request(
            f"http://{addr}/fleet-echo", data=json.dumps(42).encode(),
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert out["result"] == {"echo": 42}
    from ray_tpu.util.state import serve_status

    st = serve_status()
    assert "fleet-echo" in st["apps"]
    by_node = {p["node_id"]: p for p in st["proxies"]}
    for nid in fleet:
        assert by_node[nid]["requests"] >= 1, by_node
    serve.delete("fleet-echo")


def test_grpc_ingress_unary_and_streaming(cluster):
    """gRPC ingress beside HTTP (reference: serve's per-node gRPC
    proxy): unary Predict and server-streaming PredictStreaming, app
    routed by 'application' metadata."""
    import time

    import grpc

    @serve.deployment
    class G:
        def __call__(self, body):
            return {"doubled": (body or 0) * 2}

    @serve.deployment
    class GS:
        def __call__(self, body):
            for i in range(3):
                yield {"i": i}
                time.sleep(0.05)

    serve.run(G.bind(), name="gapp")
    serve.run(GS.bind(), name="gstream")
    serve.start_proxy(port=0)
    addr = serve.grpc_proxy_address()
    channel = grpc.insecure_channel(addr)
    ident = lambda b: b  # noqa: E731
    predict = channel.unary_unary("/ray_tpu.serve.Serve/Predict",
                                  request_serializer=ident,
                                  response_deserializer=ident)
    out = json.loads(predict(json.dumps(21).encode(),
                             metadata=(("application", "gapp"),),
                             timeout=60))
    assert out["result"] == {"doubled": 42}

    stream = channel.unary_stream("/ray_tpu.serve.Serve/PredictStreaming",
                                  request_serializer=ident,
                                  response_deserializer=ident)
    chunks = [json.loads(c)["result"] for c in
              stream(b"null",
                     metadata=(("application", "gstream"),), timeout=60)]
    assert chunks == [{"i": 0}, {"i": 1}, {"i": 2}]

    # unknown app surfaces a gRPC error, not a hang
    with pytest.raises(grpc.RpcError):
        predict(b"1", metadata=(("application", "nope"),), timeout=30)
    # grpc requests visible in proxy metrics
    st = serve.status()
    assert any(p.get("grpc", 0) >= 3 for p in st["proxies"])
    channel.close()
    serve.delete("gapp")
    serve.delete("gstream")
