"""Compiled-DAG fast-path gates (ISSUE 11).

Covers the three contracts COMPONENTS.md's fast-path section promises:

- BIT-PARITY: `dag.compile().execute()` returns exactly what the eager
  `.remote()` chain returns — same outputs, same error type, same
  cause — for chains, fans, and mid-chain failures.
- HEAD-FREE STEADY STATE: after compile, execute() performs ZERO head
  or nodelet RPCs (asserted on the live servers' per-method event
  stats) and records `dag.execute` spans for attribution.
- CHAOS: killing a mid-chain actor flips the DAG to the eager fallback
  (replaying retained inputs) or fails cleanly with the same error the
  eager path raises — with no leaked channel slots (shm segments) and
  no stranded owned oids.
"""

import gc
import glob
import sys
import threading
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.core.exceptions import ActorDiedError, RayTpuError, TaskError

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def ray_boot():
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote(num_cpus=0.2)
class Stage:
    def __init__(self, add):
        self.add = add

    def step(self, x):
        if x == "boom":
            raise ValueError("dag boom")
        return x + self.add

    def join(self, a, b):
        return a + b


def _chain_dag(actors):
    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        y = inp
        for a in actors:
            y = a.step.bind(y)
    return y


def _eager_chain(actors, x):
    ref = x
    for a in actors:
        ref = a.step.remote(ref)
    return ray_tpu.get(ref, timeout=60)


def test_bit_parity_with_eager_chain(ray_boot):
    """The gate: same inputs through compile().execute() and through
    the eager .remote() chain produce identical outputs, and a failing
    input raises the SAME TaskError with the same cause."""
    actors = [Stage.remote(i + 1) for i in range(3)]
    ray_tpu.get([a.step.remote(0) for a in actors])
    dag = _chain_dag(actors).compile()
    try:
        inputs = list(range(10)) + [-5, 1000000]
        compiled = [dag.execute(x).get() for x in inputs]
        eager = [_eager_chain(actors, x) for x in inputs]
        assert compiled == eager
        # error propagation parity: type, cause type, and message match
        with pytest.raises(TaskError) as ce:
            dag.execute("boom").get()
        with pytest.raises(TaskError) as ee:
            _eager_chain(actors, "boom")
        assert type(ce.value.cause) is type(ee.value.cause)
        assert str(ce.value.cause) == str(ee.value.cause) == "dag boom"
        # the pipeline stays aligned after an error slot
        assert dag.execute(7).get() == _eager_chain(actors, 7)
    finally:
        dag.teardown()
        for a in actors:
            ray_tpu.kill(a)


def test_multi_output_parity(ray_boot):
    from ray_tpu.dag import InputNode, MultiOutputNode

    a, b = Stage.remote(10), Stage.remote(20)
    ray_tpu.get([a.step.remote(0), b.step.remote(0)])
    with InputNode() as inp:
        out = MultiOutputNode([a.step.bind(inp), b.step.bind(inp)])
    dag = out.compile()
    try:
        for x in (0, 3, 8):
            assert dag.execute(x).get() == ray_tpu.get(
                [a.step.remote(x), b.step.remote(x)], timeout=60)
    finally:
        dag.teardown()
        ray_tpu.kill(a)
        ray_tpu.kill(b)


def test_steady_state_skips_head_and_nodelet(ray_boot):
    """THE fast-path assertion: after compile, N executions cost ZERO
    head RPCs and ZERO nodelet scheduling RPCs — intermediate results
    flow worker→worker through the channel slots; the driver only
    touches shared memory. dag.execute spans record the attribution."""
    from ray_tpu.core.api import _global_runtime

    rt = _global_runtime()
    head, nodelet = rt._booted[0], rt._booted[1]
    actors = [Stage.remote(1) for _ in range(2)]
    ray_tpu.get([a.step.remote(0) for a in actors])
    dag = _chain_dag(actors).compile()
    try:
        assert dag.execute(0).get() == 2  # pipeline warm
        rt._events.drain()  # start span capture fresh
        before_h = {m: s["count"]
                    for m, s in head.server.event_stats().items()}
        before_n = {m: s["count"]
                    for m, s in nodelet.server.event_stats().items()}
        n = 50
        refs = [dag.execute(i) for i in range(n)]
        assert [r.get() for r in refs] == [i + 2 for i in range(n)]
        after_h = head.server.event_stats()
        after_n = nodelet.server.event_stats()
        for m in ("get_actor", "create_actor", "kv_put", "kv_get"):
            assert after_h.get(m, {}).get("count", 0) == \
                before_h.get(m, 0), f"head rpc {m} on the compiled path"
        for m in ("schedule_task", "schedule_tasks", "request_lease",
                  "start_actor"):
            assert after_n.get(m, {}).get("count", 0) == \
                before_n.get(m, 0), f"nodelet rpc {m} on the compiled path"
        # the span plane still attributes every execution
        spans = rt._events.drain()
        dag_spans = [s for s in spans if s["cat"] == "dag"]
        assert len(dag_spans) >= n
    finally:
        dag.teardown()
        for a in actors:
            ray_tpu.kill(a)


def test_channel_slots_are_reused_and_released(ray_boot):
    """Compile allocates a FIXED set of channel slots; repeated
    execution mints no new segments, teardown unlinks every one."""
    actors = [Stage.remote(1) for _ in range(2)]
    ray_tpu.get([a.step.remote(0) for a in actors])
    before = set(glob.glob("/dev/shm/dagc_*"))
    dag = _chain_dag(actors).compile()
    created = set(glob.glob("/dev/shm/dagc_*")) - before
    assert len(created) == 3  # input edge, a->b edge, output edge
    try:
        refs = [dag.execute(i) for i in range(100)]
        [r.get() for r in refs]
        assert set(glob.glob("/dev/shm/dagc_*")) - before == created
    finally:
        dag.teardown()
        for a in actors:
            ray_tpu.kill(a)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and \
            set(glob.glob("/dev/shm/dagc_*")) & created:
        time.sleep(0.05)
    assert not set(glob.glob("/dev/shm/dagc_*")) & created, \
        "teardown leaked channel slots"


def test_backpressure_bounds_inflight(ray_boot):
    """A fast submitter cannot overrun a slow consumer: execute()
    blocks at max_inflight, results stay correct and ordered."""

    @ray_tpu.remote(num_cpus=0.2)
    class SlowStage:
        def step(self, x):
            time.sleep(0.02)
            return x * 2

    s = SlowStage.remote()
    ray_tpu.get(s.step.remote(0))
    dag = _chain_dag([s]).compile(max_inflight=4)
    try:
        n = 24
        seen_inflight = []
        done = threading.Event()

        def sample():
            while not done.is_set():
                seen_inflight.append(dag._seq - dag._fetched)
                time.sleep(0.005)

        t = threading.Thread(target=sample, daemon=True)
        t.start()
        refs = []

        def producer():
            for i in range(n):
                refs.append(dag.execute(i))

        p = threading.Thread(target=producer, daemon=True)
        p.start()
        out = []
        deadline = time.monotonic() + 30
        while len(out) < n and time.monotonic() < deadline:
            if len(refs) > len(out):
                out.append(refs[len(out)].get(timeout=30))
        done.set()
        p.join(timeout=10)
        t.join(timeout=2)
        assert out == [i * 2 for i in range(n)]
        assert max(seen_inflight) <= 4, \
            f"backpressure breached: {max(seen_inflight)} in flight"
    finally:
        dag.teardown()
        ray_tpu.kill(s)


def test_concurrent_executors_keep_seq_order(ray_boot):
    """Two threads calling execute() concurrently: channel writes are
    serialized in seq order, so every ref resolves to ITS input's
    result (a swapped write would silently cross the answers)."""

    @ray_tpu.remote(num_cpus=0.2)
    class Echo:
        def step(self, x):
            return x

    e = Echo.remote()
    ray_tpu.get(e.step.remote(0))
    dag = _chain_dag([e]).compile()
    try:
        results = {}
        lock = threading.Lock()

        def producer(base):
            for i in range(60):
                v = base + i
                r = dag.execute(v)
                with lock:
                    results[r._seq] = v

        ts = [threading.Thread(target=producer, args=(b,), daemon=True)
              for b in (0, 1000)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert len(results) == 120
        for seq, v in sorted(results.items()):
            assert CompiledDAGRefValue(dag, seq) == v
    finally:
        dag.teardown()
        ray_tpu.kill(e)


def CompiledDAGRefValue(dag, seq):
    from ray_tpu.dag import CompiledDAGRef

    return CompiledDAGRef(dag, seq).get(timeout=60)


def test_chaos_mid_chain_death_falls_back_cleanly(ray_boot):
    """Kill the middle actor of a 3-stage chain with executions in
    flight: pending executions land through the eager fallback with
    the SAME error an eager chain raises (ActorDiedError for the dead
    stage), nothing hangs, and neither channel slots nor owned oids
    leak."""
    from ray_tpu.core.api import _global_runtime

    rt = _global_runtime()
    actors = [Stage.remote(i + 1) for i in range(3)]
    ray_tpu.get([a.step.remote(0) for a in actors])
    gc.collect()
    time.sleep(0.3)  # let queued frees drain
    owned_before = len(rt._owned)
    shm_before = set(glob.glob("/dev/shm/dagc_*"))
    dag = _chain_dag(actors).compile()
    try:
        assert dag.execute(1).get() == 7
        ray_tpu.kill(actors[1])
        time.sleep(0.3)
        refs = [dag.execute(i) for i in range(4)]
        for r in refs:
            with pytest.raises(RayTpuError):
                # ActorDiedError (death seen at submit) or TaskError
                # wrapping it (death seen by the running call) — the
                # same surface the eager chain has
                r.get(timeout=30)
        assert dag._broken  # fallback engaged, channels abandoned
        # a LATER execute goes straight to the eager path and fails
        # identically — no hang, no desync
        with pytest.raises(RayTpuError):
            dag.execute(99).get(timeout=30)
        with pytest.raises((RayTpuError,)):
            _eager_chain(actors, 99)
    finally:
        dag.teardown()
        for a in (actors[0], actors[2]):
            ray_tpu.kill(a)
        ray_tpu.kill(actors[1], no_restart=True)
    # no leaked channel slots
    deadline = time.monotonic() + 5
    created = set(glob.glob("/dev/shm/dagc_*")) - shm_before
    while time.monotonic() < deadline and created:
        time.sleep(0.05)
        created = set(glob.glob("/dev/shm/dagc_*")) - shm_before
    assert not created, "chaos path leaked channel slots"
    # no stranded oids: the fallback's intermediate refs release once
    # their handles drop
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        gc.collect()
        if len(rt._owned) <= owned_before + 2:
            break
        time.sleep(0.1)
    assert len(rt._owned) <= owned_before + 2, \
        f"stranded oids: {len(rt._owned)} vs {owned_before}"


def _actor_state(handle):
    """Head's view of an actor: (state, address)."""
    from ray_tpu.core.api import _global_runtime

    rt = _global_runtime()
    r = rt.client.call(rt.head_address, "get_actor",
                       {"actor_id": handle._actor_id.binary(),
                        "wait": False}, timeout=10)
    return r.get("state"), r.get("address")


def _await_actor_settled(handle, old_address, deadline_s=120.0):
    """Deterministic post-heal settle barrier (the ROADMAP-noted
    module-context-load flake: the old wait loop only proved ONE eager
    call landed, which can race the heal while the head still
    publishes the dying incarnation's address — the DAG's fallback
    probe then sees ALIVE at the OLD address and keeps polling its
    dead channels). Event-gate on the actual replay preconditions:
    the head reports the actor ALIVE at a NEW address, AND an eager
    call through the handle completes against that incarnation."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            state, address = _actor_state(handle)
        except Exception:  # noqa: BLE001  # head briefly busy under load
            time.sleep(0.2)
            continue
        if state == "ALIVE" and address and address != old_address:
            try:
                ray_tpu.get(handle.step.remote(0), timeout=30)
                return address
            except RayTpuError:
                pass  # replacement not serving yet (at-most-once race)
        time.sleep(0.2)
    raise TimeoutError("actor did not settle at a new incarnation "
                       f"within {deadline_s}s")


def test_chaos_restartable_actor_replays_through_fallback(ray_boot):
    """A restartable mid-chain actor: the heal plane republishes its
    routing and the eager fallback REPLAYS retained inputs through the
    restarted incarnation — executions complete with correct values.
    Post-heal execution is gated on `_await_actor_settled` (ALIVE at a
    NEW address + a served eager call) and the replay window is wide:
    under module-context load the respawn alone can take tens of
    seconds, and the old one-successful-call wait raced the routing
    republish."""
    a = Stage.remote(1)
    b = Stage.options(max_restarts=1).remote(10)
    c = Stage.remote(100)
    ray_tpu.get([a.step.remote(0), b.step.remote(0), c.step.remote(0)])
    _, b_addr0 = _actor_state(b)
    dag = _chain_dag([a, b, c]).compile()
    try:
        assert dag.execute(0).get() == 111
        ray_tpu.kill(b, no_restart=False)
        # settle barrier: the replacement incarnation is published AND
        # serving before the DAG replays through it
        _await_actor_settled(b, b_addr0)
        refs = [dag.execute(i) for i in range(3)]
        # the fallback resolves the restarted incarnation (stages are
        # stateless, so replay values match the compiled path exactly)
        assert [r.get(timeout=120) for r in refs] == [111 + i for i in
                                                     range(3)]
        assert dag._broken
    finally:
        dag.teardown()
        for x in (a, b, c):
            ray_tpu.kill(x)
