"""Headline benchmark: GPT-2-small SPMD training throughput per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

Baseline: the reference's flagship Train config is "TorchTrainer
GPT-2-small DDP" (BASELINE.json). No per-chip token throughput is
archived in the reference's release logs, so we use a nominal
NCCL/GPU-era DDP figure of 30,000 tokens/s per accelerator for
GPT-2-small (bf16, torch DDP on A100-class hardware, nanoGPT-style
measurement) as vs_baseline=1.0.
"""

from __future__ import annotations

import json
import time

BASELINE_TOKENS_PER_SEC_PER_CHIP = 30_000.0

_PPO_SNIPPET = """
import jax, json, statistics, time
jax.config.update("jax_platforms", "cpu")
from ray_tpu.rllib import PPOConfig
algo = (PPOConfig().environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                     rollout_fragment_length=128)
        .training(num_sgd_iter=6, minibatch_size=256)).build()
algo.train(); algo.train(); algo.train()  # compile + cache warmup
# one sample = 4 iterations (~8k env steps): single-iteration samples
# are ~70ms and swing +-15% from scheduler noise alone
rates = []
for _ in range(7):
    t0 = time.perf_counter()
    steps = sum(algo.train()["num_env_steps_sampled"] for _ in range(4))
    rates.append(steps / (time.perf_counter() - t0))
print(json.dumps({"median": statistics.median(rates),
                  "stdev": statistics.pstdev(rates),
                  "max": max(rates)}))
"""


_ZERO1_SNIPPET = """
import json, time, os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp, optax
from ray_tpu.models.gpt2 import (GPT2Config, gpt2_loss,
                                 gpt2_partition_rules, init_gpt2)
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.ops import collective_op_counts
from ray_tpu.train.spmd import (batch_shardings, init_sharded_state,
                                make_train_step, optimizer_state_bytes)

cfg = GPT2Config.tiny()
mesh = build_mesh(MeshSpec(data=8))
rules = gpt2_partition_rules()
tx = optax.adamw(3e-4, weight_decay=0.1)
B, T, steps, warmup = 16, 128, 5, 2
toks = jax.random.randint(jax.random.PRNGKey(1), (B, T + 1), 0,
                          cfg.vocab_size, jnp.int32)
batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
batch = jax.device_put(batch, batch_shardings(mesh, batch))
out = {"data_axis": 8, "batch": B, "seq": T}
for name, shard in (("replicated", False), ("zero1", True)):
    state = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh, rules,
        shard_optimizer=shard)
    step = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), tx,
                           shard_optimizer=shard, mesh=mesh, rules=rules)
    opt_bytes = optimizer_state_bytes(state.opt_state)
    with mesh:
        for _ in range(warmup):
            state, m = step(state, batch)
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, batch)
        loss = float(m["loss"])
        dt = time.perf_counter() - t0
        census = collective_op_counts(
            step.jitted.lower(state, batch).compile().as_text())
    out[name] = {"tokens_per_sec": round(B * T * steps / dt, 1),
                 "opt_bytes_per_chip": opt_bytes,
                 "loss": round(loss, 6), "collectives": census}
out["opt_bytes_ratio"] = round(
    out["zero1"]["opt_bytes_per_chip"]
    / out["replicated"]["opt_bytes_per_chip"], 4)
out["loss_delta"] = round(abs(out["zero1"]["loss"]
                              - out["replicated"]["loss"]), 8)
print(json.dumps(out))
"""


def _zero1_bench_subprocess() -> dict:
    """ZeRO-1 A/B on an 8-virtual-device CPU mesh (data=8): per-chip
    optimizer bytes replicated vs sharded (the 1/8 memory win the test
    suite also gates), tokens/s for both step programs, the end loss
    delta, and each compiled program's collective op census. A smoke-
    scale shape of the TPU scenario — on hardware the freed HBM buys a
    larger per-chip batch (RAY_TPU_BENCH_ZERO1_BATCH drives that run,
    see main())."""
    import json as _json
    import os
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c", _ZERO1_SNIPPET], capture_output=True,
            text=True, timeout=600,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        return _json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001 - secondary scenario, best-effort
        return {}


_ZERO_LADDER_SNIPPET = """
import json, time, os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp, optax
from ray_tpu.models.gpt2 import (GPT2Config, gpt2_loss,
                                 gpt2_partition_rules, init_gpt2)
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.ops import collective_op_counts
from ray_tpu.train.spmd import (batch_shardings, init_sharded_state,
                                make_train_step, optimizer_state_bytes)

cfg = GPT2Config.tiny()
mesh = build_mesh(MeshSpec(data=8))
rules = gpt2_partition_rules()
tx = optax.adamw(3e-4, weight_decay=0.1)
B, T, steps, warmup, accum = 16, 128, 4, 2, 2
toks = jax.random.randint(jax.random.PRNGKey(1), (B, T + 1), 0,
                          cfg.vocab_size, jnp.int32)
batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
batch = jax.device_put(batch, batch_shardings(mesh, batch))
out = {"data_axis": 8, "batch": B, "seq": T, "accum_steps": accum}
for stage in (0, 1, 2, 3):
    state = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh, rules,
        zero_stage=stage, accum_steps=accum)
    step = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), tx,
                           zero_stage=stage, mesh=mesh, rules=rules,
                           accum_steps=accum)
    comp = {"opt_bytes": optimizer_state_bytes(state.opt_state),
            "grad_bytes": optimizer_state_bytes(state.grad_accum),
            "param_bytes": optimizer_state_bytes(state.params)}
    with mesh:
        for _ in range(warmup):
            state, m = step(state, batch)
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, batch)
        loss = float(m["loss"])
        dt = time.perf_counter() - t0
        census = collective_op_counts(
            step.jitted.lower(state, batch).compile().as_text())
    out["stage%d" % stage] = {
        "tokens_per_sec": round(B * T * steps / dt, 1),
        "loss": round(loss, 6), "collectives": census, **comp}
s0 = out["stage0"]
out["ratios"] = {
    "opt_bytes": round(
        out["stage1"]["opt_bytes"] / max(1, s0["opt_bytes"]), 4),
    "grad_bytes": round(
        out["stage2"]["grad_bytes"] / max(1, s0["grad_bytes"]), 4),
    "param_bytes": round(
        out["stage3"]["param_bytes"] / max(1, s0["param_bytes"]), 4)}
out["loss_delta_max"] = round(max(
    abs(out["stage%d" % s]["loss"] - s0["loss"]) for s in (1, 2, 3)), 8)
print(json.dumps(out))
"""


def _zero_ladder_bench_subprocess() -> dict:
    """Full ZeRO ladder A/B on an 8-virtual-device CPU mesh: stages
    0..3 of the same gpt2-tiny adamw step with accum_steps=2 (so the
    grad-accum buffer exists at every stage and its bytes are
    comparable), recording per-stage tokens/s, loss, the per-chip
    bytes of each state component (optimizer / grad / param — the
    1/8 rungs the test suite also gates), and the compiled collective
    census (stage 3 adds the just-in-time param all-gathers). On TPU
    hardware the same ladder runs inline at XL scale via
    RAY_TPU_BENCH_ZERO_STAGE (see main())."""
    import json as _json
    import os
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c", _ZERO_LADDER_SNIPPET],
            capture_output=True, text=True, timeout=600,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        return _json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001 - secondary scenario, best-effort
        return {}


def _pipeline_bench(num_stages: int = 2, num_microbatches: int = 8) -> dict:
    """1F1B pipeline-strategy scenario, flat vs interleaved at equal
    S/M. Two lanes per schedule:

    - real compute: tokens/s, step time, measured bubble. NOTE on a
      single-core host the S stage processes timeshare one core, so
      this bubble reads CPU contention, not schedule shape.
    - schedule emulation (``emulate_ms``): ops are modeled fixed
      latencies running through the real driver/actor/object-store
      path; sleeping workers overlap even on one core, so THIS bubble
      is the schedule-quality number, and the interleaved one must sit
      strictly below flat (the `train-bubble-regression` gate in
      tests/test_bench_report.py rides `emulated.interleaved_wins`).
    """
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.models.pipelined import PipelinedConfig
    from ray_tpu.parallel.pipeline import (
        theoretical_bubble,
        theoretical_bubble_interleaved,
    )
    from ray_tpu.train.pipeline_strategy import PipelineStrategy

    S, M = num_stages, num_microbatches
    cfg = PipelinedConfig(num_microbatches=M)
    B, T = 32, cfg.block_size
    rs = np.random.RandomState(0)
    batch = {
        "tokens": rs.randint(0, cfg.vocab_size, (B, T)).astype(np.int32),
        "targets": rs.randint(0, cfg.vocab_size, (B, T)).astype(np.int32),
    }
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": max(4, S + 1)})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)

    def run(R, emulate_ms=None, steps=3, warmup=2):
        ps = PipelineStrategy(cfg, num_stages=S, num_microbatches=M,
                              lr=1e-2, num_repeats=R,
                              emulate_ms=emulate_ms)
        try:
            first = ps.train_step(batch)  # compile warmup
            for _ in range(warmup - 1):
                ps.train_step(batch)
            t0 = time.perf_counter()
            ms = [ps.train_step(batch) for _ in range(steps)]
            dt = time.perf_counter() - t0
        finally:
            ps.shutdown()
        bubbles = sorted(m["bubble_ratio"] for m in ms)
        return {
            "tokens_per_sec": round(B * T * steps / dt, 1),
            "step_ms": round(1e3 * dt / steps, 1),
            "bubble_ratio": round(bubbles[len(bubbles) // 2], 4),
            "loss_first": round(first["loss"], 4),
            "loss_last": round(ms[-1]["loss"], 4),
        }

    try:
        flat = run(1)
        inter = run(2)
        emu_ms = (40.0, 80.0)  # modeled fwd/bwd per full stage
        eflat = run(1, emulate_ms=emu_ms, warmup=1)
        einter = run(2, emulate_ms=emu_ms, warmup=1)
        return {
            "stages": S, "microbatches": M, "batch": B, "seq": T,
            **flat,
            "bubble_theoretical": round(theoretical_bubble(S, M), 4),
            "interleaved": {
                **inter, "num_repeats": 2,
                "bubble_theoretical": round(
                    theoretical_bubble_interleaved(S, M, 2), 4),
            },
            "emulated": {
                "op_ms": list(emu_ms),
                "flat_bubble": eflat["bubble_ratio"],
                "flat_theoretical": round(theoretical_bubble(S, M), 4),
                "interleaved_bubble": einter["bubble_ratio"],
                "interleaved_theoretical": round(
                    theoretical_bubble_interleaved(S, M, 2), 4),
                "interleaved_wins":
                    einter["bubble_ratio"] < eflat["bubble_ratio"],
            },
        }
    except Exception:  # noqa: BLE001 - secondary scenario, best-effort
        return {}
    finally:
        try:
            ray_tpu.shutdown()
        finally:
            c.shutdown()


def _wait_for_idle(max_wait_s: float = 240.0, load_thresh: float = 0.7):
    """Idle-gate (VERDICT r4 weak item 1: the driver-captured PPO number
    regressed 16% vs an idle box — this bench is contention-sensitive on
    a 1-core VM, so wait for the load average to settle before
    measuring)."""
    import os
    import time as _t

    t0 = _t.monotonic()
    while _t.monotonic() - t0 < max_wait_s:
        try:
            load1 = os.getloadavg()[0]
        except OSError:
            return 0.0
        if load1 < load_thresh:
            return _t.monotonic() - t0
        _t.sleep(5.0)
    return _t.monotonic() - t0


def _ppo_bench_subprocess() -> dict:
    """Median-of-7 (each sample 4 iterations) with idle-gating and
    retry-on-variance: re-measure up to 3 times if stdev exceeds 8% of
    the median, report the attempt with the lowest relative stdev."""
    import json as _json
    import os
    import subprocess
    import sys

    best = {"median": 0.0, "stdev": 0.0, "max": 0.0, "rel": 1e9}
    for attempt in range(3):
        waited = _wait_for_idle()
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            out = subprocess.run(
                [sys.executable, "-c", _PPO_SNIPPET], capture_output=True,
                text=True, timeout=600, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            line = out.stdout.strip().splitlines()[-1]
            r = _json.loads(line)
        except Exception:
            continue
        rel = r["stdev"] / r["median"] if r.get("median") else 1e9
        r["rel"] = rel
        r["idle_wait_s"] = round(waited, 1)
        if rel < best["rel"]:
            best = r
        if rel <= 0.08:
            break
    best.pop("rel", None)
    return best



def _time_steps(step, state, batch, mesh, warmup: int, steps: int,
                profile_dir: str | None = None,
                collapsed_path: str | None = None):
    """Warmup, then time `steps` compiled steps. Sync via a device-to-
    host copy of the loss — block_until_ready is not a reliable barrier
    on every PJRT plugin. `profile_dir` arms a device-profiler capture
    window around exactly the TIMED steps (no warmup/compile noise in
    the capture; guarded no-op on CPU). Returns (state, final_loss,
    seconds, captured) — `captured` is the REAL capture path, or None
    when nothing was armed/written (CPU, or profiler unavailable), so
    run metadata never points at a directory that does not exist."""
    import time as _time

    from ray_tpu.train import spmd
    from ray_tpu.util import tracing as _tracing

    # at least one warmup step: it also binds `metrics` for the sync read
    warmup = max(1, warmup)
    with mesh:
        for _ in range(warmup):
            state, metrics = step(state, batch)
        float(metrics["loss"])
        # attribution runs (--trace): the table covers the TIMED steps
        # only, so phase totals compare against `dt` directly
        spmd.waterfall.reset()
        # --profile: host-side stack sampler over the SAME timed-steps
        # window as the device capture (warmup/compile excluded — the
        # collapsed output attributes steady-state host path only)
        from ray_tpu.util.profiler import capture_to_file

        with _tracing.profiler_capture(profile_dir) as captured, \
                capture_to_file(collapsed_path):
            t0 = _time.perf_counter()
            for _ in range(steps):
                state, metrics = step(state, batch)
            final_loss = float(metrics["loss"])
            dt = _time.perf_counter() - t0
    return state, final_loss, dt, captured


def main(trace: str | None = None, profile: bool = False):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.util import tracing

    from ray_tpu.models.gpt2 import (
        GPT2Config,
        count_params,
        gpt2_loss,
        gpt2_partition_rules,
        init_gpt2,
    )
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.spmd import (
        batch_shardings,
        init_sharded_state,
        make_train_step,
    )

    from ray_tpu.train import spmd

    if trace:
        # --trace turns the bench into a profiling run: per-step phase
        # attribution on (adds a device sync per step — the recorded
        # headline numbers come from runs WITHOUT --trace)
        spmd.enable_step_waterfall()

    devices = jax.devices()
    n = len(devices)
    on_tpu = devices[0].platform not in ("cpu",)

    if on_tpu:
        import os

        cfg = GPT2Config.small()
        batch_per_chip = int(os.environ.get("RAY_TPU_BENCH_BATCH", "8"))
        seq = 1024
        steps, warmup = 20, 3
    else:  # CPU smoke path so bench.py always emits a line
        cfg = GPT2Config.tiny()
        batch_per_chip, seq = 4, 128
        steps, warmup = 5, 2

    mesh = build_mesh(MeshSpec(data=-1), devices=devices)
    rules = gpt2_partition_rules()
    tx = optax.adamw(3e-4, weight_decay=0.1)
    state = init_sharded_state(
        lambda: init_gpt2(jax.random.PRNGKey(0), cfg), tx, mesh, rules
    )
    n_params = count_params(state.params)

    B = batch_per_chip * n
    toks = jax.random.randint(
        jax.random.PRNGKey(1), (B, seq + 1), 0, cfg.vocab_size, jnp.int32
    )
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    batch = jax.device_put(batch, batch_shardings(mesh, batch))

    step = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), tx)
    # --trace on TPU also arms a device-side profiler capture around
    # exactly the timed steps (jax.profiler.trace; guarded no-op on
    # CPU) — the in-program attribution (GEMM vs collective) the
    # host-side waterfall cannot see. Path lands in the run metadata
    # below and on the chrome trace as the profiler.capture span.
    profile_dir = f"{trace}.profile" if (trace and on_tpu) else None
    # --profile arms the host-side stack sampler around the TIMED steps
    # only (inside _time_steps, next to the device capture — warmup and
    # compile stay outside the window); unarmed runs construct nothing
    collapsed_path = (f"{trace}.collapsed" if trace
                      else "bench.collapsed") if profile else None
    with tracing.span("bench.gpt2", category="bench"):
        state, final_loss, dt, captured = _time_steps(
            step, state, batch, mesh, warmup, steps,
            profile_dir=profile_dir, collapsed_path=collapsed_path)
    if collapsed_path:
        print(f"# wrote collapsed stacks to {collapsed_path}",
              flush=True)
    # per-phase attribution of the timed gpt2 steps (--trace runs):
    # phases sum to ~dt, so the percents decompose the MFU number
    attribution = spmd.waterfall.summary() if trace else None
    attribution_table = spmd.waterfall.table() if trace else None

    tokens_per_sec = B * seq * steps / dt
    per_chip = tokens_per_sec / n
    # MFU against v5e peak 197 TFLOP/s bf16 (fwd+bwd ~ 6*N flops/token)
    mfu = 6.0 * n_params * per_chip / 197e12 if on_tpu else 0.0

    # second model family: Llama-small (RoPE/RMSNorm/SwiGLU/GQA) on the
    # same chip + timing recipe
    llama_per_chip = 0.0
    if on_tpu:
        from ray_tpu.models.llama import (
            LlamaConfig,
            init_llama,
            llama_loss,
            llama_partition_rules,
        )

        lcfg = LlamaConfig.small()
        lstate = init_sharded_state(
            lambda: init_llama(jax.random.PRNGKey(0), lcfg),
            tx, mesh, llama_partition_rules())
        ltoks = jax.random.randint(
            jax.random.PRNGKey(2), (B, seq + 1), 0, lcfg.vocab_size,
            jnp.int32)
        lbatch = {"tokens": ltoks[:, :-1], "targets": ltoks[:, 1:]}
        lbatch = jax.device_put(lbatch, batch_shardings(mesh, lbatch))
        lstep = make_train_step(lambda p, b: llama_loss(p, b, lcfg), tx)
        lstate, _lloss, ldt, _ = _time_steps(lstep, lstate, lbatch,
                                             mesh, warmup, steps)
        llama_per_chip = B * seq * steps / ldt / n

    # GPT-2-XL-class single-chip config (VERDICT r3 item 2): E=2048 is
    # where the GEMMs run near the MXU's efficient regime — the MFU
    # number that matters for real model sizes. ~710M params: fp32
    # params + 2 adam moments ≈ 8.5GB, fits one chip's HBM with remat.
    xl_per_chip, xl_mfu, xl_policy = 0.0, 0.0, ""
    z1_per_chip, z1_mfu, z1_batch, z1_bytes_ratio = 0.0, 0.0, 0, 0.0
    z1_stage = 0
    if on_tpu:
        import os as _os

        from ray_tpu.train.spmd import optimizer_state_bytes

        xcfg = GPT2Config(n_layer=12, n_head=16, n_embd=2048)
        xl_policy = _os.environ.get("RAY_TPU_REMAT_POLICY", "full")
        xB = int(_os.environ.get("RAY_TPU_BENCH_XL_BATCH", "8"))
        xstate = init_sharded_state(
            lambda: init_gpt2(jax.random.PRNGKey(0), xcfg), tx, mesh,
            rules)
        xp = count_params(xstate.params)
        xl_opt_bytes = optimizer_state_bytes(xstate.opt_state)
        xtoks = jax.random.randint(
            jax.random.PRNGKey(3), (xB, seq + 1), 0, xcfg.vocab_size,
            jnp.int32)
        xbatch = {"tokens": xtoks[:, :-1], "targets": xtoks[:, 1:]}
        xbatch = jax.device_put(xbatch, batch_shardings(mesh, xbatch))
        xstep = make_train_step(lambda p, b: gpt2_loss(p, b, xcfg), tx)
        xstate, _xl_loss, xdt, _ = _time_steps(xstep, xstate, xbatch,
                                               mesh, 2, 10)
        xl_per_chip = xB * seq * 10 / xdt / n
        xl_mfu = 6.0 * xp * xl_per_chip / 197e12
        del xstate, xbatch

        # ZeRO sharded update on the same XL config (direction 4):
        # optimizer state shards 1/N over the data axis (stage 1), and
        # the freed HBM buys a larger per-chip batch — the default
        # doubles it; tune with RAY_TPU_BENCH_ZERO1_BATCH. The ladder
        # rung is a knob: RAY_TPU_BENCH_ZERO_STAGE=2 keeps grads
        # resident reduce-scattered, =3 shards resident params with a
        # just-in-time gather in the step.
        if n > 1:
            z1_stage = int(_os.environ.get("RAY_TPU_BENCH_ZERO_STAGE",
                                           "1"))
            z1_batch = int(_os.environ.get("RAY_TPU_BENCH_ZERO1_BATCH",
                                           str(2 * xB)))
            zstate = init_sharded_state(
                lambda: init_gpt2(jax.random.PRNGKey(0), xcfg), tx,
                mesh, rules, zero_stage=z1_stage)
            z1_bytes_ratio = (optimizer_state_bytes(zstate.opt_state)
                              / max(1, xl_opt_bytes))
            ztoks = jax.random.randint(
                jax.random.PRNGKey(4), (z1_batch, seq + 1), 0,
                xcfg.vocab_size, jnp.int32)
            zbatch = {"tokens": ztoks[:, :-1], "targets": ztoks[:, 1:]}
            zbatch = jax.device_put(zbatch,
                                    batch_shardings(mesh, zbatch))
            zstep = make_train_step(lambda p, b: gpt2_loss(p, b, xcfg),
                                    tx, zero_stage=z1_stage, mesh=mesh,
                                    rules=rules)
            zstate, _z1_loss, zdt, _ = _time_steps(
                zstep, zstate, zbatch, mesh, 2, 10)
            z1_per_chip = z1_batch * seq * 10 / zdt / n
            z1_mfu = 6.0 * xp * z1_per_chip / 197e12
            del zstate, zbatch

    # secondary: RLlib PPO sampling+learning throughput. The env loop and
    # small-MLP learner are host-side by design (BASELINE north star
    # names PPO env-steps/sec) — run in a CPU subprocess so the measure
    # is not distorted by the TPU tunnel's per-dispatch latency.
    ppo = _ppo_bench_subprocess()

    # train-layer perf scenarios (direction 4). On CPU both run at
    # smoke scale so the shapes stay exercised everywhere; on TPU the
    # ZeRO-1 number comes from the inline XL run above and the pipeline
    # scenario opts in via RAY_TPU_BENCH_PIPELINE=1 (stage workers
    # would contend with the driver for chips).
    import os as _os2

    zero1 = {} if on_tpu else _zero1_bench_subprocess()
    zero_ladder = {} if on_tpu else _zero_ladder_bench_subprocess()
    run_pipe = (not on_tpu) or _os2.environ.get(
        "RAY_TPU_BENCH_PIPELINE", "") == "1"
    pipeline = _pipeline_bench() if run_pipe else {}

    # First-class secondary metrics (VERDICT r4 weak item 2: the E=2048
    # MFU is the number that matters for real model sizes — promote it
    # out of "extra"). vs_baseline anchors: 0.40 MFU (solid large-model
    # TPU training), 30k tok/s/chip DDP, and the reference-era 24,215
    # env-steps/s PPO record (BENCH_r02).
    secondary = [
        {"metric": "gpt2_2048_mfu", "value": round(xl_mfu, 3),
         "unit": "mfu", "vs_baseline": round(xl_mfu / 0.40, 3)},
        # anchor: 0.35 MFU on a v5e chip for this 710M config =
        # 0.35 * 197e12 / (6 * 710e6) ~= 16,170 tok/s/chip
        {"metric": "gpt2_2048_train_tokens_per_sec_per_chip",
         "value": round(xl_per_chip, 1), "unit": "tokens/s/chip",
         "vs_baseline": round(xl_per_chip / 16170.0, 3)},
        {"metric": "llama_small_train_tokens_per_sec_per_chip",
         "value": round(llama_per_chip, 1), "unit": "tokens/s/chip",
         "vs_baseline": round(
             llama_per_chip / BASELINE_TOKENS_PER_SEC_PER_CHIP, 3)},
        {"metric": "ppo_env_steps_per_sec",
         "value": round(ppo.get("median", 0.0)), "unit": "env-steps/s",
         "vs_baseline": round(ppo.get("median", 0.0) / 24215.0, 3)},
    ] if on_tpu else []
    if on_tpu and n > 1:
        # ZeRO-1 at the larger batch the freed optimizer HBM buys —
        # anchored against the same 0.40-MFU bar as the dense XL row.
        # Gated like the run itself (n > 1): a single-chip host must
        # not report the metric as 0.0 "collapse"
        secondary.append(
            {"metric": "gpt2_2048_zero1_mfu", "value": round(z1_mfu, 3),
             "unit": "mfu", "vs_baseline": round(z1_mfu / 0.40, 3)})
    print(
        json.dumps(
            {
                "metric": "gpt2_small_train_tokens_per_sec_per_chip"
                if on_tpu
                else "gpt2_tiny_cpu_smoke_tokens_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(per_chip / BASELINE_TOKENS_PER_SEC_PER_CHIP, 3),
                "secondary_metrics": secondary,
                "extra": {
                    "n_chips": n,
                    "params": n_params,
                    "batch": B,
                    "seq": seq,
                    "step_ms": round(1e3 * dt / steps, 1),
                    "mfu": round(mfu, 3),
                    "loss": round(final_loss, 4),
                    "llama_small_tokens_per_sec_per_chip":
                        round(llama_per_chip, 1),
                    "gpt2_2048_tokens_per_sec_per_chip":
                        round(xl_per_chip, 1),
                    "gpt2_2048_mfu": round(xl_mfu, 3),
                    "gpt2_2048_remat_policy": xl_policy,
                    "gpt2_2048_zero1_tokens_per_sec_per_chip":
                        round(z1_per_chip, 1),
                    "gpt2_2048_zero1_mfu": round(z1_mfu, 3),
                    "gpt2_2048_zero1_batch": z1_batch,
                    "gpt2_2048_zero_stage": z1_stage,
                    "zero1_opt_bytes_ratio": round(z1_bytes_ratio, 4),
                    "zero1": zero1,
                    "zero_ladder": zero_ladder,
                    "pipeline": pipeline,
                    "ppo_env_steps_per_sec": round(ppo.get("median", 0.0)),
                    "ppo_env_steps_per_sec_stdev":
                        round(ppo.get("stdev", 0.0), 1),
                    "ppo_env_steps_per_sec_max":
                        round(ppo.get("max", 0.0)),
                    "step_attribution": attribution,
                    "profiler_capture": captured,
                },
            }
        )
    )
    if trace:
        # the attribution table: where the headline gpt2 step time went
        # (phases sum to ~the measured step time — the waterfall
        # contract tests pin)
        print(attribution_table, flush=True)
        # bench runs double as profiling runs: the compile spans +
        # bench phase spans land in a chrome trace next to the numbers
        tracing.dump(trace)
        print(f"# wrote trace to {trace}", flush=True)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None,
                    help="also dump a chrome trace (spans incl. "
                         "compiles) to this file")
    ap.add_argument("--profile", action="store_true",
                    help="arm the stack sampler around the timed steps "
                         "and write flamegraph-compatible .collapsed "
                         "stacks next to the --trace artifact")
    _a = ap.parse_args()
    main(trace=_a.trace, profile=_a.profile)
