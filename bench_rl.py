"""RL-for-LLMs flywheel benchmark — the closed loop, end to end on CPU.

Two parts, two disciplines:

- **Learning curve** (the closed-loop acceptance artifact): N flywheel
  laps of rollout → GRPO update → drain-free weight hot-swap on the
  digit-sum verifiable task, every rollout generated through the
  serve.llm continuous-batching engine (the shared task prefix rides
  the prefix cache — hit counters prove it), every lap hot-swapping
  the new weights while probe streams are mid-generation (zero drops
  proves the drain-free contract). Fully seeded, so the committed
  curve reproduces; the gate is a run of >= 4 consecutive laps with
  strictly increasing mean reward (>= 3 strictly-improving learner
  updates).

- **Perf numbers** (PERF_NOTES round-5 recipe: idle gate, median of 7
  samples, stdev on the control metric, retry-on-variance): rollout
  throughput in generated tokens/s through the engine, and the wall
  time of one weight hot-swap with 8 streams in flight.

Emits one BENCH-style JSON line and writes RL_BENCH.json (rollout
tokens/s, prefix-cache hit ratio during rollouts, swap latency, the
reward curve).

    python bench_rl.py [--iters 12] [--prompts 12] [--group 8]
                       [--samples 7] [--lr 0.02] [--seed 0]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _build(args):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2
    from ray_tpu.rllib.llm import (
        DigitSumTask,
        LLMLearner,
        LLMLearnerConfig,
        RolloutConfig,
        RolloutWorker,
    )
    from ray_tpu.serve.llm import EngineConfig, LLMEngine

    jax.config.update("jax_platforms", "cpu")
    task = DigitSumTask()
    cfg = gpt2.GPT2Config(
        vocab_size=64, n_layer=1, n_head=2, n_embd=32, block_size=64,
        vocab_pad_multiple=64, dtype=jnp.float32, remat=False)
    learner = LLMLearner(
        "gpt2", cfg,
        config=LLMLearnerConfig(lr=args.lr, temperature=1.0),
        seed=args.seed)
    engine = LLMEngine(
        EngineConfig(model="gpt2", model_config=cfg, block_size=8,
                     num_blocks=256, max_model_len=32, max_batch_size=8,
                     prefill_chunk_size=8, seed=args.seed),
        params=learner.get_weights())
    worker = RolloutWorker(
        engine=engine, reward_fn=task.reward,
        config=RolloutConfig(group_size=args.group, max_tokens=2,
                             temperature=1.0))
    return task, learner, engine, worker


def bench_learning_curve(args) -> dict:
    """The closed loop: reward must strictly improve across >= 3
    consecutive learner updates while every lap's hot-swap lands with
    probe streams in flight and drops none."""
    from ray_tpu.rllib.llm import FlywheelConfig, RLFlywheel

    task, learner, engine, worker = _build(args)
    rng = np.random.RandomState(args.seed)

    def prompt_fn(it):
        return [task.make_prompt(rng.randint(0, 10), rng.randint(0, 10))
                for _ in range(args.prompts)]

    fly = RLFlywheel(worker, learner, prompt_fn,
                     FlywheelConfig(swap_during_rollout=True))
    curve, probe_dropped, probe_streams = [], 0, 0
    min_in_flight = 10 ** 9
    t0 = time.monotonic()
    for _ in range(args.iters):
        m = fly.iteration()
        curve.append(round(m["rollout_reward_mean"], 4))
        probe_dropped += m["swap"]["probe_dropped"]
        probe_streams += m["swap"]["probe_streams"]
        min_in_flight = min(min_in_flight, m["swap"]["in_flight_streams"])
    wall = time.monotonic() - t0
    stats = engine.stats()
    hits, misses = stats["prefix_hit_pages"], stats["prefix_miss_pages"]

    # longest strictly-increasing run of consecutive lap rewards
    best_run, run = 1, 1
    for a, b in zip(curve, curve[1:]):
        run = run + 1 if b > a else 1
        best_run = max(best_run, run)
    return {
        "reward_curve": curve,
        "reward_first": curve[0],
        "reward_last": curve[-1],
        "strict_improve_updates": best_run - 1,
        # every gate the acceptance demands: learning, a warm cache,
        # and swaps that provably landed with streams mid-generation
        "closed_loop_ok": bool(best_run - 1 >= 3 and probe_dropped == 0
                               and hits > 0 and min_in_flight >= 1),
        "min_swap_in_flight_streams": min_in_flight,
        "learner_updates": learner.version,
        "engine_weight_version": stats["weight_version"],
        "swaps_with_streams_in_flight": args.iters,
        "probe_streams": probe_streams,
        "probe_dropped": probe_dropped,
        "prefix_hit_pages": hits,
        "prefix_miss_pages": misses,
        "prefix_hit_ratio": round(hits / max(1, hits + misses), 3),
        "wall_s": round(wall, 1),
    }


def bench_perf(args) -> dict:
    """Round-5 recipe over (rollout tokens/s, swap seconds): each
    sample rolls one full batch through the engine and then hot-swaps
    fresh weights with 8 streams held in flight."""
    import jax

    from bench_serve import _recipe
    from ray_tpu.models import gpt2
    from ray_tpu.serve.llm import SamplingParams

    task, learner, engine, worker = _build(args)
    rng = np.random.RandomState(args.seed + 1)
    version = [engine.weight_version]

    def sample(i) -> dict:
        prompts = [task.make_prompt(rng.randint(0, 10),
                                    rng.randint(0, 10))
                   for _ in range(args.prompts)]
        t0 = time.monotonic()
        trajs = worker.rollout(prompts)
        dt = time.monotonic() - t0
        tokens = sum(len(t) for t in trajs)
        # swap with 8 streams mid-generation (the drain-free shape)
        sp = SamplingParams(max_tokens=8, temperature=1.0)
        streams = [engine.add_request(p, sp) for p in prompts[:8]]
        for _ in range(10):
            engine.step()
        version[0] += 1
        new = gpt2.init_gpt2(
            jax.random.PRNGKey(args.seed + version[0]), learner.cfg)
        swap = engine.update_weights(version[0], new)
        while any(s.final() is None for s in streams):
            if not engine.step():
                time.sleep(0.001)
        dropped = sum(1 for s in streams
                      if not (s.final() and s.final().get("done")))
        return {
            "rollout_tokens_per_sec": tokens / dt,
            "rollout_tokens": tokens,
            "swap_seconds": swap["swap_seconds"],
            "swap_in_flight_streams": swap["in_flight_streams"],
            "swap_dropped_streams": dropped,
        }

    return _recipe(sample, samples=args.samples,
                   control_key="rollout_tokens_per_sec")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=12,
                    help="flywheel laps for the learning curve")
    ap.add_argument("--prompts", type=int, default=12,
                    help="prompts per lap")
    ap.add_argument("--group", type=int, default=8,
                    help="completions per prompt (GRPO group)")
    ap.add_argument("--lr", type=float, default=2e-2)
    ap.add_argument("--samples", type=int, default=7,
                    help="samples per attempt (round-5 recipe)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--skip-perf", action="store_true")
    ap.add_argument("--trace", default=None,
                    help="dump a chrome trace of the run to this file")
    ap.add_argument("--profile", action="store_true",
                    help="arm the stack sampler around the measured "
                         "perf windows (rides bench_serve's _recipe "
                         "hook) and write .collapsed next to --trace")
    args = ap.parse_args()

    if args.profile:
        # the perf scenario measures through bench_serve._recipe, so
        # arming ITS hook samples exactly the measured windows
        import bench_serve as _bs

        _bs._profile_stacks = {}

    curve = bench_learning_curve(args)
    extra = {"learning": curve}
    secondary = [
        {"metric": "rl_reward_last", "unit": "mean reward",
         "value": curve["reward_last"]},
        {"metric": "rl_strict_improve_updates", "unit": "updates",
         "value": curve["strict_improve_updates"]},
        {"metric": "rl_prefix_hit_ratio", "unit": "ratio",
         "value": curve["prefix_hit_ratio"]},
    ]
    value = None
    if not args.skip_perf:
        perf = bench_perf(args)
        extra["perf"] = perf
        value = round(perf["rollout_tokens_per_sec"], 1)
        secondary.append(
            {"metric": "rl_weight_swap_seconds", "unit": "s",
             "value": round(perf["swap_seconds"], 4)})
    out = {
        "metric": "rl_rollout_tokens_per_sec",
        "value": value,
        "unit": "tokens/s",
        "secondary_metrics": secondary,
        "extra": extra,
    }
    print(json.dumps(out))
    with open("RL_BENCH.json", "w") as f:
        json.dump(out, f, indent=2)
    if args.trace:
        from ray_tpu.util import tracing

        tracing.dump(args.trace)
        print(f"# wrote trace to {args.trace}")
    if args.profile:
        import bench_serve as _bs
        from ray_tpu.util import profiler

        path = (f"{args.trace}.collapsed" if args.trace
                else "bench_rl.collapsed")
        profiler.write_collapsed(path, _bs._profile_stacks or {})
        print(f"# wrote collapsed stacks to {path}")


if __name__ == "__main__":
    main()
