"""Core-runtime microbenchmarks — the reference ray_perf.py shapes.

Reference parity: python/ray/_private/ray_perf.py (the published
microbenchmark suite behind BASELINE.md's table). Same shapes, measured
against ray_tpu's runtime:

  - 1:1 / 1:n / n:n actor calls (sync, async batches)
  - single/multi-client task submission (sync, async batches)
  - put/get calls (small objects), put throughput (large buffers)
  - compiled-DAG steady state (4-stage actor chain, executions/s) —
    measured with the PERF_NOTES round-5 recipe (idle gate,
    median-of-7, retry-on-variance); no reference baseline exists for
    this shape, the eager 4-stage chain measured in the same run is
    the comparison

Run: `python bench_core.py [--quick]`. Prints one JSON line per metric
and writes CORE_BENCH.json with {metric: {value, unit, baseline,
vs_baseline}}. Baselines from BASELINE.md (reference 2.9.3 release
microbenchmark.json, 1 AWS node); this VM is a small Firecracker guest —
see the "environment" entry recorded alongside the numbers.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import ray_tpu

BASELINES = {
    "actor_calls_sync_1_1": (2033, "calls/s"),
    "actor_calls_async_1_1": (8886, "calls/s"),
    "actor_calls_concurrent_1_1": (5095, "calls/s"),
    "actor_calls_async_1_n": (8570, "calls/s"),
    "actor_calls_async_n_n": (27667, "calls/s"),
    "tasks_sync_single_client": (1007, "tasks/s"),
    "tasks_async_single_client": (8444, "tasks/s"),
    "tasks_async_multi_client": (25166, "tasks/s"),
    "put_calls_single_client": (5545, "puts/s"),
    "get_calls_single_client": (10182, "gets/s"),
    "put_gigabytes_single_client": (20.88, "GB/s"),
    "put_gigabytes_multi_client": (35.88, "GB/s"),
}


@ray_tpu.remote(num_cpus=0)
class Sink:
    def ping(self):
        return b"ok"


@ray_tpu.remote(num_cpus=0, max_concurrency=4)
class ConcurrentSink:
    def ping(self):
        return b"ok"


@ray_tpu.remote(num_cpus=0)
class Client:
    """A driver-like process hammering its own targets (the reference's
    n:n shape runs one client actor per sink actor)."""

    def __init__(self):
        pass

    def actor_rounds(self, n_calls: int) -> float:
        sink = Sink.options(num_cpus=0).remote()
        ray_tpu.get(sink.ping.remote())
        t0 = time.perf_counter()
        ray_tpu.get([sink.ping.remote() for _ in range(n_calls)])
        dt = time.perf_counter() - t0
        ray_tpu.kill(sink)
        return n_calls / dt

    def task_rounds(self, n_tasks: int) -> float:
        @ray_tpu.remote(num_cpus=1)
        def nop():
            return b"ok"

        ray_tpu.get(nop.remote())
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n_tasks)])
        return n_tasks / (time.perf_counter() - t0)

    def put_gb(self, n: int, mb: int) -> float:
        arr = np.zeros(mb << 20, np.uint8)
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.put(arr)
        return n * arr.nbytes / (time.perf_counter() - t0) / 1e9


# --profile: collapsed stacks accumulated across the timed shapes only
# (never warmup, idle gates, or teardown). None = unarmed = free.
_profile_stacks = None


def _armed():
    from ray_tpu.util import profiler

    return profiler.accumulate(_profile_stacks)


def _rate(fn, n):
    with _armed():
        t0 = time.perf_counter()
        fn(n)
        return n / (time.perf_counter() - t0)


def _wait_for_idle(max_wait_s: float = 240.0, load_thresh: float = 0.7):
    """Idle-gate (PERF_NOTES round 5): contention-sensitive on a 1-core
    VM — wait for the load average to settle before measuring."""
    import os as _os

    t0 = time.monotonic()
    while time.monotonic() - t0 < max_wait_s:
        try:
            load1 = _os.getloadavg()[0]
        except OSError:
            return 0.0
        if load1 < load_thresh:
            return time.monotonic() - t0
        time.sleep(5.0)
    return time.monotonic() - t0


def _bench_compiled_dag(quick: bool) -> dict:
    """4-stage actor chain: steady-state compiled executions/s vs the
    eager .remote() chain, round-5 recipe (median-of-7, stdev,
    retry-on-variance)."""
    import statistics

    from ray_tpu.dag import InputNode

    @ray_tpu.remote(num_cpus=0)
    class DagStage:
        def step(self, x):
            return x + 1

    stages = [DagStage.remote() for _ in range(4)]
    ray_tpu.get([s.step.remote(0) for s in stages])
    with InputNode() as inp:
        y = inp
        for s in stages:
            y = s.step.bind(y)
    dag = y.compile()
    n = 100 if quick else 400

    def one_sample(kind):
        t0 = time.perf_counter()
        if kind == "compiled":
            refs = [dag.execute(i) for i in range(n)]
            out = [r.get(timeout=120) for r in refs]
        else:
            out = []
            refs = []
            for i in range(n):
                r = i
                for s in stages:
                    r = s.step.remote(r)
                refs.append(r)
            out = ray_tpu.get(refs, timeout=300)
        assert out == [i + 4 for i in range(n)]
        return n / (time.perf_counter() - t0)

    one_sample("compiled")  # pipeline warm
    one_sample("eager")
    best = None
    samples = 3 if quick else 7
    for attempt in range(3):
        # short gate: the main shapes just ran, so this box's 1-min
        # loadavg needs minutes to decay below the round-5 threshold —
        # cap the wait; compiled and eager samples interleave the same
        # contention either way and the RATIO is the headline
        waited = _wait_for_idle(max_wait_s=60.0)
        with _armed():
            compiled = [one_sample("compiled") for _ in range(samples)]
            eager = [one_sample("eager") for _ in range(3)]
        med = statistics.median(compiled)
        sd = statistics.pstdev(compiled)
        agg = {
            "value": round(med, 2),
            "unit": "execs/s",
            "stdev": round(sd, 2),
            "rel_stdev": round((sd / med) if med else 1e9, 3),
            "eager_chain_execs_per_s": round(statistics.median(eager), 2),
            "speedup_vs_eager": round(med / statistics.median(eager), 2),
            "samples": samples,
            "attempt": attempt + 1,
            "idle_wait_s": round(waited, 1),
        }
        if best is None or agg["rel_stdev"] < best["rel_stdev"]:
            best = agg
        if agg["rel_stdev"] <= 0.08:
            break
    dag.teardown()
    for s in stages:
        ray_tpu.kill(s)
    return best


def main():
    quick = "--quick" in sys.argv
    scale = 0.2 if quick else 1.0
    # --trace out.json: dump a chrome trace of the run (task/actor/user
    # spans) — every bench driver doubles as a profiling run
    trace = None
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        if i + 1 >= len(sys.argv):
            print("error: --trace needs a filename", file=sys.stderr)
            sys.exit(2)
        trace = sys.argv[i + 1]
    # --profile: arm the stack sampler around the timed shapes and
    # write .collapsed next to the --trace artifact
    if "--profile" in sys.argv:
        global _profile_stacks
        _profile_stacks = {}

    def N(n):
        return max(10, int(n * scale))

    # asserted CPUs: the benchmark measures runtime overhead, not this
    # host's core count (reference ray_perf runs on a large node)
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    results: dict[str, float] = {}

    @ray_tpu.remote(num_cpus=1)
    def nop():
        return b"ok"

    # -- actor calls ------------------------------------------------------
    a = Sink.remote()
    ray_tpu.get(a.ping.remote())
    results["actor_calls_sync_1_1"] = _rate(
        lambda n: [ray_tpu.get(a.ping.remote()) for _ in range(n)], N(1000))
    results["actor_calls_async_1_1"] = _rate(
        lambda n: ray_tpu.get([a.ping.remote() for _ in range(n)]), N(10000))
    c = ConcurrentSink.remote()
    ray_tpu.get(c.ping.remote())
    results["actor_calls_concurrent_1_1"] = _rate(
        lambda n: ray_tpu.get([c.ping.remote() for _ in range(n)]), N(10000))
    n_sinks = 8
    sinks = [Sink.options(num_cpus=0).remote() for _ in range(n_sinks)]
    ray_tpu.get([s.ping.remote() for s in sinks])
    results["actor_calls_async_1_n"] = _rate(
        lambda n: ray_tpu.get(
            [sinks[i % n_sinks].ping.remote() for i in range(n)]), N(10000))
    for s in sinks:
        ray_tpu.kill(s)

    # n:n — client actors each driving their own sink
    n_clients = 4
    clients = [Client.remote() for _ in range(n_clients)]
    per = [cl.actor_rounds.remote(N(4000)) for cl in clients]
    results["actor_calls_async_n_n"] = sum(ray_tpu.get(per, timeout=300))

    # -- tasks ------------------------------------------------------------
    # latency-bound shapes get an idle gate (round-5 discipline): the
    # preceding burst sections leave loadavg high on this 1-core guest
    # and depress sync-shape captures ~25% (PERF_NOTES)
    _wait_for_idle(max_wait_s=180.0)
    ray_tpu.get(nop.remote())
    results["tasks_sync_single_client"] = _rate(
        lambda n: [ray_tpu.get(nop.remote()) for _ in range(n)], N(1000))
    results["tasks_async_single_client"] = _rate(
        lambda n: ray_tpu.get([nop.remote() for _ in range(n)]), N(10000))
    per = [cl.task_rounds.remote(N(4000)) for cl in clients]
    results["tasks_async_multi_client"] = sum(ray_tpu.get(per, timeout=300))

    # -- objects ----------------------------------------------------------
    _wait_for_idle(max_wait_s=180.0)
    results["put_calls_single_client"] = _rate(
        lambda n: [ray_tpu.put(b"x" * 100) for _ in range(n)], N(5000))
    ref = ray_tpu.put(b"y" * 100)
    results["get_calls_single_client"] = _rate(
        lambda n: [ray_tpu.get(ref) for _ in range(n)], N(10000))
    big = np.zeros(64 << 20, np.uint8)
    t0 = time.perf_counter()
    reps = 3 if quick else 10
    for _ in range(reps):
        ray_tpu.put(big)
    results["put_gigabytes_single_client"] = \
        reps * big.nbytes / (time.perf_counter() - t0) / 1e9
    per = [cl.put_gb.remote(3 if quick else 6, 32) for cl in clients]
    results["put_gigabytes_multi_client"] = sum(ray_tpu.get(per, timeout=300))

    for cl in clients:
        ray_tpu.kill(cl)

    # -- compiled DAG (round-5 recipe; no reference baseline) ------------
    dag_entry = _bench_compiled_dag(quick)
    print(json.dumps({"metric": "compiled_dag_4stage", **dag_entry}))

    # -- report -----------------------------------------------------------
    report = {}
    for metric, value in results.items():
        base, unit = BASELINES[metric]
        entry = {"value": round(value, 2), "unit": unit, "baseline": base,
                 "vs_baseline": round(value / base, 3)}
        report[metric] = entry
        print(json.dumps({"metric": metric, **entry}))
    report["compiled_dag_4stage"] = dag_entry
    import os as _os

    report["environment"] = {
        "physical_cores": _os.cpu_count(),
        "note": ("this guest is a Firecracker VM with "
                 f"{_os.cpu_count()} physical core(s); the reference "
                 "numbers come from a large many-core AWS node. "
                 "Latency-bound shapes (sync calls, put/get calls) are "
                 "apples-to-apples; their run-to-run noise band on "
                 "this timeshared guest is large (sync shapes span "
                 "0.8k-2.9k/s across same-day runs — isolated loops "
                 "right before/after a full-bench capture differ "
                 "~25% from the in-bench number from loadavg alone). "
                 "Throughput-bound shapes (async batches, n:n, "
                 "multi-client) ride the ISSUE-11 coalesced fast "
                 "path: pending submissions to one peer pack into one "
                 "batched frame (actor_calls / schedule_tasks / "
                 "multi-spec execute_leased) and workers batch "
                 "task_done returns symmetrically, which lifted these "
                 "shapes 3-4x at unchanged sync latency — the "
                 "remaining gap to baseline is core count (every "
                 "worker process timeshares one core). Put THROUGHPUT "
                 "is capped by this guest's raw memcpy bandwidth "
                 "(~1.5-8 GB/s measured via bytearray-to-bytearray "
                 "copies); zero-copy reads are why get_calls lands "
                 "orders of magnitude above baseline. The controlled "
                 "transport measure is the raw RPC echo round trip: "
                 "135us median with the r4 exclusive-lock socket "
                 "driver, zero concurrent libzmq access by "
                 "construction. compiled_dag_4stage has no reference "
                 "baseline; its in-run eager-chain rate is the "
                 "comparison (~80x)."),
    }
    with open("CORE_BENCH.json", "w") as f:
        json.dump(report, f, indent=1)
    if trace:
        from ray_tpu.util import tracing

        # dump BEFORE shutdown: the merged timeline needs the runtime
        tracing.dump(trace)
        print(f"# wrote trace to {trace}")
    if _profile_stacks is not None:
        from ray_tpu.util import profiler

        path = f"{trace}.collapsed" if trace else "bench_core.collapsed"
        profiler.write_collapsed(path, _profile_stacks)
        print(f"# wrote collapsed stacks to {path}")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
