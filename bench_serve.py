"""Serving benchmark — synthetic open-loop load on the LLM engine.

Open-loop (arrivals don't wait for completions, Poisson
inter-arrivals) is the honest serving shape: closed-loop benchmarks
self-throttle and hide queueing collapse. Emits one BENCH-style JSON
line (headline: generated tokens/s; secondary: p50/p99 TTFT) and
writes SERVE_BENCH.json, so future PRs have a serving perf
trajectory next to bench.py's training numbers.

    python bench_serve.py [--n 64] [--rate 8] [--model gpt2]
                          [--preset tiny] [--max-tokens 16] [--serve]

Default drives a bare in-process engine (scheduler+runner+cache, no
RPC). `--serve` runs the same load through a real serve deployment and
DeploymentHandle streaming instead — engine + serve overhead together.
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import numpy as np


def _requests(n, seed, max_len=32):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 500, size=int(rng.randint(8, max_len))).tolist()
            for _ in range(n)]


def bench_engine(args) -> dict:
    from ray_tpu.serve.llm import EngineConfig, LLMEngine, SamplingParams

    eng = LLMEngine(EngineConfig(
        model=args.model, preset=args.preset, block_size=16,
        max_model_len=args.max_model_len, max_batch_size=args.batch,
        num_blocks=args.num_blocks))
    prompts = _requests(args.n, seed=0, max_len=args.max_model_len // 2)
    sp = SamplingParams(max_tokens=args.max_tokens)

    # compile every bucketed program outside the measured window
    eng.warmup()

    stop = threading.Event()

    def step_loop():
        while not stop.is_set():
            if not eng.step():
                time.sleep(0.0005)

    stepper = threading.Thread(target=step_loop, daemon=True)
    stepper.start()

    # one reader thread per stream: TTFT is measured at first-token
    # ARRIVAL, concurrent with the open-loop arrivals — a sequential
    # post-hoc drain would just re-measure the enqueue schedule
    rng = np.random.RandomState(1)
    n = args.n
    ttft = [float("nan")] * n
    finals = [None] * n

    def consume(i, stream, te):
        try:
            first = stream.next_event(timeout=300)
            if first is not None:
                ttft[i] = (time.monotonic() - te) * 1e3
            for _ in stream:
                pass
            finals[i] = stream.final()
        except Exception:  # noqa: BLE001  (stalled engine: leave None)
            pass

    readers = []
    t0 = time.monotonic()
    for i, p in enumerate(prompts):
        te = time.monotonic()
        s = eng.add_request(p, sp)
        th = threading.Thread(target=consume, args=(i, s, te), daemon=True)
        th.start()
        readers.append(th)
        time.sleep(float(rng.exponential(1.0 / args.rate)))
    for th in readers:
        th.join(timeout=300)
    wall = time.monotonic() - t0
    stop.set()
    stepper.join(timeout=5)

    n_tokens = sum(f["num_generated"] for f in finals if f)
    dropped = sum(1 for f in finals
                  if f is None or f["finish_reason"].startswith("error"))
    st = eng.stats()
    return {
        "tokens_per_sec": n_tokens / wall,
        "ttft_p50_ms": float(np.nanpercentile(ttft, 50)),
        "ttft_p99_ms": float(np.nanpercentile(ttft, 99)),
        "requests": args.n,
        "dropped": dropped,
        "wall_s": wall,
        "total_tokens": n_tokens,
        "preemptions": st["preemptions"],
        "compiled_programs": st["compiled_programs"],
        "mode": "engine",
    }


def bench_serve_deployment(args) -> dict:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    ray_tpu.init(num_cpus=8)
    handle = serve.run(build_llm_app(
        model=args.model, preset=args.preset,
        engine_config={"block_size": 16,
                       "max_model_len": args.max_model_len,
                       "max_batch_size": args.batch,
                       "num_blocks": args.num_blocks}), name="bench-llm")
    sh = handle.options(stream=True, generator_backpressure=128)
    prompts = _requests(args.n, seed=0, max_len=args.max_model_len // 2)
    # warm-up
    for r in sh.remote({"prompt": prompts[0], "max_tokens": 2}):
        ray_tpu.get(r, timeout=300)

    results = [None] * args.n
    ttft = [float("nan")] * args.n

    def consume(i, gen, te):
        events = []
        for r in gen:
            events.append(ray_tpu.get(r, timeout=300))
            if len(events) == 1:
                ttft[i] = (time.monotonic() - te) * 1e3
        results[i] = events[-1]

    rng = np.random.RandomState(1)
    threads = []
    t0 = time.monotonic()
    for i, p in enumerate(prompts):
        te = time.monotonic()
        gen = sh.remote({"prompt": p, "max_tokens": args.max_tokens})
        th = threading.Thread(target=consume, args=(i, gen, te),
                              daemon=True)
        th.start()
        threads.append(th)
        time.sleep(float(rng.exponential(1.0 / args.rate)))
    for th in threads:
        th.join(timeout=300)
    wall = time.monotonic() - t0

    n_tokens = sum(r["num_generated"] for r in results if r)
    dropped = sum(1 for r in results if not r)
    serve.delete("bench-llm")
    return {
        "tokens_per_sec": n_tokens / wall,
        "ttft_p50_ms": float(np.nanpercentile(ttft, 50)),
        "ttft_p99_ms": float(np.nanpercentile(ttft, 99)),
        "requests": args.n,
        "dropped": dropped,
        "wall_s": wall,
        "total_tokens": n_tokens,
        "mode": "serve",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--rate", type=float, default=8.0,
                    help="open-loop arrival rate, req/s")
    ap.add_argument("--model", default="gpt2")
    ap.add_argument("--preset", default="tiny")
    ap.add_argument("--max-tokens", type=int, default=16)
    ap.add_argument("--max-model-len", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--num-blocks", type=int, default=None)
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--trace", default=None,
                    help="also dump a chrome trace to this file "
                         "(merged cluster timeline in --serve mode)")
    args = ap.parse_args()

    extra = bench_serve_deployment(args) if args.serve \
        else bench_engine(args)
    out = {
        "metric": "serve_llm_tokens_per_sec",
        "value": round(extra["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "secondary_metrics": [
            {"metric": "serve_llm_ttft_p50", "unit": "ms",
             "value": round(extra["ttft_p50_ms"], 1)},
            {"metric": "serve_llm_ttft_p99", "unit": "ms",
             "value": round(extra["ttft_p99_ms"], 1)},
        ],
        "extra": extra,
    }
    print(json.dumps(out))
    with open("SERVE_BENCH.json", "w") as f:
        json.dump(out, f, indent=2)
    if args.trace:
        from ray_tpu.util import tracing

        tracing.dump(args.trace)
        print(f"# wrote trace to {args.trace}")


if __name__ == "__main__":
    main()
