"""Serving benchmark — synthetic open-loop load on the LLM engine.

Open-loop (arrivals don't wait for completions, Poisson
inter-arrivals) is the honest serving shape: closed-loop benchmarks
self-throttle and hide queueing collapse. Two scenarios:

- **open-loop** (headline): random prompts, fresh every sample —
  measures raw continuous-batching throughput + TTFT;
- **shared-prefix**: N requests sharing one long common prefix (the
  RL-rollout / system-prompt shape), run twice — once against a
  cold engine with prefix caching DISABLED and once against a warm
  prefix cache — so the automatic-prefix-caching win is measured
  against its own cold baseline;
- **spec-decode**: greedy open-loop A/B on a repetitive workload —
  speculation off vs n-gram drafts at K in {2, 4, 8} — in the
  shallow-batch latency regime where speculative decoding lives
  (SERVING.md "Speculative decoding"); reports tokens/s per arm,
  accept rate, and an output-identity check (greedy spec-on must be
  bit-identical to spec-off).

Both scenarios follow the PERF_NOTES round-5 recipe instead of
single-shot numbers: idle-gate (wait for loadavg < 0.7), median of 7
samples with a stdev field, and retry-on-variance (re-measure up to 3
attempts when stdev > 8% of the median, keep the steadiest attempt).

Emits one BENCH-style JSON line (headline: generated tokens/s;
secondary: TTFT p50/p99 and the warm/cold shared-prefix TTFTs) and
writes SERVE_BENCH.json, so future PRs have a serving perf trajectory
next to bench.py's training numbers.

    python bench_serve.py [--n 64] [--rate 8] [--model gpt2]
                          [--preset tiny] [--max-tokens 16] [--serve]
                          [--samples 7] [--skip-shared-prefix]

Default drives a bare in-process engine (scheduler+runner+cache, no
RPC). `--serve` runs the open-loop load through a real serve
deployment and DeploymentHandle streaming instead — engine + serve
overhead together (single-shot: RPC latency dominates, the recipe's
variance control buys little there).
"""

from __future__ import annotations

import argparse
import json
import statistics
import threading
import time

import numpy as np


def _requests(n, seed, max_len=32):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 500, size=int(rng.randint(8, max_len))).tolist()
            for _ in range(n)]


def _wait_for_idle(max_wait_s: float = 240.0, load_thresh: float = 0.7):
    """Idle-gate (PERF_NOTES round 5): this bench is contention-
    sensitive on a 1-core VM, so wait for the load average to settle
    before measuring."""
    import os

    t0 = time.monotonic()
    while time.monotonic() - t0 < max_wait_s:
        try:
            load1 = os.getloadavg()[0]
        except OSError:
            return 0.0
        if load1 < load_thresh:
            return time.monotonic() - t0
        time.sleep(5.0)
    return time.monotonic() - t0


# --profile: collapsed stacks accumulated across every measured window
# (the _recipe sample runs — never warmup, engine build, or idle-gate
# waits). None = unarmed = nothing constructed.
_profile_stacks: dict | None = None


def _measured(run_sample, samples: int) -> list:
    """Run one attempt's sample windows, under the stack sampler when
    --profile armed it (the sampler covers exactly the measured
    region; unarmed runs construct nothing)."""
    from ray_tpu.util import profiler

    with profiler.accumulate(_profile_stacks):
        return [run_sample(i) for i in range(samples)]


def _recipe(run_sample, *, samples: int, control_key: str,
            attempts: int = 3) -> dict:
    """Round-5 measurement recipe: idle gate, median-of-`samples` for
    every numeric metric the sample returns, stdev + relative stdev on
    `control_key`, retry-on-variance (keep the steadiest attempt)."""
    best = None
    for attempt in range(attempts):
        waited = _wait_for_idle()
        rows = _measured(run_sample, samples)
        keys = [k for k, v in rows[0].items()
                if isinstance(v, (int, float))]
        agg = {k: float(statistics.median([r[k] for r in rows]))
               for k in keys}
        ctl = [r[control_key] for r in rows]
        med = statistics.median(ctl)
        sd = statistics.pstdev(ctl)
        agg.update({
            f"{control_key}_stdev": sd,
            "rel_stdev": (sd / med) if med else 1e9,
            "samples": samples,
            "attempt": attempt + 1,
            "idle_wait_s": round(waited, 1),
        })
        if best is None or agg["rel_stdev"] < best["rel_stdev"]:
            best = agg
        if agg["rel_stdev"] <= 0.08:
            break
    return best


def _drive_open_loop(eng, prompts, sp, rate, seed) -> dict:
    """Submit `prompts` open-loop (Poisson at `rate` req/s) against a
    running engine; one reader thread per stream so TTFT is measured at
    first-token ARRIVAL, concurrent with the arrivals."""
    n = len(prompts)
    ttft = [float("nan")] * n
    finals = [None] * n

    def consume(i, stream, te):
        try:
            first = stream.next_event(timeout=300)
            if first is not None:
                ttft[i] = (time.monotonic() - te) * 1e3
            for _ in stream:
                pass
            finals[i] = stream.final()
        except Exception:  # noqa: BLE001  (stalled engine: leave None)
            pass

    rng = np.random.RandomState(seed)
    readers = []
    t0 = time.monotonic()
    for i, p in enumerate(prompts):
        te = time.monotonic()
        s = eng.add_request(p, sp)
        th = threading.Thread(target=consume, args=(i, s, te), daemon=True)
        th.start()
        readers.append(th)
        time.sleep(float(rng.exponential(1.0 / rate)))
    for th in readers:
        th.join(timeout=300)
    wall = time.monotonic() - t0

    n_tokens = sum(f["num_generated"] for f in finals if f)
    dropped = sum(1 for f in finals
                  if f is None or f["finish_reason"].startswith("error"))
    return {
        "tokens_per_sec": n_tokens / wall,
        "ttft_p50_ms": float(np.nanpercentile(ttft, 50)),
        "ttft_p99_ms": float(np.nanpercentile(ttft, 99)),
        "requests": n,
        "dropped": dropped,
        "wall_s": wall,
        "total_tokens": n_tokens,
    }


def _mk_engine(args, **overrides):
    from ray_tpu.serve.llm import EngineConfig, LLMEngine

    cfg = dict(model=args.model, preset=args.preset, block_size=16,
               max_model_len=args.max_model_len, max_batch_size=args.batch,
               num_blocks=args.num_blocks)
    cfg.update(overrides)
    eng = LLMEngine(EngineConfig(**cfg))
    eng.warmup()  # compile every bucketed program outside measurement
    stop = threading.Event()

    def step_loop():
        while not stop.is_set():
            if not eng.step():
                time.sleep(0.0005)

    threading.Thread(target=step_loop, daemon=True).start()
    return eng, stop


def bench_engine(args) -> dict:
    from ray_tpu.serve.llm import SamplingParams

    eng, stop = _mk_engine(args)
    sp = SamplingParams(max_tokens=args.max_tokens)

    def sample(i):
        # fresh prompts every sample: the open-loop scenario must stay
        # prefix-cache-cold or it would quietly measure the warm path
        prompts = _requests(args.n, seed=1000 + i,
                            max_len=args.max_model_len // 2)
        return _drive_open_loop(eng, prompts, sp, args.rate, seed=i)

    out = _recipe(sample, samples=args.samples,
                  control_key="tokens_per_sec")
    st = eng.stats()
    stop.set()
    out.update({
        "preemptions": st["preemptions"],
        "compiled_programs": st["compiled_programs"],
        "mode": "engine",
    })
    return out


def bench_shared_prefix(args) -> dict:
    """N requests x one long common prefix. Cold = prefix caching
    disabled (every request pays the full prefill); warm = caching on,
    cache primed. The acceptance gate compares warm TTFT p50 against
    the cold run's."""
    from ray_tpu.serve.llm import SamplingParams

    rng = np.random.RandomState(77)
    # the scenario runs its own engine with a context of >= 512: the
    # cold/warm contrast is the prefix's prefill COMPUTE, which must
    # dominate the fixed per-step dispatch overhead (~ms on this box)
    # that both sides pay per request — a 96-token prefix on the tiny
    # preset is below that floor and the measured ratio degenerates to
    # overhead/overhead regardless of how much prefill was skipped
    ctx_len = max(args.max_model_len, 512)
    prefix_len = int(ctx_len * 0.75)
    prefix = rng.randint(1, 500, size=prefix_len).tolist()
    # stretch the preset's positional range to the scenario context
    import dataclasses

    from ray_tpu.serve.llm.runner import adapters

    model_cfg = dataclasses.replace(
        adapters()[args.model].presets[args.preset](), block_size=ctx_len)
    suffix_len = 4
    # TTFT is a PREFILL metric: any decode tail adds identical work to
    # both runs, and with a burst deeper than max_batch_size it comes
    # to DOMINATE slot turnover — queued requests then wait on
    # predecessors' decodes, not their prefills, and the cold/warm
    # contrast drowns. One token per request keeps slot turnover pure
    # prefill (the first token is sampled from the final chunk's
    # logits; no decode step runs at all).
    sp = SamplingParams(max_tokens=1)
    # 32 bursty requests per sample: the cold/warm contrast is one
    # ~prefix_len prefill per request, which at 16 requests is the same
    # order as this box's scheduler jitter — a deeper queue amplifies
    # the contrast and steadies the per-sample percentiles
    n = min(args.n, 32)
    # TRUE burst arrivals (zero inter-arrival gap): the shared-prefix
    # shape IS the burst shape (thousands of rollouts forking one
    # prompt at once), and it is the queued-up prefill BACKLOG that
    # caching removes from TTFT. A finite rate lets arrivals outpace
    # the queue on an idle box and the contrast collapses to a single
    # prefill — the measurement then flips between queued and
    # unqueued regimes run to run.
    rate = float("inf")

    def prompts_for(sample):
        r = np.random.RandomState(500 + sample)
        return [prefix + r.randint(1, 500, size=suffix_len).tolist()
                for _ in range(n)]

    results = {}
    for label, overrides in (
            ("cold", {"enable_prefix_cache": False}),
            ("warm", {"enable_prefix_cache": True})):
        eng, stop = _mk_engine(args, max_model_len=ctx_len,
                               model_config=model_cfg, **overrides)
        if label == "warm":  # prime the prefix once, outside measurement
            eng.generate(prefix + [7] * suffix_len,
                         SamplingParams(max_tokens=1), timeout=300)

        def sample(i, eng=eng):
            return _drive_open_loop(eng, prompts_for(i), sp, rate,
                                    seed=i)

        results[label] = _recipe(sample, samples=args.samples,
                                 control_key="ttft_p50_ms")
        st = eng.stats()
        results[label].update({
            "prefix_hit_pages": st["prefix_hit_pages"],
            "prefix_evictions": st["prefix_evictions"],
        })
        stop.set()
    warm, cold = results["warm"], results["cold"]
    speedup = cold["ttft_p50_ms"] / warm["ttft_p50_ms"] \
        if warm["ttft_p50_ms"] else float("nan")
    return {"cold": cold, "warm": warm,
            "prefix_tokens": prefix_len,
            "ttft_p50_speedup": round(speedup, 2)}


def bench_spec_decode(args) -> dict:
    """Greedy A/B: speculation off vs n-gram drafts at K in {2,4,8}.

    The workload is repetitive-by-construction (each prompt is a short
    motif tiled) AND repetitive-by-behavior: tiny greedy models settle
    into a periodic cycle within a few tokens, and once one full cycle
    is in the history the prompt-lookup proposer drafts the next K
    tokens of the model's own loop — the shape RL rollouts and
    template-heavy serving traffic actually have.

    The scenario is DECODE-dominated and pinned to a shallow decode
    batch (max_batch_size=2, short prompts, long generation):
    speculative decoding is a latency-regime optimization — its win is
    committed tokens per program dispatch, and at full batch the plain
    decode path already amortizes dispatch across lanes, while a
    prefill-heavy mix dilutes any decode win with admission time both
    arms pay identically (the deep-batch, prefill-mixed regime belongs
    to the headline open-loop scenario above). Arrivals are a burst so
    the measured wall is completion time, not the arrival span."""
    from ray_tpu.serve.llm import SamplingParams

    sp = SamplingParams(max_tokens=64, temperature=0.0)
    n = min(args.n, 4)

    def prompts_for(sample):
        r = np.random.RandomState(900 + sample)
        out = []
        for _ in range(n):
            motif = r.randint(1, 500, size=4).tolist()
            out.append(motif * 2)  # one-chunk prefill, cycle visible
        return out

    arms: dict = {}
    outputs: dict = {}
    check_prompts = prompts_for(0)[:4]
    for label, k in (("off", 0), ("k2", 2), ("k4", 4), ("k8", 8)):
        overrides: dict = {"max_batch_size": 2}
        if k:
            overrides["speculative"] = {"num_draft_tokens": k}
        eng, stop = _mk_engine(args, **overrides)

        def sample(i, eng=eng):
            return _drive_open_loop(eng, prompts_for(i), sp,
                                    float("inf"), seed=i)

        arms[label] = _recipe(sample, samples=args.samples,
                              control_key="tokens_per_sec")
        # bit-identity probe: greedy outputs on fixed prompts must not
        # depend on whether speculation ran (the acceptance rule only
        # ever commits tokens the target program sampled itself)
        outputs[label] = [tuple(eng.generate(p, sp, timeout=300)
                                ["token_ids"]) for p in check_prompts]
        st = eng.stats()
        arms[label]["accept_rate"] = round(
            st["spec_accepted"] / st["spec_proposed"], 3) \
            if st["spec_proposed"] else None
        arms[label]["draft_tokens"] = k
        # TPOT from the engine's own waterfall: decode + verify seconds
        # over tokens-after-the-first (every request here emits exactly
        # max_tokens, probes included) — the spec win should show up as
        # a lower per-token cost, not just a wall-clock artifact
        ph = st["phase_seconds"]
        n_out = st["finished_requests"] * (sp.max_tokens - 1)
        arms[label]["tpot_ms"] = round(
            1e3 * (ph.get("decode", 0.0) + ph.get("verify", 0.0))
            / max(1, n_out), 3)
        stop.set()

    base = arms["off"]["tokens_per_sec"]
    speedup = {label: round(arms[label]["tokens_per_sec"] / base, 2)
               for label in ("k2", "k4", "k8")}
    best = max(speedup, key=speedup.get)
    return {
        **arms,
        "speedup": speedup,
        "best": {"arm": best, "speedup": speedup[best]},
        "outputs_match": all(outputs[lbl] == outputs["off"]
                             for lbl in ("k2", "k4", "k8")),
    }


def bench_serve_deployment(args) -> dict:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    ray_tpu.init(num_cpus=8)
    handle = serve.run(build_llm_app(
        model=args.model, preset=args.preset,
        engine_config={"block_size": 16,
                       "max_model_len": args.max_model_len,
                       "max_batch_size": args.batch,
                       "num_blocks": args.num_blocks}), name="bench-llm")
    sh = handle.options(stream=True, generator_backpressure=128)
    prompts = _requests(args.n, seed=0, max_len=args.max_model_len // 2)
    # warm-up
    for r in sh.remote({"prompt": prompts[0], "max_tokens": 2}):
        ray_tpu.get(r, timeout=300)

    results = [None] * args.n
    ttft = [float("nan")] * args.n

    def consume(i, gen, te):
        events = []
        for r in gen:
            events.append(ray_tpu.get(r, timeout=300))
            if len(events) == 1:
                ttft[i] = (time.monotonic() - te) * 1e3
        results[i] = events[-1]

    rng = np.random.RandomState(1)
    threads = []
    t0 = time.monotonic()
    for i, p in enumerate(prompts):
        te = time.monotonic()
        gen = sh.remote({"prompt": p, "max_tokens": args.max_tokens})
        th = threading.Thread(target=consume, args=(i, gen, te),
                              daemon=True)
        th.start()
        threads.append(th)
        time.sleep(float(rng.exponential(1.0 / args.rate)))
    for th in threads:
        th.join(timeout=300)
    wall = time.monotonic() - t0

    n_tokens = sum(r["num_generated"] for r in results if r)
    dropped = sum(1 for r in results if not r)
    serve.delete("bench-llm")
    return {
        "tokens_per_sec": n_tokens / wall,
        "ttft_p50_ms": float(np.nanpercentile(ttft, 50)),
        "ttft_p99_ms": float(np.nanpercentile(ttft, 99)),
        "requests": args.n,
        "dropped": dropped,
        "wall_s": wall,
        "total_tokens": n_tokens,
        "mode": "serve",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--rate", type=float, default=8.0,
                    help="open-loop arrival rate, req/s")
    ap.add_argument("--model", default="gpt2")
    ap.add_argument("--preset", default="tiny")
    ap.add_argument("--max-tokens", type=int, default=16)
    ap.add_argument("--max-model-len", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--num-blocks", type=int, default=None)
    ap.add_argument("--samples", type=int, default=7,
                    help="samples per attempt (round-5 recipe)")
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--skip-shared-prefix", action="store_true")
    ap.add_argument("--skip-spec", action="store_true")
    ap.add_argument("--trace", default=None,
                    help="also dump a chrome trace to this file "
                         "(merged cluster timeline in --serve mode)")
    ap.add_argument("--profile", action="store_true",
                    help="arm the stack sampler around the measured "
                         "windows and write flamegraph-compatible "
                         ".collapsed stacks next to the --trace "
                         "artifact")
    args = ap.parse_args()

    global _profile_stacks
    if args.profile:
        _profile_stacks = {}
    extra = bench_serve_deployment(args) if args.serve \
        else bench_engine(args)
    secondary = [
        {"metric": "serve_llm_ttft_p50", "unit": "ms",
         "value": round(extra["ttft_p50_ms"], 1)},
        {"metric": "serve_llm_ttft_p99", "unit": "ms",
         "value": round(extra["ttft_p99_ms"], 1)},
    ]
    if not args.serve and not args.skip_shared_prefix:
        shared = bench_shared_prefix(args)
        extra["shared_prefix"] = shared
        secondary += [
            {"metric": "serve_llm_shared_prefix_ttft_p50_cold",
             "unit": "ms",
             "value": round(shared["cold"]["ttft_p50_ms"], 1)},
            {"metric": "serve_llm_shared_prefix_ttft_p50_warm",
             "unit": "ms",
             "value": round(shared["warm"]["ttft_p50_ms"], 1)},
            {"metric": "serve_llm_shared_prefix_ttft_speedup",
             "unit": "x", "value": shared["ttft_p50_speedup"]},
        ]
    if not args.serve and not args.skip_spec:
        spec = bench_spec_decode(args)
        extra["spec_decode"] = spec
        secondary += [
            {"metric": "serve_llm_spec_tokens_per_sec_off",
             "unit": "tokens/s",
             "value": round(spec["off"]["tokens_per_sec"], 1)},
            {"metric": f"serve_llm_spec_tokens_per_sec_{spec['best']['arm']}",
             "unit": "tokens/s",
             "value": round(spec[spec["best"]["arm"]]["tokens_per_sec"], 1)},
            {"metric": "serve_llm_spec_speedup_best", "unit": "x",
             "value": spec["best"]["speedup"]},
            {"metric": "serve_llm_spec_accept_rate_k4", "unit": "ratio",
             "value": spec["k4"]["accept_rate"]},
        ]
    out = {
        "metric": "serve_llm_tokens_per_sec",
        "value": round(extra["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "secondary_metrics": secondary,
        "extra": extra,
    }
    print(json.dumps(out))
    with open("SERVE_BENCH.json", "w") as f:
        json.dump(out, f, indent=2)
    if args.trace:
        from ray_tpu.util import tracing

        tracing.dump(args.trace)
        print(f"# wrote trace to {args.trace}")
    if args.profile:
        from ray_tpu.util import profiler

        path = (f"{args.trace}.collapsed" if args.trace
                else "bench_serve.collapsed")
        profiler.write_collapsed(path, _profile_stacks or {})
        print(f"# wrote collapsed stacks to {path}")


if __name__ == "__main__":
    main()
