"""GCP TPU node provider — pod-slice autoscaling via queued resources.

Reference parity: autoscaler/_private/gcp/node.py:191 (GCPTPUNode /
queued-resource lifecycle), gcp/config.py:15 (accelerator-type →
slice shape), gcp/tpu_command_runner.py:1 (per-host fan-out of setup
commands across a pod slice). The GCP surface is mocked
(FakeTPUQueuedResourceAPI) because this image has zero egress — the
provider speaks the same request/state machine a real client would
(create → ACCEPTED → PROVISIONING → ACTIVE; delete is whole-slice
atomic), so swapping in the real REST client is a transport change,
not a redesign.

TPU-native semantics the generic provider lacks:
- the unit of creation/deletion is a SLICE (N hosts appear/vanish
  together, matching queued-resources atomicity);
- every host registers with slice-identity labels
  (ray.io/tpu-slice, ray.io/tpu-worker-id, pod type, topology) so
  slice-gang placement groups land on one slice in worker-id order;
- worker 0 asserts the `TPU-{pod_type}-head` marker resource.
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu.autoscaler import NodeProvider
from ray_tpu.core import tpu as tpu_mod


def slice_shape(accelerator_type: str) -> tuple[int, int]:
    """(num_hosts, chips_per_host) for an accelerator type like
    "v4-16". The numeric suffix counts TensorCores for v2/v3 (8 per
    host) and chips for v4+ (4 per host) — reference: gcp/config.py
    accelerator parsing + tpu.py pod-type arithmetic."""
    try:
        gen, n = accelerator_type.split("-", 1)
        n = int(n)
    except ValueError:
        raise ValueError(f"malformed accelerator_type {accelerator_type!r}")
    per_host = 8 if gen in ("v2", "v3") else 4
    return max(1, n // per_host), per_host if gen not in ("v2", "v3") else 4


# ------------------------------------------------------------ fake API

ACCEPTED = "ACCEPTED"
PROVISIONING = "PROVISIONING"
ACTIVE = "ACTIVE"
FAILED = "FAILED"
DELETING = "DELETING"


class FakeTPUQueuedResourceAPI:
    """In-memory double of the TPU queued-resources API: the same
    create/get/delete verbs and state machine, advancing one state per
    poll so tests drive provisioning deterministically."""

    def __init__(self, provision_polls: int = 2):
        self._qrs: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._provision_polls = provision_polls
        self._fail_next = 0
        self.create_calls = 0
        self.delete_calls = 0

    def fail_next_creations(self, n: int):
        """Inject provisioning failures (stockout) for the next n QRs."""
        with self._lock:
            self._fail_next = n

    def create_queued_resource(self, name: str, accelerator_type: str,
                               runtime_version: str = "tpu-ubuntu2204-base"):
        with self._lock:
            if name in self._qrs:
                raise ValueError(f"queued resource {name!r} already exists")
            hosts, chips = slice_shape(accelerator_type)
            fail = self._fail_next > 0
            if fail:
                self._fail_next -= 1
            self._qrs[name] = {
                "name": name,
                "accelerator_type": accelerator_type,
                "runtime_version": runtime_version,
                "state": ACCEPTED,
                "polls": 0,
                "will_fail": fail,
                "num_hosts": hosts,
                "chips_per_host": chips,
            }
            self.create_calls += 1
            return dict(self._qrs[name])

    def get_queued_resource(self, name: str) -> dict:
        with self._lock:
            qr = self._qrs.get(name)
            if qr is None:
                raise KeyError(name)
            if qr["state"] in (ACCEPTED, PROVISIONING):
                qr["polls"] += 1
                if qr["will_fail"]:
                    qr["state"] = FAILED
                elif qr["polls"] >= self._provision_polls:
                    qr["state"] = ACTIVE
                else:
                    qr["state"] = PROVISIONING
            if qr["state"] == ACTIVE and "hosts" not in qr:
                qr["hosts"] = [
                    {"worker_id": i,
                     "internal_ip": f"10.130.0.{i + 1}",
                     "hostname": f"{name}-w{i}"}
                    for i in range(qr["num_hosts"])
                ]
            return dict(qr)

    def delete_queued_resource(self, name: str):
        """Whole-slice atomic delete (all hosts vanish together)."""
        with self._lock:
            if name in self._qrs:
                self._qrs[name]["state"] = DELETING
                del self._qrs[name]
                self.delete_calls += 1

    def list_queued_resources(self) -> list[dict]:
        with self._lock:
            return [dict(q) for q in self._qrs.values()]


# ------------------------------------------------------------ provider


class _SliceHost:
    """One host of a provisioned slice; the autoscaler sees hosts, the
    provider deletes slices."""

    __slots__ = ("slice_name", "worker_id", "nodelet")

    def __init__(self, slice_name: str, worker_id: int, nodelet):
        self.slice_name = slice_name
        self.worker_id = worker_id
        self.nodelet = nodelet


class _PendingHost:
    """Placeholder for a host of a still-provisioning slice so the
    autoscaler's max_workers accounting sees in-flight capacity and
    does not over-launch."""

    __slots__ = ("slice_name",)

    def __init__(self, slice_name: str):
        self.slice_name = slice_name


class GCPTPUNodeProvider(NodeProvider):
    """NodeProvider over (fake) queued resources. node_types entries:
    {"accelerator_type": "v4-16", "cpus_per_host": 4, "topology": "2x2x2"}.

    In this image the "hosts" boot as in-process Nodelets (the same
    trick as FakeNodeProvider); a real deployment replaces _boot_host
    with a TPUCommandRunner-style SSH bootstrap per host (reference:
    gcp/tpu_command_runner.py fans one command out to every pod
    worker)."""

    def __init__(self, head_address: str, node_types: dict[str, dict],
                 api: FakeTPUQueuedResourceAPI | None = None,
                 session_dir: str = "/tmp/ray_tpu/gcp"):
        self.head_address = head_address
        self.node_types = node_types
        self.api = api or FakeTPUQueuedResourceAPI()
        self.session_dir = session_dir
        self._lock = threading.Lock()
        self._counter = 0
        self._pending: dict[str, dict] = {}  # slice -> node_type spec
        self._booting: dict[str, dict] = {}  # claimed by a poll(), booting
        self._slices: dict[str, list[_SliceHost]] = {}
        self.failed_slices: list[str] = []

    # -- NodeProvider surface -------------------------------------------

    def create_node(self, node_type: str):
        spec = self.node_types[node_type]
        with self._lock:
            self._counter += 1
            name = f"qr-{node_type}-{self._counter}"
        self.api.create_queued_resource(name, spec["accelerator_type"])
        with self._lock:
            self._pending[name] = spec
        return _PendingHost(name)

    def terminate_node(self, handle: Any):
        name = handle.slice_name
        self.api.delete_queued_resource(name)
        with self._lock:
            hosts = self._slices.pop(name, [])
            self._pending.pop(name, None)
            self._booting.pop(name, None)
        for h in hosts:  # whole-slice teardown, worker order irrelevant
            try:
                h.nodelet.stop()
            except Exception:  # noqa: BLE001
                pass

    def non_terminated_nodes(self) -> list:
        self.poll()
        out: list = []
        with self._lock:
            for hosts in self._slices.values():
                out.extend(hosts)
            for name, spec in {**self._pending, **self._booting}.items():
                n_hosts, _ = slice_shape(spec["accelerator_type"])
                out.extend(_PendingHost(name) for _ in range(n_hosts))
        return out

    def node_id(self, handle: Any) -> bytes:
        if isinstance(handle, _SliceHost):
            return handle.nodelet.node_id
        return b""  # pending: not in the head view yet

    # -- queued-resource reconciliation ---------------------------------

    def poll(self):
        """Advance pending slices; boot every host of a slice the moment
        it turns ACTIVE (hosts of one slice appear together)."""
        with self._lock:
            pending = list(self._pending.items())
        for name, spec in pending:
            try:
                qr = self.api.get_queued_resource(name)
            except KeyError:
                with self._lock:
                    self._pending.pop(name, None)
                continue
            if qr["state"] == FAILED:
                self.api.delete_queued_resource(name)
                with self._lock:
                    self._pending.pop(name, None)
                    self.failed_slices.append(name)
                continue
            if qr["state"] != ACTIVE:
                continue
            # CLAIM the slice under the lock BEFORE booting: concurrent
            # poll() callers (autoscaler loop + any non_terminated_nodes
            # caller) would otherwise each boot N nodelets and leak the
            # loser's set under duplicate slice/worker-id labels
            with self._lock:
                if self._pending.pop(name, None) is None:
                    continue  # another poll() claimed it
                self._booting[name] = spec  # still counted as capacity
            hosts = []
            for h in qr["hosts"]:
                hosts.append(self._boot_host(name, spec, qr, h))
            with self._lock:
                self._booting.pop(name, None)
                self._slices[name] = hosts

    def _boot_host(self, slice_name: str, spec: dict, qr: dict,
                   host: dict) -> _SliceHost:
        from ray_tpu.core.nodelet import Nodelet

        wid = host["worker_id"]
        labels = {
            tpu_mod.SLICE_LABEL: slice_name,
            tpu_mod.WORKER_ID_LABEL: str(wid),
            tpu_mod.POD_TYPE_LABEL: spec["accelerator_type"],
        }
        if spec.get("topology"):
            labels[tpu_mod.TOPOLOGY_LABEL] = spec["topology"]
        resources = {
            "CPU": float(spec.get("cpus_per_host", 4)),
            "TPU": float(qr["chips_per_host"]),
        }
        resources.update(tpu_mod.head_marker_resources(labels))
        nl = Nodelet(self.head_address, resources, labels=labels,
                     session_dir=self.session_dir,
                     store_capacity=spec.get("store_capacity",
                                             64 * 1024 * 1024)).start()
        return _SliceHost(slice_name, wid, nl)
