"""Blockwise causal flash attention — pallas TPU kernels, fwd + bwd.

This is the fused-attention role the reference delegates to
cuDNN/torch SDPA (SURVEY.md §2.5); on TPU we own the kernel. Design
(FlashAttention-2 style, online softmax):

- forward: grid (B*H, T/Bq, T/Bk), innermost k-blocks sequential; scratch
  carries the running row-max m, row-sum l and the f32 output accumulator
  across k-blocks; softmax statistics are float32 always; the logsumexp
  per row is emitted for the backward pass.
- backward: two kernels (no atomics on TPU) — dq over (BH, q, k) and
  dk/dv over (BH, k, q) — both recompute p = exp(s - lse) blockwise, so
  nothing O(T²) is ever materialized.
- causal blocks strictly above the diagonal are skipped entirely
  (`pl.when` on block indices), halving compute at long T.
- matmuls run on the MXU with preferred_element_type=float32; inputs may
  be bfloat16.

All kernels run in interpret mode on CPU for testing.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable without TPU; interpret mode needs no hardware
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


@dataclasses.dataclass(frozen=True)
class _Cfg:
    causal: bool
    sm_scale: float
    block_q: int
    block_k: int
    interpret: bool


def _vmem_spec(shape, index_map):
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s, *, cfg,
                nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    Bq = q_ref.shape[1]
    Bk = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, -jnp.inf)
        l_s[:] = jnp.zeros_like(l_s)
        acc[:] = jnp.zeros_like(acc)

    run = True
    if cfg.causal:
        run = ki * Bk <= qi * Bq + Bq - 1

    @pl.when(run)
    def _step():
        q = q_ref[0]  # (Bq, D)
        k = k_ref[0]  # (Bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * cfg.sm_scale
        if cfg.causal:
            rows = qi * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
            cols = ki * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
            s = jnp.where(cols <= rows, s, DEFAULT_MASK_VALUE)
        m_prev = m_s[:, :1]  # (Bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # (Bq, 1)
        p = jnp.exp(s - m_new)  # (Bq, Bk) f32
        l_new = alpha * l_s[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc[:] = acc[:] * alpha + pv
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == nk - 1)
    def _emit():
        l = l_s[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_s[:, :1] + jnp.log(l_safe)


def _fwd(q, k, v, cfg: _Cfg):
    BH, T, D = q.shape
    nq = T // cfg.block_q
    nk = T // cfg.block_k
    Bq, Bk = cfg.block_q, cfg.block_k
    kernel = functools.partial(_fwd_kernel, cfg=cfg, nk=nk)
    scratch = [
        _scratch((Bq, D), jnp.float32),
        _scratch((Bq, 128), jnp.float32),
        _scratch((Bq, 128), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            _vmem_spec((1, Bq, D), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, Bk, D), lambda b, i, j: (b, j, 0)),
            _vmem_spec((1, Bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            _vmem_spec((1, Bq, D), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, Bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=cfg.interpret,
    )(q, k, v)
    return o, lse


def _scratch(shape, dtype):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    return pl.ANY(shape, dtype)  # pragma: no cover


# ---------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, cfg, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    Bq = q_ref.shape[1]
    Bk = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if cfg.causal:
        run = ki * Bk <= qi * Bq + Bq - 1

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * cfg.sm_scale
        p = jnp.exp(s - lse_ref[0])  # (Bq, Bk); lse block is (Bq, 1)
        if cfg.causal:
            rows = qi * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
            cols = ki * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dp = jax.lax.dot_general(
            do_ref[0], v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * cfg.sm_scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _emit():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc, dv_acc, *, cfg, nq):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    Bk = k_ref.shape[1]
    Bq = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if cfg.causal:
        run = ki * Bk <= qi * Bq + Bq - 1

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * cfg.sm_scale
        p = jnp.exp(s - lse_ref[0])
        if cfg.causal:
            rows = qi * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
            cols = ki * Bk + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * cfg.sm_scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _emit():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, cfg: _Cfg):
    BH, T, D = q.shape
    Bq, Bk = cfg.block_q, cfg.block_k
    nq, nk = T // Bq, T // Bk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # (BH, T, 1)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg=cfg, nk=nk),
        grid=(BH, nq, nk),
        in_specs=[
            _vmem_spec((1, Bq, D), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, Bk, D), lambda b, i, j: (b, j, 0)),
            _vmem_spec((1, Bk, D), lambda b, i, j: (b, j, 0)),
            _vmem_spec((1, Bq, D), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, Bq, 1), lambda b, i, j: (b, i, 0)),
            _vmem_spec((1, Bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=_vmem_spec((1, Bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[_scratch((Bq, D), jnp.float32)],
        interpret=cfg.interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg=cfg, nq=nq),
        grid=(BH, nk, nq),
        in_specs=[
            _vmem_spec((1, Bq, D), lambda b, j, i: (b, i, 0)),
            _vmem_spec((1, Bk, D), lambda b, j, i: (b, j, 0)),
            _vmem_spec((1, Bk, D), lambda b, j, i: (b, j, 0)),
            _vmem_spec((1, Bq, D), lambda b, j, i: (b, i, 0)),
            _vmem_spec((1, Bq, 1), lambda b, j, i: (b, i, 0)),
            _vmem_spec((1, Bq, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            _vmem_spec((1, Bk, D), lambda b, j, i: (b, j, 0)),
            _vmem_spec((1, Bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        scratch_shapes=[_scratch((Bk, D), jnp.float32),
                        _scratch((Bk, D), jnp.float32)],
        interpret=cfg.interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------- public


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, cfg: _Cfg):
    o, _ = _fwd(q, k, v, cfg)
    return o


def _flash_fwd(q, k, v, cfg: _Cfg):
    o, lse = _fwd(q, k, v, cfg)
    # Name the kernel outputs so a remat policy can SAVE them: under
    # jax.checkpoint(block) the backward replay would otherwise re-run
    # this pallas forward just to rebuild (o, lse) residuals — the
    # lse-saving policy (models.gpt2 remat_policy="save_flash") keeps
    # them and the replay's flash fwd is dead-code-eliminated.
    from jax.ad_checkpoint import checkpoint_name

    o_res = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o_res, lse)


def _flash_bwd(cfg: _Cfg, res, do):
    q, k, v, o, lse = res
    return _bwd(q, k, v, o, lse, do, cfg)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fit_block(T: int, want: int) -> int:
    """Largest power-of-two block <= want that divides T (so e.g. T=1536
    runs with 512 blocks instead of failing the 1024 default)."""
    b = min(want, T)
    while b > 128 and T % b:
        b //= 2
    return b


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: float | None = None,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: bool = False) -> jax.Array:
    """q,k,v: (B, T, H, D) -> (B, T, H, D).

    Differentiable (custom VJP with flash backward kernels). Requires T
    divisible by the block sizes (the dispatcher in ops.attention falls
    back to the einsum path otherwise)."""
    B, T, H, D = q.shape
    block_q = _fit_block(T, block_q)
    block_k = _fit_block(T, block_k)
    if T % block_q or T % block_k:
        raise ValueError(f"T={T} not divisible by blocks "
                         f"({block_q},{block_k})")
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    cfg = _Cfg(causal=causal, sm_scale=float(sm_scale),
               block_q=block_q, block_k=block_k, interpret=interpret)

    def to_bh(t):  # (B,T,H,D) -> (B*H, T, D)
        return t.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    o = _flash(to_bh(q), to_bh(k), to_bh(v), cfg)
    return o.reshape(B, H, T, D).transpose(0, 2, 1, 3)
