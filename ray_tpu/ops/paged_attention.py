"""Paged attention — pallas TPU kernel over the serve.llm KV page pool.

The serve decode/verify programs historically gathered each lane's
pages into a dense ``(L, S, max_blocks_per_seq * block_size, H_kv, D)``
context before attending (runner.py) — O(max_model_len) HBM traffic per
step regardless of how long the sequence actually is. This kernel is
the vLLM-PagedAttention shape instead (PAPERS.md): queries index the
page pool *in place* through the block table, one page per grid step,
with the table and context lengths delivered via scalar prefetch so the
page id is known before the page's DMA is issued.

Layout (one layer at a time — the models scan layers and call this
inside the scan body, so it compiles once):

- ``q``                (S, W, H, D)  — W query positions per sequence:
  W=1 is plain decode, W=K+1 is the speculative verify window;
- ``own_k``/``own_v``  (S, W, H_kv, D) — the window's OWN keys/values
  (they are never in the pages: decode/verify scatter them after the
  step), attended causally within the window;
- ``k_pages``/``v_pages`` (num_blocks, block_size, H_kv, D) — the pool;
- ``tables``           (S, max_blocks_per_seq) i32 — logical page i of
  sequence s lives in physical page ``tables[s, i]`` (padding points at
  the null page 0, which the length mask excludes anyway);
- ``ctx_len``          (S,) i32 — valid cached slots (positions
  < ctx_len[s] are real; everything else in the mapped pages is
  garbage past the lane's frontier).

Grid is (S, H, max_blocks_per_seq): the page axis is innermost and
sequential, carrying the online-softmax state (running max, sum, f32
accumulator) in VMEM scratch exactly like ops/flash_attention.py; pages
wholly past ``ctx_len`` are skipped with ``pl.when``; the final grid
step folds in the causal own-window block and normalizes. GQA maps
query head h to KV head ``h // (H // H_kv)`` in the index maps, so
grouped heads re-read the same page block.

``interpret=True`` runs the same kernel through the pallas interpreter
on CPU (tests, parity gates); on TPU it compiles for real. The dense
reference (`paged_attention_reference`) is the parity oracle at
atol 1e-4.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fine without TPU; interpret mode needs no hardware
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _vmem_spec(shape, index_map):
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)  # pragma: no cover


def _scratch(shape, dtype):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    return pl.ANY(shape, dtype)  # pragma: no cover


def _paged_kernel(tables_ref, ctxlen_ref, q_ref, ko_ref, vo_ref, kp_ref,
                  vp_ref, o_ref, acc, m_s, l_s, *, scale, nb, bs):
    s_i = pl.program_id(0)
    b = pl.program_id(2)
    W = q_ref.shape[1]

    @pl.when(b == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, -jnp.inf)
        l_s[:] = jnp.zeros_like(l_s)
        acc[:] = jnp.zeros_like(acc)

    ctx = ctxlen_ref[s_i]
    q = q_ref[0, :, 0, :]  # (W, D)

    def _accum(k, v, valid):  # k/v (N, D); valid (W, N) bool
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
        m_prev = m_s[:, :1]  # (W, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # (W, N) f32
        l_new = alpha * l_s[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc[:] = acc[:] * alpha + pv
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    # pages wholly past the frontier are skipped (their DMA still
    # lands, but no FLOPs are spent and the mask math never runs)
    @pl.when(b * bs < ctx)
    def _page():
        cols = b * bs + jax.lax.broadcasted_iota(jnp.int32, (W, bs), 1)
        _accum(kp_ref[0, :, 0, :], vp_ref[0, :, 0, :], cols < ctx)

    # last grid step: fold in the window's own keys (causal within the
    # window — query j sees keys 0..j) and emit the normalized output
    @pl.when(b == nb - 1)
    def _own_and_emit():
        rows = jax.lax.broadcasted_iota(jnp.int32, (W, W), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (W, W), 1)
        _accum(ko_ref[0, :, 0, :], vo_ref[0, :, 0, :], cols <= rows)
        l = l_s[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, :, 0, :] = (acc[:] / l_safe).astype(o_ref.dtype)


def paged_attention(q, own_k, own_v, k_pages, v_pages, tables, ctx_len,
                    *, sm_scale: float | None = None,
                    interpret: bool = False):
    """One layer of paged attention; see the module docstring for the
    operand layout. Returns (S, W, H, D) in q's dtype. Every query row
    attends [cached slots < ctx_len[s]] ++ [own window, causally]."""
    S, W, H, D = q.shape
    HK = own_k.shape[2]
    bs = k_pages.shape[1]
    maxB = tables.shape[1]
    rep = H // HK
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)
    kernel = functools.partial(_paged_kernel, scale=scale, nb=maxB,
                               bs=bs)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, H, maxB),
        in_specs=[
            _vmem_spec((1, W, 1, D),
                       lambda s, h, b, t, c: (s, 0, h, 0)),
            _vmem_spec((1, W, 1, D),
                       lambda s, h, b, t, c: (s, 0, h // rep, 0)),
            _vmem_spec((1, W, 1, D),
                       lambda s, h, b, t, c: (s, 0, h // rep, 0)),
            _vmem_spec((1, bs, 1, D),
                       lambda s, h, b, t, c: (t[s, b], 0, h // rep, 0)),
            _vmem_spec((1, bs, 1, D),
                       lambda s, h, b, t, c: (t[s, b], 0, h // rep, 0)),
        ],
        out_specs=_vmem_spec((1, W, 1, D),
                             lambda s, h, b, t, c: (s, 0, h, 0)),
        scratch_shapes=[
            _scratch((W, D), jnp.float32),
            _scratch((W, 128), jnp.float32),
            _scratch((W, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((S, W, H, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tables.astype(jnp.int32), ctx_len.astype(jnp.int32),
      q, own_k, own_v, k_pages, v_pages)


def paged_attention_reference(q, own_k, own_v, k_pages, v_pages, tables,
                              ctx_len):
    """Dense jnp oracle for the kernel (tests): gather pages through the
    table, mask by ctx_len, causal own window. Same operand layout."""
    S, W, H, D = q.shape
    HK = own_k.shape[2]
    bs = k_pages.shape[1]
    maxB = tables.shape[1]
    C = maxB * bs
    rep = H // HK
    k_ctx = k_pages[tables].reshape(S, C, HK, D)
    v_ctx = v_pages[tables].reshape(S, C, HK, D)
    k_ctx = jnp.repeat(k_ctx, rep, axis=2)
    v_ctx = jnp.repeat(v_ctx, rep, axis=2)
    ko = jnp.repeat(own_k, rep, axis=2)
    vo = jnp.repeat(own_v, rep, axis=2)
    scale = 1.0 / (D**0.5)
    s_ctx = jnp.einsum("swhd,schd->shwc", q, k_ctx).astype(jnp.float32)
    s_own = jnp.einsum("swhd,sxhd->shwx", q, ko).astype(jnp.float32)
    s = jnp.concatenate([s_ctx, s_own], axis=-1) * scale
    ctx_valid = jnp.arange(C)[None, :] < ctx_len[:, None]  # (S, C)
    causal = jnp.tril(jnp.ones((W, W), dtype=bool))
    valid = jnp.concatenate(
        [jnp.broadcast_to(ctx_valid[:, None, :], (S, W, C)),
         jnp.broadcast_to(causal[None], (S, W, W))], axis=-1)
    s = jnp.where(valid[:, None, :, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    att = jnp.einsum("shwc,schd->swhd", p[..., :C],
                     v_ctx.astype(jnp.float32)) \
        + jnp.einsum("shwx,sxhd->swhd", p[..., C:],
                     vo.astype(jnp.float32))
    return att.astype(q.dtype)
