"""Hot ops: attention (jnp reference + pallas TPU kernels), collective
overlap helpers. The pallas kernels are the TPU analogue of the
reference's reliance on cuDNN/torch fused kernels."""

from ray_tpu.ops.attention import causal_attention

__all__ = ["causal_attention"]
