"""Causal multi-head attention.

Two paths:
- `causal_attention_reference`: plain jnp einsum formulation — XLA fuses
  this well and it runs on any backend (CPU tests, interpret mode).
- `flash_attention`: pallas TPU kernel (ray_tpu.ops.flash_attention) with
  online softmax and block-sparse causal masking, used automatically on
  TPU for long sequences.

Softmax statistics are computed in float32 regardless of input dtype
(bfloat16 accumulation loses too much precision on long sequences).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Sequence length at or above which the pallas kernel pays for itself.
_FLASH_MIN_SEQ = 512


def causal_attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array
) -> jax.Array:
    """q,k,v: (B, T, H, D) -> (B, T, H, D), causal."""
    B, T, H, D = q.shape
    scale = 1.0 / (D**0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def causal_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Dispatch: pallas flash kernel on TPU for long sequences, reference
    einsum elsewhere. Failure to use the advertised kernel is LOUD (one
    warning per process), never a silent O(T²) degradation."""
    T = q.shape[1]
    if T >= _FLASH_MIN_SEQ and _on_tpu():
        try:
            from ray_tpu.ops.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=True)
        except Exception as e:  # noqa: BLE001
            _warn_fallback(repr(e))
    return causal_attention_reference(q, k, v)


@functools.cache
def _warn_fallback(reason: str):
    import warnings

    warnings.warn(
        f"pallas flash attention unavailable ({reason}); falling back to "
        f"the O(T^2) einsum path — expect reduced MFU",
        RuntimeWarning, stacklevel=3)


@functools.cache
def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
