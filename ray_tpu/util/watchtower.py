"""Watchtower — head-side metric history, SLO rules, structured alerts.

The metrics plane (cluster scrape) and the attribution plane (waterfalls,
spans) are scrape-on-demand: nothing retains history and nothing watches
the cluster between scrapes, so a replica flap or a queue ramp is
invisible until an operator happens to run `ray_tpu metrics`. Watchtower
closes that gap — reference shape: the always-on health evaluation
Podracer/RLAX-class systems run next to their gangs, plus the
Prometheus alerting-rule state machine (pending → firing → resolved):

- **Metric history.** A head-side loop samples the head's own
  `_cluster_metrics_text()` aggregation (the PR 3 scrape fan-out, so
  sampling costs one extra consumer, not a second scrape plane) every
  `period_s` (default 5s) into bounded per-series ring buffers. Total
  series are capped (overflow COUNTED, never unbounded); per-series
  depth is a ring. Exposed as `util.state.cluster_metrics_history()`
  and the head `metrics_history` RPC — the time-series substrate
  rate/derivative rules and an SLO autoscaler both need.
- **Rule engine.** Declarative `WatchRule`s evaluated each sample tick
  against the history: threshold, rate-of-change, and absence/staleness
  predicates, each with a `for_s` hold-down (condition must hold that
  long before pending promotes to firing). `default_rules()` ships a
  pack covering the existing metric catalog end-to-end.
- **Structured alerts.** Fingerprinted, deduplicated `Alert`s with a
  pending → firing → resolved state machine and a bounded transition
  history, surfaced four ways: `watchtower_alerts_firing{severity}` /
  `watchtower_alerts_total{rule}` in the metric catalog,
  `util.state.alerts()` + the `ray_tpu alerts` CLI, an `alerts.json`
  artifact in `debug-dump`, and spans under the `watchtower` category
  on the merged timeline.
- **Alert-triggered flight recorder.** The first critical-severity
  firing transition can auto-invoke `debug_dump` (off by default;
  `RAY_TPU_WATCHTOWER_AUTODUMP` or a head knob), rate-limited to once
  per cooldown window — the post-mortem is captured while the incident
  is live instead of after the operator notices.

Everything runs on the watchtower's own thread: nothing here touches
the request hot path, and the `metrics_history`/`alerts` RPC handlers
only read state already gathered (they never RPC back into their own
server — the GL013 shape).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import re
import threading
import time
from collections import deque

# ------------------------------------------------------------------ parsing

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

# series key: (metric name, tuple(sorted (label, value) pairs))
SeriesKey = tuple


def parse_prometheus(text: str) -> dict[SeriesKey, float]:
    """Sample lines of one exposition page → {(name, tags): value}.
    Histogram `_bucket`/`_sum`/`_count` lines parse as ordinary series
    (the `le` tag included), which is exactly what quantile rules need.
    Unparsable lines and non-numeric values are skipped."""
    out: dict[SeriesKey, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, raw_tags, raw_val = m.groups()
        try:
            value = float(raw_val)
        except ValueError:
            continue
        tags = tuple(sorted(_LABEL_RE.findall(raw_tags))) if raw_tags \
            else ()
        out[(name, tags)] = value
    return out


# ------------------------------------------------------------------ history

class MetricHistory:
    """Bounded per-series ring buffers over sampled exposition pages.

    Memory contract: at most `max_series` retained series (a NEW series
    arriving past the cap is dropped and COUNTED in
    `dropped_series_total`; known series always update) × at most
    `samples_per_series` (t, value) points each — the window is a ring,
    oldest samples age out. Not thread-safe on its own: the owning
    Watchtower serializes access under its lock."""

    def __init__(self, max_series: int = 4096,
                 samples_per_series: int = 240):
        self.max_series = max_series
        self.samples_per_series = samples_per_series
        self._series: dict[SeriesKey, deque] = {}
        self.dropped_series_total = 0

    def append(self, t: float, samples: dict[SeriesKey, float]) -> None:
        for key, value in samples.items():
            ring = self._series.get(key)
            if ring is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series_total += 1
                    continue
                ring = self._series[key] = deque(
                    maxlen=self.samples_per_series)
            ring.append((t, value))

    @property
    def series_count(self) -> int:
        return len(self._series)

    def prune(self, min_t: float) -> int:
        """Evict series whose NEWEST sample predates `min_t` — they
        vanished from the scrape (node died, replica replaced). Without
        this, label churn (fresh node ids per boot) fills the series
        cap permanently and the watchtower goes silently blind to
        every series born after saturation. Returns the evict count
        (bookkept separately from cap rejections)."""
        dead = [k for k, ring in self._series.items()
                if ring and ring[-1][0] < min_t]
        for k in dead:
            del self._series[k]
        return len(dead)

    def series(self, name: str, labels: dict | None = None
               ) -> list[tuple[dict, deque]]:
        """All retained series of `name` whose tags contain `labels`
        (subset match); [(tags_dict, ring)] — rings are NOT copied."""
        out = []
        for (n, tags), ring in self._series.items():
            if n != name:
                continue
            td = dict(tags)
            if labels and any(td.get(k) != v for k, v in labels.items()):
                continue
            out.append((td, ring))
        return out

    def window(self, ring: deque, now: float, window_s: float
               ) -> list[tuple[float, float]]:
        lo = now - window_s
        return [(t, v) for t, v in ring if t >= lo]

    def query(self, names=None, window_s: float | None = None,
              now: float | None = None) -> list[dict]:
        """[{name, tags, samples: [[t, v], ...]}] for `names` (all
        retained series when None), clipped to the trailing window."""
        if now is None:
            now = time.monotonic()
        wanted = set(names) if names else None
        out = []
        for (name, tags), ring in self._series.items():
            if wanted is not None and name not in wanted:
                continue
            pts = list(ring) if window_s is None else \
                self.window(ring, now, window_s)
            if pts:
                out.append({"name": name, "tags": dict(tags),
                            "samples": [[t, v] for t, v in pts]})
        return out


# ------------------------------------------------------------------ rules

@dataclasses.dataclass
class WatchRule:
    """One declarative watch predicate, evaluated every sample tick.

    kind:
      - "threshold": `stat` over `window_s` compared against
        `threshold` with `op`;
      - "rate": per-second change of the aggregated series over
        `window_s` (counters: monotone rate with reset clamp; gauges:
        slope — the queue-ramp detector) compared with `op`;
      - "absence": staleness — seconds since the (counter) series last
        INCREASED; fires when >= `window_s` and the series showed
        activity before (a cluster that never trained never alerts).
        Firing is bounded by `resolve_after_s` (default 3x window_s):
        past that staleness the workload is considered ENDED, not
        stalled, and the alert resolves — a normally-completed train
        run must not page critical forever.

    stat (threshold kind): "last" (latest value), "p50"/"p99"
    (histogram quantile from `<metric>_bucket` deltas over the window),
    "skew" (p99/p50 of the same deltas — the straggler signal), or
    "hit_ratio" (rate(metric) / (rate(metric) + rate(ratio_metric)),
    gated on `min_rate` combined events/s so an idle cache never
    alerts).

    `for_s` is the hold-down: the condition must hold continuously that
    long before pending promotes to firing (one flappy sample never
    pages). `agg` folds multiple series (nodes/replicas) into the one
    evaluated value."""

    name: str
    metric: str
    kind: str = "threshold"        # threshold | rate | absence
    op: str = ">"                  # > | >= | < | <=
    threshold: float = 0.0
    window_s: float = 60.0
    for_s: float = 0.0
    severity: str = "warning"      # info | warning | critical
    stat: str = "last"             # last | p50 | p99 | skew | hit_ratio
    agg: str = "sum"               # sum | max | min | avg
    ratio_metric: str | None = None
    min_rate: float = 0.0
    labels: dict | None = None
    description: str = ""
    resolve_after_s: float = 0.0  # absence: 0 = 3x window_s

    def compare(self, value: float) -> bool:
        if self.op == ">":
            return value > self.threshold
        if self.op == ">=":
            return value >= self.threshold
        if self.op == "<":
            return value < self.threshold
        if self.op == "<=":
            return value <= self.threshold
        raise ValueError(f"bad op {self.op!r}")


def _agg(values: list[float], how: str) -> float | None:
    if not values:
        return None
    if how == "sum":
        return sum(values)
    if how == "max":
        return max(values)
    if how == "min":
        return min(values)
    if how == "avg":
        return sum(values) / len(values)
    raise ValueError(f"bad agg {how!r}")


def _series_rate(pts: list[tuple[float, float]],
                 counter: bool) -> float | None:
    """Per-second change over the window's endpoints. Counter resets
    (value decreased — the process restarted) yield None for the
    window rather than a huge negative rate."""
    if len(pts) < 2:
        return None
    (t0, v0), (t1, v1) = pts[0], pts[-1]
    if t1 <= t0:
        return None
    if counter and v1 < v0:
        return None
    return (v1 - v0) / (t1 - t0)


def _rate(history: MetricHistory, metric: str, labels, now: float,
          window_s: float, agg: str, counter: bool = True
          ) -> float | None:
    rates = []
    for _tags, ring in history.series(metric, labels):
        r = _series_rate(history.window(ring, now, window_s), counter)
        if r is not None:
            rates.append(r)
    return _agg(rates, agg)


def _bucket_deltas(history: MetricHistory, metric: str, labels,
                   now: float, window_s: float) -> list[tuple[float, float]]:
    """[(le, observations landed in that bucket over the window)],
    cumulative in `le` order, summed across every matching series —
    the rate() + sum by (le) a Prometheus quantile query would do."""
    per_le: dict[float, float] = {}
    for tags, ring in history.series(metric + "_bucket", labels):
        le_raw = tags.get("le")
        if le_raw is None:
            continue
        le = float("inf") if le_raw in ("+Inf", "inf") else float(le_raw)
        pts = history.window(ring, now, window_s)
        if len(pts) < 2:
            continue
        delta = pts[-1][1] - pts[0][1]
        if delta < 0:  # counter reset
            continue
        per_le[le] = per_le.get(le, 0.0) + delta
    return sorted(per_le.items())


def _quantile(buckets: list[tuple[float, float]], q: float
              ) -> float | None:
    """Linear-interpolated quantile over cumulative bucket deltas
    (histogram_quantile semantics). None when no observations landed in
    the window."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in buckets:
        if cum >= rank:
            if le == float("inf"):
                return prev_le  # open-ended top bucket: its lower edge
            span = cum - prev_cum
            frac = (rank - prev_cum) / span if span > 0 else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return buckets[-1][0]


def evaluate_rule(rule: WatchRule, history: MetricHistory,
                  now: float) -> tuple[float | None, bool]:
    """One rule against the history at `now` → (value, condition).
    value is None when the window holds no usable data — the rule
    neither fires nor resolves on silence (except `absence`, where
    silence after activity IS the signal)."""
    if rule.kind == "absence":
        staleness = None
        for _tags, ring in history.series(rule.metric, rule.labels):
            pts = list(ring)
            last_inc = None
            for i in range(len(pts) - 1, 0, -1):
                if pts[i][1] > pts[i - 1][1]:
                    last_inc = pts[i][0]
                    break
            if last_inc is None:
                # no increase inside the ring: activity (a nonzero
                # counter) predates the retained window entirely
                if pts and pts[-1][1] > 0 and \
                        now - pts[0][0] >= rule.window_s:
                    last_inc = pts[0][0] - rule.window_s
                else:
                    continue
            s = now - last_inc
            if staleness is None or s > staleness:
                staleness = s
        if staleness is None:
            return None, False
        # quiet-for-too-long is "ended", not "stalled": past the
        # resolve horizon the alert clears instead of firing forever
        # after every normally-completed run
        horizon = rule.resolve_after_s or 3 * rule.window_s
        return staleness, rule.window_s <= staleness < horizon

    if rule.kind == "rate":
        # gauges ramp down too: no reset clamp (counter=False keeps a
        # draining queue's negative slope meaningful for "<" rules)
        value = _rate(history, rule.metric, rule.labels, now,
                      rule.window_s, rule.agg,
                      counter=rule.metric.endswith("_total"))
        return value, value is not None and rule.compare(value)

    # threshold kind, by stat
    if rule.stat == "last":
        values = []
        for _tags, ring in history.series(rule.metric, rule.labels):
            pts = history.window(ring, now, rule.window_s)
            if pts:
                values.append(pts[-1][1])
        value = _agg(values, rule.agg)
    elif rule.stat in ("p50", "p99"):
        buckets = _bucket_deltas(history, rule.metric, rule.labels,
                                 now, rule.window_s)
        value = _quantile(buckets, 0.5 if rule.stat == "p50" else 0.99)
    elif rule.stat == "skew":
        buckets = _bucket_deltas(history, rule.metric, rule.labels,
                                 now, rule.window_s)
        p50 = _quantile(buckets, 0.5)
        p99 = _quantile(buckets, 0.99)
        value = (p99 / p50) if p50 and p99 is not None else None
    elif rule.stat == "hit_ratio":
        hits = _rate(history, rule.metric, rule.labels, now,
                     rule.window_s, "sum")
        misses = _rate(history, rule.ratio_metric or "", rule.labels,
                       now, rule.window_s, "sum")
        if hits is None and misses is None:
            value = None
        else:
            total = (hits or 0.0) + (misses or 0.0)
            value = None if total < rule.min_rate or total <= 0 \
                else (hits or 0.0) / total
    else:
        raise ValueError(f"bad stat {rule.stat!r}")
    return value, value is not None and rule.compare(value)


def default_rules() -> list[WatchRule]:
    """The shipped rule pack — one watcher per failure mode the metric
    catalog can already express (see OBSERVABILITY.md "Alerting" for
    the table + rationale). Thresholds are deliberately conservative:
    a rule that cries wolf gets disabled, and then nothing watches."""
    ttft_target_ms = float(os.environ.get(
        "RAY_TPU_WATCHTOWER_TTFT_SLO_MS", "2000"))
    return [
        WatchRule(
            "serve-ttft-slo-burn", metric="serve_slo_ttft_ms",
            stat="p99", labels={"phase": "total"}, op=">",
            threshold=ttft_target_ms, window_s=60, for_s=15,
            severity="critical",
            description="serve TTFT p99 over the SLO target "
                        f"({ttft_target_ms:g}ms) — the autoscaler "
                        "signal, escalated"),
        WatchRule(
            "serve-queue-ramp", metric="serve_llm_queue_depth",
            kind="rate", agg="sum", op=">", threshold=0.2,
            window_s=45, for_s=15, severity="warning",
            description="aggregate serve queue depth ramping "
                        ">0.2 req/s sustained — demand outrunning "
                        "decode capacity"),
        WatchRule(
            "replica-flapping", metric="serve_replica_restarts_total",
            kind="rate", agg="sum", op=">", threshold=3 / 180.0,
            window_s=180, for_s=0, severity="critical",
            description="replica replacements faster than 3 per 3min "
                        "— the self-healing loop is churning, not "
                        "healing"),
        WatchRule(
            "span-plane-overload", metric="spans_dropped_total",
            kind="rate", agg="sum", op=">", threshold=100.0,
            window_s=30, for_s=10, severity="warning",
            description="span plane dropping >100 spans/s — the "
                        "timeline is lossy; lower span rates or raise "
                        "the sampling cap"),
        WatchRule(
            "prefix-cache-thrash",
            metric="serve_llm_prefix_cache_hits_total",
            stat="hit_ratio",
            ratio_metric="serve_llm_prefix_cache_misses_total",
            op="<", threshold=0.2, min_rate=50.0, window_s=60,
            for_s=20, severity="warning",
            description="prefix-cache hit ratio collapsed under 20% "
                        "at >=50 pages/s — working set outgrew the "
                        "pool (thrash)"),
        WatchRule(
            "spec-accept-collapse",
            metric="serve_llm_spec_accepted_total",
            stat="hit_ratio",
            ratio_metric="serve_llm_spec_rejected_total",
            op="<", threshold=0.2, min_rate=50.0, window_s=60,
            for_s=20, severity="warning",
            description="speculative accept ratio collapsed under 20% "
                        "at >=50 proposed drafts/s — the proposer "
                        "stopped predicting this workload; every "
                        "verify step is wasted width"),
        WatchRule(
            "train-straggler", metric="train_step_seconds",
            stat="skew", op=">", threshold=2.0, window_s=120,
            for_s=30, severity="warning",
            description="train step p99/p50 skew >2x — a straggler "
                        "rank is gating the gang"),
        WatchRule(
            "train-stall", metric="train_step_seconds_count",
            kind="absence", window_s=120, for_s=0,
            severity="critical",
            description="train step counter stopped increasing for "
                        "2min after prior activity — a hung gang "
                        "(deadlocked collective, dead worker)"),
        WatchRule(
            "train-pipeline-bubble",
            metric="train_pipeline_bubble_ratio",
            stat="last", agg="max", op=">", threshold=0.5,
            window_s=60, for_s=60, severity="warning",
            description="pipeline bubble ratio >0.5 sustained 60s — "
                        "more than half the stage-seconds are idle; "
                        "the microbatch count is mis-sized for the "
                        "stage count (raise M toward "
                        "bubble=(S-1)/(S-1+M)) or a stage is a "
                        "straggler"),
        WatchRule(
            "train-zero-gather-stall",
            metric="train_zero_gather_share",
            stat="last", agg="max", op=">",
            threshold=float(os.environ.get(
                "RAY_TPU_WATCHTOWER_GATHER_SHARE", "0.35")),
            window_s=60, for_s=30, severity="warning",
            description="all-gather share of the train step over "
                        "RAY_TPU_WATCHTOWER_GATHER_SHARE (default "
                        "0.35) sustained 30s with zero_stage >= 3 — "
                        "the just-in-time param gather dominates the "
                        "step; drop to stage 2 or widen the per-chip "
                        "batch to amortize it"),
        WatchRule(
            "log-error-spike", metric="log_records_total",
            kind="rate", agg="sum", labels={"level": "error"},
            op=">", threshold=float(os.environ.get(
                "RAY_TPU_WATCHTOWER_LOG_ERRORS_PER_S", "5.0")),
            window_s=30, for_s=10, severity="warning",
            description="error-level log records faster than "
                        "RAY_TPU_WATCHTOWER_LOG_ERRORS_PER_S (default "
                        "5/s) sustained — something is failing "
                        "repeatedly; the firing alert carries the last "
                        "error lines as context"),
        WatchRule(
            "task-queue-stall", metric="task_queue_wait_seconds",
            stat="p99", op=">", threshold=float(os.environ.get(
                "RAY_TPU_WATCHTOWER_QUEUE_WAIT_P99_S", "5.0")),
            window_s=60, for_s=60, severity="warning",
            description="task queue-wait p99 over "
                        "RAY_TPU_WATCHTOWER_QUEUE_WAIT_P99_S (default "
                        "5s) sustained 60s — the dispatch queue is "
                        "stalling; `ray_tpu explain <task_id>` names "
                        "the unsatisfiable constraint for the head of "
                        "the queue"),
        WatchRule(
            "object-stranded-refs",
            metric="object_store_stranded_bytes",
            stat="last", agg="sum", op=">",
            threshold=float(os.environ.get(
                "RAY_TPU_WATCHTOWER_STRANDED_BYTES",
                str(128 << 20))),
            window_s=120, for_s=30, severity="warning",
            description="owned refs past the stranded-age threshold "
                        "with no consumer progress are holding more "
                        "bytes than RAY_TPU_WATCHTOWER_STRANDED_BYTES "
                        "(default 128MB) — the stranded-oid leak "
                        "shape; `ray_tpu memory` names the "
                        "owner/creator"),
    ]


# ------------------------------------------------------------------ alerts

class AlertState:
    PENDING = "pending"
    FIRING = "firing"
    RESOLVED = "resolved"


class Alert:
    """One deduplicated alert instance: a rule's condition holding.
    Fingerprint = rule name + the rule's label filter, so repeated
    condition-true ticks UPDATE the one alert instead of multiplying
    it (the dedup contract)."""

    __slots__ = ("rule", "severity", "state", "fingerprint", "value",
                 "threshold", "since", "firing_since", "resolved_at",
                 "description", "context")

    def __init__(self, rule: WatchRule, value: float, now_wall: float):
        self.rule = rule.name
        self.severity = rule.severity
        self.state = AlertState.PENDING
        self.fingerprint = alert_fingerprint(rule)
        self.value = value
        self.threshold = rule.threshold
        self.since = now_wall
        self.firing_since: float | None = None
        self.resolved_at: float | None = None
        self.description = rule.description
        # last-N error-level log lines attached at the firing
        # transition (bounded; None until/unless the alert fires with a
        # log_context_fn wired)
        self.context: list[dict] | None = None

    def to_dict(self) -> dict:
        out = {"rule": self.rule, "severity": self.severity,
               "state": self.state, "fingerprint": self.fingerprint,
               "value": self.value, "threshold": self.threshold,
               "since": self.since, "firing_since": self.firing_since,
               "resolved_at": self.resolved_at,
               "description": self.description}
        if self.context is not None:
            out["context"] = self.context
        return out


def alert_fingerprint(rule: WatchRule) -> str:
    basis = f"{rule.name}|{sorted((rule.labels or {}).items())}"
    return hashlib.blake2s(basis.encode(), digest_size=6).hexdigest()


# ---------------------------------------------------------------- watchtower

class Watchtower:
    """The head's always-on watcher: sample → retain → evaluate → alert.

    `scrape` is the head's `_cluster_metrics_text` (sampling reuses the
    existing scrape fan-out); `span_sink` is the head's `_ingest_spans`
    (alert transitions land on the merged timeline under the
    `watchtower` category); `dump_fn(out_dir)` overrides the autodump
    action (tests; default runs `util.state.debug_dump` against
    `address_fn()`). All mutable state is guarded by `_lock`; the RPC
    handlers the head registers only read under it."""

    def __init__(self, scrape, period_s: float | None = None,
                 rules: list[WatchRule] | None = None,
                 max_series: int | None = None,
                 samples_per_series: int | None = None,
                 autodump: str | bool | None = None,
                 autodump_cooldown_s: float | None = None,
                 address_fn=None, span_sink=None, dump_fn=None,
                 history_limit: int = 200,
                 series_ttl_s: float | None = None,
                 log_context_fn=None, log_context_n: int = 20):
        self._scrape = scrape
        self._address_fn = address_fn
        self._span_sink = span_sink
        self._dump_fn = dump_fn
        # log_context_fn(n) -> last n error-level log records; attached
        # to alerts at their firing transition (fetched OUTSIDE the
        # lock — it is an RPC fan-out on the head)
        self._log_context_fn = log_context_fn
        self._log_context_n = log_context_n
        if period_s is None:
            period_s = float(os.environ.get(
                "RAY_TPU_WATCHTOWER_PERIOD_S", "5.0"))
        if os.environ.get("RAY_TPU_WATCHTOWER", "1") in ("0", "off"):
            period_s = 0.0
        self.period_s = period_s
        self.rules = list(default_rules() if rules is None else rules)
        self._lock = threading.Lock()
        self.history = MetricHistory(  # guarded_by(_lock)
            max_series=max_series or int(os.environ.get(
                "RAY_TPU_WATCHTOWER_MAX_SERIES", "4096")),
            samples_per_series=samples_per_series or int(os.environ.get(
                "RAY_TPU_WATCHTOWER_SAMPLES", "240")))
        # series that miss this many seconds of scrapes are pruned
        # (dead nodes/replicas free their cap slots for new series)
        self.series_ttl_s = (series_ttl_s if series_ttl_s is not None
                             else max(300.0, 60 * (period_s or 5.0)))
        self._active: dict[str, Alert] = {}  # guarded_by(_lock)
        self._transitions = deque(maxlen=history_limit)  # guarded_by(_lock)
        self._samples_total = 0  # guarded_by(_lock)
        self._published: dict[str, int] = {}  # guarded_by(_lock)
        # epoch anchor so RPC surfaces report wall-clock timestamps
        # while windows/holds run on the monotonic clock
        self._anchor = time.time() - time.monotonic()
        if autodump is None:
            autodump = os.environ.get("RAY_TPU_WATCHTOWER_AUTODUMP", "")
        if autodump in ("", "0", False, None, "off"):
            self._autodump_dir = None
        elif autodump in ("1", True, "on"):
            self._autodump_dir = "ray_tpu-autodump"
        else:
            self._autodump_dir = str(autodump)
        self._autodump_cooldown_s = (
            autodump_cooldown_s if autodump_cooldown_s is not None
            else float(os.environ.get(
                "RAY_TPU_WATCHTOWER_AUTODUMP_COOLDOWN_S", "600")))
        self._last_autodump: float | None = None  # guarded_by(_lock)
        self.autodumps = 0  # guarded_by(_lock)
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="watchtower")

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Watchtower":
        if self.period_s > 0:
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self.period_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001
                pass  # a failed scrape skips one tick, never the loop

    # ------------------------------------------------------------- sampling

    def sample_once(self, now: float | None = None) -> None:
        """One tick: scrape → parse → retain → evaluate. `now` is a
        monotonic-seconds override for deterministic tests. The scrape
        happens OUTSIDE the lock (it is an RPC fan-out)."""
        text = self._scrape()
        if now is None:
            now = time.monotonic()
        samples = parse_prometheus(text)
        dump_requests: list[str] = []
        fired: list[Alert] = []
        with self._lock:
            self.history.append(now, samples)
            self.history.prune(now - self.series_ttl_s)
            self._samples_total += 1
            self._evaluate_locked(now, dump_requests, fired)
            self._publish_metrics_locked()
        if fired and self._log_context_fn is not None:
            # attach the last error-level log lines as bounded context.
            # Fetched OUTSIDE the lock (it is an RPC fan-out); a failed
            # fetch just leaves the alert context-less.
            try:
                context = self._log_context_fn(self._log_context_n)
            except Exception:  # noqa: BLE001
                context = None
            if context:
                with self._lock:
                    for alert in fired:
                        alert.context = context[-self._log_context_n:]
        for rule_name in dump_requests:
            self._spawn_autodump(rule_name)

    # ----------------------------------------------------------- evaluation

    def _evaluate_locked(self, now: float, dump_requests: list[str],
                         fired: list["Alert"] | None = None) -> None:
        now_wall = now + self._anchor
        for rule in self.rules:
            try:
                value, cond = evaluate_rule(rule, self.history, now)
            except Exception:  # noqa: BLE001
                continue  # a broken rule must not take down the tick
            fp = alert_fingerprint(rule)
            alert = self._active.get(fp)
            if cond:
                if alert is None:
                    alert = Alert(rule, value, now_wall)
                    self._active[fp] = alert
                    self._transition_locked(alert, None,
                                            AlertState.PENDING, now)
                    # zero hold-down promotes on the same tick
                alert.value = value
                if alert.state == AlertState.PENDING and \
                        now_wall - alert.since >= rule.for_s:
                    alert.state = AlertState.FIRING
                    alert.firing_since = now_wall
                    if fired is not None:
                        fired.append(alert)
                    self._transition_locked(alert, AlertState.PENDING,
                                            AlertState.FIRING, now)
                    if rule.severity == "critical" and \
                            self._autodump_dir is not None:
                        if self._last_autodump is None or \
                                now - self._last_autodump >= \
                                self._autodump_cooldown_s:
                            self._last_autodump = now
                            self.autodumps += 1
                            dump_requests.append(rule.name)
            elif alert is not None:
                # condition cleared OR the signal went silent: pending
                # quietly de-escalates, firing resolves. A vanished
                # signal resolving (rather than latching) is
                # deliberate — an alert that can never resolve is an
                # alert nobody re-trusts; the transition history still
                # records that it fired.
                prev = alert.state
                alert.state = AlertState.RESOLVED
                alert.resolved_at = now_wall
                self._active.pop(fp, None)
                self._transition_locked(alert, prev,
                                        AlertState.RESOLVED, now)

    def _transition_locked(self, alert: Alert, prev: str | None,
                           state: str, now: float) -> None:
        self._transitions.append({
            "t": now + self._anchor, "rule": alert.rule,
            "fingerprint": alert.fingerprint, "from": prev,
            "to": state, "value": alert.value,
            "severity": alert.severity})
        if state == AlertState.FIRING:
            from ray_tpu.util.metrics import Counter

            Counter("watchtower_alerts_total",
                    "Alert pending->firing transitions, by rule",
                    tag_keys=("rule",)).inc(tags={"rule": alert.rule})
        if self._span_sink is not None:
            from ray_tpu.utils.events import epoch_us

            try:
                self._span_sink([{
                    "name": f"watchtower.{alert.rule}",
                    "cat": "watchtower", "ph": "X", "ts": epoch_us(),
                    "dur": 1.0, "node": "head", "proc": "watchtower",
                    "tid": 0,
                    "args": {"from": prev, "to": state,
                             "value": alert.value,
                             "severity": alert.severity}}])
            except Exception:  # noqa: BLE001
                pass

    def _publish_metrics_locked(self) -> None:
        from ray_tpu.util.metrics import Counter, Gauge

        firing = Gauge("watchtower_alerts_firing",
                       "Alerts currently firing, by severity",
                       tag_keys=("severity",))
        counts = {"info": 0, "warning": 0, "critical": 0}
        for a in self._active.values():
            if a.state == AlertState.FIRING:
                counts[a.severity] = counts.get(a.severity, 0) + 1
        for sev, n in counts.items():
            firing.set(n, tags={"severity": sev})
        Gauge("watchtower_series",
              "Metric-history series currently retained"
              ).set(self.history.series_count)
        # counters publish DELTAS since the last tick (the registry is
        # process-shared: several in-process heads may feed one counter)
        def delta(counter, total, key):
            d = total - self._published.get(key, 0)
            if d > 0:
                counter.inc(d)
                self._published[key] = total

        delta(Counter("watchtower_series_dropped_total",
                      "New series rejected by the history series cap"),
              self.history.dropped_series_total, "dropped")
        delta(Counter("watchtower_samples_total",
                      "Metric-history sample ticks completed"),
              self._samples_total, "samples")
        delta(Counter("watchtower_autodumps_total",
                      "Debug dumps auto-triggered by critical alerts"),
              self.autodumps, "dumps")

    # ------------------------------------------------------------- autodump

    def _spawn_autodump(self, rule_name: str) -> None:
        """Fire-and-forget flight recording on its own thread — the
        sampling loop must keep ticking while the dump (up to its
        deadline) gathers artifacts. Rate limiting already happened
        under the lock at the firing transition."""
        stamp = time.strftime("%Y%m%d-%H%M%S")
        out_dir = os.path.join(self._autodump_dir,
                               f"{stamp}-{rule_name}")

        def run():
            try:
                if self._dump_fn is not None:
                    self._dump_fn(out_dir)
                else:
                    from ray_tpu.util import state

                    state.debug_dump(
                        out_dir=out_dir,
                        address=self._address_fn()
                        if self._address_fn else None,
                        deadline_s=45.0)
            except Exception:  # noqa: BLE001
                pass  # best-effort, like every flight-recorder path

        threading.Thread(target=run, daemon=True,
                         name="watchtower-autodump").start()

    # ------------------------------------------------------------- surfaces

    def history_dict(self, names=None, window_s: float | None = None
                     ) -> dict:
        """The `metrics_history` RPC body: series samples with
        epoch-seconds timestamps, plus the bounds bookkeeping."""
        with self._lock:
            series = self.history.query(names, window_s)
            for s in series:
                s["samples"] = [[t + self._anchor, v]
                                for t, v in s["samples"]]
            return {"series": series, "period_s": self.period_s,
                    "series_count": self.history.series_count,
                    "series_dropped":
                        self.history.dropped_series_total,
                    "samples_total": self._samples_total}

    def alerts_dict(self, include_history: bool = True) -> dict:
        """The `alerts` RPC body: active (pending+firing) alerts plus
        the bounded transition history, and the rule pack itself so a
        consumer can show what is being watched."""
        with self._lock:
            out = {"alerts": [a.to_dict()
                              for a in self._active.values()],
                   "rules": [dataclasses.asdict(r)
                             for r in self.rules],
                   "autodumps": self.autodumps}
            if include_history:
                out["history"] = list(self._transitions)
            return out
