"""Deterministic fault injection for tests and game-days.

Two planes compose here:

- RPC chaos (``ray_tpu.core.rpc.set_chaos``): drop or delay the first N
  sends of a method in this process — exercises retry/timeout paths.
- Replica chaos (this module): abruptly kill a live serve replica —
  exercises the serve control plane's heal path (health loop, routing
  removal, replacement, handle failover) end to end.

``kill_replica`` is the injector the self-healing acceptance gate runs
on: it makes the replica's worker PROCESS exit immediately
(``os._exit`` — no finally blocks, no drain), which is what a real
OOM-kill, segfault, or node loss looks like to the rest of the
cluster. In ``local_mode`` there is no process to kill, so it falls
back to ``ray_tpu.kill`` (the closest local-semantics equivalent).
"""

from __future__ import annotations


def set_chaos(spec: str) -> None:
    """Re-export of :func:`ray_tpu.core.rpc.set_chaos` so test code has
    one chaos namespace (``"method=N"`` drops, ``"method=delayN"``
    delays)."""
    from ray_tpu.core import rpc

    rpc.set_chaos(spec)


def list_replicas(app_name: str) -> list:
    """Live replica handles of a serve app, straight from the
    controller's routing set."""
    import ray_tpu
    from ray_tpu.serve.api import _CONTROLLER_NAME

    ctrl = ray_tpu.get_actor(_CONTROLLER_NAME)
    r = ray_tpu.get(ctrl.get_replicas.remote(app_name), timeout=30)
    return list(r["replicas"])


def kill_replica(app_name: str, index: int | None = None,
                 busiest: bool = False) -> str:
    """Abruptly kill one replica of `app_name`; returns the killed
    replica's ident (the id handles/controllers route by).

    `index` picks a specific replica from the current routing set;
    `busiest=True` picks the one with the most ongoing requests (so a
    mid-stream kill provably lands on in-flight work); default is the
    first replica. The kill is a process exit injected over the
    replica's CONTROL concurrency group, so it fires even while every
    request lane is busy streaming."""
    import ray_tpu
    from ray_tpu.core.api import _global_runtime
    from ray_tpu.serve.api import _replica_ident

    replicas = list_replicas(app_name)
    if not replicas:
        raise ValueError(f"no live replicas for app {app_name!r}")
    victim = replicas[index if index is not None else 0]
    if busiest and index is None and len(replicas) > 1:
        try:
            loads = ray_tpu.get(
                [r.ongoing.options(concurrency_group="control").remote()
                 for r in replicas], timeout=10)
            victim = replicas[max(range(len(loads)),
                                  key=lambda i: loads[i])]
        except Exception:  # noqa: BLE001
            pass  # probe raced a death: the default victim still dies
    ident = _replica_ident(victim)
    if _global_runtime().context_info().get("local_mode"):
        # local mode: replicas are threads in THIS process — os._exit
        # would kill the test itself. ray_tpu.kill is the local
        # equivalent of abrupt death (pending calls fail ActorDied).
        ray_tpu.kill(victim)
        return ident
    # fire-and-forget: the process exits before any reply can be sent,
    # so the returned ref resolves to ActorDiedError — by design
    # graftlint: disable=discarded-future
    victim.chaos_exit.options(concurrency_group="control").remote()
    return ident
