"""Scheduling strategies (reference:
python/ray/util/scheduling_strategies.py — NodeAffinity / NodeLabel
strategies; PlacementGroupSchedulingStrategy lives in
util/placement_group.py).

On this framework node affinity lowers to a LABEL MATCH: every nodelet
auto-labels itself "ray.io/node-id"=<hex id> (reference:
node_affinity_scheduling_policy.h:29), so the one label scheduler
serves explicit selectors, node affinity, and TPU-slice gangs alike.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    """Pin a task/actor to one node by id (reference:
    scheduling_strategies.py:58). `soft=True` allows fallback anywhere
    if the node is gone; hard affinity fails the placement instead."""

    node_id: str
    soft: bool = False

    def to_label_selector(self) -> dict[str, str]:
        return {"ray.io/node-id": self.node_id}


@dataclasses.dataclass
class NodeLabelSchedulingStrategy:
    """Schedule onto any node whose labels match (reference:
    scheduling_strategies.py NodeLabelSchedulingStrategy hard match)."""

    hard: dict[str, str]

    def to_label_selector(self) -> dict[str, str]:
        return dict(self.hard)
