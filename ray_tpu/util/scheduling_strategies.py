"""Scheduling strategies (reference:
python/ray/util/scheduling_strategies.py — NodeAffinity / NodeLabel
strategies; PlacementGroupSchedulingStrategy lives in
util/placement_group.py).

On this framework node affinity lowers to a LABEL MATCH: every nodelet
auto-labels itself "ray.io/node-id"=<hex id> (reference:
node_affinity_scheduling_policy.h:29), so the one label scheduler
serves explicit selectors, node affinity, and TPU-slice gangs alike.
"""

from __future__ import annotations

import dataclasses

# Marker key carried inside a label selector: "prefer nodes matching the
# other keys, but fall back anywhere if none exists". Schedulers pop it
# before matching (nodelet._place / head._pick_node).
SOFT_AFFINITY_LABEL = "ray.io/soft-node-affinity"


def split_soft_selector(selector: dict | None) -> tuple[dict, bool]:
    """(selector-without-marker, is_soft)."""
    sel = dict(selector or {})
    soft = sel.pop(SOFT_AFFINITY_LABEL, None) is not None
    return sel, soft


def labels_match(labels: dict, selector: dict) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    """Pin a task/actor to one node by id (reference:
    scheduling_strategies.py:58). `soft=True` allows fallback anywhere
    if the node is gone; hard affinity fails the placement instead."""

    node_id: str
    soft: bool = False

    def to_label_selector(self) -> dict[str, str]:
        sel = {"ray.io/node-id": self.node_id}
        if self.soft:
            sel[SOFT_AFFINITY_LABEL] = "1"
        return sel


@dataclasses.dataclass
class NodeLabelSchedulingStrategy:
    """Schedule onto any node whose labels match (reference:
    scheduling_strategies.py NodeLabelSchedulingStrategy hard match)."""

    hard: dict[str, str]

    def to_label_selector(self) -> dict[str, str]:
        return dict(self.hard)
