"""Critical-path analysis over the merged span timeline.

Walks the spans of one traced execution (a compiled-DAG run, a
pipeline step, a serve request — anything sharing a trace_id) and
returns the BLOCKING CHAIN: the sequence of spans where each entry is
the latest-finishing work that had to complete before the next could
start, with per-edge slack (idle gap between predecessor end and
successor start). Aggregated across executions, the chain answers
"where does p99 live" in one call (reference: the per-stage bubble
attribution that Podracer/MPMD-pipeline papers do by hand over
profiler dumps).

Spans are the TaskEventLog dicts that ride the task_events lane:
``{"name", "cat", "ph": "X", "ts": <epoch µs>, "dur": <µs>,
"node"?, "proc"?, "args": {"trace_id": ...}}``. Only complete
("ph" == "X") spans with a duration participate.
"""

from __future__ import annotations

# Two spans separated by less than this (µs) are treated as
# contiguous: scheduler handoff jitter, not real slack.
_EPS_US = 50.0


def _trace_of(span: dict) -> str:
    args = span.get("args") or {}
    return args.get("trace_id") or ""


def _complete(spans) -> list[dict]:
    return [s for s in spans
            if s.get("ph", "X") == "X" and float(s.get("dur") or 0) > 0]


def critical_path(spans, trace_id: str | None = None) -> dict:
    """Blocking chain of one execution.

    Returns ``{"trace_id", "chain": [{name, node, proc, ts, dur_ms,
    slack_ms}...], "e2e_ms", "path_ms", "coverage", "slowest"}`` where
    `coverage` is the fraction of the measured end-to-end window the
    chain's spans cover (union of intervals — overlapping parent/child
    entries are not double counted) and `slowest` names the chain
    entry with the largest duration.
    """
    if trace_id:
        spans = [s for s in spans if _trace_of(s) == trace_id]
    spans = _complete(spans)
    if not spans:
        return {"trace_id": trace_id or "", "chain": [], "e2e_ms": 0.0,
                "path_ms": 0.0, "coverage": 0.0, "slowest": None}
    start = min(float(s["ts"]) for s in spans)
    end = max(float(s["ts"]) + float(s["dur"]) for s in spans)
    e2e_us = max(0.0, end - start)

    def s_end(s):
        return float(s["ts"]) + float(s["dur"])

    # walk backwards from the latest-finishing span: the predecessor of
    # a chain entry is the latest-finishing span that ended at or
    # before the entry started (what it plausibly waited on); when
    # nothing precedes it cleanly, fall back to an overlapping span
    # that started earlier (a covering parent), then stop.
    cur = max(spans, key=s_end)
    chain_rev = [cur]
    while True:
        t0 = float(cur["ts"])
        preds = [s for s in spans
                 if s is not cur and s_end(s) <= t0 + _EPS_US]
        if not preds:
            preds = [s for s in spans
                     if s is not cur and float(s["ts"]) < t0 - _EPS_US
                     and s_end(s) < s_end(cur)]
            if not preds:
                break
        cur = max(preds, key=s_end)
        chain_rev.append(cur)
    chain_spans = list(reversed(chain_rev))

    chain = []
    prev_end = None
    for s in chain_spans:
        t0, dur = float(s["ts"]), float(s["dur"])
        slack = 0.0 if prev_end is None else max(0.0, t0 - prev_end)
        chain.append({
            "name": s.get("name", ""),
            "cat": s.get("cat", ""),
            "node": s.get("node", ""),
            "proc": s.get("proc", ""),
            "ts": t0,
            "dur_ms": round(dur / 1e3, 3),
            "slack_ms": round(slack / 1e3, 3),
        })
        prev_end = max(prev_end or 0.0, t0 + dur)

    # coverage: union of the chain's intervals over the e2e window
    ivals = sorted((float(s["ts"]), s_end(s)) for s in chain_spans)
    covered = 0.0
    cur_lo = cur_hi = None
    for lo, hi in ivals:
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                covered += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        covered += cur_hi - cur_lo

    slowest = max(chain, key=lambda c: c["dur_ms"]) if chain else None
    return {
        "trace_id": trace_id if trace_id is not None else _trace_of(
            chain_spans[-1]),
        "chain": chain,
        "e2e_ms": round(e2e_us / 1e3, 3),
        "path_ms": round(sum(c["dur_ms"] for c in chain), 3),
        "coverage": round(covered / e2e_us, 4) if e2e_us > 0 else 0.0,
        "slowest": slowest["name"] if slowest else None,
    }


def aggregate(spans, min_spans: int = 2) -> dict:
    """Critical paths of EVERY trace in a span dump, aggregated by
    chain-entry name: which work blocks executions, how often, and for
    how much total/mean/max time. Traces with fewer than `min_spans`
    complete spans are skipped (a lone span has no chain).

    Returns ``{"traces": N, "entries": [{name, count, total_ms,
    mean_ms, max_ms, share}...]}`` sorted by total blocking time;
    `share` is the fraction of summed path time the entry accounts
    for — "where does p99 live" reads off the top row.
    """
    by_trace: dict[str, list] = {}
    for s in _complete(spans):
        t = _trace_of(s)
        if t:
            by_trace.setdefault(t, []).append(s)
    agg: dict[str, dict] = {}
    n_traces = 0
    for t, group in by_trace.items():
        if len(group) < min_spans:
            continue
        n_traces += 1
        for entry in critical_path(group)["chain"]:
            a = agg.setdefault(entry["name"], {
                "name": entry["name"], "count": 0, "total_ms": 0.0,
                "max_ms": 0.0})
            a["count"] += 1
            a["total_ms"] += entry["dur_ms"]
            a["max_ms"] = max(a["max_ms"], entry["dur_ms"])
    total = sum(a["total_ms"] for a in agg.values()) or 1.0
    entries = []
    for a in sorted(agg.values(), key=lambda x: -x["total_ms"]):
        entries.append({
            "name": a["name"], "count": a["count"],
            "total_ms": round(a["total_ms"], 3),
            "mean_ms": round(a["total_ms"] / a["count"], 3),
            "max_ms": round(a["max_ms"], 3),
            "share": round(a["total_ms"] / total, 4),
        })
    return {"traces": n_traces, "entries": entries}
