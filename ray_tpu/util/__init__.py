from ray_tpu.util.placement_group import (
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
)

__all__ = [
    "PlacementGroup",
    "PlacementGroupSchedulingStrategy",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
]
