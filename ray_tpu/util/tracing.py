"""User-facing tracing: nested in-task spans + trace-context access.

Reference parity: ray.util.tracing (tracing_helper.py:34 span
propagation), minus the OpenTelemetry dependency — spans land in the
process's TaskEventLog and flow to the head's cluster-wide span buffer,
so `ray_tpu.timeline()` shows them on the merged timeline next to the
runtime's own task/actor spans.

    from ray_tpu.util import tracing

    with tracing.span("preprocess"):          # inside a task, a driver,
        with tracing.span("tokenize"):        # or plain local code
            ...

Entering a span makes it the CURRENT trace context: tasks/actor calls
submitted inside it carry a child context, so a whole driver→actor→task
chain shares one trace_id (correlate with the `args` on timeline spans).
Works without an initialized runtime too (bench scripts, bare engines):
spans then collect in a process-local fallback log that `dump()`
exports."""

from __future__ import annotations

import contextlib
import threading
import time

from ray_tpu.utils.events import TaskEventLog, child_trace

# spans recorded before/without ray_tpu.init() (bench.py, bare LLMEngine)
_fallback_log = TaskEventLog()
_fallback_ctx = threading.local()


def _runtime():
    from ray_tpu.core import api

    return api._runtime


def _ctx_and_log():
    rt = _runtime()
    if rt is not None and hasattr(rt, "_ctx") and hasattr(rt, "_events"):
        return rt._ctx, rt._events
    return _fallback_ctx, _fallback_log


def current_trace() -> dict | None:
    """The active {trace_id, span_id, parent_id} context, if any."""
    ctx, _ = _ctx_and_log()
    return getattr(ctx, "trace", None)


@contextlib.contextmanager
def span(name: str, category: str = "user"):
    """Record a span around the enclosed block and make it the current
    trace context (children — nested spans, submitted tasks, actor
    calls — link to it). Yields the span's trace context."""
    ctx, log = _ctx_and_log()
    parent = getattr(ctx, "trace", None)
    trace = child_trace(parent)
    ctx.trace = trace
    try:
        with log.span(name, category, trace=trace):
            yield trace
    finally:
        ctx.trace = parent


def record_span(name: str, duration_s: float, category: str = "user",
                trace: dict | None = None) -> None:
    """Log an already-measured span ending now (for code that timed
    itself — compile hooks, collective wrappers)."""
    _, log = _ctx_and_log()
    t1 = time.monotonic_ns()
    log.record(name, category, t1 - int(duration_s * 1e9), t1,
               trace=trace or current_trace())


def record_interval(name: str, t0_monotonic_s: float,
                    t1_monotonic_s: float, category: str = "user",
                    trace: dict | None = None) -> None:
    """Log a span over an explicit [t0, t1] monotonic-seconds window
    (time.monotonic() readings) — how waterfall producers lay phase
    spans at their true positions instead of 'ending now'."""
    _, log = _ctx_and_log()
    log.record(name, category, int(t0_monotonic_s * 1e9),
               int(t1_monotonic_s * 1e9), trace=trace or current_trace())


def configure_sampling(policy: dict | None) -> None:
    """Install a span sampling policy on this process's active span log
    (``{"max_per_s": N, "categories": {cat: N}}``, 0 = unlimited)."""
    _, log = _ctx_and_log()
    log.configure_sampling(policy)


@contextlib.contextmanager
def profiler_capture(out_dir: str | None):
    """Arm a `jax.profiler.trace` capture window around the enclosed
    block — the device-side (TPU) profile that attributes in-program
    time (collective vs. GEMM vs. copy) the host-side span plane cannot
    see. Guarded no-op on CPU and when `out_dir` is falsy, so bench
    drivers call it unconditionally: on TPU a `--trace` run captures N
    timed steps, on CPU nothing is armed and nothing is written.

    The capture window rides the span API: a `profiler.capture` span
    (category `profiler`) covers the armed block, and its trace args
    carry the capture path — so the chrome timeline records WHERE the
    device profile for that window lives. Yields the capture directory
    (None when not armed)."""
    if not out_dir:
        # genuinely free no-op: no jax import, no backend init
        yield None
        return
    import jax

    if jax.devices()[0].platform in ("cpu",):
        yield None
        return
    try:
        profile = jax.profiler.trace(out_dir)
        profile.__enter__()
    except Exception:  # noqa: BLE001  # profiler unavailable on this
        yield None  # backend/build: the bench still runs, un-profiled
        return
    with span("profiler.capture", category="profiler") as trace:
        trace["capture_path"] = out_dir
        try:
            yield out_dir
        finally:
            try:
                profile.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass  # a failed stop must not eat the bench result


def jit_cache_size(jit_fn) -> int:
    """Compiled-program count of a `jax.jit` callable, or -1 when the
    (private) `_cache_size` API is unavailable. The ONE wrapper around
    that private API — every compile-miss probe (train/spmd.py,
    serve/llm/runner.py) goes through here, so a JAX upgrade breaks
    exactly one call site."""
    try:
        return jit_fn._cache_size()
    except Exception:  # noqa: BLE001
        return -1


def note_compile_if_grew(jit_fn, before: int, duration_s: float,
                         miss_counter, compile_hist, span_name: str,
                         tags: dict | None = None) -> bool:
    """The compile-miss protocol, in one place: if `jit_fn`'s cache grew
    past the `before` reading, account `duration_s` as a compile (miss
    counter + compile histogram + a compile-category span) and return
    True; otherwise return False (the caller accounts a normal step)."""
    if before < 0 or jit_cache_size(jit_fn) <= before:
        return False
    miss_counter.inc(tags=tags)
    compile_hist.observe(duration_s, tags=tags)
    record_span(span_name, duration_s, category="compile")
    return True


def dump(filename: str):
    """Write this process's trace to `filename`: the merged cluster
    timeline when a runtime is initialized, else the fallback log
    (bench scripts without a cluster)."""
    rt = _runtime()
    if rt is not None and hasattr(rt, "timeline"):
        return rt.timeline(filename)
    return _fallback_log.chrome_trace(filename)
