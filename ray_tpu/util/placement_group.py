"""Placement group public API.

Reference parity: python/ray/util/placement_group.py:41,145
(placement_group(), PlacementGroup.ready()/wait(), remove_placement_group)
and scheduling strategies (python/ray/util/scheduling_strategies.py:15).
"""

from __future__ import annotations

import time

from ray_tpu.core.api import _global_runtime
from ray_tpu.core.exceptions import PlacementGroupError
from ray_tpu.core.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: list[dict]):
        self.id = pg_id
        self.bundle_specs = bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def _state(self) -> dict:
        rt = _global_runtime()
        return rt.client.call(rt.head_address, "pg_table",
                              {"pg_id": self.id.binary()}, timeout=10)

    def wait(self, timeout_seconds: float = 30) -> bool:
        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            if self._state().get("state") == "CREATED":
                return True
            time.sleep(0.05)
        return self._state().get("state") == "CREATED"

    def ready(self):
        """ObjectRef-like blocking readiness (reference returns an
        ObjectRef; here a ref produced by a trivial task inside the PG
        would deadlock a 0-CPU test cluster, so wait() semantics)."""
        if not self.wait(timeout_seconds=60):
            raise PlacementGroupError(
                f"placement group {self.id.hex()[:12]} not ready")
        return self

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(bundles: list[dict], strategy: str = "PACK",
                    name: str | None = None, lifetime: str | None = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or not all(isinstance(b, dict) and b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    rt = _global_runtime()
    pg_id = PlacementGroupID.random()
    rt.client.call(rt.head_address, "create_pg", {
        "pg_id": pg_id.binary(),
        "bundles": [dict(b) for b in bundles],
        "strategy": strategy,
        "name": name,
    }, timeout=30)
    return PlacementGroup(pg_id, [dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup):
    rt = _global_runtime()
    rt.client.call(rt.head_address, "remove_pg", {"pg_id": pg.id.binary()},
                   timeout=30)


def placement_group_table(pg: PlacementGroup | None = None) -> dict:
    rt = _global_runtime()
    return rt.client.call(rt.head_address, "pg_table",
                          {"pg_id": pg.id.binary() if pg else None}, timeout=10)


class PlacementGroupSchedulingStrategy:
    """Reference: python/ray/util/scheduling_strategies.py:15."""

    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks
