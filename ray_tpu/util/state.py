"""State API — programmatic cluster introspection.

Reference parity: ray.util.state (python/ray/util/state/api.py —
list_actors/list_nodes/list_placement_groups; task events feed `ray list
tasks` in the reference; here per-process task events are exported via
ray_tpu.timeline())."""

from __future__ import annotations


def _head_call(method: str, msg: dict | None = None,
               address: str | None = None):
    from ray_tpu.core.rpc import RpcClient

    if address is None:
        from ray_tpu.core import api as _api

        rt = _api._runtime
        if rt is None or not hasattr(rt, "head_address"):
            raise RuntimeError("state API needs ray_tpu.init() or an "
                               "explicit head address")
        address = rt.head_address
    return RpcClient.shared().call(address, method, msg or {}, timeout=30)


def list_actors(address: str | None = None) -> list[dict]:
    return _head_call("list_actors", address=address)["actors"]


def list_nodes(address: str | None = None) -> list[dict]:
    view = _head_call("cluster_view", address=address)
    return [
        {
            "node_id": n["node_id"].hex(),
            "address": n["address"],
            "alive": n["alive"],
            "resources": n["resources"],
            "available": n["available"],
            "labels": n["labels"],
        }
        for n in view["nodes"]
    ]


def list_tasks(address: str | None = None, limit: int = 1000) -> list[dict]:
    """Executor-reported task events (reference: `ray list tasks` over
    GcsTaskManager task events)."""
    return _head_call("list_tasks", {"limit": limit},
                      address=address)["tasks"]


def list_placement_groups(address: str | None = None) -> list[dict]:
    return _head_call("pg_table", address=address).get("groups", [])


def summarize(address: str | None = None) -> dict:
    nodes = list_nodes(address)
    actors = list_actors(address)
    total: dict[str, float] = {}
    avail: dict[str, float] = {}
    for n in nodes:
        if not n["alive"]:
            continue
        for r, q in n["resources"].items():
            total[r] = total.get(r, 0.0) + q
        for r, q in n["available"].items():
            avail[r] = avail.get(r, 0.0) + q
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
        "resources_total": total,
        "resources_available": avail,
    }
