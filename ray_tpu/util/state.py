"""State API — programmatic cluster introspection.

Reference parity: ray.util.state (python/ray/util/state/api.py —
list_actors/list_nodes/list_placement_groups; task events feed `ray list
tasks`); cluster_timeline/cluster_metrics expose the merged tracing +
metrics plane (see OBSERVABILITY.md)."""

from __future__ import annotations


def _head_call(method: str, msg: dict | None = None,
               address: str | None = None, timeout: float = 30):
    from ray_tpu.core.rpc import RpcClient

    if address is None:
        from ray_tpu.core import api as _api

        rt = _api._runtime
        if rt is None or not hasattr(rt, "head_address"):
            raise RuntimeError("state API needs ray_tpu.init() or an "
                               "explicit head address")
        address = rt.head_address
    return RpcClient.shared().call(address, method, msg or {},
                                   timeout=timeout)


def list_actors(address: str | None = None,
                timeout: float = 30) -> list[dict]:
    return _head_call("list_actors", address=address,
                      timeout=timeout)["actors"]


def list_nodes(address: str | None = None,
               timeout: float = 30) -> list[dict]:
    view = _head_call("cluster_view", address=address, timeout=timeout)
    return [
        {
            "node_id": n["node_id"].hex(),
            "address": n["address"],
            "alive": n["alive"],
            "resources": n["resources"],
            "available": n["available"],
            "labels": n["labels"],
        }
        for n in view["nodes"]
    ]


def list_tasks(address: str | None = None, limit: int = 1000,
               timeout: float = 30) -> list[dict]:
    """Executor-reported task events (reference: `ray list tasks` over
    GcsTaskManager task events)."""
    return _head_call("list_tasks", {"limit": limit},
                      address=address, timeout=timeout)["tasks"]


def task_ledger(task_id: str | None = None, limit: int = 0,
                address: str | None = None, timeout: float = 30) -> dict:
    """The head task lifecycle ledger (the fifth observability pillar):
    per-state counts over the bounded ring plus its drop/spill stats —
    ``{"counts": {state: n}, "stats": {...}}`` — and, when asked, one
    joined record by `task_id` hex prefix (``"record"``, including the
    evicted-to-disk spill) or the last-N record summaries
    (``"records"``). Each record carries the full transition history:
    SUBMITTED → QUEUED → LEASED/SCHEDULED/DISPATCHED → RUNNING →
    FINISHED/FAILED/RETRIED with epoch timestamps and the scheduler's
    last placement verdict."""
    msg: dict = {}
    if task_id:
        msg["task_id"] = task_id
    if limit:
        msg["limit"] = limit
    return _head_call("task_ledger", msg, address=address,
                      timeout=timeout)


def explain_task(task_id: str, address: str | None = None,
                 timeout: float = 15) -> dict:
    """`ray_tpu explain` — why is this task pending / why was it slow.

    The head answers from the ledger (the transition waterfall and the
    scheduler's recorded placement verdict) and, for a task that is
    not yet terminal, fans out to every alive nodelet for live queue
    state (is it queued there, queue position, wait so far, and a
    per-node feasibility table naming which resource/label constraint
    fails where). The fan-out runs under ONE shared deadline — a dead
    node becomes an ``errors`` entry, never a failed query. Returns
    ``{"record", "waterfall", "verdict", "nodes": {node12: {...}},
    "errors": {node12: why}}``."""
    return _head_call("explain_task",
                      {"task_id": task_id, "timeout": timeout},
                      address=address, timeout=timeout + 5)


def critical_path(trace_id: str | None = None, address: str | None = None,
                  timeout: float = 30) -> dict:
    """Critical-path analysis over the head's span buffer (see
    ``ray_tpu.util.critpath``): with a `trace_id`, the blocking chain
    of that one execution (per-edge slack, e2e coverage, the slowest
    entry); without, the aggregate across every trace in the buffer —
    which work blocks executions and for how much total time ("where
    does p99 live")."""
    from ray_tpu.util import critpath as _cp

    spans = _head_call("dump_timeline", address=address,
                       timeout=timeout)["spans"]
    if trace_id:
        return _cp.critical_path(spans, trace_id)
    return _cp.aggregate(spans)


def cluster_metrics(address: str | None = None,
                    timeout: float = 30) -> str:
    """One Prometheus page for the whole cluster: the head scrapes every
    alive nodelet (which fans out to its workers) and injects node/proc
    tags (reference: the dashboard's cluster metrics aggregation)."""
    return _head_call("cluster_metrics", address=address,
                      timeout=timeout)["text"]


def cluster_metrics_history(names=None, window_s: float | None = None,
                            address: str | None = None,
                            timeout: float = 30) -> dict:
    """The head watchtower's retained metric time series: the head
    samples its own cluster-wide scrape every few seconds (default 5s)
    into bounded per-series ring buffers, so rate/derivative questions
    ("is the queue ramping?", "did TTFT p99 move in the last 10min?")
    have history to run against — the substrate an SLO autoscaler
    consumes. Returns ``{"series": [{name, tags, samples: [[epoch_s,
    value], ...]}], "period_s", "series_count", "series_dropped",
    "samples_total"}``; `names` filters to those metric names,
    `window_s` clips to the trailing window. Memory is bounded by a
    series cap (rejected new series are COUNTED in
    ``series_dropped``) times a per-series ring."""
    return _head_call("metrics_history",
                      {"names": list(names) if names else None,
                       "window_s": window_s},
                      address=address, timeout=timeout)


def alerts(address: str | None = None, include_history: bool = True,
           timeout: float = 30) -> dict:
    """The watchtower's structured alerts: ``{"alerts": [...],
    "history": [...], "rules": [...], "autodumps": N}`` — active
    (pending/firing) alerts, the bounded transition history
    (pending→firing→resolved events), and the rule pack being
    evaluated. The same facts surface as
    ``watchtower_alerts_firing{severity}`` on the cluster metrics page
    and through ``ray_tpu alerts``."""
    return _head_call("alerts", {"history": include_history},
                      address=address, timeout=timeout)


def profile(duration_s: float = 5.0, hz: float | None = None,
            address: str | None = None, include_driver: bool = True,
            timeout: float | None = None) -> dict:
    """Cluster-wide sampling profile: the head arms a capture window in
    every process — head, each alive nodelet, each ready worker — via
    the `profile_capture` fan-out (one shared deadline, the metrics
    scrape shape), and this driver samples itself in parallel. Returns
    ``{"stacks": {collapsed: count}, "samples", "dropped", "procs",
    "errors", "hz", "duration_s"}`` where each collapsed stack is
    prefixed with ``node:<id>;proc:<id>`` pseudo-frames, ready for
    `profiler.collapsed_text` / flamegraph tooling. Dormant processes
    pay nothing outside the window; see OBSERVABILITY.md "Profiling &
    memory attribution" for the capture contract."""
    import threading

    from ray_tpu.core import api as _api
    from ray_tpu.util import profiler

    local: dict = {}
    th = None
    if include_driver and _api._runtime is not None:
        def _local_capture():
            local.update(profiler.capture_collapsed(duration_s, hz=hz))

        th = threading.Thread(target=_local_capture, daemon=True,
                              name="profile-driver-capture")
        th.start()
    if timeout is None:
        timeout = float(duration_s) + 30.0
    r = _head_call("profile_capture",
                   {"duration_s": duration_s, "hz": hz},
                   address=address, timeout=timeout)
    if th is not None:
        th.join(timeout=float(duration_s) + 10.0)
    if local:
        r["stacks"] = profiler.merge_collapsed([
            r["stacks"],
            profiler.prefix_stacks(local["stacks"],
                                   "node:driver;proc:driver")])
        r["samples"] += local["samples"]
        r["dropped"] += local["dropped"]
        r["procs"] += 1
    return r


def cpu_attribution(address: str | None = None, top_n: int = 20,
                    timeout: float = 20) -> dict:
    """Per-task / per-actor-method CPU attribution, cluster-wide: every
    worker's exec loop accounts `time.thread_time` deltas by (label,
    kind); this aggregates the tables across all alive nodes and
    returns the top-N by cumulative CPU — ``{"rows": [{label, kind,
    cpu_seconds, calls, procs}], "total_cpu_seconds"}``. The straggler
    question ("which actor method is eating the node?") as a lookup
    instead of a profiling session."""
    from ray_tpu.core.rpc import RpcClient

    agg: dict[tuple, dict] = {}
    for n in list_nodes(address, timeout=timeout):
        if not n["alive"]:
            continue
        try:
            r = RpcClient.shared().call(n["address"], "node_cpu_stats",
                                        {}, timeout=timeout)
        except Exception:  # noqa: BLE001
            continue
        for row in r.get("rows", ()):
            key = (row["label"], row["kind"])
            ent = agg.setdefault(key, {"label": row["label"],
                                       "kind": row["kind"],
                                       "cpu_seconds": 0.0, "calls": 0,
                                       "procs": 0})
            ent["cpu_seconds"] += row["cpu_seconds"]
            ent["calls"] += row["calls"]
            ent["procs"] += 1
    rows = sorted(agg.values(), key=lambda e: -e["cpu_seconds"])
    return {"rows": rows[:top_n],
            "total_cpu_seconds": sum(e["cpu_seconds"]
                                     for e in agg.values())}


def cluster_timeline(address: str | None = None,
                     filename: str | None = None, timeout: float = 30):
    """The merged cluster chrome trace from the head's span buffer
    (pid = node, tid = worker/thread, epoch-aligned timestamps; spilled
    history merged back in). In a connected driver prefer
    `ray_tpu.timeline()`, which also flushes the driver's own spans
    first."""
    from ray_tpu.utils.events import merge_spans

    spans = _head_call("dump_timeline", address=address,
                       timeout=timeout)["spans"]
    return merge_spans(spans, filename)


def _node_address(node_id: str, address: str | None) -> str:
    for n in list_nodes(address):
        if n["node_id"].startswith(node_id) and n["alive"]:
            return n["address"]
    raise ValueError(f"no live node matching {node_id!r}")


def node_stats(node_id: str, address: str | None = None) -> dict:
    """Per-node agent stats through the nodelet (reference:
    dashboard/agent.py stats collection — loadavg, per-worker RSS,
    store usage)."""
    from ray_tpu.core.rpc import RpcClient

    target = _node_address(node_id, address)
    return RpcClient.shared().call(target, "node_stats", {}, timeout=30)


def list_logs(node_id: str, address: str | None = None) -> list[dict]:
    """Log files on a node (reference: `ray logs` / the dashboard log
    monitor, _private/log_monitor.py:103)."""
    from ray_tpu.core.rpc import RpcClient

    target = _node_address(node_id, address)
    return RpcClient.shared().call(target, "list_logs", {},
                                   timeout=30)["logs"]


def tail_log(node_id: str, file: str, nbytes: int = 64 * 1024,
             offset: int = -1, address: str | None = None):
    """Tail (or incrementally follow via `offset`) one log file on a
    node. Returns (text, end_offset)."""
    from ray_tpu.core.rpc import RpcClient

    target = _node_address(node_id, address)
    value, frames = RpcClient.shared().call_frames(
        target, "tail_log", {"file": file, "nbytes": nbytes,
                             "offset": offset}, timeout=30)
    if not value.get("ok"):
        raise FileNotFoundError(value.get("error", "log unavailable"))
    return frames[0].decode(errors="replace"), value["end_offset"]


def cluster_logs(address: str | None = None, *, level: str | None = None,
                 grep: str | None = None, node: str | None = None,
                 task: str | None = None, trace_id: str | None = None,
                 proc: str | None = None, limit: int = 1000,
                 window_s: float | None = None,
                 offsets: dict | None = None,
                 timeout: float = 15) -> dict:
    """Cluster-wide structured-log query (the fourth observability
    plane): the head fans `log_query` out to every alive nodelet under
    ONE shared deadline and returns the merged, ts-sorted records —
    ``{"records": [...], "errors": {node12: why}, "offsets": {node12:
    {file: cursor}}, "truncated"}``. Cursors are OPAQUE round-trip
    values (currently ``[inode, byte]`` — rotation is detected by file
    identity); pass them back verbatim, never construct them. A
    stopped node costs at most the shared deadline and lands in
    ``errors``; it never fails the query.

    Filters: ``level`` is a minimum severity, ``grep`` a regex over
    msg/logger, ``node`` a node-id hex prefix, ``task``/``trace_id``
    exact ids (the correlation keys every record carries — see
    OBSERVABILITY.md "Logging"), ``window_s`` a trailing wall-clock
    window. Pass a reply's ``offsets`` back in to read only new
    records (the `--follow` primitive)."""
    import time as _time

    if grep:
        # validate HERE: a bad regex raised inside every nodelet's
        # log_query is indistinguishable from N dead nodes
        import re as _re

        try:
            _re.compile(grep)
        except _re.error as e:
            raise ValueError(f"invalid grep regex {grep!r}: {e}") from e
    from ray_tpu.utils.logging import LEVELS

    if level and str(level).lower() not in LEVELS:
        # level_no() ranks unknown names as info — fine for ranking a
        # record, silently wrong as a FILTER ("warn" must not widen
        # the view to info-and-up)
        raise ValueError(f"unknown level {level!r}; one of "
                         f"{sorted(LEVELS)}")
    msg: dict = {"level": level, "grep": grep, "node": node,
                 "task": task, "trace_id": trace_id, "proc": proc,
                 "limit": limit, "offsets": offsets,
                 "timeout": timeout}
    if window_s is not None:
        msg["since"] = _time.time() - float(window_s)
    return _head_call("cluster_logs", msg, address=address,
                      timeout=timeout + 5)


def list_placement_groups(address: str | None = None,
                          timeout: float = 30) -> list[dict]:
    return _head_call("pg_table", address=address,
                      timeout=timeout).get("groups", [])


def _node_object_tables(address: str | None, timeout: float = 20
                        ) -> tuple[list[dict], list[dict]]:
    """One fan-out pass: (per-node rows incl. store stats, all owned
    objects — workers' via their nodelet + the calling driver's own).
    `timeout` bounds each per-node call (a dead-but-not-yet-aged node
    costs at most that)."""
    from ray_tpu.core import api as _api
    from ray_tpu.core.rpc import RpcClient

    objects: list[dict] = []
    rt = _api._runtime
    if rt is not None and hasattr(rt, "_h_list_objects"):
        objects.extend(rt._h_list_objects({}, [])["objects"])
    nodes = []
    for n in list_nodes(address, timeout=timeout):
        if not n["alive"]:
            continue
        try:
            r = RpcClient.shared().call(n["address"], "list_node_objects",
                                        {}, timeout=timeout)
        except Exception:  # noqa: BLE001
            continue
        objects.extend(r.get("objects", ()))
        store = r.get("store", {})
        nodes.append({
            "node_id": n["node_id"],
            "address": n["address"],
            "store_bytes_allocated": store.get("bytes_allocated", 0),
            "store_capacity": store.get("capacity", 0),
            "store_num_objects": store.get("num_objects", 0),
            "store_evictions": store.get("evictions", 0),
            "oom_kills": r.get("oom_kills", 0),
        })
    return nodes, objects


def list_objects(address: str | None = None,
                 timeout: float = 20) -> list[dict]:
    """Cluster-wide owner-side object tables (reference:
    `ray list objects`, python/ray/util/state/api.py:1). Covers every
    worker's owned objects via its nodelet, plus the calling driver's
    own table."""
    return _node_object_tables(address, timeout)[1]


_AGE_BUCKETS = ((60.0, "<1m"), (300.0, "1-5m"), (float("inf"), ">5m"))


def _age_bucket(age_s: float) -> str:
    for bound, name in _AGE_BUCKETS:
        if age_s < bound:
            return name
    return _AGE_BUCKETS[-1][1]


def _attr_agg(table: dict, key: str, o: dict, stranded: bool) -> None:
    agg = table.setdefault(key, {
        "count": 0, "bytes": 0, "spilled": 0, "borrowed": 0,
        "stranded_count": 0, "stranded_bytes": 0,
        "ages": {name: 0 for _, name in _AGE_BUCKETS}})
    size = o.get("size", 0) or 0
    agg["count"] += 1
    agg["bytes"] += size
    agg["spilled"] += 1 if o.get("spilled") else 0
    agg["borrowed"] += o.get("borrowers", 0)
    agg["ages"][_age_bucket(o.get("age_s", 0.0))] += 1
    if stranded:
        agg["stranded_count"] += 1
        agg["stranded_bytes"] += size


def memory_summary(address: str | None = None, timeout: float = 20,
                   stranded_age_s: float | None = None) -> dict:
    """Per-node store usage + per-owner AND per-creator object
    attribution with age buckets and the stranded-ref audit (reference:
    the `ray memory` report). A ref counts as STRANDED when it is
    ready, older than `stranded_age_s` (default
    ``RAY_TPU_STRANDED_AGE_S``, 300s), and shows no consumer progress —
    never get()-consumed, never served to a borrower, no live
    borrower. `by_label` groups by what CREATED the object (task /
    actor-method name, `put`, `deferred`), which is what names the
    leaking code path."""
    from ray_tpu.core.cluster_runtime import _stranded_age_s, is_stranded

    if stranded_age_s is None:
        stranded_age_s = _stranded_age_s()
    nodes, objects = _node_object_tables(address, timeout)
    by_owner: dict[str, dict] = {}
    by_label: dict[str, dict] = {}
    stranded_rows: list[dict] = []
    for o in objects:
        # the ONE predicate the auditor gauge uses — report and alert
        # can never disagree about what counts as stranded
        stranded = is_stranded(o.get("ready", False),
                               o.get("consumed", False),
                               o.get("borrowers", 0),
                               o.get("age_s", 0.0), stranded_age_s)
        _attr_agg(by_owner, o["owner"], o, stranded)
        _attr_agg(by_label, o.get("label") or "?", o, stranded)
        if stranded:
            stranded_rows.append(o)
    stranded_rows.sort(key=lambda o: -(o.get("size", 0) or 0))
    return {
        "nodes": nodes,
        "objects_total": len(objects),
        "objects_bytes": sum((o.get("size") or 0) for o in objects),
        "by_owner": by_owner,
        "by_label": by_label,
        "stranded_age_s": stranded_age_s,
        "stranded": {
            "count": len(stranded_rows),
            "bytes": sum((o.get("size") or 0) for o in stranded_rows),
            "top": stranded_rows[:20],
        },
    }


def _attr_lines(title: str, table: dict) -> list[str]:
    lines = [title]
    for key, agg in sorted(table.items(), key=lambda kv: -kv[1]["bytes"]):
        ages = " ".join(f"{name}={agg['ages'][name]}"
                        for _, name in _AGE_BUCKETS)
        lines.append(
            f"  {key:<28} count={agg['count']:<6} "
            f"bytes={agg['bytes'] / (1 << 20):8.1f}MB "
            f"spilled={agg['spilled']:<4} borrowed={agg['borrowed']:<4} "
            f"stranded={agg['stranded_count']:<4} ages[{ages}]")
    return lines


def memory_report(address: str | None = None, timeout: float = 20,
                  stranded_age_s: float | None = None) -> str:
    """Human-readable `ray_tpu memory` view: per-node store usage, the
    per-owner and per-creator attribution tables, and the stranded-ref
    audit."""
    s = memory_summary(address, timeout, stranded_age_s)
    lines = ["=== object store per node ==="]
    for n in s["nodes"]:
        cap = n["store_capacity"] or 1
        lines.append(
            f"  {n['node_id'][:12]} {n['address']:<21} "
            f"{n['store_bytes_allocated'] / (1 << 20):8.1f}MB / "
            f"{cap / (1 << 20):7.1f}MB  objs={n['store_num_objects']:<6} "
            f"evictions={n['store_evictions']:<6} "
            f"oom_kills={n['oom_kills']}")
    lines.append(f"=== owned objects: {s['objects_total']} "
                 f"({s['objects_bytes'] / (1 << 20):.1f}MB) ===")
    lines += _attr_lines("=== by owner ===", s["by_owner"])
    lines += _attr_lines("=== by creator ===", s["by_label"])
    st = s["stranded"]
    lines.append(
        f"=== stranded refs (age > {s['stranded_age_s']:g}s, no consumer "
        f"progress): {st['count']} ({st['bytes'] / (1 << 20):.1f}MB) ===")
    for o in st["top"]:
        lines.append(
            f"  {o['object_id'][:16]} label={o.get('label', '?'):<24} "
            f"owner={o['owner']:<21} "
            f"bytes={(o.get('size') or 0) / (1 << 20):8.1f}MB "
            f"age={o.get('age_s', 0.0):8.1f}s "
            f"error={bool(o.get('error'))}")
    return "\n".join(lines)


def summarize(address: str | None = None) -> dict:
    nodes = list_nodes(address)
    actors = list_actors(address)
    total: dict[str, float] = {}
    avail: dict[str, float] = {}
    for n in nodes:
        if not n["alive"]:
            continue
        for r, q in n["resources"].items():
            total[r] = total.get(r, 0.0) + q
        for r, q in n["available"].items():
            avail[r] = avail.get(r, 0.0) + q
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
        "resources_total": total,
        "resources_available": avail,
    }


def cluster_summary(address: str | None = None,
                    timeout: float = 20) -> dict:
    """One-screen cluster overview (`ray_tpu summary`): nodes
    alive/dead, actors by state, ledger task counts by lifecycle
    state, object totals + stranded bytes, and firing alerts — each
    section best-effort (a failed collector becomes an ``errors``
    entry, the rest of the screen still renders)."""
    out: dict = {"errors": {}}

    def section(name, fn):
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001
            out["errors"][name] = repr(e)

    section("cluster", lambda: summarize(address))

    def _actors():
        by_state: dict[str, int] = {}
        for a in list_actors(address, timeout=timeout):
            by_state[a["state"]] = by_state.get(a["state"], 0) + 1
        return by_state

    section("actors_by_state", _actors)
    section("tasks", lambda: task_ledger(address=address, timeout=timeout))

    def _objects():
        m = memory_summary(address, timeout=timeout)
        return {"objects_total": m["objects_total"],
                "objects_bytes": m["objects_bytes"],
                "stranded_count": m["stranded"]["count"],
                "stranded_bytes": m["stranded"]["bytes"]}

    section("objects", _objects)

    def _alerts():
        r = alerts(address, include_history=False, timeout=timeout)
        return [a for a in r.get("alerts", ())
                if a.get("state") in ("pending", "firing")]

    section("alerts", _alerts)
    return out


def serve_status(address: str | None = None) -> dict:
    """Serve apps + per-replica health + per-proxy request metrics
    (reference: `ray serve status` / the serve state surface). The
    ``health`` key carries the self-healing plane's per-app view —
    live replicas with probe-miss counts, restart totals, degraded
    flags, and the bounded replica lifecycle history (deaths with
    reasons, replacements, restart-cap events) — which is what
    ``debug_dump`` persists as ``serve_status.json``, so a post-mortem
    can reconstruct WHEN each replica died and why. The serve control
    plane lives in actors, so this needs a runtime: with `address`
    given it connects to that head when no runtime exists, and refuses
    to silently answer from a DIFFERENT cluster than the one asked
    about."""
    import ray_tpu
    from ray_tpu import serve

    if address is not None:
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        else:
            from ray_tpu.core.api import _global_runtime

            current = getattr(_global_runtime(), "head_address", None)
            if current is not None and current != address:
                raise ValueError(
                    f"runtime is connected to {current!r}, not "
                    f"{address!r}; serve status reflects the connected "
                    "cluster")
    return serve.status()


def llm_status(app_name: str, timeout: float = 30) -> list[dict]:
    """Per-replica LLM engine stats for a `serve.llm` app: queue depth,
    running lanes, cache utilization, preemptions, compiled-program
    count, cumulative request-phase seconds. One dict per replica (the
    handle routes to a single replica; this asks the controller for the
    full set). Probes ride the replicas' control concurrency group, so
    they answer even while every request lane is mid-stream. `timeout`
    bounds EACH of the two round trips (controller, then replicas)."""
    import ray_tpu
    from ray_tpu.serve.api import _CONTROLLER_NAME

    ctrl = ray_tpu.get_actor(_CONTROLLER_NAME)
    r = ray_tpu.get(ctrl.get_replicas.remote(app_name), timeout=timeout)
    if not r["replicas"]:
        raise ValueError(f"no serve application named {app_name!r}")
    refs = [rep.handle_request.options(
        concurrency_group="control").remote("engine_stats", (), {})
        for rep in r["replicas"]]
    return ray_tpu.get(refs, timeout=timeout)


# --------------------------------------------------------------------------
# Flight recorder — `debug_dump()` / `ray_tpu debug-dump`
# --------------------------------------------------------------------------

def debug_dump(out_dir: str | None = None, address: str | None = None,
               deadline_s: float = 60.0, log_tail_bytes: int = 64 * 1024
               ) -> str:
    """One-call cluster flight recorder: write a post-mortem directory
    with everything an incident writeup needs — state-API listings
    (nodes/actors/tasks/objects/placement groups), the memory report,
    serve + llm status, the merged cluster timeline, the cluster-wide
    /metrics page, and per-node log tails.

    Every artifact is gathered best-effort under ONE deadline: each RPC
    gets at most min(10s, remaining budget), a dead or hung node costs
    its timeout and nothing more, and the dump itself never raises —
    per-artifact failures land in ``summary.json`` next to the
    successes. The one exception is ``serve.status()``, whose internal
    probes carry fixed 10-30s timeouts; it is only attempted while >15s
    of budget remains. Returns the output directory path.

    Layout::

        <dir>/summary.json              what was captured, what failed
        <dir>/nodes.json ...            state listings
        <dir>/memory.txt                `ray_tpu memory` report
        <dir>/serve_status.json         serve apps (when serve is up)
        <dir>/llm_status.json           per-replica engine stats
        <dir>/timeline.json             merged chrome trace
        <dir>/metrics.prom              cluster Prometheus page
        <dir>/alerts.json               watchtower alerts + transitions
        <dir>/profile.collapsed         short cluster stack capture
        <dir>/logs/<node12>/<file>      per-node log tails

    ``memory.txt`` is the full attribution report (per-owner +
    per-creator tables, age buckets, the stranded-ref audit);
    ``profile.collapsed`` is a best-effort ~2s cluster-wide sampling
    capture (flamegraph-compatible), taken only while real budget
    remains — success or failure lands in ``summary.json`` like every
    other artifact.
    """
    import json
    import os
    import time

    t_wall = time.time()
    t0 = time.monotonic()
    deadline = t0 + deadline_s
    if out_dir is None:
        out_dir = time.strftime("ray_tpu-debug-%Y%m%d-%H%M%S")
    os.makedirs(out_dir, exist_ok=True)
    summary: dict = {"started_at": t_wall, "deadline_s": deadline_s,
                     "address": address, "artifacts": {}, "errors": {}}

    def budget(cap: float = 10.0) -> float:
        return max(0.5, min(cap, deadline - time.monotonic()))

    def step(name: str, fn, writer=None):
        """Run one artifact collector under the shared deadline; record
        its outcome, never raise."""
        if time.monotonic() >= deadline:
            summary["errors"][name] = "deadline exhausted"
            return None
        t_a = time.monotonic()
        try:
            value = fn()
        except Exception as e:  # noqa: BLE001
            summary["errors"][name] = repr(e)
            return None
        try:
            if writer is not None:
                writer(value)
        except Exception as e:  # noqa: BLE001
            summary["errors"][name] = f"write failed: {e!r}"
            return value
        summary["artifacts"][name] = round(time.monotonic() - t_a, 3)
        return value

    def jwrite(fname):
        def w(value):
            with open(os.path.join(out_dir, fname), "w") as f:
                json.dump(value, f, indent=1, default=str)
        return w

    def twrite(fname):
        def w(text):
            with open(os.path.join(out_dir, fname), "w") as f:
                f.write(text)
        return w

    nodes = step("nodes",
                 lambda: list_nodes(address, timeout=budget()),
                 jwrite("nodes.json"))
    step("actors", lambda: list_actors(address, timeout=budget()),
         jwrite("actors.json"))
    step("tasks", lambda: list_tasks(address, timeout=budget()),
         jwrite("tasks.json"))

    # ledger records as JSONL: the joined per-task state machines with
    # transition history — the first artifact a "why did task X stall"
    # post-mortem greps (tasks.json above stays the flat event view)
    def _task_ledger():
        r = task_ledger(limit=2000, address=address, timeout=budget())
        lines = [json.dumps(rec, default=str)
                 for rec in r.get("records", ())]
        return "\n".join(lines) + ("\n" if lines else "")

    step("task_ledger", _task_ledger, twrite("tasks.jsonl"))
    step("placement_groups",
         lambda: list_placement_groups(address, timeout=budget()),
         jwrite("placement_groups.json"))
    step("objects", lambda: list_objects(address, timeout=budget()),
         jwrite("objects.json"))
    step("memory", lambda: memory_report(address, timeout=budget()),
         twrite("memory.txt"))
    step("metrics", lambda: cluster_metrics(address, timeout=budget()),
         twrite("metrics.prom"))
    step("alerts", lambda: alerts(address, timeout=budget()),
         jwrite("alerts.json"))
    step("timeline",
         lambda: cluster_timeline(
             address, os.path.join(out_dir, "timeline.json"),
             timeout=budget()))

    # incident-window structured logs: the last ~10min of records at
    # warning-and-up, cluster-wide and trace/task-tagged — the filtered
    # view an incident writeup greps FIRST (the raw per-node tails
    # below stay for everything the structured plane did not capture)
    def _cluster_logs():
        r = cluster_logs(address, level="warning", window_s=600.0,
                         limit=2000, timeout=budget())
        lines = [json.dumps(rec, default=str) for rec in r["records"]]
        for nid, err in r.get("errors", {}).items():
            summary["errors"][f"cluster_logs:{nid}"] = err
        return "\n".join(lines) + ("\n" if lines else "")

    step("cluster_logs", _cluster_logs, twrite("logs.jsonl"))

    # short cluster profile: where every process's threads were while
    # the incident was live (the alert-triggered autodump path rides
    # this too, so a critical firing captures a flamegraph for free).
    # The capture costs its window in wall time, so it runs only while
    # real budget remains beyond the window.
    if deadline - time.monotonic() > 8.0:
        def _profile():
            from ray_tpu.util import profiler

            # the remaining dump budget bounds the whole capture RPC —
            # a hung node must cost this STEP its timeout, never
            # stretch the dump past deadline_s like every other step
            r = profile(duration_s=min(2.0, budget(5.0) / 2),
                        address=address, timeout=budget())
            return profiler.collapsed_text(r["stacks"])

        step("profile", _profile, twrite("profile.collapsed"))
    else:
        summary["errors"]["profile"] = "insufficient budget left"

    # serve control plane (needs a connected runtime; absent serve apps
    # are an error entry, not a failure). serve.status()'s internal
    # probes carry their own 10-30s timeouts which this step cannot
    # shorten, so it is attempted only while a real budget remains —
    # a hung controller must not stretch the dump to multiples of the
    # deadline.
    status = None
    if deadline - time.monotonic() > 15.0:
        status = step("serve_status", lambda: serve_status(address),
                      jwrite("serve_status.json"))
    else:
        summary["errors"]["serve_status"] = "insufficient budget left"
    if status:
        def _llm():
            out = {}
            for app in status.get("apps", {}):
                if time.monotonic() >= deadline:
                    break
                try:
                    out[app] = llm_status(app, timeout=budget())
                except Exception:  # noqa: BLE001
                    continue  # not an LLM app (or replicas gone)
            return out

        step("llm_status", _llm, jwrite("llm_status.json"))

    # per-node log tails (alive nodes only: a dead nodelet has no RPC
    # endpoint to tail from — its logs are on its disk)
    from ray_tpu.core.rpc import RpcClient

    for n in nodes or []:
        if not n.get("alive"):
            summary["errors"][f"logs:{n['node_id'][:12]}"] = "node dead"
            continue
        nid = n["node_id"][:12]

        def _tail_node(n=n, nid=nid):
            node_dir = os.path.join(out_dir, "logs", nid)
            os.makedirs(node_dir, exist_ok=True)
            logs = RpcClient.shared().call(
                n["address"], "list_logs", {},
                timeout=budget(5.0))["logs"]
            for entry in logs[:50]:
                if time.monotonic() >= deadline:
                    break
                try:
                    value, frames = RpcClient.shared().call_frames(
                        n["address"], "tail_log",
                        {"file": entry["file"],
                         "nbytes": log_tail_bytes, "offset": -1},
                        timeout=budget(5.0))
                    if value.get("ok"):
                        with open(os.path.join(node_dir, entry["file"]),
                                  "wb") as f:
                            f.write(frames[0])
                except Exception:  # noqa: BLE001
                    continue
            return len(logs)

        step(f"logs:{nid}", _tail_node)

    summary["elapsed_s"] = round(time.monotonic() - t0, 3)
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1, default=str)
    return out_dir
