"""State API — programmatic cluster introspection.

Reference parity: ray.util.state (python/ray/util/state/api.py —
list_actors/list_nodes/list_placement_groups; task events feed `ray list
tasks`); cluster_timeline/cluster_metrics expose the merged tracing +
metrics plane (see OBSERVABILITY.md)."""

from __future__ import annotations


def _head_call(method: str, msg: dict | None = None,
               address: str | None = None):
    from ray_tpu.core.rpc import RpcClient

    if address is None:
        from ray_tpu.core import api as _api

        rt = _api._runtime
        if rt is None or not hasattr(rt, "head_address"):
            raise RuntimeError("state API needs ray_tpu.init() or an "
                               "explicit head address")
        address = rt.head_address
    return RpcClient.shared().call(address, method, msg or {}, timeout=30)


def list_actors(address: str | None = None) -> list[dict]:
    return _head_call("list_actors", address=address)["actors"]


def list_nodes(address: str | None = None) -> list[dict]:
    view = _head_call("cluster_view", address=address)
    return [
        {
            "node_id": n["node_id"].hex(),
            "address": n["address"],
            "alive": n["alive"],
            "resources": n["resources"],
            "available": n["available"],
            "labels": n["labels"],
        }
        for n in view["nodes"]
    ]


def list_tasks(address: str | None = None, limit: int = 1000) -> list[dict]:
    """Executor-reported task events (reference: `ray list tasks` over
    GcsTaskManager task events)."""
    return _head_call("list_tasks", {"limit": limit},
                      address=address)["tasks"]


def cluster_metrics(address: str | None = None) -> str:
    """One Prometheus page for the whole cluster: the head scrapes every
    alive nodelet (which fans out to its workers) and injects node/proc
    tags (reference: the dashboard's cluster metrics aggregation)."""
    return _head_call("cluster_metrics", address=address)["text"]


def cluster_timeline(address: str | None = None,
                     filename: str | None = None):
    """The merged cluster chrome trace from the head's span buffer
    (pid = node, tid = worker/thread, epoch-aligned timestamps). In a
    connected driver prefer `ray_tpu.timeline()`, which also flushes the
    driver's own spans first."""
    from ray_tpu.utils.events import merge_spans

    spans = _head_call("dump_timeline", address=address)["spans"]
    return merge_spans(spans, filename)


def _node_address(node_id: str, address: str | None) -> str:
    for n in list_nodes(address):
        if n["node_id"].startswith(node_id) and n["alive"]:
            return n["address"]
    raise ValueError(f"no live node matching {node_id!r}")


def node_stats(node_id: str, address: str | None = None) -> dict:
    """Per-node agent stats through the nodelet (reference:
    dashboard/agent.py stats collection — loadavg, per-worker RSS,
    store usage)."""
    from ray_tpu.core.rpc import RpcClient

    target = _node_address(node_id, address)
    return RpcClient.shared().call(target, "node_stats", {}, timeout=30)


def list_logs(node_id: str, address: str | None = None) -> list[dict]:
    """Log files on a node (reference: `ray logs` / the dashboard log
    monitor, _private/log_monitor.py:103)."""
    from ray_tpu.core.rpc import RpcClient

    target = _node_address(node_id, address)
    return RpcClient.shared().call(target, "list_logs", {},
                                   timeout=30)["logs"]


def tail_log(node_id: str, file: str, nbytes: int = 64 * 1024,
             offset: int = -1, address: str | None = None):
    """Tail (or incrementally follow via `offset`) one log file on a
    node. Returns (text, end_offset)."""
    from ray_tpu.core.rpc import RpcClient

    target = _node_address(node_id, address)
    value, frames = RpcClient.shared().call_frames(
        target, "tail_log", {"file": file, "nbytes": nbytes,
                             "offset": offset}, timeout=30)
    if not value.get("ok"):
        raise FileNotFoundError(value.get("error", "log unavailable"))
    return frames[0].decode(errors="replace"), value["end_offset"]


def list_placement_groups(address: str | None = None) -> list[dict]:
    return _head_call("pg_table", address=address).get("groups", [])


def _node_object_tables(address: str | None) -> tuple[list[dict],
                                                      list[dict]]:
    """One fan-out pass: (per-node rows incl. store stats, all owned
    objects — workers' via their nodelet + the calling driver's own)."""
    from ray_tpu.core import api as _api
    from ray_tpu.core.rpc import RpcClient

    objects: list[dict] = []
    rt = _api._runtime
    if rt is not None and hasattr(rt, "_h_list_objects"):
        objects.extend(rt._h_list_objects({}, [])["objects"])
    nodes = []
    for n in list_nodes(address):
        if not n["alive"]:
            continue
        try:
            r = RpcClient.shared().call(n["address"], "list_node_objects",
                                        {}, timeout=20)
        except Exception:  # noqa: BLE001
            continue
        objects.extend(r.get("objects", ()))
        store = r.get("store", {})
        nodes.append({
            "node_id": n["node_id"],
            "address": n["address"],
            "store_bytes_allocated": store.get("bytes_allocated", 0),
            "store_capacity": store.get("capacity", 0),
            "store_num_objects": store.get("num_objects", 0),
            "store_evictions": store.get("evictions", 0),
            "oom_kills": r.get("oom_kills", 0),
        })
    return nodes, objects


def list_objects(address: str | None = None) -> list[dict]:
    """Cluster-wide owner-side object tables (reference:
    `ray list objects`, python/ray/util/state/api.py:1). Covers every
    worker's owned objects via its nodelet, plus the calling driver's
    own table."""
    return _node_object_tables(address)[1]


def memory_summary(address: str | None = None) -> dict:
    """Per-node store usage + per-owner object footprint (reference:
    the `ray memory` report)."""
    nodes, objects = _node_object_tables(address)
    by_owner: dict[str, dict] = {}
    for o in objects:
        agg = by_owner.setdefault(o["owner"], {"count": 0, "bytes": 0,
                                               "spilled": 0, "borrowed": 0})
        agg["count"] += 1
        agg["bytes"] += o.get("size", 0) or 0
        agg["spilled"] += 1 if o.get("spilled") else 0
        agg["borrowed"] += o.get("borrowers", 0)
    return {
        "nodes": nodes,
        "objects_total": len(objects),
        "objects_bytes": sum((o.get("size") or 0) for o in objects),
        "by_owner": by_owner,
    }


def memory_report(address: str | None = None) -> str:
    """Human-readable `ray_tpu memory` view."""
    s = memory_summary(address)
    lines = ["=== object store per node ==="]
    for n in s["nodes"]:
        cap = n["store_capacity"] or 1
        lines.append(
            f"  {n['node_id'][:12]} {n['address']:<21} "
            f"{n['store_bytes_allocated'] / (1 << 20):8.1f}MB / "
            f"{cap / (1 << 20):7.1f}MB  objs={n['store_num_objects']:<6} "
            f"evictions={n['store_evictions']:<6} "
            f"oom_kills={n['oom_kills']}")
    lines.append(f"=== owned objects: {s['objects_total']} "
                 f"({s['objects_bytes'] / (1 << 20):.1f}MB) ===")
    for owner, agg in sorted(s["by_owner"].items(),
                             key=lambda kv: -kv[1]["bytes"]):
        lines.append(
            f"  {owner:<21} count={agg['count']:<6} "
            f"bytes={agg['bytes'] / (1 << 20):8.1f}MB "
            f"spilled={agg['spilled']:<4} borrowed={agg['borrowed']}")
    return "\n".join(lines)


def summarize(address: str | None = None) -> dict:
    nodes = list_nodes(address)
    actors = list_actors(address)
    total: dict[str, float] = {}
    avail: dict[str, float] = {}
    for n in nodes:
        if not n["alive"]:
            continue
        for r, q in n["resources"].items():
            total[r] = total.get(r, 0.0) + q
        for r, q in n["available"].items():
            avail[r] = avail.get(r, 0.0) + q
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
        "resources_total": total,
        "resources_available": avail,
    }


def serve_status(address: str | None = None) -> dict:
    """Serve apps + per-proxy request metrics (reference: `ray serve
    status` / the serve state surface). The serve control plane lives in
    actors, so this needs a runtime: with `address` given it connects to
    that head when no runtime exists, and refuses to silently answer
    from a DIFFERENT cluster than the one asked about."""
    import ray_tpu
    from ray_tpu import serve

    if address is not None:
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        else:
            from ray_tpu.core.api import _global_runtime

            current = getattr(_global_runtime(), "head_address", None)
            if current is not None and current != address:
                raise ValueError(
                    f"runtime is connected to {current!r}, not "
                    f"{address!r}; serve status reflects the connected "
                    "cluster")
    return serve.status()


def llm_status(app_name: str) -> list[dict]:
    """Per-replica LLM engine stats for a `serve.llm` app: queue depth,
    running lanes, cache utilization, preemptions, compiled-program
    count. One dict per replica (the handle routes to a single replica;
    this asks the controller for the full set). Probes ride the
    replicas' control concurrency group, so they answer even while
    every request lane is mid-stream."""
    import ray_tpu
    from ray_tpu.serve.api import _CONTROLLER_NAME

    ctrl = ray_tpu.get_actor(_CONTROLLER_NAME)
    r = ray_tpu.get(ctrl.get_replicas.remote(app_name), timeout=30)
    if not r["replicas"]:
        raise ValueError(f"no serve application named {app_name!r}")
    refs = [rep.handle_request.options(
        concurrency_group="control").remote("engine_stats", (), {})
        for rep in r["replicas"]]
    return ray_tpu.get(refs, timeout=30)
