"""State API — programmatic cluster introspection.

Reference parity: ray.util.state (python/ray/util/state/api.py —
list_actors/list_nodes/list_placement_groups; task events feed `ray list
tasks` in the reference; here per-process task events are exported via
ray_tpu.timeline())."""

from __future__ import annotations


def _head_call(method: str, msg: dict | None = None,
               address: str | None = None):
    from ray_tpu.core.rpc import RpcClient

    if address is None:
        from ray_tpu.core import api as _api

        rt = _api._runtime
        if rt is None or not hasattr(rt, "head_address"):
            raise RuntimeError("state API needs ray_tpu.init() or an "
                               "explicit head address")
        address = rt.head_address
    return RpcClient.shared().call(address, method, msg or {}, timeout=30)


def list_actors(address: str | None = None) -> list[dict]:
    return _head_call("list_actors", address=address)["actors"]


def list_nodes(address: str | None = None) -> list[dict]:
    view = _head_call("cluster_view", address=address)
    return [
        {
            "node_id": n["node_id"].hex(),
            "address": n["address"],
            "alive": n["alive"],
            "resources": n["resources"],
            "available": n["available"],
            "labels": n["labels"],
        }
        for n in view["nodes"]
    ]


def list_tasks(address: str | None = None, limit: int = 1000) -> list[dict]:
    """Executor-reported task events (reference: `ray list tasks` over
    GcsTaskManager task events)."""
    return _head_call("list_tasks", {"limit": limit},
                      address=address)["tasks"]


def _node_address(node_id: str, address: str | None) -> str:
    for n in list_nodes(address):
        if n["node_id"].startswith(node_id) and n["alive"]:
            return n["address"]
    raise ValueError(f"no live node matching {node_id!r}")


def list_logs(node_id: str, address: str | None = None) -> list[dict]:
    """Log files on a node (reference: `ray logs` / the dashboard log
    monitor, _private/log_monitor.py:103)."""
    from ray_tpu.core.rpc import RpcClient

    target = _node_address(node_id, address)
    return RpcClient.shared().call(target, "list_logs", {},
                                   timeout=30)["logs"]


def tail_log(node_id: str, file: str, nbytes: int = 64 * 1024,
             offset: int = -1, address: str | None = None):
    """Tail (or incrementally follow via `offset`) one log file on a
    node. Returns (text, end_offset)."""
    from ray_tpu.core.rpc import RpcClient

    target = _node_address(node_id, address)
    value, frames = RpcClient.shared().call_frames(
        target, "tail_log", {"file": file, "nbytes": nbytes,
                             "offset": offset}, timeout=30)
    if not value.get("ok"):
        raise FileNotFoundError(value.get("error", "log unavailable"))
    return frames[0].decode(errors="replace"), value["end_offset"]


def list_placement_groups(address: str | None = None) -> list[dict]:
    return _head_call("pg_table", address=address).get("groups", [])


def summarize(address: str | None = None) -> dict:
    nodes = list_nodes(address)
    actors = list_actors(address)
    total: dict[str, float] = {}
    avail: dict[str, float] = {}
    for n in nodes:
        if not n["alive"]:
            continue
        for r, q in n["resources"].items():
            total[r] = total.get(r, 0.0) + q
        for r, q in n["available"].items():
            avail[r] = avail.get(r, 0.0) + q
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
        "resources_total": total,
        "resources_available": avail,
    }
