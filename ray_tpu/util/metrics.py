"""Metrics: counters/gauges/histograms + Prometheus text exposition.

Reference parity: the user metrics API (python/ray/util/metrics.py:137-262
— Counter/Gauge/Histogram with tag_keys) over a per-process registry
(C++ reference: src/ray/stats/metric.h:103), exported in Prometheus text
format (reference: _private/prometheus_exporter.py). Core runtime
components register their own metrics into the same registry."""

from __future__ import annotations

import threading
from typing import Sequence


class Registry:
    """A metric namespace. The module-level default serves the process
    (the reference shape); components that can share one process in
    tests (in-process nodelets of cluster_utils.Cluster) own a PRIVATE
    instance so same-named gauges never alias across components and
    per-node attribution stays exact."""

    def __init__(self):
        self._metrics: dict[str, "Metric"] = {}
        self._lock = threading.Lock()

    def register(self, m: "Metric"):
        with self._lock:
            existing = self._metrics.get(m.name)
            if existing is not None:
                return existing
            self._metrics[m.name] = m
            return m

    def collect(self) -> list["Metric"]:
        with self._lock:
            return list(self._metrics.values())

    def clear(self):
        with self._lock:
            self._metrics.clear()


_registry = Registry()


def _fmt_tags(tags: dict | None) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
    return "{" + inner + "}"


class Metric:
    TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = (),
                 registry: "Registry | None" = None):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()
        registered = (registry or _registry).register(self)
        self._shared_from = registered if registered is not self else None
        if self._shared_from is not None:
            # same-name re-creation shares state (reference behavior);
            # subclasses adopt their extra stores in _adopt_shared
            self._values = registered._values
            self._lock = registered._lock

    def _key(self, tags: dict | None) -> tuple:
        tags = tags or {}
        return tuple(tags.get(k, "") for k in self.tag_keys)

    def _tags_of(self, key: tuple) -> dict:
        return dict(zip(self.tag_keys, key))

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} {self.TYPE}"]
        with self._lock:
            items = list(self._values.items())
        if not items:
            lines.append(f"{self.name} 0")
        for key, v in items:
            lines.append(f"{self.name}{_fmt_tags(self._tags_of(key))} {v}")
        return lines


class Counter(Metric):
    TYPE = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None):
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    TYPE = "gauge"

    def set(self, value: float, tags: dict | None = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def inc(self, value: float = 1.0, tags: dict | None = None):
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def dec(self, value: float = 1.0, tags: dict | None = None):
        self.inc(-value, tags)


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (),
                 tag_keys: Sequence[str] = (),
                 registry: "Registry | None" = None):
        self.boundaries = tuple(boundaries) or (
            0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)
        super().__init__(name, description, tag_keys, registry)
        shared = self._shared_from
        if shared is not None and isinstance(shared, Histogram):
            # observations must land in the registered instance's stores,
            # or re-created histograms silently drop data from /metrics
            self._counts = shared._counts
            self._sums = shared._sums
            self._totals = shared._totals
            self.boundaries = shared.boundaries
        else:
            self._counts: dict[tuple, list[int]] = {}
            self._sums: dict[tuple, float] = {}
            self._totals: dict[tuple, int] = {}

    def observe(self, value: float, tags: dict | None = None):
        k = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def sum_total(self) -> float:
        """Sum of all observed values across every tag combination —
        the cheap 'how much time went here so far' probe waterfall
        snapshots diff."""
        with self._lock:
            return sum(self._sums.values())

    def sums_by_tag(self, tag_key: str) -> dict[str, float]:
        """Observed-value sums grouped by one tag's values (other tags
        summed over) — what lets the step waterfall split a phase into
        per-op buckets by diffing snapshots. Unknown tag key: {}."""
        try:
            i = self.tag_keys.index(tag_key)
        except ValueError:
            return {}
        with self._lock:
            out: dict[str, float] = {}
            for k, s in self._sums.items():
                out[k[i]] = out.get(k[i], 0.0) + s
            return out

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.description}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            keys = list(self._counts)
            for k in keys:
                tags = self._tags_of(k)
                cum = 0
                for i, b in enumerate(self.boundaries):
                    cum += self._counts[k][i]
                    t = dict(tags, le=str(b))
                    lines.append(f"{self.name}_bucket{_fmt_tags(t)} {cum}")
                cum += self._counts[k][-1]
                t = dict(tags, le="+Inf")
                lines.append(f"{self.name}_bucket{_fmt_tags(t)} {cum}")
                lines.append(
                    f"{self.name}_sum{_fmt_tags(tags)} {self._sums[k]}")
                lines.append(
                    f"{self.name}_count{_fmt_tags(tags)} {self._totals[k]}")
        return lines


def prometheus_text(registry: "Registry | None" = None) -> str:
    """A registry's metrics in Prometheus exposition format (the
    process-default registry when none is given)."""
    lines: list[str] = []
    for m in (registry or _registry).collect():
        lines.extend(m.expose())
    return "\n".join(lines) + "\n"


def inject_labels(sample_line: str, tags: dict) -> str:
    """Add labels to one exposition SAMPLE line (`name 1` or
    `name{a="b"} 1`) — how the cluster aggregator stamps each scraped
    page with its origin (node=..., proc=...) without touching the
    producing process's registry. A key the series already carries is
    left alone (duplicate label names are invalid exposition format
    and would fail the whole scrape)."""
    if not tags:
        return sample_line
    if "{" in sample_line:
        import re as _re

        head, sep, value = sample_line.rpartition("} ")
        if not sep:
            return sample_line
        items = [(k, v) for k, v in sorted(tags.items())
                 # exact label-name match only: `node=` must not be
                 # shadowed by a series that carries `src_node=`
                 if not _re.search(rf'[{{,]{_re.escape(k)}="', head)]
        if not items:
            return sample_line
        extra = ",".join(f'{k}="{v}"' for k, v in items)
        return f"{head},{extra}}} {value}"
    name, sep, value = sample_line.partition(" ")
    if not sep:
        return sample_line
    extra = ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))
    return f"{name}{{{extra}}} {value}"


def merge_prometheus(pages: list[tuple[dict, str]]) -> str:
    """Merge scraped exposition pages into one, injecting each page's
    origin tags into its sample lines. Samples are GROUPED BY FAMILY
    with the HELP/TYPE header emitted once above all of them — standard
    Prometheus parsers require a family's samples contiguous under its
    header (interleaving families demotes them to untyped). Within a
    page, samples belong to the most recent header's family (the shape
    prometheus_text() and this function itself both emit, so merges
    compose). Series stay distinct because every page carries
    distinguishing tags (node/proc)."""
    order: list[str] = []
    headers: dict[str, list[str]] = {}
    samples: dict[str, list[str]] = {}

    def family(fam: str) -> str:
        if fam not in samples:
            order.append(fam)
            samples[fam] = []
            headers.setdefault(fam, [])
        return fam

    for tags, text in pages:
        current = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) >= 3:
                    current = family(parts[2])
                    directive = parts[1]  # HELP / TYPE, one each
                    if not any(h.split(None, 3)[1] == directive
                               for h in headers[current]):
                        headers[current].append(line)
                continue
            fam = current
            if fam is None:  # headerless sample: its own family
                fam = family(line.split("{", 1)[0].split(" ", 1)[0])
            samples[fam].append(inject_labels(line, tags))
    out: list[str] = []
    for fam in order:
        out.extend(headers.get(fam, ()))
        out.extend(samples[fam])
    return "\n".join(out) + "\n"


def scrape_pages(client, targets: list[tuple[str, str]], method: str,
                 timeout_s: float, tag_key: str) -> list[tuple[dict, str]]:
    """Concurrently scrape `method` (a handler returning {"text": ...})
    from (tag_value, address) targets under ONE shared deadline — a
    slow or dead target costs the whole scrape at most `timeout_s`, not
    timeout_s apiece (RpcClient.call_gather also reclaims timed-out
    reply slots, so repeated scrapes of a hung peer cannot leak).
    Shared by the head's node fan-out and the nodelet's worker
    fan-out."""
    results = client.call_gather(
        [(addr, method, {}) for _, addr in targets], timeout=timeout_s)
    pages: list[tuple[dict, str]] = []
    for (tag, _), r in zip(targets, results):
        if r is not None:  # dead/slow target: the rest of the page stands
            pages.append(({tag_key: tag}, r["text"]))
    return pages


def clear_registry():
    _registry.clear()


def serve_metrics_http(port: int = 0, text_fn=None) -> int:
    """Expose /metrics over HTTP (reference: metrics agent endpoint).
    `text_fn` overrides the page source — the head passes its
    cluster-wide aggregation so one port serves every node's metrics.
    Returns the bound port."""
    import http.server
    import threading as _t

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = (text_fn or prometheus_text)().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    _t.Thread(target=server.serve_forever, daemon=True,
              name="metrics-http").start()
    return server.server_address[1]
