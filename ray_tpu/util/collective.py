"""Out-of-band collectives between named groups of tasks/actors.

Reference parity: python/ray/util/collective/collective.py
(GroupManager :40, init_collective_group :120, allreduce :258,
allgather :423, reducescatter :472, send/recv :531,594, broadcast,
barrier) with NCCL/Gloo backends.

TPU-first split (SURVEY.md §2.5): tensors that live on device inside an
SPMD program use in-program XLA collectives (ray_tpu.parallel.ops —
psum/all_gather/ppermute over mesh axes; zero extra machinery, rides
ICI). THIS module is the host-side path the reference's Gloo backend
covers: numpy arrays held by N separate actor/task processes. It runs
over a rendezvous actor (per group) through the object store — correct
everywhere, used for metadata barriers, weight broadcast, and CPU
reductions, not for the training hot loop (which is in-program).
"""

from __future__ import annotations

import threading
import time
from typing import Any

import numpy as np


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


_REDUCERS = {
    ReduceOp.SUM: lambda arrs: _tree_binop(arrs, np.add),
    ReduceOp.PRODUCT: lambda arrs: _tree_binop(arrs, np.multiply),
    ReduceOp.MIN: lambda arrs: _tree_binop(arrs, np.minimum),
    ReduceOp.MAX: lambda arrs: _tree_binop(arrs, np.maximum),
    ReduceOp.MEAN: lambda arrs: _tree_scale(_tree_binop(arrs, np.add),
                                            1.0 / len(arrs)),
}


def _tree_binop(arrs, op):
    out = arrs[0]
    for a in arrs[1:]:
        out = _map2(out, a, op)
    return out


def _map2(a, b, op):
    if isinstance(a, dict):
        return {k: _map2(a[k], b[k], op) for k in a}
    if isinstance(a, (list, tuple)):
        t = [_map2(x, y, op) for x, y in zip(a, b)]
        return type(a)(t) if not isinstance(a, tuple) else tuple(t)
    return op(a, b)


def _tree_scale(a, s):
    if isinstance(a, dict):
        return {k: _tree_scale(v, s) for k, v in a.items()}
    if isinstance(a, (list, tuple)):
        t = [_tree_scale(x, s) for x in a]
        return tuple(t) if isinstance(a, tuple) else t
    return a * s


class _Rendezvous:
    """Coordinator actor for one collective group. All ops are keyed by a
    per-member monotonically increasing sequence number, so members may
    pipeline ops without cross-talk."""

    def __init__(self, world_size: int):
        self.world = world_size
        self._lock = threading.Lock()
        self._rounds: dict[tuple, dict] = {}  # (kind, seq) -> state
        self._mail: dict[tuple, Any] = {}  # (src, dst, seq) -> payload

    def _round(self, key):
        with self._lock:
            r = self._rounds.get(key)
            if r is None:
                r = self._rounds[key] = {"data": {}, "event": threading.Event(),
                                         "result": None, "done": 0}
            return r

    def _finish(self, key, r):
        # last reader cleans up
        with self._lock:
            r["done"] += 1
            if r["done"] >= self.world:
                self._rounds.pop(key, None)

    def contribute(self, kind: str, seq: int, rank: int, data, op: str | None,
                   root: int | None = None):
        key = (kind, seq)
        r = self._round(key)
        with self._lock:
            r["data"][rank] = data
            complete = len(r["data"]) == self.world
            if complete and r["result"] is None:
                ordered = [r["data"][i] for i in range(self.world)]
                if kind == "allreduce":
                    r["result"] = _REDUCERS[op](ordered)
                elif kind == "allgather":
                    r["result"] = ordered
                elif kind == "broadcast":
                    r["result"] = r["data"][root]
                elif kind == "barrier":
                    r["result"] = True
                elif kind == "reducescatter":
                    reduced = _REDUCERS[op](ordered)
                    r["result"] = reduced
                r["event"].set()
        if not r["event"].wait(timeout=120):
            raise TimeoutError(f"collective {kind}#{seq} timed out "
                               f"({len(r['data'])}/{self.world} arrived)")
        result = r["result"]
        self._finish(key, r)
        return result

    def send(self, src: int, dst: int, seq: int, payload):
        with self._lock:
            self._mail[(src, dst, seq)] = payload
        return True

    def recv(self, src: int, dst: int, seq: int, timeout: float = 120):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if (src, dst, seq) in self._mail:
                    return self._mail.pop((src, dst, seq))
            time.sleep(0.002)
        raise TimeoutError(f"recv from {src} (seq {seq}) timed out")


class _GroupState:
    def __init__(self, name, world_size, rank, coordinator):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.seq = 0
        self.pt_seq = {}

    def next_seq(self) -> int:
        s = self.seq
        self.seq += 1
        return s


_groups: dict[str, _GroupState] = {}
_groups_lock = threading.Lock()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "auto",
                          group_name: str = "default"):
    """Join (and lazily create) the named group. Every member must call
    this before using collectives (reference: collective.py:120)."""
    import ray_tpu

    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside [0, {world_size})")
    coord_cls = ray_tpu.remote(num_cpus=0)(_Rendezvous)
    coord = coord_cls.options(
        name=f"__collective_{group_name}", get_if_exists=True,
        max_concurrency=max(4, 2 * world_size)).remote(world_size)
    with _groups_lock:
        _groups[group_name] = _GroupState(group_name, world_size, rank, coord)
    barrier(group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default"):
    with _groups_lock:
        _groups.pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    return _get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get(group_name).world_size


def _get(group_name) -> _GroupState:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this process")
    return g


def _sync(g: _GroupState, kind, data, op=None, root=None):
    import ray_tpu

    seq = g.next_seq()
    return ray_tpu.get(
        g.coordinator.contribute.remote(kind, seq, g.rank, data, op, root),
        timeout=180)


def allreduce(tensor, group_name: str = "default", op: str = ReduceOp.SUM):
    return _sync(_get(group_name), "allreduce", tensor, op=op)


def allreduce_multigpu(tensor_list, group_name="default", op=ReduceOp.SUM):
    return [allreduce(t, group_name, op) for t in tensor_list]


def allgather(tensor, group_name: str = "default") -> list:
    return _sync(_get(group_name), "allgather", tensor)


def reducescatter(tensor, group_name: str = "default", op: str = ReduceOp.SUM):
    """Each rank gets its 1/world shard (along axis 0) of the reduction."""
    g = _get(group_name)
    reduced = _sync(g, "reducescatter", tensor, op=op)
    return np.array_split(reduced, g.world_size, axis=0)[g.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _get(group_name)
    return _sync(g, "broadcast", tensor if g.rank == src_rank else None,
                 root=src_rank)


def barrier(group_name: str = "default"):
    _sync(_get(group_name), "barrier", None)


def send(tensor, dst_rank: int, group_name: str = "default"):
    import ray_tpu

    g = _get(group_name)
    key = (g.rank, dst_rank)
    seq = g.pt_seq.get(key, 0)
    g.pt_seq[key] = seq + 1
    ray_tpu.get(g.coordinator.send.remote(g.rank, dst_rank, seq, tensor))


def recv(src_rank: int, group_name: str = "default"):
    import ray_tpu

    g = _get(group_name)
    key = (src_rank, g.rank)
    seq = g.pt_seq.get(key, 0)
    g.pt_seq[key] = seq + 1
    return ray_tpu.get(g.coordinator.recv.remote(src_rank, g.rank, seq))
