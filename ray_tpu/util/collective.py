"""Out-of-band collectives between named groups of tasks/actors.

Reference parity: python/ray/util/collective/collective.py
(GroupManager :40, init_collective_group :120, allreduce :258,
allgather :423, reducescatter :472, send/recv :531,594, broadcast,
barrier) with NCCL/Gloo backends.

TPU-first split (SURVEY.md §2.5): tensors that live on device inside an
SPMD program use in-program XLA collectives (ray_tpu.parallel.ops —
psum/all_gather/ppermute over mesh axes; zero extra machinery, rides
ICI). THIS module is the host-side path the reference's Gloo backend
covers: numpy arrays held by N separate actor/task processes. It runs
over a rendezvous actor (per group) through the object store — correct
everywhere, used for metadata barriers, weight broadcast, and CPU
reductions, not for the training hot loop (which is in-program).

Design notes (round-2 rewrite):
- Group state is keyed by the *calling execution context* (actor id or
  task id), not just the process: in local mode every member shares one
  process, and per-process state made members overwrite each other's
  rank (the round-1 hang).
- The rendezvous protocol is two-phase and non-blocking on the actor:
  `offer` records a contribution and returns immediately; members then
  `poll` until the round's result is ready. No actor threads are ever
  parked waiting on other members, so progress never depends on the
  coordinator's max_concurrency.
- `offer` is idempotent per (kind, seq, rank): at-least-once RPC
  delivery (submitter retries) cannot corrupt a round.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

import numpy as np


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


_REDUCERS = {
    ReduceOp.SUM: lambda arrs: _tree_binop(arrs, np.add),
    ReduceOp.PRODUCT: lambda arrs: _tree_binop(arrs, np.multiply),
    ReduceOp.MIN: lambda arrs: _tree_binop(arrs, np.minimum),
    ReduceOp.MAX: lambda arrs: _tree_binop(arrs, np.maximum),
    ReduceOp.MEAN: lambda arrs: _tree_scale(_tree_binop(arrs, np.add),
                                            1.0 / len(arrs)),
}


def _tree_binop(arrs, op):
    out = arrs[0]
    for a in arrs[1:]:
        out = _map2(out, a, op)
    return out


def _map2(a, b, op):
    if isinstance(a, dict):
        return {k: _map2(a[k], b[k], op) for k in a}
    if isinstance(a, (list, tuple)):
        t = [_map2(x, y, op) for x, y in zip(a, b)]
        return type(a)(t) if not isinstance(a, tuple) else tuple(t)
    return op(a, b)


def _tree_scale(a, s):
    if isinstance(a, dict):
        return {k: _tree_scale(v, s) for k, v in a.items()}
    if isinstance(a, (list, tuple)):
        t = [_tree_scale(x, s) for x in a]
        return tuple(t) if isinstance(a, tuple) else t
    return a * s


class _Rendezvous:
    """Coordinator actor for one collective group. Rounds are keyed by
    (kind, seq); members pipeline ops freely because every member keeps
    its own monotonically increasing seq."""

    def __init__(self, world_size: int):
        self.world = world_size
        self._lock = threading.Lock()
        self._rounds: dict[tuple, dict] = {}  # (kind, seq) -> round state
        self._done: deque[tuple] = deque(maxlen=1024)  # completed round keys
        self._done_set: set[tuple] = set()
        self._mail: dict[tuple, Any] = {}  # (src, dst, seq) -> payload

    def offer(self, kind: str, seq: int, rank: int, data, op: str | None,
              root: int | None = None) -> bool:
        """Record `rank`'s contribution to round (kind, seq). Returns
        immediately; never blocks on other members."""
        key = (kind, seq)
        with self._lock:
            if key in self._done_set:
                return True  # duplicate delivery of a finished round
            r = self._rounds.get(key)
            if r is None:
                r = self._rounds[key] = {"data": {}, "result": None,
                                         "ready": False, "fetched": 0}
            if rank in r["data"]:
                return True  # duplicate contribution (RPC retry)
            r["data"][rank] = data
            if len(r["data"]) == self.world and not r["ready"]:
                ordered = [r["data"][i] for i in range(self.world)]
                if kind == "allreduce":
                    r["result"] = _REDUCERS[op](ordered)
                elif kind == "allgather":
                    r["result"] = ordered
                elif kind == "broadcast":
                    r["result"] = r["data"][root]
                elif kind == "barrier":
                    r["result"] = True
                elif kind == "reducescatter":
                    r["result"] = _REDUCERS[op](ordered)
                r["ready"] = True
        return True

    def poll(self, kind: str, seq: int):
        """(ready, result). Once every member has fetched, the round is
        retired into the done-set so retried offers stay idempotent."""
        key = (kind, seq)
        with self._lock:
            r = self._rounds.get(key)
            if r is None:
                # either unknown or already retired: treat retired rounds
                # as an error (a member polled twice) — callers poll once.
                return (False, None)
            if not r["ready"]:
                return (False, None)
            result = r["result"]
            r["fetched"] += 1
            if r["fetched"] >= self.world:
                self._rounds.pop(key, None)
                self._done.append(key)
                self._done_set.add(key)
                while len(self._done) >= self._done.maxlen:
                    old = self._done.popleft()
                    self._done_set.discard(old)
            return (True, result)

    def progress(self, kind: str, seq: int) -> int:
        with self._lock:
            r = self._rounds.get((kind, seq))
            return len(r["data"]) if r else -1

    def send(self, src: int, dst: int, seq: int, payload):
        with self._lock:
            self._mail[(src, dst, seq)] = payload
        return True

    def try_recv(self, src: int, dst: int, seq: int):
        with self._lock:
            if (src, dst, seq) in self._mail:
                return (True, self._mail.pop((src, dst, seq)))
        return (False, None)


class _GroupState:
    def __init__(self, name, world_size, rank, coordinator):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.seq = 0
        self.pt_seq = {}

    def next_seq(self) -> int:
        s = self.seq
        self.seq += 1
        return s


# Keyed by (context key, group name). The context key distinguishes
# members that share one OS process (local mode, threaded actors).
_groups: dict[tuple, _GroupState] = {}
_groups_lock = threading.Lock()


def _ctx_key() -> str:
    import ray_tpu

    try:
        ctx = ray_tpu.get_runtime_context()
    except Exception:
        return "driver"
    if ctx.actor_id is not None:
        return f"a:{ctx.actor_id.hex()}"
    if ctx.task_id is not None:
        return f"t:{ctx.task_id.hex()}"
    return "driver"


def init_collective_group(world_size: int, rank: int,
                          backend: str = "auto",
                          group_name: str = "default"):
    """Join (and lazily create) the named group. Every member must call
    this before using collectives (reference: collective.py:120)."""
    import ray_tpu

    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside [0, {world_size})")
    coord_cls = ray_tpu.remote(num_cpus=0)(_Rendezvous)
    coord = coord_cls.options(
        name=f"__collective_{group_name}", get_if_exists=True,
        max_concurrency=max(4, world_size)).remote(world_size)
    with _groups_lock:
        _groups[(_ctx_key(), group_name)] = _GroupState(
            group_name, world_size, rank, coord)
    barrier(group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    return (_ctx_key(), group_name) in _groups


def destroy_collective_group(group_name: str = "default"):
    with _groups_lock:
        _groups.pop((_ctx_key(), group_name), None)


def get_rank(group_name: str = "default") -> int:
    return _get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get(group_name).world_size


def _get(group_name) -> _GroupState:
    g = _groups.get((_ctx_key(), group_name))
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"task/actor context")
    return g


_coll_hist = None

# Round kinds -> canonical `op=` label values on collective_seconds.
# The canonical names are shared with the in-program collective
# attribution (parallel/ops.collective_op_counts and the step
# waterfall's collective.<op> buckets), so host-side and in-program
# views of "where did collective time go" use one vocabulary.
_OP_LABELS = {"allgather": "all_gather", "reducescatter": "reduce_scatter"}


def _collective_seconds():
    global _coll_hist
    if _coll_hist is None:
        from ray_tpu.util.metrics import Histogram

        _coll_hist = Histogram(
            "collective_seconds",
            "Host-side collective wall time (offer -> result ready)",
            boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30),
            tag_keys=("op",))
    return _coll_hist


def _sync(g: _GroupState, kind, data, op=None, root=None,
          timeout: float = 120.0):
    import ray_tpu

    t0 = time.perf_counter()
    seq = g.next_seq()
    ray_tpu.get(g.coordinator.offer.remote(kind, seq, g.rank, data, op, root),
                timeout=60)
    deadline = time.monotonic() + timeout
    sleep = 0.001
    while time.monotonic() < deadline:
        ready, result = ray_tpu.get(g.coordinator.poll.remote(kind, seq),
                                    timeout=60)
        if ready:
            dt = time.perf_counter() - t0
            _collective_seconds().observe(
                dt, tags={"op": _OP_LABELS.get(kind, kind)})
            from ray_tpu.util import tracing

            tracing.record_span(f"collective.{kind}", dt,
                                category="collective")
            return result
        time.sleep(sleep)
        sleep = min(sleep * 2, 0.05)
    arrived = ray_tpu.get(g.coordinator.progress.remote(kind, seq), timeout=60)
    raise TimeoutError(f"collective {kind}#{seq} timed out "
                       f"({arrived}/{g.world_size} arrived)")


def allreduce(tensor, group_name: str = "default", op: str = ReduceOp.SUM):
    return _sync(_get(group_name), "allreduce", tensor, op=op)


def allreduce_multigpu(tensor_list, group_name="default", op=ReduceOp.SUM):
    return [allreduce(t, group_name, op) for t in tensor_list]


def allgather(tensor, group_name: str = "default") -> list:
    return _sync(_get(group_name), "allgather", tensor)


def reducescatter(tensor, group_name: str = "default", op: str = ReduceOp.SUM):
    """Each rank gets its 1/world shard (along axis 0) of the reduction.

    Note: the reduction rides through the coordinator whole (allreduce
    cost); this path is for metadata/CPU tensors — in-program XLA
    reduce_scatter (parallel/ops.py) is the device path."""
    g = _get(group_name)
    reduced = _sync(g, "reducescatter", tensor, op=op)
    return np.array_split(reduced, g.world_size, axis=0)[g.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _get(group_name)
    return _sync(g, "broadcast", tensor if g.rank == src_rank else None,
                 root=src_rank)


def barrier(group_name: str = "default"):
    _sync(_get(group_name), "barrier", None)


def send(tensor, dst_rank: int, group_name: str = "default"):
    import ray_tpu

    g = _get(group_name)
    key = (g.rank, dst_rank)
    seq = g.pt_seq.get(key, 0)
    g.pt_seq[key] = seq + 1
    ray_tpu.get(g.coordinator.send.remote(g.rank, dst_rank, seq, tensor))


def recv(src_rank: int, group_name: str = "default", timeout: float = 120.0):
    import ray_tpu

    g = _get(group_name)
    key = (src_rank, g.rank)
    seq = g.pt_seq.get(key, 0)
    g.pt_seq[key] = seq + 1
    deadline = time.monotonic() + timeout
    sleep = 0.001
    while time.monotonic() < deadline:
        ok, payload = ray_tpu.get(
            g.coordinator.try_recv.remote(src_rank, g.rank, seq), timeout=60)
        if ok:
            return payload
        time.sleep(sleep)
        sleep = min(sleep * 2, 0.05)
    raise TimeoutError(f"recv from {src_rank} (seq {seq}) timed out")
