"""Profiler plane — in-process sampling profiler + collapsed-stack
plumbing.

Reference parity: Ray ships cluster profiling as first-class state-API
tooling (`ray stack` / per-worker py-spy capture in the dashboard,
`ray memory` for object attribution). The two biggest recent perf wins
here (the ~100us `os.urandom` submit tax, the traceback-pinned
stranded-ObjectRef leak) were found by *ad-hoc* profiling; this module
mechanizes that: every process can answer "where are your threads right
now, statistically" on demand.

Design:

- **Dormant by default.** No thread exists until a capture window is
  armed; an unarmed process pays literally nothing. A `StackSampler`
  *is* one capture window: construct, `start()`, work, `stop()`,
  `collapsed()`. The sampling thread walks `sys._current_frames()` at
  `hz`, excluding itself, and aggregates root-first `;`-joined stacks
  into a bounded dict of collapsed-stack counts — samples landing past
  the unique-stack cap are dropped AND counted (`stacks_dropped`),
  never silently lost. The sampler records its own CPU cost
  (`cpu_seconds`, via `time.thread_time`) so the <2% overhead contract
  is a measured number, not a hope.
- **Wall-clock sampling.** Every thread is sampled, including parked
  ones — "32 handler threads in `queue.get`" is exactly the signal an
  operator wants when asking why a node is idle. CPU-only attribution
  is the separate per-task `time.thread_time` accounting in the worker
  exec loop (`core_task_cpu_seconds_total{kind}` +
  `util.state.cpu_attribution()`).
- **Collapsed format.** `stack count` lines (`collapsed_text`) are
  directly consumable by flamegraph.pl / speedscope / inferno. Cluster
  merges prefix each page with `node:<id>`/`proc:<id>` pseudo-frames
  (`prefix_stacks` + `merge_collapsed`), so one flamegraph splits by
  node, then process, then code.
- **Capture windows are cheap but not free** (a sample costs one GIL
  grab + a frame walk), so captures are explicitly armed per window —
  by the `profile_capture` RPC fan-out (`util.state.profile`), a bench
  driver's `--profile` flag (`capture_to_file`), or the debug-dump
  flight recorder — and bounded by `MAX_CAPTURE_S`.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time

DEFAULT_HZ = 25.0
MAX_CAPTURE_S = 60.0
MAX_UNIQUE_STACKS = 2000
MAX_DEPTH = 48


def _frame_label(frame) -> str:
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class StackSampler:
    """One capture window over this process's threads.

    Not reusable: arm with `start()`, end with `stop()`, read
    `collapsed()`/`samples`/`stacks_dropped`/`cpu_seconds`. Dormant
    processes hold no instance at all — the daemon thread exists only
    between start() and stop()."""

    def __init__(self, hz: float | None = None,
                 max_unique_stacks: int | None = None,
                 max_depth: int = MAX_DEPTH):
        self.hz = float(hz) if hz else DEFAULT_HZ
        self.max_unique_stacks = int(max_unique_stacks or
                                     MAX_UNIQUE_STACKS)
        self.max_depth = max_depth
        self._stacks: dict[str, int] = {}  # guarded_by(_lock)
        self._lock = threading.Lock()
        self.samples = 0  # sample ticks taken (all threads each tick)
        self.stacks_dropped = 0  # thread-samples rejected by the cap
        self.cpu_seconds = 0.0  # the sampler thread's own CPU cost
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "StackSampler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="stack-sampler")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        period = 1.0 / self.hz
        me = threading.get_ident()
        cpu0 = time.thread_time()
        next_t = time.monotonic()
        while not self._stop.is_set():
            # one GIL-holding pass: snapshot every thread's top frame,
            # walk to the roots OUTSIDE any locks of ours
            frames = sys._current_frames()
            tick: list[str] = []
            for tid, frame in frames.items():
                if tid == me:
                    continue
                parts = []
                f = frame
                while f is not None and len(parts) < self.max_depth:
                    parts.append(_frame_label(f))
                    f = f.f_back
                parts.reverse()  # root first — the collapsed convention
                tick.append(";".join(parts))
            del frames
            with self._lock:
                self.samples += 1
                for s in tick:
                    cur = self._stacks.get(s)
                    if cur is not None:
                        self._stacks[s] = cur + 1
                    elif len(self._stacks) < self.max_unique_stacks:
                        self._stacks[s] = 1
                    else:
                        self.stacks_dropped += 1
            # drift-corrected tick; when sampling falls behind (GIL
            # contention), re-anchor instead of bursting to catch up
            next_t += period
            delay = next_t - time.monotonic()
            if delay <= 0:
                next_t = time.monotonic()
            elif self._stop.wait(delay):
                break
        self.cpu_seconds = time.thread_time() - cpu0

    def collapsed(self) -> dict[str, int]:
        """{root-first `;`-joined stack: sample count}."""
        with self._lock:
            return dict(self._stacks)


def _note_capture(sampler: StackSampler) -> None:
    """Account a finished capture window in the process metrics page."""
    try:
        from ray_tpu.util.metrics import Counter

        Counter("profile_captures_total",
                "Sampling-profiler capture windows completed").inc()
        Counter("profile_samples_total",
                "Stack sample ticks taken across capture windows"
                ).inc(sampler.samples)
        if sampler.stacks_dropped:
            Counter("profile_stacks_dropped_total",
                    "Thread-samples rejected by the unique-stack cap"
                    ).inc(sampler.stacks_dropped)
    except Exception:  # noqa: BLE001
        pass  # metrics are a rider, never a capture failure


def capture_collapsed(duration_s: float, hz: float | None = None,
                      max_unique_stacks: int | None = None) -> dict:
    """Blocking capture of THIS process: arm a sampler, sleep the
    window, return ``{"stacks", "samples", "dropped", "hz",
    "duration_s"}``. The unit every `profile_capture` RPC handler
    serves — the handler thread sleeping IS the capture window."""
    duration_s = max(0.05, min(float(duration_s), MAX_CAPTURE_S))
    s = StackSampler(hz=hz, max_unique_stacks=max_unique_stacks).start()
    try:
        time.sleep(duration_s)
    finally:
        s.stop()
    _note_capture(s)
    return {"stacks": s.collapsed(), "samples": s.samples,
            "dropped": s.stacks_dropped, "hz": s.hz,
            "duration_s": duration_s}


@contextlib.contextmanager
def accumulate(stacks: dict | None, hz: float | None = None):
    """Arm a sampler around the enclosed block and merge its collapsed
    stacks into `stacks` IN PLACE — the bench drivers' measured-window
    hook (arm per window, accumulate across windows, write once at the
    end). ``stacks=None`` is a genuinely free no-op: nothing is
    constructed."""
    if stacks is None:
        yield None
        return
    s = StackSampler(hz=hz).start()
    try:
        yield s
    finally:
        s.stop()
        _note_capture(s)
        for k, n in s.collapsed().items():
            stacks[k] = stacks.get(k, 0) + n


@contextlib.contextmanager
def capture_to_file(path: str | None, hz: float | None = None):
    """Arm a sampler around the enclosed block and write the collapsed
    output to `path` (the bench drivers' `--profile` shape). A falsy
    path is a genuinely free no-op — nothing is constructed, matching
    the step-waterfall one-bool discipline."""
    if not path:
        yield None
        return
    s = StackSampler(hz=hz).start()
    try:
        yield s
    finally:
        s.stop()
        _note_capture(s)
        write_collapsed(path, s.collapsed())


# ------------------------------------------------------------------ merging

def prefix_stacks(stacks: dict[str, int], prefix: str) -> dict[str, int]:
    """Prepend origin pseudo-frames (``node:<id>`` / ``proc:<id>``) so
    merged flamegraphs split by origin before code."""
    return {f"{prefix};{s}": n for s, n in stacks.items()}


def merge_collapsed(pages: list[dict]) -> dict[str, int]:
    """Sum collapsed-stack pages; identical stacks accumulate."""
    out: dict[str, int] = {}
    for page in pages:
        for s, n in page.items():
            out[s] = out.get(s, 0) + n
    return out


def collapsed_text(stacks: dict[str, int]) -> str:
    """Flamegraph-compatible `.collapsed` text: one ``stack count``
    line per unique stack, heaviest first (deterministic: ties break
    on the stack string)."""
    lines = [f"{s} {n}" for s, n in
             sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def write_collapsed(path: str, stacks: dict[str, int]) -> str:
    with open(path, "w") as f:
        f.write(collapsed_text(stacks))
    return path


def collapsed_to_chrome(stacks: dict[str, int], hz: float,
                        filename: str | None = None):
    """Convert merged collapsed stacks to a chrome trace laid out on
    a synthetic timeline: pid = node pseudo-frame, tid = proc
    pseudo-frame, one ``X`` event per unique stack whose duration is
    its sampled share (count / hz), laid heaviest-first per track.
    Not a real time axis — a flamegraph-by-area view that opens in the
    same chrome://tracing / perfetto page as the merged timeline."""
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    meta: list[dict] = []
    events: list[dict] = []
    cursor: dict[tuple, float] = {}
    per_sample_us = 1e6 / max(hz, 1e-9)
    for stack, count in sorted(stacks.items(),
                               key=lambda kv: (-kv[1], kv[0])):
        frames = stack.split(";")
        node = "local"
        proc = "main"
        while frames and (frames[0].startswith("node:")
                          or frames[0].startswith("proc:")):
            tag = frames.pop(0)
            if tag.startswith("node:"):
                node = tag[5:]
            else:
                proc = tag[5:]
        pid = pids.get(node)
        if pid is None:
            pid = pids[node] = len(pids) + 1
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "args": {"name": f"node:{node[:16]}"}})
        tkey = (pid, proc)
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = len(tids) + 1
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": proc[:16]}})
        ts = cursor.get(tkey, 0.0)
        dur = count * per_sample_us
        cursor[tkey] = ts + dur
        events.append({
            "name": frames[-1] if frames else "(empty)",
            "cat": "profile", "ph": "X", "ts": ts, "dur": dur,
            "pid": pid, "tid": tid,
            "args": {"stack": ";".join(frames), "samples": count}})
    out = meta + events
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(out, f)
        return filename
    return out
