"""The metric catalog — the single machine-readable registry of every
metric this codebase can emit.

Three consumers keep each other honest through it (the CI drift gate in
tests/test_observability4.py):

- the SOURCE: every ``Counter/Gauge/Histogram("name", ...)`` literal in
  the package (extracted by `source_metrics()`, an AST scan) must have
  a catalog entry, and vice versa;
- the DOCS: every catalog name must appear in OBSERVABILITY.md's
  catalog table, and every metric named there must exist here;
- the DASHBOARD: ``python -m ray_tpu.devtools.grafana`` generates
  dashboards/ray_tpu.json from this catalog (one panel per metric,
  typed expressions), and the committed JSON must match a regeneration.

Adding a metric therefore means: construct it, add its row here, add
its OBSERVABILITY.md row, regenerate the dashboard. Forgetting any of
the four fails the gate.
"""

from __future__ import annotations

import ast
import os

# (name, type, where, what) — grouped/ordered like OBSERVABILITY.md
CATALOG: list[dict] = [
    # train
    {"name": "train_step_seconds", "type": "histogram",
     "where": "ray_tpu/train/spmd.py",
     "what": "host-side train-step dispatch time"},
    {"name": "train_compile_misses_total", "type": "counter",
     "where": "ray_tpu/train/spmd.py",
     "what": "train steps that triggered an XLA compile"},
    {"name": "train_compile_seconds", "type": "histogram",
     "where": "ray_tpu/train/spmd.py",
     "what": "XLA compile time for the train step"},
    {"name": "train_step_phase_seconds", "type": "histogram",
     "where": "ray_tpu/train/spmd.py",
     "what": "per-step waterfall phases incl. collective.<op> buckets "
             "(attribution runs only)"},
    {"name": "train_optimizer_state_bytes", "type": "gauge",
     "where": "ray_tpu/train/spmd.py",
     "what": "per-chip optimizer-state bytes, by layout "
             "(replicated|zero1) — the ZeRO-1 memory win"},
    {"name": "train_grad_state_bytes", "type": "gauge",
     "where": "ray_tpu/train/spmd.py",
     "what": "per-chip resident grad-accum bytes, by layout "
             "(replicated|zero2) — the ZeRO-2 memory win"},
    {"name": "train_param_state_bytes", "type": "gauge",
     "where": "ray_tpu/train/spmd.py",
     "what": "per-chip resident param bytes, by layout "
             "(replicated|zero3) — the ZeRO-3 memory win"},
    {"name": "train_zero_gather_share", "type": "gauge",
     "where": "ray_tpu/train/spmd.py",
     "what": "all-gather share of train step time at zero_stage >= 3 "
             "(attribution runs) — the JIT param-gather cost"},
    {"name": "train_pipeline_bubble_ratio", "type": "gauge",
     "where": "ray_tpu/train/pipeline_strategy.py",
     "what": "measured 1F1B bubble fraction of the last pipeline step"},
    {"name": "train_pipeline_virtual_stages", "type": "gauge",
     "where": "ray_tpu/train/pipeline_strategy.py",
     "what": "virtual stages (stages x repeats) of the running "
             "pipeline — > stages means interleaved 1F1B is active"},
    {"name": "train_microbatches_total", "type": "counter",
     "where": "ray_tpu/train/pipeline_strategy.py",
     "what": "microbatches executed by the pipeline train strategy"},
    # collectives
    {"name": "collective_seconds", "type": "histogram",
     "where": "ray_tpu/util/collective.py",
     "what": "host-side collective wall time (offer -> ready)"},
    # object plane
    {"name": "object_store_pull_bytes_total", "type": "counter",
     "where": "ray_tpu/core/nodelet.py",
     "what": "inbound node-to-node object transfer bytes"},
    {"name": "object_store_pull_seconds", "type": "histogram",
     "where": "ray_tpu/core/nodelet.py",
     "what": "inbound node-to-node object transfer latency"},
    {"name": "object_store_push_bytes_total", "type": "counter",
     "where": "ray_tpu/core/nodelet.py",
     "what": "bytes served to other nodes"},
    {"name": "object_store_bytes_allocated", "type": "gauge",
     "where": "ray_tpu/core/nodelet.py",
     "what": "store occupancy in bytes (refreshed at scrape)"},
    {"name": "object_store_num_objects", "type": "gauge",
     "where": "ray_tpu/core/nodelet.py", "what": "objects resident"},
    {"name": "object_store_evictions", "type": "gauge",
     "where": "ray_tpu/core/nodelet.py", "what": "objects evicted"},
    {"name": "object_store_created_objects_total", "type": "counter",
     "where": "ray_tpu/core/object_store.py",
     "what": "per-process store writes (count)"},
    {"name": "object_store_created_bytes_total", "type": "counter",
     "where": "ray_tpu/core/object_store.py",
     "what": "per-process store writes (bytes)"},
    {"name": "object_store_stranded_bytes", "type": "gauge",
     "where": "ray_tpu/core/cluster_runtime.py",
     "what": "bytes held by owned refs past the stranded-age threshold "
             "with no consumer progress (refreshed at scrape)"},
    # serve.llm engine
    {"name": "serve_llm_tokens_generated_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py", "what": "tokens generated"},
    {"name": "serve_llm_requests_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "requests finished, by outcome"},
    {"name": "serve_llm_preemptions_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "sequences preempted on cache exhaustion"},
    {"name": "serve_llm_queue_depth", "type": "gauge",
     "where": "ray_tpu/serve/llm/engine.py", "what": "waiting requests"},
    {"name": "serve_llm_running", "type": "gauge",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "sequences in the decode set"},
    {"name": "serve_llm_cache_utilization", "type": "gauge",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "KV pool pages in use / usable"},
    {"name": "serve_llm_tokens_per_sec", "type": "gauge",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "generation throughput (~5s window)"},
    {"name": "serve_llm_ttft_ms", "type": "histogram",
     "where": "ray_tpu/serve/llm/engine.py", "what": "time to first token"},
    {"name": "serve_llm_step_ms", "type": "histogram",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "engine step latency, by kind"},
    {"name": "serve_llm_prefix_cache_hits_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "KV pages served from the prefix cache at admission"},
    {"name": "serve_llm_prefix_cache_misses_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "KV pages prefilled at admission"},
    {"name": "serve_llm_prefix_cache_evictions_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "cached refcount-0 pages evicted for reuse"},
    {"name": "serve_llm_prefix_cached_blocks", "type": "gauge",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "refcount-0 pages retained for prefix reuse"},
    {"name": "serve_llm_prefill_chunks_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py", "what": "prefill chunks run"},
    {"name": "serve_llm_prefill_stall_ms", "type": "histogram",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "decode stall imposed by a prefill step"},
    {"name": "serve_llm_compile_misses_total", "type": "counter",
     "where": "ray_tpu/serve/llm/runner.py",
     "what": "prefill/decode calls that triggered an XLA compile"},
    {"name": "serve_llm_compile_seconds", "type": "histogram",
     "where": "ray_tpu/serve/llm/runner.py",
     "what": "XLA compile time per LLM program"},
    {"name": "serve_llm_weight_swaps_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "weight hot-swaps installed at a step boundary"},
    {"name": "serve_llm_spec_proposed_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "draft tokens proposed to the speculative verify program"},
    {"name": "serve_llm_spec_accepted_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "draft tokens accepted by the verify program"},
    {"name": "serve_llm_spec_rejected_total", "type": "counter",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "draft tokens rejected by the verify program (the "
             "spec-accept-collapse rule's miss side)"},
    {"name": "serve_llm_spec_accept_ratio", "type": "gauge",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "cumulative accepted / proposed draft tokens"},
    {"name": "serve_llm_verify_step_ms", "type": "histogram",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "speculative verify step latency (K+1-wide program)"},
    {"name": "serve_llm_paged_attn_enabled", "type": "gauge",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "1 when decode/verify run the pallas paged-attention "
             "kernel, 0 on the dense gather fallback"},
    # serve SLO attribution (the per-request waterfall's metric face)
    {"name": "serve_slo_ttft_ms", "type": "histogram",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "TTFT decomposed: phase=queue|prefill|total"},
    {"name": "serve_slo_tpot_ms", "type": "histogram",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "decode seconds per output token after the first"},
    # serve proxy
    {"name": "serve_num_http_requests", "type": "counter",
     "where": "ray_tpu/serve/api.py", "what": "HTTP ingress, by status"},
    {"name": "serve_http_request_latency_ms", "type": "histogram",
     "where": "ray_tpu/serve/api.py", "what": "HTTP ingress latency"},
    # serve self-healing
    {"name": "serve_replica_health_checks_total", "type": "counter",
     "where": "ray_tpu/serve/api.py",
     "what": "controller health probes, by result (ok|miss|dead)"},
    {"name": "serve_replica_restarts_total", "type": "counter",
     "where": "ray_tpu/serve/api.py",
     "what": "replica replacements started by the self-healing loop"},
    {"name": "serve_replicas_healthy", "type": "gauge",
     "where": "ray_tpu/serve/api.py",
     "what": "replicas passing their latest health probe round"},
    {"name": "serve_request_failovers_total", "type": "counter",
     "where": "ray_tpu/serve/api.py",
     "what": "requests re-submitted after replica death (unary "
             "retries + mid-stream resumes)"},
    # RL flywheel
    {"name": "rl_rollout_tokens_total", "type": "counter",
     "where": "ray_tpu/rllib/llm/rollout.py",
     "what": "tokens generated by RL rollouts"},
    {"name": "rl_reward_mean", "type": "gauge",
     "where": "ray_tpu/rllib/llm/rollout.py",
     "what": "mean reward of the latest rollout batch"},
    {"name": "rl_traj_staleness", "type": "histogram",
     "where": "ray_tpu/rllib/llm/learner.py",
     "what": "weight-version lag of offered trajectories"},
    {"name": "rl_traj_dropped_total", "type": "counter",
     "where": "ray_tpu/rllib/llm/learner.py",
     "what": "trajectories refused by the staleness guard"},
    {"name": "rl_weight_swap_seconds", "type": "histogram",
     "where": "ray_tpu/serve/llm/engine.py",
     "what": "drain-free weight hot-swap wall time"},
    # core fast path (coalesced submission + compiled DAGs)
    {"name": "rpc_oneway_batch_size", "type": "histogram",
     "where": "ray_tpu/core/rpc.py",
     "what": "messages coalesced per flushed batch frame"},
    {"name": "core_submit_coalesced_total", "type": "counter",
     "where": "ray_tpu/core/cluster_runtime.py",
     "what": "submissions/returns that rode a coalesced frame, by kind"},
    {"name": "dag_executions_total", "type": "counter",
     "where": "ray_tpu/dag/__init__.py",
     "what": "compiled-DAG executions, by path (compiled|eager_fallback)"},
    # task flight recorder (lifecycle ledger)
    {"name": "task_queue_wait_seconds", "type": "histogram",
     "where": "ray_tpu/core/nodelet.py",
     "what": "time tasks spend in a nodelet's dispatch queue (enqueue "
             "to dispatch) — the task-queue-stall rule's input"},
    {"name": "task_ledger_events_total", "type": "counter",
     "where": "ray_tpu/core/task_ledger.py",
     "what": "lifecycle transitions ingested by the head task ledger"},
    {"name": "task_ledger_dropped_total", "type": "counter",
     "where": "ray_tpu/core/task_ledger.py",
     "what": "lifecycle transitions dropped by the per-record "
             "transition cap — drops counted, never silent"},
    # profiler plane
    {"name": "core_task_cpu_seconds_total", "type": "counter",
     "where": "ray_tpu/core/cluster_runtime.py",
     "what": "CPU seconds consumed executing tasks and actor methods, "
             "by kind (fed by the worker exec loop)"},
    {"name": "profile_captures_total", "type": "counter",
     "where": "ray_tpu/util/profiler.py",
     "what": "sampling-profiler capture windows completed"},
    {"name": "profile_samples_total", "type": "counter",
     "where": "ray_tpu/util/profiler.py",
     "what": "stack sample ticks taken across capture windows"},
    {"name": "profile_stacks_dropped_total", "type": "counter",
     "where": "ray_tpu/util/profiler.py",
     "what": "thread-samples rejected by the unique-stack cap"},
    # log plane
    {"name": "log_records_total", "type": "counter",
     "where": "ray_tpu/utils/logging.py",
     "what": "structured log records emitted, by level (the "
             "error-rate-spike rule's input)"},
    {"name": "log_bytes_total", "type": "counter",
     "where": "ray_tpu/utils/logging.py",
     "what": "structured JSONL log bytes written"},
    {"name": "log_records_dropped_total", "type": "counter",
     "where": "ray_tpu/utils/logging.py",
     "what": "log records lost to serialization/disk failure "
             "(drops counted, never silent)"},
    # span plane
    {"name": "spans_sampled_total", "type": "counter",
     "where": "ray_tpu/utils/events.py",
     "what": "spans admitted into the local buffer, by category"},
    {"name": "spans_dropped_total", "type": "counter",
     "where": "ray_tpu/utils/events.py",
     "what": "spans rejected (sampling policy or full buffer)"},
    # watchtower (alerting plane)
    {"name": "watchtower_alerts_firing", "type": "gauge",
     "where": "ray_tpu/util/watchtower.py",
     "what": "alerts currently firing, by severity"},
    {"name": "watchtower_alerts_total", "type": "counter",
     "where": "ray_tpu/util/watchtower.py",
     "what": "pending->firing transitions, by rule"},
    {"name": "watchtower_samples_total", "type": "counter",
     "where": "ray_tpu/util/watchtower.py",
     "what": "metric-history sample ticks completed"},
    {"name": "watchtower_series", "type": "gauge",
     "where": "ray_tpu/util/watchtower.py",
     "what": "metric-history series retained"},
    {"name": "watchtower_series_dropped_total", "type": "counter",
     "where": "ray_tpu/util/watchtower.py",
     "what": "new series rejected by the history series cap"},
    {"name": "watchtower_autodumps_total", "type": "counter",
     "where": "ray_tpu/util/watchtower.py",
     "what": "debug dumps auto-triggered by critical alerts"},
]


def catalog_names() -> set[str]:
    return {m["name"] for m in CATALOG}


def source_metrics(package_root: str | None = None) -> dict[str, str]:
    """{metric name: type} for every Counter/Gauge/Histogram construction
    with a literal name in the package source — the 'registered at
    runtime' side of the drift gate, extracted statically so the gate
    covers paths no test instantiates."""
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    found: dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                f_ = node.func
                ctor = (f_.id if isinstance(f_, ast.Name)
                        else f_.attr if isinstance(f_, ast.Attribute)
                        else None)
                if ctor in ("Counter", "Gauge", "Histogram") \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    found[node.args[0].value] = ctor.lower()
    return found
