"""The Finding record and its stable fingerprint.

Fingerprints key the baseline file. They deliberately exclude the line
NUMBER — a finding must survive unrelated edits above it — and instead
hash the file path, rule name, the stripped source line text, and an
occurrence index to disambiguate identical lines in one file.

Findings produced by the interprocedural (semantic-index) layer carry
a ``chain``: the call-path evidence from the reported site to the
effect that makes it a violation, one human-readable hop per entry.
The chain is evidence, not identity — it is excluded from the
fingerprint so a baseline entry survives refactors that reroute the
chain without fixing the bug.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


@dataclass
class Finding:
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    col: int           # 0-based
    rule: str          # rule name, e.g. "guarded-by"
    code: str          # rule code, e.g. "GL005"
    message: str
    line_text: str = ""
    occurrence: int = field(default=0)  # nth identical (path,rule,text)
    chain: tuple = ()  # interprocedural evidence, one str per hop

    def fingerprint(self) -> str:
        h = hashlib.sha1()
        h.update(self.path.encode())
        h.update(b"\x00")
        h.update(self.rule.encode())
        h.update(b"\x00")
        h.update(self.line_text.strip().encode())
        h.update(b"\x00")
        h.update(str(self.occurrence).encode())
        return h.hexdigest()

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "code": self.code,
            "message": self.message,
            "chain": list(self.chain),
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.code} [{self.rule}] {self.message}")


def assign_occurrences(findings: list[Finding]) -> list[Finding]:
    """Number findings that share (path, rule, line text) so their
    fingerprints stay distinct and stable under reordering."""
    seen: dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        key = (f.path, f.rule, f.line_text.strip())
        f.occurrence = seen.get(key, 0)
        seen[key] = f.occurrence + 1
    return findings
