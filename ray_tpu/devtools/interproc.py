"""Interprocedural concurrency rules over the semantic index.

These are the whole-package layers of GL009/GL012/GL013 (same rule
name and code as the per-file layer, ``subcode = "inter"``, so one
suppression comment covers both) plus GL017, which only exists because
the class map does. Each finding carries ``chain`` evidence — the call
path from the reported site to the effect that makes it a violation —
printed by ``--explain`` and included in JSON output.

Division of labor with the per-file layer, per rule:

- **GL012.inter** fires on a *call* site that runs under a held
  ``guarded_by`` lock when the callee is transitively blocking. The
  per-file layer owns direct blocking primitives under the lock; the
  indexed layer owns everything hidden behind a function call, so the
  two never double-report the same site.
- **GL013.inter** fires when a registered handler *reaches* (through
  one or more call hops) a synchronous RPC that targets its own
  service — either literally self-addressed, or through a multi-hop
  cycle across service classes (A's handler calls a method of B whose
  handler calls back into a method of A). Self-addressed RPC directly
  in the handler body stays with the per-file layer. Same-class
  name-only edges (A calling a method that only A registers) are NOT
  cycle edges: peer-to-peer traffic between instances of one service
  class on different nodes is the normal idiom. Handlers registered
  ``slow=True`` run off the service loop and cannot deadlock it, so
  edges out of them are skipped, as are ``send_oneway`` sends (no
  reply to park on).
- **GL009.inter** merges every nested acquisition — lexical and
  lock-held-at-a-call-site-that-transitively-acquires — into one
  global lock-order graph and reports pairwise inversions. Inversions
  whose both directions are lexical within the same file and class are
  the per-file layer's finding and skipped here.
"""

from __future__ import annotations

from ray_tpu.devtools.registry import IndexRule, register_index
from ray_tpu.devtools.semindex import SemanticIndex, _is_lock_name


def _held_guarded(index: SemanticIndex, s: dict, cls: str,
                  held: list[str]) -> list[tuple[str, str]]:
    """(raw, resolved lock id) for each held with-context that is a
    lock carrying a guarded_by annotation somewhere in the package."""
    out = []
    for raw in held:
        if not _is_lock_name(raw):
            continue
        lid = index.resolve_lock(s, cls, raw)
        if lid in index.guarded_ids:
            out.append((raw, lid))
    return out


@register_index
class InterBlockingUnderLock(IndexRule):
    name = "blocking-under-lock"
    code = "GL012"
    subcode = "inter"
    description = ("call under a held guarded_by lock to a function "
                   "that transitively blocks (sleeps, sync RPC, "
                   "timeout-less result())")
    invariant = ("critical sections guarded for cross-thread state "
                 "stay short even when the blocking call hides behind "
                 "helper functions")

    def check(self, index: SemanticIndex) -> list:
        findings: list = []
        for key, (s, fn) in sorted(index.functions.items()):
            for callee, site in index.edges.get(key, ()):
                if callee not in index.blocking:
                    continue
                guarded = _held_guarded(index, s, fn["cls"],
                                        site["held"])
                if not guarded:
                    continue
                raw, lid = guarded[0]
                chain = [f"{s['rel']}:{site['line']}: "
                         f"{index.fn_display(key)} holds {raw} "
                         f"(guarded_by lock {lid}) and calls "
                         f"{index.fn_display(callee)}"]
                chain += index.blocking_chain(callee)
                self.report(
                    index, findings, s["rel"], site["line"],
                    f"call to {index.fn_display(callee)}() blocks "
                    f"while holding guarded lock {raw} "
                    f"(run with --explain for the call chain)",
                    chain)
        return findings


@register_index
class InterHandlerReentry(IndexRule):
    name = "handler-reentry"
    code = "GL013"
    subcode = "inter"
    description = ("RPC handler that reaches, through helper calls or "
                   "a cycle across service classes, a synchronous RPC "
                   "back into its own service")
    invariant = ("a service loop never waits synchronously on itself "
                 "— directly, through helpers, or through another "
                 "service calling back")

    def _reach(self, index: SemanticIndex, start: str):
        """BFS over call edges: fn key -> (depth, call-hop chain)."""
        seen = {start: (0, [])}
        todo = [start]
        while todo:
            key = todo.pop(0)
            depth, path = seen[key]
            for callee, site in index.edges.get(key, ()):
                if callee in seen:
                    continue
                rel = index.functions[key][0]["rel"]
                hop = (f"{rel}:{site['line']}: "
                       f"{index.fn_display(key)} calls "
                       f"{index.fn_display(callee)}")
                seen[callee] = (depth + 1, path + [hop])
                todo.append(callee)
        return seen

    def check(self, index: SemanticIndex) -> list:
        findings: list = []
        # class-level RPC edge graph: service class -> set of service
        # classes it synchronously calls into (from non-slow handlers),
        # with one representative evidence record per edge
        class_edges: dict[str, dict[str, dict]] = {}
        sites: list[dict] = []  # every candidate (handler, rpc site)
        for fkey in sorted(index.handler_fns):
            for ckey, hkey, method, oneway, slow in \
                    index.handler_fns[fkey]:
                if slow:
                    continue  # slow lane runs off the service loop
                reach = self._reach(index, hkey)
                for rkey, (depth, path) in sorted(reach.items()):
                    rs, rfn = index.functions[rkey]
                    if rfn["effects_annot"] is not None and \
                            rkey != hkey:
                        continue  # '# effects:' froze this function
                    for rpc in rfn["rpc"]:
                        sites.append({
                            "cls": ckey, "handler": hkey,
                            "method": method, "depth": depth,
                            "path": path, "rel": rs["rel"],
                            "rpc": rpc, "anchor": hkey
                            if depth else rkey})
        for site in sites:
            rpc = site["rpc"]
            for tgt in rpc["targets"]:
                # ---- transitive literal self-reentry (>=1 call hop;
                # depth 0 is the per-file layer's finding)
                if tgt["self"] and site["depth"] >= 1:
                    hs, hfn = index.functions[site["handler"]]
                    chain = ([f"{hs['rel']}:{hfn['line']}: handler "
                              f"'{site['method']}' is "
                              f"{index.fn_display(site['handler'])}"]
                             + site["path"]
                             + [f"{site['rel']}:{rpc['line']}: "
                                f"synchronous .{rpc['kind']}() targets "
                                f"the service's own address"])
                    self.report(
                        index, findings, hs["rel"], hfn["line"],
                        f"handler '{site['method']}' reaches a "
                        f"synchronous self-targeted RPC via "
                        f"{site['depth']} call hop(s) — the service "
                        f"loop would wait on itself", chain)
                # ---- class-level edges for cycle detection
                m = tgt["method"]
                if tgt["self"] or m is None:
                    continue
                for tckey, thkey, _, toneway, _ in \
                        index.rpc_registry.get(m, ()):
                    if tckey == site["cls"]:
                        continue  # same-class peer traffic idiom
                    ev = {"site": site, "target_method": m,
                          "target_cls": tckey, "target_handler": thkey}
                    class_edges.setdefault(
                        site["cls"], {}).setdefault(tckey, ev)
        # report each edge that closes a cycle back to its origin class
        for a in sorted(class_edges):
            for b, ev in sorted(class_edges[a].items()):
                path = self._class_path(class_edges, b, a)
                if path is None:
                    continue
                site, rpc = ev["site"], ev["site"]["rpc"]
                hs, hfn = index.functions[site["handler"]]
                chain = ([f"{hs['rel']}:{hfn['line']}: {a} handler "
                          f"'{site['method']}' is "
                          f"{index.fn_display(site['handler'])}"]
                         + site["path"]
                         + [f"{site['rel']}:{rpc['line']}: "
                            f".{rpc['kind']}('{ev['target_method']}') "
                            f"enters {b}"]
                         + [self._edge_desc(index, hop)
                            for hop in path])
                self.report(
                    index, findings, site["rel"], rpc["line"],
                    f"handler '{site['method']}' of {a} calls "
                    f"'{ev['target_method']}' of {b}, which can call "
                    f"back into {a} ({len(path) + 1}-hop reentry "
                    f"cycle)", chain)
        return findings

    def _class_path(self, class_edges: dict, start: str,
                    goal: str) -> list[dict] | None:
        """Edge evidence along a path start -> ... -> goal, or None."""
        seen = {start: []}
        todo = [start]
        while todo:
            c = todo.pop(0)
            for nxt, ev in sorted(class_edges.get(c, {}).items()):
                if nxt in seen:
                    continue
                seen[nxt] = seen[c] + [ev]
                if nxt == goal:
                    return seen[nxt]
                todo.append(nxt)
        return None

    def _edge_desc(self, index: SemanticIndex, ev: dict) -> str:
        site, rpc = ev["site"], ev["site"]["rpc"]
        return (f"{site['rel']}:{rpc['line']}: {site['cls']} handler "
                f"'{site['method']}' then calls "
                f"'{ev['target_method']}' of {ev['target_cls']}")


@register_index
class InterLockOrder(IndexRule):
    name = "lock-order"
    code = "GL009"
    subcode = "inter"
    description = ("lock-order inversion in the global acquisition "
                   "graph, including locks held in a caller while a "
                   "callee transitively acquires another")
    invariant = ("every pair of locks is acquired in one global order "
                 "across the whole package, not just within one "
                 "function")

    def check(self, index: SemanticIndex) -> list:
        # (outer lock id, inner lock id) -> [edge records]
        edges: dict[tuple[str, str], list[dict]] = {}

        def add(outer: str, inner: str, rec: dict) -> None:
            if outer != inner:
                edges.setdefault((outer, inner), []).append(rec)

        for key, (s, fn) in sorted(index.functions.items()):
            cls = fn["cls"]
            if fn["effects_annot"] is not None:
                continue  # annotated: effects (and ordering) frozen
            for a in fn["acquires"]:
                inner = index.resolve_lock(s, cls, a["lock"])
                for raw in a["held"]:
                    if not _is_lock_name(raw):
                        continue
                    add(index.resolve_lock(s, cls, raw), inner, {
                        "kind": "lexical", "rel": s["rel"],
                        "line": a["line"], "scope": (s["rel"], cls),
                        "chain": [
                            f"{s['rel']}:{a['line']}: "
                            f"{index.fn_display(key)} acquires "
                            f"{a['lock']} while holding {raw}"]})
            for callee, site in index.edges.get(key, ()):
                held = [(raw, index.resolve_lock(s, cls, raw))
                        for raw in site["held"]
                        if _is_lock_name(raw)]
                if not held:
                    continue
                for inner in index.acquires.get(callee, {}):
                    for raw, outer in held:
                        add(outer, inner, {
                            "kind": "call", "rel": s["rel"],
                            "line": site["line"],
                            "scope": (s["rel"], cls),
                            "chain": [
                                f"{s['rel']}:{site['line']}: "
                                f"{index.fn_display(key)} holds {raw} "
                                f"and calls "
                                f"{index.fn_display(callee)}"]
                            + index.acquire_chain(callee, inner)})
        findings: list = []
        for (a, b) in sorted(edges):
            if a >= b or (b, a) not in edges:
                continue  # visit each unordered pair once
            fwd, rev = edges[(a, b)], edges[(b, a)]
            if self._same_scope_lexical(fwd, rev):
                continue  # per-file GL009 already reports this one
            # anchor at the lexically-latest edge site so the report
            # lands on the acquisition that completed the inversion
            all_edges = [(e, (b, a) if e in rev else (a, b))
                         for e in fwd + rev]
            anchor, order = max(
                all_edges, key=lambda p: (p[0]["rel"], p[0]["line"]))
            other = (rev if order == (a, b) else fwd)[0]
            chain = (anchor["chain"]
                     + [f"...but the opposite order holds elsewhere:"]
                     + other["chain"])
            self.report(
                index, findings, anchor["rel"], anchor["line"],
                f"lock order inversion: {order[0]} -> {order[1]} "
                f"here, but {order[1]} -> {order[0]} at "
                f"{other['rel']}:{other['line']}", chain)
        return findings

    @staticmethod
    def _same_scope_lexical(fwd: list[dict], rev: list[dict]) -> bool:
        f_scopes = {e["scope"] for e in fwd if e["kind"] == "lexical"}
        r_scopes = {e["scope"] for e in rev if e["kind"] == "lexical"}
        return bool(f_scopes & r_scopes)


@register_index
class StaleGuardedBy(IndexRule):
    name = "stale-guarded-by"
    code = "GL017"
    subcode = ""
    description = ("guarded_by(<lock>) annotation naming a lock "
                   "attribute the class (or module) never defines")
    invariant = ("every guarded_by annotation points at a real lock, "
                 "so the guarded-by rules enforce something")

    def check(self, index: SemanticIndex) -> list:
        findings: list = []
        for rel in sorted(index.files):
            s = index.files[rel]
            for g in s["guarded"]:
                name = g["lock"].split(".", 1)[0]
                if self._defined(index, s, g["scope"], name):
                    continue
                where = (f"class {g['scope']}" if g["scope"]
                         else f"module {s['module']}")
                self.report(
                    index, findings, rel, g["line"],
                    f"guarded_by({g['lock']}) names a lock the "
                    f"{where} never defines — stale annotation "
                    f"guards nothing")
        return findings

    @staticmethod
    def _defined(index: SemanticIndex, s: dict, scope: str,
                 name: str) -> bool:
        if name in s["module_assigns"] or name in s["imports"]:
            return True
        if not scope:
            return False
        has = index.class_defines_attr(f"{s['module']}.{scope}", name)
        # None: a base class escapes the index — assume defined there
        return has is not False
